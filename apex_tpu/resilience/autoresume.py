"""Preemption-safe autoresume: the realized ADLR autoresume hook.

The reference carries a vestigial ``get_autoresume()`` returning the
ADLR cluster's autoresume object (ref:
apex/transformer/pipeline_parallel/utils.py:131-133, always ``None``
here).  :class:`AutoResume` makes it real for TPU pods, where
preemption is routine: a SIGTERM (the scheduler's eviction notice) or
SIGINT flips a flag the training loop polls at step boundaries —
``termination_requested()``, the Megatron-parity call — so the loop can
cut a final *synchronous* checkpoint, write a clean-exit marker, and
exit 0 instead of dying mid-step with a half-written step dir.

Lifecycle::

    ar = AutoResume(marker_dir=ckpt_dir).install()   # main thread
    ...
    for step in range(start, steps):
        params = train_step(params)
        if ar.termination_requested():
            mgr.save(step + 1, params); mgr.wait()   # sync final save
            ar.mark_clean_exit(step + 1)
            break
    ar.uninstall()

``install()`` also registers the instance with
``apex_tpu.transformer.pipeline_parallel.utils.set_autoresume`` so
Megatron-parity call sites reading ``get_autoresume()`` light up
without plumbing.

The signal handler itself only sets state — it must not emit telemetry
or take locks: it runs between bytecodes of the main thread, which may
be inside ``JsonlSink.emit`` holding the (non-reentrant) sink lock.
The loop emits the ``resilience`` events from safe context instead.
A second delivery of the same signal falls through to the previously
installed handler (for SIGINT that means KeyboardInterrupt — the
standard "press ^C twice to really stop" contract).
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

#: Marker file proving the previous run exited through the graceful
#: preemption path (final checkpoint durable) — the scheduler / driver
#: distinguishes "preempted cleanly, just resume" from "crashed".
CLEAN_EXIT_MARKER = "CLEAN_EXIT.json"

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class AutoResume:
    """SIGTERM/SIGINT-aware preemption handler.

    ``marker_dir`` is where :meth:`mark_clean_exit` drops
    ``CLEAN_EXIT.json`` (typically the checkpoint directory).  ``sink``
    optionally receives ``resilience`` events from the *safe-context*
    methods (never from the signal handler).
    """

    def __init__(self, *, marker_dir: Optional[str] = None, sink=None,
                 signals=DEFAULT_SIGNALS, wall_clock=time.time):
        self.marker_dir = marker_dir
        self._sink = sink
        self._signals = tuple(signals)
        self._wall = wall_clock
        self._requested = threading.Event()
        self._source: Optional[str] = None
        self._prev: Dict[int, Any] = {}
        self._installed = False

    # -- telemetry (safe context only) ---------------------------------------

    def _emit(self, name: str, value=None, step=None, **attrs) -> None:
        from ..monitor.events import emit_resilience

        emit_resilience(self._sink, name, value=value, step=step,
                        clock=self._wall, **attrs)

    # -- signal wiring -------------------------------------------------------

    def install(self) -> "AutoResume":
        """Register the handlers (idempotent; main thread only) and
        publish the instance through ``set_autoresume`` so
        ``get_autoresume()`` call sites see it."""
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        self._installed = True
        from ..transformer.pipeline_parallel.utils import set_autoresume

        set_autoresume(self)
        return self

    def uninstall(self) -> None:
        """Restore the previous handlers and unpublish the instance."""
        if not self._installed:
            return
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev.clear()
        self._installed = False
        from ..transformer.pipeline_parallel.utils import (get_autoresume,
                                                           set_autoresume)

        if get_autoresume() is self:
            set_autoresume(None)

    def _handler(self, signum, frame) -> None:
        # Flag-set only — no telemetry, no locks (see module docstring).
        if self._requested.is_set():
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        try:
            self._source = signal.Signals(signum).name
        except ValueError:  # pragma: no cover - exotic signum
            self._source = str(signum)
        self._requested.set()

    # -- the Megatron-parity surface -----------------------------------------

    def termination_requested(self) -> bool:
        """Poll at step boundaries: True once preemption was signalled
        (or :meth:`request_termination` was called)."""
        return self._requested.is_set()

    @property
    def source(self) -> Optional[str]:
        """What requested termination (signal name or caller tag)."""
        return self._source

    def request_termination(self, source: str = "api") -> None:
        """Programmatic preemption (tests, cluster RPC callbacks)."""
        if not self._requested.is_set():
            self._source = source
            self._requested.set()
            self._emit("termination_requested", source=source)

    # -- clean-exit marker ---------------------------------------------------

    def marker_path(self, marker_dir: Optional[str] = None) -> str:
        d = marker_dir or self.marker_dir
        if d is None:
            raise ValueError("no marker_dir configured")
        return os.path.join(d, CLEAN_EXIT_MARKER)

    def mark_clean_exit(self, step: int, **attrs) -> str:
        """Atomically write the clean-exit marker (tmp + rename) after
        the final checkpoint is durable.  Returns the marker path."""
        path = self.marker_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        payload = {"step": int(step), "time": self._wall(),
                   "source": self._source or "api"}
        payload.update(attrs)
        tmp = path + ".partial"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._emit("clean_exit", step=int(step), source=payload["source"],
                   marker=path)
        return path

    def clear_clean_exit(self) -> None:
        """Remove a stale marker at run start — a marker must only ever
        describe the *most recent* exit."""
        try:
            os.remove(self.marker_path())
        except FileNotFoundError:
            pass

    def __enter__(self) -> "AutoResume":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def read_clean_exit(marker_dir: str) -> Optional[dict]:
    """Parse ``CLEAN_EXIT.json`` under ``marker_dir``; None if absent
    or unreadable (a torn marker is treated as no marker)."""
    path = os.path.join(marker_dir, CLEAN_EXIT_MARKER)
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None
