"""apex_tpu.resilience — turn failures into resumed runs.

PR 2's :mod:`apex_tpu.monitor` built the eyes (structured telemetry,
watchdog alarms); this package is the hands.  Four pieces, spanning the
checkpoint, monitor, and driver layers:

1. **AutoResume** (:mod:`.autoresume`) — the realized ADLR autoresume
   hook: SIGTERM/SIGINT set a flag the loop polls at step boundaries
   (``termination_requested()``, wired into the Megatron-parity
   ``get_autoresume()``), enabling a final synchronous checkpoint and a
   ``CLEAN_EXIT.json`` marker instead of a corpse.

2. **Checkpoint integrity** — lives in
   :mod:`apex_tpu.utils.checkpoint`: ``latest_valid_step()`` spots
   partial/unfinalized step dirs structurally; ``restore()`` falls back
   step-by-step past corrupt ones (emitting ``ckpt_skipped`` /
   ``ckpt_gc`` events and GC'ing the garbage) and names the available
   steps when an explicitly requested step is missing.

3. **Retrying driver** (:mod:`.driver`) — :func:`run_resumable`:
   bounded restarts, exponential backoff + per-process jitter, every
   attempt / give-up on the event log; paired with
   :class:`~.escalation.EscalationPolicy`, which turns watchdog alarms
   into checkpoint-then-abort restarts via :class:`EscalationAbort`.

4. **Fault injection** (:mod:`.faults`) — deterministic injectors
   (``crash@K`` / ``kill@K`` / ``sigterm@K`` / ``nan@K`` / ``stall@K``
   and on-disk checkpoint corruption) proving kill-at-K + resume
   reproduces the uninterrupted run bitwise (tests/test_resilience.py,
   ``--fault`` on the smoke drivers, tools/ci.sh step 5).

Full lifecycle walkthrough + escalation table: docs/api/resilience.md.
"""
from .autoresume import CLEAN_EXIT_MARKER, AutoResume, read_clean_exit
from .driver import GiveUp, backoff_delay, run_resumable
from .escalation import (
    ABORT,
    CHECKPOINT_THEN_ABORT,
    DEFAULT_POLICY,
    DEFAULT_SERVE_POLICY,
    IGNORE,
    SNAPSHOT_THEN_DRAIN,
    EscalationAbort,
    EscalationPolicy,
    serve_policy,
)
from .faults import (
    PARENT_KINDS,
    PROCESS_FATAL_KINDS,
    FaultInjector,
    InjectedCrash,
    InjectedFault,
    corrupt_checkpoint,
    corrupt_journal,
    parse_fault,
    split_fault,
)

__all__ = [
    "AutoResume", "read_clean_exit", "CLEAN_EXIT_MARKER",
    "run_resumable", "backoff_delay", "GiveUp",
    "EscalationPolicy", "EscalationAbort", "DEFAULT_POLICY",
    "DEFAULT_SERVE_POLICY", "serve_policy",
    "IGNORE", "ABORT", "CHECKPOINT_THEN_ABORT", "SNAPSHOT_THEN_DRAIN",
    "FaultInjector", "parse_fault", "split_fault",
    "PARENT_KINDS", "PROCESS_FATAL_KINDS",
    "InjectedFault", "InjectedCrash",
    "corrupt_checkpoint", "corrupt_journal",
]
