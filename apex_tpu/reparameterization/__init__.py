"""Parameter reparameterization (parity with ``apex/reparameterization``).

The reference installs forward-pre hooks that recompute weights from
auxiliary parameters before every module call
(ref: apex/reparameterization/__init__.py:4-103).  The functional
workflow here (pass the SAME ``dim`` to every call — it is not stored in
the tree; flax ``(in, out)`` kernels want ``dim=-1`` for per-output
magnitudes)::

    params = apply_weight_norm(params, dim=-1)          # w -> (w_v, w_g)
    def loss_fn(params):
        real = reparameterize_weight_norm(params, dim=-1)   # inside jit
        return model.apply({"params": real}, x)
    params = remove_weight_norm(params, dim=-1)         # collapse back
"""
from functools import partial

from .reparameterization import (
    Reparameterization,
    apply_reparameterization,
    remove_reparameterization,
    reparameterize,
)
from .weight_norm import WeightNorm


def apply_weight_norm(params, name: str = "", dim=0, predicate=None):
    """ref: apex/reparameterization/__init__.py ``apply_weight_norm`` —
    decompose matching leaves into ``_v``/``_g`` pairs."""
    return apply_reparameterization(params, WeightNorm, name=name,
                                    dim=dim, predicate=predicate)


def remove_weight_norm(params, name: str = "", dim=0):
    """ref: apex/reparameterization/__init__.py ``remove_weight_norm``."""
    return remove_reparameterization(params, WeightNorm, name=name, dim=dim)


reparameterize_weight_norm = partial(reparameterize, reparameterization=WeightNorm)

__all__ = [
    "Reparameterization",
    "WeightNorm",
    "apply_reparameterization",
    "remove_reparameterization",
    "reparameterize",
    "apply_weight_norm",
    "remove_weight_norm",
    "reparameterize_weight_norm",
]
