"""Weight normalization: w = g * v / ||v||.

Parity surface for ``apex/reparameterization/weight_norm.py:22``
(``WeightNorm``; norm-over-all-dims-except-``dim`` per ``_norm`` at :8-18;
Salimans & Kingma, arXiv:1602.07868).  The reference's
``Fused_Weight_Norm`` CUDA kernel is unnecessary on TPU: the norm + scale
is a tiny reduction XLA fuses into the consumer matmul's epilogue.

Note on conventions: the reference's ``dim=0`` norms per *output* channel
of a torch ``(out, in)`` weight.  Flax kernels are ``(in, out)``, so the
per-output-channel norm there is ``dim=-1``.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .reparameterization import Reparameterization


def _norm(p: jnp.ndarray, dim: Optional[int]) -> jnp.ndarray:
    """L2 norm over all dimensions except ``dim`` (keepdims), computed in
    fp32 (ref: apex/reparameterization/weight_norm.py:8-18)."""
    p32 = p.astype(jnp.float32)
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(p32)))
    axes = tuple(i for i in range(p.ndim) if i != dim % p.ndim)
    return jnp.sqrt(jnp.sum(jnp.square(p32), axis=axes, keepdims=True))


class WeightNorm(Reparameterization):
    """Decouple magnitude from direction: leaf ``w`` becomes ``w_v``
    (direction, shaped like w) and ``w_g`` (magnitude, one per ``dim``
    slice) (ref: apex/reparameterization/weight_norm.py:22-60)."""

    SUFFIXES: Tuple[str, ...] = ("_v", "_g")

    @staticmethod
    def decompose(weight: jnp.ndarray, dim: Optional[int]):
        g = _norm(weight, dim).astype(weight.dtype)
        return weight, g

    @staticmethod
    def compute_weight(v: jnp.ndarray, g: jnp.ndarray,
                       dim: Optional[int]):
        w32 = (g.astype(jnp.float32) / (_norm(v, dim) + 0.0)
               ) * v.astype(jnp.float32)
        return w32.astype(v.dtype)
