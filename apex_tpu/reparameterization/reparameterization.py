"""Generic parameter reparameterization over pytrees.

Parity surface for ``apex/reparameterization/reparameterization.py``
(``Reparameterization`` base: decompose a weight into auxiliary
parameters, recompute it before every forward).  The reference installs
module forward-pre hooks; JAX has no module mutation, so the same
contract is functional: :func:`apply_reparameterization` rewrites a param
pytree (each targeted leaf ``w`` becomes ``w_v``/``w_g`` style auxiliary
leaves) and :func:`reparameterize` — called at the top of the user's
apply/loss function, *inside* jit — materializes the weights again, so
gradients flow to the auxiliary parameters exactly as the hook-based
recompute does.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp


class Reparameterization:
    """Base class: how one weight decomposes and recomposes.

    Subclasses define ``SUFFIXES`` (auxiliary leaf name suffixes),
    :meth:`decompose` (weight -> aux tuple) and :meth:`compute_weight`
    (aux tuple -> weight), mirroring the reference's
    ``compute_weight``/``reparameterize``/``remove`` triple
    (ref: apex/reparameterization/reparameterization.py).
    """

    SUFFIXES: Tuple[str, ...] = ()

    @staticmethod
    def decompose(weight: jnp.ndarray, dim: Optional[int]):
        raise NotImplementedError

    @staticmethod
    def compute_weight(*aux, dim: Optional[int]):
        raise NotImplementedError


def _is_mapping(x) -> bool:
    # Accept flax FrozenDict and any mapping, not just plain dict.
    import collections.abc
    return isinstance(x, collections.abc.Mapping)


def _rebuild(node, out: dict):
    """Reconstruct with the input's mapping type (FrozenDict stays
    frozen)."""
    if isinstance(node, dict):
        return out
    try:
        return type(node)(out)
    except TypeError:
        return out  # mapping type without a dict-like constructor


def _aux_base(node, k: str, sfx) -> Optional[str]:
    """k is part of a decomposition only if the FULL suffix family is
    present at this level — a leaf merely *named* like one (e.g. a plain
    'gate_g' parameter) is left untouched."""
    for s in sfx:
        if k.endswith(s):
            base = k[: -len(s)]
            if all(base + s2 in node for s2 in sfx):
                return base
    return None


def default_predicate(name: str, leaf) -> bool:
    """Reference default: all parameters except 1-d vectors and scalars
    (ref: apex/reparameterization/__init__.py apply_weight_norm doc)."""
    arr = jnp.asarray(leaf)
    return (jnp.issubdtype(arr.dtype, jnp.floating) and arr.ndim >= 2)


def apply_reparameterization(params: Any, reparameterization,
                             name: str = "", dim: Optional[int] = 0,
                             predicate: Optional[Callable] = None) -> Any:
    """Rewrite a (nested-dict) param tree, replacing each targeted weight
    leaf with its auxiliary decomposition
    (ref: apex/reparameterization/__init__.py ``apply_reparameterization``).

    ``name`` selects a specific leaf name; empty selects every leaf the
    ``predicate`` accepts (default: floating, ndim>=2).
    """
    pred = predicate or default_predicate
    sfx = reparameterization.SUFFIXES

    def walk(node):
        if not _is_mapping(node):
            return node
        out = {}
        for k, v in node.items():
            if _is_mapping(v):
                out[k] = walk(v)
            elif (name and k == name) or (not name and pred(k, v)):
                aux = reparameterization.decompose(jnp.asarray(v), dim)
                for s, a in zip(sfx, aux):
                    out[k + s] = a
            else:
                out[k] = v
        return _rebuild(node, out)

    return walk(params)


def reparameterize(params: Any, reparameterization,
                   dim: Optional[int] = 0) -> Any:
    """Materialize weights from auxiliary leaves (differentiable; call
    inside the jitted forward — the functional analogue of the reference's
    forward-pre hook recompute)."""
    return _recompose_walk(params, reparameterization, dim, name=None)


def remove_reparameterization(params: Any, reparameterization,
                              name: str = "", dim: Optional[int] = 0) -> Any:
    """Collapse auxiliary leaves back into plain weights
    (ref: apex/reparameterization/__init__.py ``remove_reparameterization``).
    ``name`` restricts removal to one leaf name; empty removes all."""
    return _recompose_walk(params, reparameterization, dim,
                           name=name or None)


def _recompose_walk(params: Any, reparameterization, dim,
                    name: Optional[str]) -> Any:
    """Shared walk for reparameterize/remove: collapse each complete
    suffix family (optionally restricted to ``name``) into its weight."""
    sfx = reparameterization.SUFFIXES
    primary = sfx[0]

    def walk(node):
        if not _is_mapping(node):
            return node
        out = {}
        for k, v in node.items():
            if _is_mapping(v):
                out[k] = walk(v)
                continue
            base = _aux_base(node, k, sfx)
            if base is None or (name is not None and base != name):
                out[k] = v
            elif k.endswith(primary):
                aux = tuple(node[base + s] for s in sfx)
                out[base] = reparameterization.compute_weight(*aux, dim=dim)
            # non-primary aux leaves are consumed by the primary
        return _rebuild(node, out)

    return walk(params)
