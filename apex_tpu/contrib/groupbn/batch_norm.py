"""BatchNorm2d_NHWC: group-synchronized BN with fused add+relu.

Parity surface for ``apex/contrib/groupbn/batch_norm.py:115-237``
(``BatchNorm2d_NHWC(num_features, fuse_relu, bn_group, ...)``, forward
``(x, z=None)`` where ``z`` is the residual added before the relu — the
bn_addrelu fusion, ref :63-113).  Statistics sync uses the mesh data
axis (``lax.psum``) instead of the reference's CUDA IPC peer-memory
exchange; ``bn_group`` maps onto the axis name (None = local BN).  The
CUDA occupancy knobs (``max_cta_per_sm``, ``cta_launch_margin``,
``multi_stream``) have no TPU meaning and are accepted-and-ignored for
signature parity.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ... import parallel_state
from ...parallel.sync_batchnorm import SyncBatchNorm


class BatchNorm2d_NHWC(nn.Module):
    """ref: apex/contrib/groupbn/batch_norm.py:115."""

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    max_cta_per_sm: int = 2        # GPU knob, ignored
    cta_launch_margin: int = 12    # GPU knob, ignored
    multi_stream: bool = False     # GPU knob, ignored
    eps: float = 1e-5
    momentum: float = 0.1
    axis_name: Optional[str] = parallel_state.DATA_AXIS

    @nn.compact
    def __call__(self, x, z: Optional[jnp.ndarray] = None,
                 use_running_average: bool = False):
        """``z`` is the residual input of the bn_addrelu fusion
        (ref :210-231: ``bn_addrelu`` when z is not None)."""
        bn = SyncBatchNorm(
            num_features=self.num_features, eps=self.eps,
            momentum=self.momentum,
            # bn_group=1 means LOCAL batch norm in the reference (stats
            # sync only engages for groups of >1 devices,
            # ref: batch_norm.py:117 bn_group semantics).
            axis_name=self.axis_name if self.bn_group > 1 else None,
            fuse_relu=False, name="bn")
        y = bn(x, use_running_average=use_running_average)
        if z is not None:
            y = y + z
        if self.fuse_relu:
            y = jnp.maximum(y, 0)
        return y
