"""Group batch norm (parity with ``apex/contrib/groupbn``).

The reference's ``bnp`` extension is an NHWC persistent batch norm with
fused add+relu, synchronizing statistics across a ``bn_group`` of GPUs
via raw CUDA IPC peer memory (ref: apex/contrib/groupbn/batch_norm.py:239,
csrc/groupbn/ipc.cu) — a hand-rolled bypass of NCCL.  On TPU:

* NHWC is the native conv layout; nothing to opt into.
* cross-device stats = ``lax.psum`` over a mesh axis — the IPC trick is
  GPU-specific and needs no equivalent (XLA collectives ride ICI).
* the add+relu epilogue fusion is a module option XLA folds into the
  surrounding computation.

So :class:`BatchNorm2d_NHWC` here is SyncBatchNorm (whose psum-stats
implementation already covers the welford machinery,
apex_tpu/parallel/sync_batchnorm.py) plus the reference's fused
``z``-add + relu forward signature (``forward(x, z=None)``,
ref: batch_norm.py:210-231).
"""
from .batch_norm import BatchNorm2d_NHWC

__all__ = ["BatchNorm2d_NHWC"]
