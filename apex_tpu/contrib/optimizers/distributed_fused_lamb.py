"""DistributedFusedLAMB — ZeRO-sharded LAMB over the data axis.

TPU-native equivalent of the reference's pipelined distributed LAMB
(ref: apex/contrib/optimizers/distributed_fused_lamb.py:1-910 —
reduce_scatter + allreduce pipeline :590-612, L2-norm pipelining, param
all_gather after step).  LAMB's per-tensor trust ratios need norms over
tensors that straddle shard boundaries: each device computes per-tensor
partial sums over its shard via segment reduction (ids computed on
device — no packed-length constants), one ``psum`` restores the full
per-tensor norms, and the trust ratio is gathered back per-element —
the collective form of the reference's two-phase
``multi_tensor_l2norm`` + ``multi_tensor_lamb`` kernels.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
from ..._compat import axis_index, axis_size
import jax.numpy as jnp
import optax

from ...ops import multi_tensor
from ...optimizers.fused_adam import ScalarOrSchedule, _lr_at
from .distributed_fused_adam import _shard_padded


class DistributedFusedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def distributed_fused_lamb(
        learning_rate: ScalarOrSchedule = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        bias_correction: bool = True,
        grad_averaging: bool = True,
        adam_w_mode: bool = True,
        max_grad_norm: float = 1.0,
        axis_name: str = "data",
        grad_average: bool = True) -> optax.GradientTransformation:

    def init(params):
        world = axis_size(axis_name)
        metas = multi_tensor.compute_metas(params)
        shards = tuple(
            jnp.zeros((_shard_padded(m, world) // world,), jnp.float32)
            for m in metas)
        return DistributedFusedLAMBState(
            count=jnp.zeros((), jnp.int32),
            m=shards, v=tuple(jnp.zeros_like(s) for s in shards))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_lamb requires params")
        world = axis_size(axis_name)
        rank = axis_index(axis_name)
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0

        metas = multi_tensor.compute_metas(params)
        gbufs = multi_tensor.pack(grads, metas)
        pbufs = multi_tensor.pack(params, metas)

        # Stage 1a: reduce-scatter grads to shards.
        g_shards, p_shards, seg_shards = [], [], []
        for i, meta in enumerate(metas):
            padded = _shard_padded(meta, world)
            shard = padded // world
            g = gbufs[i].astype(jnp.float32)
            p = pbufs[i].astype(jnp.float32)
            if padded != meta.padded:
                g = jnp.pad(g, (0, padded - meta.padded))
                p = jnp.pad(p, (0, padded - meta.padded))
            g_sh = jax.lax.psum_scatter(g, axis_name, scatter_dimension=0,
                                        tiled=True)
            if grad_average:
                g_sh = g_sh / world
            p_sh = jax.lax.dynamic_slice_in_dim(p, rank * shard, shard)
            # Per-element tensor ids for this shard, computed on device
            # (positions depend on the traced rank; a materialized
            # full-buffer id constant would explode program size — see
            # multi_tensor.device_segment_ids).
            idx = rank * shard + jnp.arange(shard, dtype=jnp.int32)
            seg_sh = multi_tensor.device_segment_ids(meta, idx)
            g_shards.append(g_sh)
            p_shards.append(p_sh)
            seg_shards.append(seg_sh)

        # Stage 1b: global grad norm for clipping
        # (ref: distributed_fused_lamb.py L2-norm pipelining + clip);
        # multi_tensor.sumsq carries the TPU reduction-shape guard.
        local_sq = sum(multi_tensor.sumsq(g) for g in g_shards)
        gnorm = jnp.sqrt(jax.lax.psum(local_sq, axis_name))
        clip = jnp.where(gnorm > max_grad_norm,
                         max_grad_norm / jnp.maximum(gnorm, 1e-12), 1.0) \
            if max_grad_norm and max_grad_norm > 0 else jnp.float32(1.0)

        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            g = g_shards[i] * clip
            p = p_shards[i]
            segs = seg_shards[i]
            m = beta1 * state.m[i] + beta3 * g
            v = beta2 * state.v[i] + (1.0 - beta2) * g * g
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode:
                upd = upd + weight_decay * p
            else:
                upd = upd  # L2 mode folds decay into g pre-moment; keep
                # AdamW default as the reference's distributed LAMB does.
            # Stage 2: per-tensor norms across shard boundaries: per-
            # shard segment sums (ids computed on device, see
            # device_segment_ids) + one psum.  segment_sum keeps exact
            # per-segment accumulation (a cumsum range-difference would
            # lose small late tensors to fp32 cancellation); the
            # scatter's (index, update) pair temp is bounded by the
            # ZeRO shard size, 1/world of the group.
            nseg = len(meta.sizes) + 1
            w_sq = jax.lax.psum(
                jax.ops.segment_sum(p * p, segs, num_segments=nseg)[:-1],
                axis_name)
            u_sq = jax.lax.psum(
                jax.ops.segment_sum(upd * upd, segs,
                                    num_segments=nseg)[:-1],
                axis_name)
            w_norm = jnp.sqrt(w_sq)
            u_norm = jnp.sqrt(u_sq)
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / jnp.maximum(u_norm, 1e-12), 1.0)
            ratio = jnp.concatenate(
                [ratio, jnp.ones((1,), jnp.float32)])  # padding id
            delta_sh = -lr * ratio[segs] * upd
            full = jax.lax.all_gather(delta_sh, axis_name, tiled=True)
            deltas.append(full[:meta.padded])
            new_m.append(m)
            new_v.append(v)

        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.unpack_groups(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, DistributedFusedLAMBState(
            count, tuple(new_m), tuple(new_v))

    return optax.GradientTransformation(init, update)


DistributedFusedLAMB = distributed_fused_lamb
