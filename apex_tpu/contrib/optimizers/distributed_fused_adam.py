"""DistributedFusedAdam — ZeRO-sharded Adam over the data axis.

TPU-native equivalent of the reference's distributed optimizer family
(ref: apex/contrib/optimizers/distributed_fused_adam.py /_v2/_v3):
instead of the reference's hand-pipelined flat-buffer
``reduce_scatter`` + inter-node allreduce on dedicated process groups
with backward-hook overlap (ref: distributed_fused_lamb.py:590-612
``_pipeline_block_reductions``; same structure in the adam variants),
the JAX formulation is three collectives XLA schedules freely:

    grad shard   = psum_scatter(flat_grads) / world     (ZeRO reduce)
    state update = fused Adam on the 1/N shard          (sharded m, v)
    new params   = all_gather(delta shards)             (param sync)

Optimizer state (m, v) only ever exists shard-sized — the ZeRO memory
saving.  Must be called inside ``shard_map`` over ``axis_name``; init
must also run in that context (shard sizes depend on the axis size).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
from ..._compat import axis_index, axis_size
import jax.numpy as jnp
import optax

from ...mesh_plan import MeshPlan
from ...ops import fused_optim, multi_tensor
from ...optimizers.fused_adam import ScalarOrSchedule, _adam_jnp, _lr_at


class DistributedFusedAdamState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]   # 1/N shard per dtype group (fp32)
    v: Tuple[jnp.ndarray, ...]


def _shard_padded(meta: multi_tensor.FlatMeta, world: int) -> int:
    """Padded group length divisible by world * LANE-tile."""
    unit = world * multi_tensor._PAD_TO
    return -(-meta.padded // unit) * unit


def zero_adam_plan(world: int, num_groups: int = 1, *,
                   axis_name: str = "data") -> MeshPlan:
    """The ZeRO topology contract as data: ONE ``zero``-kind axis; the
    optimizer state (``m``/``v`` flat buffers) sharded 1/world over it
    — the memory saving that IS ZeRO, and exactly what a replicated-
    state regression silently destroys (rule APX701); params and the
    pre-reduce grads full per device; one psum_scatter (grad reduce)
    plus one all_gather (delta sync) per dtype group per step.

    Declaring the state spec here is what turned up the real finding
    this plan shipped with: the ZeRO bench driver carried the sharded
    state through its shard_map boundary as ``P()`` (replicated) —
    right on its 1-device bench mesh, silently wrong on any real one.
    The boundary specs now derive from this plan
    (``plan.partition_spec``)."""
    import jax

    # pre-vma jax routes _compat.axis_index through ONE extra
    # psum_scatter (the partition_id-free rank derivation); the budget
    # prices the implementation as it actually lowers on this stack —
    # a jax upgrade that drops the hop shows up as a reviewed
    # baseline diff, not a silent under-budget
    rank_hop = 0 if hasattr(jax, "shard_map") else 1
    return MeshPlan.build(
        axes=((axis_name, world, "zero"),),
        tensor_specs={
            # the sharded flat state buffers: global (padded,) arrays,
            # one 1/world slice per device (matched on NamedTuple field
            # names — state.m / state.v — however the entry spells its
            # argument paths)
            r"\.(m|v)\b": (axis_name,),
            # scalar step count: replicated
            r"\.count\b": (),
        },
        # psum_scatter traces as the reduce_scatter primitive — the
        # census speaks jaxpr
        collective_budget={"reduce_scatter": num_groups + rank_hop,
                           "all_gather": num_groups})


def distributed_fused_adam(
        learning_rate: ScalarOrSchedule = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        adam_w_mode: bool = True,
        bias_correction: bool = True,
        axis_name: str = "data",
        grad_average: bool = True,
        use_pallas: bool = None) -> optax.GradientTransformation:
    """Build the sharded transformation.  ``update`` receives *local*
    (unreduced) gradients — the reduce is fused into the scatter."""

    def init(params):
        world = axis_size(axis_name)
        metas = multi_tensor.compute_metas(params)
        shards = tuple(
            jnp.zeros((_shard_padded(m, world) // world,), jnp.float32)
            for m in metas)
        return DistributedFusedAdamState(
            count=jnp.zeros((), jnp.int32),
            m=shards, v=tuple(jnp.zeros_like(s) for s in shards))

    def update(grads, state, params=None):
        fused = use_pallas if use_pallas is not None \
            else jax.default_backend() == "tpu"
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        world = axis_size(axis_name)
        rank = axis_index(axis_name)
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        metas = multi_tensor.compute_metas(params)
        gbufs = multi_tensor.pack(grads, metas)
        pbufs = multi_tensor.pack(params, metas)
        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            padded = _shard_padded(meta, world)
            shard = padded // world
            g = gbufs[i].astype(jnp.float32)
            if padded != meta.padded:
                g = jnp.pad(g, (0, padded - meta.padded))
            # ZeRO reduce: each device keeps the summed 1/N shard
            # (ref: _pipeline_block_reductions reduce_scatter stage).
            g_shard = jax.lax.psum_scatter(g, axis_name,
                                           scatter_dimension=0, tiled=True)
            if grad_average:
                g_shard = g_shard / world
            p = pbufs[i]
            if padded != meta.padded:
                p = jnp.pad(p, (0, padded - meta.padded))
            p_shard = jax.lax.dynamic_slice_in_dim(p, rank * shard, shard)
            if fused:
                d, m, v = fused_optim.adam_update(
                    g_shard, p_shard, state.m[i], state.v[i],
                    lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    weight_decay=weight_decay,
                    bias_correction1=bc1, bias_correction2=bc2,
                    adam_w_mode=adam_w_mode)
            else:
                d, m, v = _adam_jnp(g_shard, p_shard, state.m[i],
                                    state.v[i], lr, beta1, beta2, eps,
                                    weight_decay, bc1, bc2, adam_w_mode)
            # Param sync: gather delta shards back to the full buffer
            # (ref: param all_gather after step,
            # distributed_fused_adam.py _pipeline_step).
            full = jax.lax.all_gather(d.astype(jnp.float32), axis_name,
                                      tiled=True)
            deltas.append(full[:meta.padded])
            new_m.append(m)
            new_v.append(v)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.unpack_groups(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, DistributedFusedAdamState(
            count, tuple(new_m), tuple(new_v))

    return optax.GradientTransformation(init, update)


DistributedFusedAdam = distributed_fused_adam
