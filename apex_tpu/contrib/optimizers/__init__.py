"""ZeRO-style distributed fused optimizers (ref: apex/contrib/optimizers)."""
from .distributed_fused_adam import (DistributedFusedAdam,
                                     distributed_fused_adam,
                                     zero_adam_plan)
from .distributed_fused_lamb import (DistributedFusedLAMB,
                                     distributed_fused_lamb)

__all__ = ["distributed_fused_adam", "DistributedFusedAdam",
           "distributed_fused_lamb", "DistributedFusedLAMB",
           "zero_adam_plan"]
