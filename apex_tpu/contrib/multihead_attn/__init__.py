"""Fused multi-head attention module family
(parity with ``apex/contrib/multihead_attn``).

The reference ships 8 CUDA extension variants (self/encdec × {plain,
bias, bias+additive-mask, norm_add}) plus a fused masked-softmax-dropout;
here the variants are module *options* over one Pallas-backed core
(flash attention / scaled-masked softmax), which is the TPU-idiomatic
shape of the same capability: options compose inside one jitted graph
instead of multiplying kernels.
"""
from .encdec_multihead_attn import EncdecMultiheadAttn
from .functional import attn_core, mask_softmax_dropout
from .self_multihead_attn import SelfMultiheadAttn

__all__ = [
    "SelfMultiheadAttn",
    "EncdecMultiheadAttn",
    "attn_core",
    "mask_softmax_dropout",
]
