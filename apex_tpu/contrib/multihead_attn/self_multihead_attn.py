"""SelfMultiheadAttn: fused self-attention module.

Parity surface for ``apex/contrib/multihead_attn/self_multihead_attn.py``
(:31-178): packed 3E in-projection (or ``separate_qkv_params``), optional
biases, byte key-padding mask / additive mask / time (causal) mask,
attention dropout, and the ``include_norm_add`` variant (pre-LayerNorm +
residual add with hidden dropout, the fast_self_multihead_attn_norm_add
fusion).  ``impl='fast'`` routes the core through the Pallas kernels
(flash attention / scaled-masked softmax — superseding the 8
fast_multihead_attn CUDA modules); ``impl='default'`` is the plain XLA
path (the reference's torch fallback), used for parity testing.

Layout: inputs are (time, batch, embed) exactly as the reference
(``Input shape: Time x Batch x Channel``, ref :124-132).
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...normalization import FusedLayerNorm
from .functional import attn_core_qkv


class SelfMultiheadAttn(nn.Module):
    """ref: apex/contrib/multihead_attn/self_multihead_attn.py:31."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"              # 'fast' (Pallas) | 'default' (XLA)
    separate_qkv_params: bool = False
    mask_additive: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        assert self.embed_dim % self.num_heads == 0, \
            "embed_dim must be divisible by num_heads"
        assert self.impl in ("fast", "default"), \
            f"Unsupported impl: {self.impl} !"
        if self.mask_additive:
            assert not self.include_norm_add, \
                "additive mask not supported with layer norm"
        e = self.embed_dim
        # in_proj_weight is [3E, E] init'd like an [E, E] matrix: xavier
        # with gain sqrt(2) (ref :100-108 and the comment there).
        if self.separate_qkv_params:
            init = nn.initializers.xavier_uniform()
            self.q_weight = self.param("q_weight", init, (e, e), self.dtype)
            self.k_weight = self.param("k_weight", init, (e, e), self.dtype)
            self.v_weight = self.param("v_weight", init, (e, e), self.dtype)
        else:
            init = nn.initializers.variance_scaling(
                2.0, "fan_avg", "uniform")  # xavier_uniform gain sqrt(2)
            self.in_proj_weight = self.param(
                "in_proj_weight", init, (3 * e, e), self.dtype)
        self.out_proj_weight = self.param(
            "out_proj_weight", nn.initializers.xavier_uniform(),
            (e, e), self.dtype)
        if self.bias:
            zeros = nn.initializers.zeros
            if self.separate_qkv_params:
                self.q_bias = self.param("q_bias", zeros, (e,), self.dtype)
                self.k_bias = self.param("k_bias", zeros, (e,), self.dtype)
                self.v_bias = self.param("v_bias", zeros, (e,), self.dtype)
            else:
                self.in_proj_bias = self.param(
                    "in_proj_bias", zeros, (3 * e,), self.dtype)
            self.out_proj_bias = self.param(
                "out_proj_bias", zeros, (e,), self.dtype)
        if self.include_norm_add:
            self.lyr_nrm = FusedLayerNorm(normalized_shape=self.embed_dim)

    def _qkv_weights(self):
        """Interleave per-head q/k/v blocks exactly as the reference
        packs separate params into the fused layout (ref :133-141)."""
        e, h = self.embed_dim, self.num_heads
        d = e // h
        if not self.separate_qkv_params:
            w = self.in_proj_weight
            b = self.in_proj_bias if self.bias else None
            return w, b
        w = jnp.concatenate([
            self.q_weight.reshape(h, 1, d, e),
            self.k_weight.reshape(h, 1, d, e),
            self.v_weight.reshape(h, 1, d, e),
        ], axis=1).reshape(3 * e, e)
        b = None
        if self.bias:
            b = jnp.concatenate([
                self.q_bias.reshape(h, 1, d),
                self.k_bias.reshape(h, 1, d),
                self.v_bias.reshape(h, 1, d),
            ], axis=1).reshape(3 * e)
        return w, b

    def __call__(self, query, key=None, value=None,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 need_weights: bool = False,
                 attn_mask: Optional[jnp.ndarray] = None,
                 is_training: bool = True):
        """ref :124-178.  ``key``/``value`` accepted for signature parity
        (self-attention ignores them); ``key_padding_mask`` is
        (batch, src_len) with 1 = padding (byte-mask convention) or an
        additive float mask when ``mask_additive``; ``attn_mask`` marks
        the causal time mask.  Returns ``(output, None)``.
        """
        del key, value, need_weights
        sq, b, e = query.shape
        h = self.num_heads
        d = e // h
        scaling = d ** -0.5

        assert not (key_padding_mask is not None and attn_mask is not None), \
            "attn_mask and key_padding_mask should not be both defined!"
        if attn_mask is not None:
            assert not self.mask_additive, \
                "additive mask not supported for time mask"

        residual = query
        x = self.lyr_nrm(query) if self.include_norm_add else query

        w, bias_ = self._qkv_weights()
        qkv = x @ w.T  # (sq, b, 3e)
        if bias_ is not None:
            qkv = qkv + bias_
        # reference layout: [sq, b, h, 3, d] — q/k/v interleaved per head
        # (ref: self_attn_func.py:31-38); attn_core_qkv consumes it
        # directly (flash-eligible cases take the E-layout kernel with
        # one batch-time relayout per side instead of four per-tensor
        # head transposes)
        qkv = qkv.reshape(sq, b, h, 3, d)

        mask = None
        use_time_mask = False
        if key_padding_mask is not None:
            # (b, sk) -> (b, 1, 1, sk)
            mask = key_padding_mask[:, None, None, :]
        elif attn_mask is not None:
            mask = attn_mask
            use_time_mask = True

        rng = None
        if self.dropout > 0.0 and is_training:
            rng = self.make_rng("dropout")

        ctx = attn_core_qkv(qkv, scaling, mask=mask,
                            mask_additive=self.mask_additive,
                            use_time_mask=use_time_mask,
                            dropout_prob=self.dropout, rng=rng,
                            is_training=is_training,
                            use_fast=self.impl == "fast")

        out = ctx @ self.out_proj_weight.T
        if self.bias:
            out = out + self.out_proj_bias

        if self.include_norm_add:
            # hidden dropout + residual add (ref jit_dropout_add :19-23)
            if self.dropout > 0.0 and is_training:
                keep = jax.random.bernoulli(
                    self.make_rng("dropout"), 1.0 - self.dropout,
                    out.shape)
                out = jnp.where(keep, out / (1.0 - self.dropout), 0.0)
            out = residual + out
        return out, None
