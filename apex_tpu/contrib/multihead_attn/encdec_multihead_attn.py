"""EncdecMultiheadAttn: fused encoder-decoder cross-attention module.

Parity surface for ``apex/contrib/multihead_attn/encdec_multihead_attn.py``
(:31-160): separate Q projection (from the decoder query) and packed 2E
KV projection (from the encoder output), byte key-padding / time masks,
attention dropout, and the ``include_norm_add`` pre-LN + residual
variant.  Layout (time, batch, embed) as the reference.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...normalization import FusedLayerNorm
from .functional import attn_core


class EncdecMultiheadAttn(nn.Module):
    """ref: apex/contrib/multihead_attn/encdec_multihead_attn.py:31."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        assert self.embed_dim % self.num_heads == 0, \
            "embed_dim must be divisible by num_heads"
        assert self.impl in ("fast", "default"), \
            f"Unsupported impl: {self.impl} !"
        e = self.embed_dim
        self.in_proj_weight_q = self.param(
            "in_proj_weight_q", nn.initializers.xavier_uniform(),
            (e, e), self.dtype)
        # [2E, E] init'd like [E, E]: xavier gain sqrt(1.5) (ref :81-86).
        self.in_proj_weight_kv = self.param(
            "in_proj_weight_kv",
            nn.initializers.variance_scaling(1.5, "fan_avg", "uniform"),
            (2 * e, e), self.dtype)
        self.out_proj_weight = self.param(
            "out_proj_weight", nn.initializers.xavier_uniform(),
            (e, e), self.dtype)
        if self.bias:
            zeros = nn.initializers.zeros
            self.in_proj_bias_q = self.param(
                "in_proj_bias_q", zeros, (e,), self.dtype)
            self.in_proj_bias_kv = self.param(
                "in_proj_bias_kv", zeros, (2 * e,), self.dtype)
            self.out_proj_bias = self.param(
                "out_proj_bias", zeros, (e,), self.dtype)
        if self.include_norm_add:
            self.lyr_nrm = FusedLayerNorm(normalized_shape=self.embed_dim)

    def __call__(self, query, key, value=None,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 need_weights: bool = False,
                 attn_mask: Optional[jnp.ndarray] = None,
                 is_training: bool = True):
        """ref :98-160.  ``query`` (tq, b, e) from the decoder; ``key``
        (tk, b, e) from the encoder (``value`` must alias it, as in the
        fused reference).  Returns ``(output, None)``."""
        del need_weights
        assert value is None or value is key, \
            "encdec attention requires value is key (fused KV projection)"
        sq, b, e = query.shape
        sk = key.shape[0]
        h = self.num_heads
        d = e // h
        scaling = d ** -0.5

        assert not (key_padding_mask is not None and attn_mask is not None), \
            "attn_mask and key_padding_mask should not be both defined!"

        residual = query
        x_q = self.lyr_nrm(query) if self.include_norm_add else query

        q = x_q @ self.in_proj_weight_q.T
        kv = key @ self.in_proj_weight_kv.T
        if self.bias:
            q = q + self.in_proj_bias_q
            kv = kv + self.in_proj_bias_kv
        # reference packs kv per head as [sk, b, h, 2, d]
        # (ref: encdec_multihead_attn_func.py kv slicing)
        kv = kv.reshape(sk, b, h, 2, d)
        q = jnp.transpose(q.reshape(sq, b, h, d), (1, 2, 0, 3))
        k = jnp.transpose(kv[:, :, :, 0], (1, 2, 0, 3))
        v = jnp.transpose(kv[:, :, :, 1], (1, 2, 0, 3))

        mask = None
        use_time_mask = False
        if key_padding_mask is not None:
            mask = key_padding_mask[:, None, None, :]
        elif attn_mask is not None:
            mask = attn_mask
            use_time_mask = True

        rng = None
        if self.dropout > 0.0 and is_training:
            rng = self.make_rng("dropout")

        ctx = attn_core(q, k, v, scaling, mask=mask,
                        mask_additive=False,
                        use_time_mask=use_time_mask,
                        dropout_prob=self.dropout, rng=rng,
                        is_training=is_training,
                        use_fast=self.impl == "fast")

        ctx = jnp.transpose(ctx, (2, 0, 1, 3)).reshape(sq, b, e)
        out = ctx @ self.out_proj_weight.T
        if self.bias:
            out = out + self.out_proj_bias

        if self.include_norm_add:
            if self.dropout > 0.0 and is_training:
                keep = jax.random.bernoulli(
                    self.make_rng("dropout"), 1.0 - self.dropout,
                    out.shape)
                out = jnp.where(keep, out / (1.0 - self.dropout), 0.0)
            out = residual + out
        return out, None
