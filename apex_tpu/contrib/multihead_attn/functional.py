"""Functional attention cores for the MHA module family.

Parity surface for the reference's attention autograd Functions
(ref: apex/contrib/multihead_attn/self_multihead_attn_func.py:6-160,
fast_self_multihead_attn_func.py, mask_softmax_dropout_func.py:6-80).
The reference hand-schedules cuBLAS batched GEMMs + fused
softmax-dropout CUDA kernels; on TPU the same dataflow is expressed as
jnp einsums + the Pallas kernels (flash attention for the unmasked /
causal paths, scaled-masked softmax otherwise) and XLA fuses the rest.
Dropout uses explicit JAX PRNG keys instead of in-kernel philox states —
same semantics (independent mask per call), reproducible by key.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops.flash_attention import (dropout_seed_from_key,
                                    flash_attention,
                                    flash_attention_e,
                                    flash_e_supported)
from ...ops.scaled_softmax import (scaled_masked_softmax,
                                   scaled_upper_triang_masked_softmax)

NEG_INF = -10000.0  # the reference's masked-fill value


# id-keyed memo for the host-side causal check (eager callers pass the
# same mask object every step; avoid a device->host copy per call).  The
# mask object is kept in the value so its id cannot be recycled.
_CAUSAL_MEMO: dict = {}


def _is_causal_mask(mask, sq: int, sk: int) -> bool:
    """True iff ``mask`` is concretely the strict-upper-triangle boolean
    mask (True = masked).  Traced masks return False — the generic
    masked softmax then handles them (always correct; callers that know
    their mask is causal should pass ``mask_is_causal=True`` to
    :func:`attn_core` to keep the fast path under jit)."""
    key = (id(mask), sq, sk)
    hit = _CAUSAL_MEMO.get(key)
    if hit is not None and hit[0] is mask:
        return hit[1]
    try:
        import numpy as np

        m = np.asarray(mask).astype(bool)
    except (TypeError, ValueError):
        return False  # traced: no memo (tracer ids recycle fast);
        # TracerArrayConversionError is a TypeError subclass
    if m.shape[-2:] != (sq, sk):
        result = False
    else:
        want = ~np.tri(sq, sk, dtype=bool)
        result = bool((m.reshape((-1, sq, sk)) == want).all())
    _CAUSAL_MEMO[key] = (mask, result)
    if len(_CAUSAL_MEMO) > 1024:
        _CAUSAL_MEMO.clear()
    return result


def mask_softmax_dropout(inputs: jnp.ndarray,
                         pad_mask: Optional[jnp.ndarray] = None,
                         mask_additive: bool = False,
                         dropout_prob: float = 0.0,
                         rng: Optional[jax.Array] = None,
                         is_training: bool = True,
                         heads: Optional[int] = None) -> jnp.ndarray:
    """Fused softmax(+mask)+dropout over attention scores
    (ref: apex/contrib/multihead_attn/mask_softmax_dropout_func.py:6-80).

    ``inputs``: (..., sq, sk) scores.  ``pad_mask``: boolean with 1 =
    masked-out (reference byte-mask convention) broadcastable to inputs,
    or additive float mask when ``mask_additive``.  ``heads`` is accepted
    for signature parity (the reference needs it to unflatten; the array
    layout here already carries it).
    """
    x = inputs.astype(jnp.float32)
    if pad_mask is not None:
        if mask_additive:
            x = x + pad_mask.astype(jnp.float32)
        else:
            x = jnp.where(pad_mask.astype(bool), NEG_INF, x)
    probs = jax.nn.softmax(x, axis=-1).astype(inputs.dtype)
    if dropout_prob > 0.0 and is_training:
        if rng is None:
            raise ValueError("dropout requires an rng key")
        keep = jax.random.bernoulli(rng, 1.0 - dropout_prob, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_prob), 0.0)
    return probs


def _flash_route(mask, mask_additive, use_time_mask, mask_is_causal,
                 b, sq, sk):
    """Which flash lane a mask qualifies for: returns
    ``(causal, kpm)`` — ``causal`` when the time mask is concretely the
    strict upper triangle (or asserted via ``mask_is_causal``), ``kpm``
    the (b, sk) key-padding byte mask (1 = masked out) when the mask is
    key-padding-shaped.  The single source of truth for both the split
    (:func:`attn_core`) and packed (:func:`attn_core_qkv`) entries."""
    if mask_is_causal is None:
        mask_is_causal = _is_causal_mask(mask, sq, sk) \
            if mask is not None else False
    causal = (use_time_mask and mask is not None and not mask_additive
              and mask_is_causal)
    kpm = None
    if mask is not None and not mask_additive and not use_time_mask:
        # key-padding masks only: (b, sk), or the modules' pre-expanded
        # (b, 1, 1, sk).  A (sq, sk) attention mask stays on the
        # generic path (it is per-query, not per-key).
        if mask.ndim == 2 and mask.shape == (b, sk):
            kpm = mask
        elif mask.ndim == 4 and mask.shape == (b, 1, 1, sk):
            kpm = mask[:, 0, 0, :]
    return causal, kpm


def attn_core_qkv(qkv: jnp.ndarray,
                  scaling: float,
                  mask: Optional[jnp.ndarray] = None,
                  mask_additive: bool = False,
                  use_time_mask: bool = False,
                  dropout_prob: float = 0.0,
                  rng: Optional[jax.Array] = None,
                  is_training: bool = True,
                  use_fast: bool = True,
                  mask_is_causal: Optional[bool] = None) -> jnp.ndarray:
    """:func:`attn_core` over the module-native PACKED projection:
    ``qkv`` (sq, b, h, 3, d) — the reference's per-head-interleaved
    in-proj layout (ref: self_attn_func.py:31-38) — returning
    (sq, b, h*d).

    Flash-eligible dispatches (no mask / causal time mask / key-padding
    byte mask — attention dropout INCLUDED, applied in-kernel) ride
    ``flash_attention_e``: ONE (sq, b) <-> (b, sq) relayout on each
    side replaces the four per-tensor (b, h, s, d) transposes the split
    path pays (the E kernel consumes the interleaved lanes directly).
    Everything else splits and delegates to :func:`attn_core` unchanged.
    """
    sq, b, h, three, d = qkv.shape
    dropping = dropout_prob > 0.0 and is_training
    causal, kpm = _flash_route(mask, mask_additive, use_time_mask,
                               mask_is_causal, b, sq, sq)
    flash_ok = (use_fast
                and (mask is None or causal or kpm is not None)
                and flash_e_supported(sq, h, d))
    if flash_ok:
        qkv_e = qkv.reshape(sq, b, h * 3 * d).transpose(1, 0, 2) \
            .reshape(b, sq, h, 3 * d)
        kv_mask = None if kpm is None else ~kpm.astype(bool)
        drop = 0.0
        seed = None
        if dropping:
            # attention dropout stays in-kernel on the E route (the
            # reference's fused MHA kernels apply philox dropout
            # in-kernel, ref: apex/contrib/csrc/multihead_attn)
            if rng is None:
                raise ValueError("attention dropout requires an rng key")
            seed = dropout_seed_from_key(rng)
            drop = dropout_prob
        ctx = flash_attention_e(qkv_e, scale=scaling, causal=causal,
                                kv_mask=kv_mask, dropout_rate=drop,
                                dropout_seed=seed)     # (b, sq, h*d)
        return ctx.transpose(1, 0, 2)
    q = jnp.transpose(qkv[:, :, :, 0], (1, 2, 0, 3))
    k = jnp.transpose(qkv[:, :, :, 1], (1, 2, 0, 3))
    v = jnp.transpose(qkv[:, :, :, 2], (1, 2, 0, 3))
    ctx = attn_core(q, k, v, scaling, mask=mask,
                    mask_additive=mask_additive,
                    use_time_mask=use_time_mask,
                    dropout_prob=dropout_prob, rng=rng,
                    is_training=is_training, use_fast=use_fast,
                    mask_is_causal=mask_is_causal)
    return jnp.transpose(ctx, (2, 0, 1, 3)).reshape(sq, b, h * d)


def attn_core(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              scaling: float,
              mask: Optional[jnp.ndarray] = None,
              mask_additive: bool = False,
              use_time_mask: bool = False,
              dropout_prob: float = 0.0,
              rng: Optional[jax.Array] = None,
              is_training: bool = True,
              use_fast: bool = True,
              mask_is_causal: Optional[bool] = None) -> jnp.ndarray:
    """softmax(scale * q k^T [masked]) v with attention dropout.

    Shapes: (b, h, s, d).  Dispatch mirrors the reference's impl split:

    * no mask / causal time-mask, no attention dropout -> Pallas flash
      attention (the fast_*_attn kernels' successor; no seqlen cap);
    * causal with dropout -> Pallas causal softmax + explicit AV;
    * padding/additive masks -> scaled-masked softmax + explicit AV
      (ref: self_attn_func's matmul1 -> masked softmax -> dropout ->
      matmul2 pipeline).
    """
    dropping = dropout_prob > 0.0 and is_training
    sq, sk = q.shape[-2], k.shape[-2]
    # The reference honors the CONTENT of the time mask (masked_fill
    # with the caller's matrix, ref: self_attn_func.py); only a mask
    # that is literally the strict upper triangle may take the
    # specialized causal kernels.  Under jit the mask is a tracer and
    # the content check cannot run — pass ``mask_is_causal=True`` to
    # assert causality and keep the flash path.
    causal, kpm = _flash_route(mask, mask_additive, use_time_mask,
                               mask_is_causal, q.shape[0], sq, sk)
    if use_fast and not dropping and (mask is None or causal):
        return flash_attention(q, k, v, scale=scaling,
                               causal=causal)
    if use_fast and not dropping and kpm is not None:
        # (1 = masked out, the reference's boolean convention) rides
        # the flash kernel's kv_mask lane — no [b, h, sq, sk] score
        # materialization.  Degenerate all-padding rows emit exact
        # zeros here vs the -10000-fill path's uniform mean(v); both
        # are garbage by construction, zeros are the safer garbage
        # (zero gradients).
        return flash_attention(q, k, v, scale=scaling,
                               kv_mask=~kpm.astype(bool))

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if causal:
        probs = scaled_upper_triang_masked_softmax(scores, scale=scaling)
    elif mask is not None and not mask_additive:
        # boolean mask, 1 = masked out; the Pallas kernel broadcasts
        # over heads itself, so normalize to (b, 1, sq, sk)
        b, _h, sq, sk = scores.shape
        m = mask.astype(bool)
        while m.ndim < 4:
            m = m[:, None] if m.ndim >= 2 and m.shape[0] == b \
                else m[None]
        m = jnp.broadcast_to(m, (b, 1, sq, sk))
        probs = scaled_masked_softmax(scores, m, scale=scaling)
    else:
        x = scores.astype(jnp.float32) * scaling
        if mask is not None:  # additive
            x = x + mask.astype(jnp.float32)
        probs = jax.nn.softmax(x, axis=-1).astype(scores.dtype)
    if dropping:
        if rng is None:
            raise ValueError("attention dropout requires an rng key")
        keep = jax.random.bernoulli(rng, 1.0 - dropout_prob, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_prob), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
