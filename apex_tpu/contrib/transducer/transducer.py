"""RNN-T transducer joint and loss.

Parity surface for ``apex/contrib/transducer/transducer.py:1-195``
(+ ``transducer_joint_kernel.cu`` 973 LoC, ``transducer_loss_kernel.cu``
767 LoC).  "Sequence Transduction with Recurrent Neural Networks"
(Graves 2012) semantics:

* **Joint**: ``out[b,t,u,:] = f[b,t,:] + g[b,u,:]`` with optional fused
  ReLU and dropout (the reference's opt=1 tiled kernel).  On TPU the
  broadcast-add + activation is one XLA fusion; no kernel needed.
* **Loss**: -log P(label | x) via the alpha lattice recursion
  ``alpha[t,u] = logaddexp(alpha[t-1,u] + blank(t-1,u),
  alpha[t,u-1] + y(t,u-1))``.  The reference walks the (T,U) lattice
  with warp-synchronous CUDA kernels; here the same wavefront order is a
  ``lax.scan`` over anti-diagonals (T+U-1 steps, each a vectorized
  length-U update) — the natural TPU mapping.  The backward pass is JAX
  autodiff through the scan (the reference hand-writes a beta-lattice
  kernel; ``fuse_softmax_backward`` is accepted for parity — XLA fuses
  the log-softmax backward on its own).

Packed (ragged) layouts (ref: transducer.py:51-63 joint ``pack_output``,
:99-116 loss ``packed_input``): the reference's CUDA kernels consume the
ragged buffer natively; under XLA's static-shape model the packed buffer
is a STATIC-length (packed_batch, ...) array and conversion is gather /
scatter index arithmetic (:func:`pack_joint_output` /
:func:`unpack_loss_input`).  ``TransducerJoint(pack_output=True)`` and
``TransducerLoss(packed_input=True)`` accept the reference's packed
tensors and ``batch_offset`` convention (cumsum of per-batch row
counts, t-major rows within a batch) — capability parity; the compute
itself runs the padded wavefront.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG = -1e30  # -inf stand-in that stays finite under autodiff


def transducer_joint(f: jnp.ndarray, g: jnp.ndarray,
                     f_len: Optional[jnp.ndarray] = None,
                     g_len: Optional[jnp.ndarray] = None,
                     relu: bool = False,
                     dropout_prob: float = 0.0,
                     rng: Optional[jax.Array] = None,
                     is_training: bool = True) -> jnp.ndarray:
    """Joint: (B,T,H) + (B,U,H) -> (B,T,U,H)
    (ref: transducer.py:43-66, TransducerJointFunc :158-193).

    ``f_len``/``g_len`` zero out padding positions (the packed layout's
    don't-care removal, expressed as masking)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_prob > 0.0 and is_training:
        if rng is None:
            raise ValueError("dropout requires an rng key")
        keep = jax.random.bernoulli(rng, 1.0 - dropout_prob, out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_prob), 0.0)
    if f_len is not None:
        t_ok = jnp.arange(f.shape[1])[None, :] < f_len[:, None]
        out = out * t_ok[:, :, None, None]
    if g_len is not None:
        u_ok = jnp.arange(g.shape[1])[None, :] <= g_len[:, None]
        out = out * u_ok[:, None, :, None]
    return out


def transducer_loss(x: jnp.ndarray, label: jnp.ndarray,
                    f_len: jnp.ndarray, y_len: jnp.ndarray,
                    blank_idx: int = 0) -> jnp.ndarray:
    """RNN-T negative log likelihood per batch element
    (ref: transducer.py:89-156, TransducerLossFunc :127-156).

    ``x``: (B, T, U, V) joint logits (log-softmax applied internally,
    matching the reference's fused-softmax path); ``label``: (B, U-1)
    target symbols; ``f_len``: input time lengths; ``y_len``: label
    lengths (so the lattice ends at (f_len-1, y_len)).
    """
    B, T, U, V = x.shape
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)

    # blank and label emission lattices (B, T, U)
    pb = logp[..., blank_idx]
    lab = jnp.concatenate(
        [label, jnp.zeros((B, 1), label.dtype)], axis=1)  # pad u=U-1
    py = jnp.take_along_axis(logp, lab[:, None, :, None],
                             axis=-1)[..., 0]
    # emitting a label at u >= y_len is invalid
    u_valid = jnp.arange(U)[None, :] < y_len[:, None]     # (B, U)
    py = jnp.where(u_valid[:, None, :], py, _NEG)

    u_ar = jnp.arange(U)

    def diag_step(alpha_prev, d):
        # alpha_prev[b, u] = alpha[d-1-u, u]; compute alpha[d-u, u].
        t = d - u_ar                                       # (U,)
        idx = jnp.clip(d - 1 - u_ar, 0, T - 1)             # (U,)
        pb_diag = pb[:, idx, u_ar]                         # pb[b,d-1-u,u]
        py_diag = py[:, idx, u_ar]                         # py[b,d-1-u,u]

        # advance in time: alpha[t-1, u] + blank(t-1, u)
        term_t = jnp.where((t >= 1) & (t <= T - 1),
                           alpha_prev + pb_diag, _NEG)
        # advance in label: alpha[t, u-1] + y(t, u-1); note
        # py_diag[u-1] = py[b, d-u, u-1] = py[b, t, u-1]
        shifted = jnp.concatenate(
            [jnp.full((B, 1), _NEG), alpha_prev[:, :-1] + py_diag[:, :-1]],
            axis=1)
        term_u = jnp.where((u_ar >= 1) & (t >= 0) & (t <= T - 1),
                           shifted, _NEG)
        alpha_new = jnp.logaddexp(term_t, term_u)
        alpha_new = jnp.where((t >= 0) & (t <= T - 1), alpha_new, _NEG)
        return alpha_new, alpha_new

    alpha0 = jnp.full((B, U), _NEG).at[:, 0].set(0.0)
    _, alphas = jax.lax.scan(diag_step, alpha0,
                             jnp.arange(1, T + U - 1))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (D, B, U)

    # terminal: alpha[f_len-1, y_len] + blank(f_len-1, y_len)
    d_star = (f_len - 1) + y_len                             # (B,)
    a_T = alphas[d_star, jnp.arange(B), y_len]
    pb_T = pb[jnp.arange(B), f_len - 1, y_len]
    return -(a_T + pb_T)


def pack_joint_output(out: jnp.ndarray, f_len: jnp.ndarray,
                      g_len: jnp.ndarray, batch_offset: jnp.ndarray,
                      packed_batch: int) -> jnp.ndarray:
    """Padded joint output (B, T, U, H) -> the reference's packed
    layout (packed_batch, H) (ref: transducer.py:51-63): batch b's
    valid (t, u) pairs occupy rows [batch_offset[b-1], batch_offset[b])
    in t-major order (row = offset_b + t * g_len[b] + u), with
    ``batch_offset = cumsum(f_len * g_len)``.  ``packed_batch`` is the
    STATIC buffer length (>= batch_offset[-1]); tail rows are zero."""
    B, T, U, H = out.shape
    p = jnp.arange(packed_batch)
    b = jnp.clip(jnp.searchsorted(batch_offset, p, side="right"),
                 0, B - 1)
    start = jnp.where(b > 0, batch_offset[jnp.maximum(b - 1, 0)], 0)
    r = p - start
    g = jnp.maximum(g_len[b], 1)
    t = jnp.clip(r // g, 0, T - 1)
    u = jnp.clip(r % g, 0, U - 1)
    valid = p < batch_offset[B - 1]
    return jnp.where(valid[:, None], out[b, t, u], 0)


def unpack_loss_input(x_packed: jnp.ndarray, f_len: jnp.ndarray,
                      g_len: jnp.ndarray, batch_offset: jnp.ndarray,
                      max_f_len: int, U: int) -> jnp.ndarray:
    """The reference's packed loss input (N, V) -> padded (B, T, U, V)
    (ref: transducer.py:99-116; ``batch_offset = cumsum(f_len *
    (y_len + 1))``, t-major rows).  Invalid (padding) positions come
    back 0 — the wavefront only reads t < f_len, u <= y_len, which is
    exactly the packed region."""
    N, V = x_packed.shape
    B = f_len.shape[0]
    T = max_f_len
    start = jnp.concatenate([jnp.zeros((1,), batch_offset.dtype),
                             batch_offset[:-1]])
    t_ar = jnp.arange(T)[None, :, None]
    u_ar = jnp.arange(U)[None, None, :]
    idx = start[:, None, None] + t_ar * g_len[:, None, None] + u_ar
    valid = (t_ar < f_len[:, None, None]) \
        & (u_ar < g_len[:, None, None])
    vals = x_packed[jnp.clip(idx, 0, N - 1)]
    return jnp.where(valid[..., None], vals, 0.0)


class TransducerJoint:
    """Module wrapper (ref: transducer.py:5-66).  ``pack_output=True``
    emits the reference's packed (packed_batch, H) layout via
    :func:`pack_joint_output` (static-length buffer; the ragged CUDA
    kernel's role is played by gather index arithmetic)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, opt: int = 1,
                 fwd_tile_size: int = 4, dropout_prob: float = 0.0,
                 probe_mask: bool = False):
        del opt, fwd_tile_size, probe_mask  # GPU tiling knobs
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=0, rng=None, is_training=True):
        out = transducer_joint(
            f, g, f_len, g_len, relu=self.relu,
            dropout_prob=self.dropout_prob if self.dropout else 0.0,
            rng=rng, is_training=is_training)
        if self.pack_output:
            if batch_offset is None or packed_batch == 0:
                # the reference's exact contract (transducer.py:60-61)
                raise ValueError(
                    "Please specify batch_offset and packed_batch when "
                    "packing is enabled")
            if f_len is None or g_len is None:
                raise ValueError("pack_output requires f_len and g_len")
            return pack_joint_output(out, f_len, g_len, batch_offset,
                                     int(packed_batch))
        return out


class TransducerLoss:
    """Module wrapper (ref: transducer.py:68-126).  ``packed_input=True``
    accepts the reference's packed (N, V) logits + ``batch_offset`` +
    ``max_f_len`` and unpacks to the padded wavefront layout via
    :func:`unpack_loss_input`."""

    def __init__(self, fuse_softmax_backward: bool = True, opt: int = 1,
                 packed_input: bool = False):
        del fuse_softmax_backward, opt  # XLA fuses; level n/a
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0,
                 batch_offset=None, max_f_len=None, debug_list=None):
        if self.packed_input:
            if batch_offset is None or max_f_len is None:
                # the reference's exact contract (transducer.py:114-116)
                raise ValueError(
                    "Please specify batch_offset and max_f_len when "
                    "packing is enabled")
            U = label.shape[1] + 1
            x = unpack_loss_input(x, f_len, y_len + 1, batch_offset,
                                  int(max_f_len), U)
        if debug_list is not None:
            # parity hook: expose the alpha lattice for debugging
            debug_list.append(_alphas_for_debug(x, label, f_len, y_len,
                                                blank_idx))
        return transducer_loss(x, label, f_len, y_len, blank_idx)


def _alphas_for_debug(x, label, f_len, y_len, blank_idx):
    """Materialize the (T, U) alpha lattice per batch (diagonal layout
    unfolded), mirroring the reference's debug_list=[alpha, beta]."""
    B, T, U, _ = x.shape
    # recompute via the public path but capture diagonals
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    pb = logp[..., blank_idx]
    lab = jnp.concatenate([label, jnp.zeros((B, 1), label.dtype)], axis=1)
    py = jnp.take_along_axis(logp, lab[:, None, :, None], axis=-1)[..., 0]
    u_valid = jnp.arange(U)[None, :] < y_len[:, None]
    py = jnp.where(u_valid[:, None, :], py, _NEG)
    alpha = jnp.full((B, T, U), _NEG).at[:, 0, 0].set(0.0)
    for t in range(T):
        for u in range(U):
            if t == 0 and u == 0:
                continue
            a = alpha[:, t - 1, u] + pb[:, t - 1, u] if t > 0 \
                else jnp.full((B,), _NEG)
            b = alpha[:, t, u - 1] + py[:, t, u - 1] if u > 0 \
                else jnp.full((B,), _NEG)
            alpha = alpha.at[:, t, u].set(jnp.logaddexp(a, b))
    return alpha
