"""RNN-T transducer (parity with ``apex/contrib/transducer``)."""
from .transducer import (
    TransducerJoint,
    TransducerLoss,
    pack_joint_output,
    transducer_joint,
    transducer_loss,
    unpack_loss_input,
)

__all__ = [
    "TransducerJoint",
    "TransducerLoss",
    "pack_joint_output",
    "transducer_joint",
    "transducer_loss",
    "unpack_loss_input",
]
