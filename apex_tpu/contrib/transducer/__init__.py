"""RNN-T transducer (parity with ``apex/contrib/transducer``)."""
from .transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)

__all__ = [
    "TransducerJoint",
    "TransducerLoss",
    "transducer_joint",
    "transducer_loss",
]
