"""Fused softmax cross-entropy with label smoothing.

TPU-native equivalent of ``xentropy_cuda``
(ref: apex/contrib/xentropy/softmax_xentropy.py:1-28,
apex/contrib/csrc/xentropy/xentropy_kernel.cu).  The memory win the
reference's kernel provides — never materializing the [tokens, vocab]
softmax in the forward — is achieved with a custom VJP: forward keeps
only the per-row logsumexp; backward recomputes the softmax from logits
on the fly, where XLA fuses it into the gradient expression.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits: jnp.ndarray,
                               labels: jnp.ndarray,
                               smoothing: float = 0.0,
                               half_to_float: bool = False,
                               padding_idx: int | None = None) -> jnp.ndarray:
    """Per-example CE loss over (tokens, vocab) logits with label smoothing.

    Rows whose label equals ``padding_idx`` contribute zero loss and zero
    gradient (ref: SoftmaxCrossEntropyLoss,
    apex/contrib/xentropy/softmax_xentropy.py:9 ``losses.masked_fill_``
    and :23 ``grad_loss.masked_fill_``).  ``None`` disables the mask.
    """
    return _xent_fwd(logits, labels, smoothing, half_to_float,
                     padding_idx)[0]


def _xent_fwd(logits, labels, smoothing, half_to_float, padding_idx):
    x = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(x, axis=-1)
    nll = lse - jnp.take_along_axis(
        x, labels[..., None], axis=-1).squeeze(-1)
    if smoothing > 0.0:
        # (1-eps)*nll + eps*mean_j(lse - x_j)
        # (ref: xentropy_kernel.cu label-smoothing path).
        smooth = lse - jnp.mean(x, axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * smooth
    else:
        loss = nll
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss, (logits, labels, lse)


def _xent_bwd(smoothing, half_to_float, padding_idx, res, dloss):
    logits, labels, lse = res
    x = logits.astype(jnp.float32)
    probs = jnp.exp(x - lse[..., None])
    vocab = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, vocab, dtype=jnp.float32)
    target = (1.0 - smoothing) * onehot + smoothing / vocab
    dloss = dloss.astype(jnp.float32)
    if padding_idx is not None:
        dloss = jnp.where(labels == padding_idx, 0.0, dloss)
    dx = (probs - target) * dloss[..., None]
    return dx.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class-style parity shim (ref: softmax_xentropy.py:6)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          half_to_float, padding_idx)
