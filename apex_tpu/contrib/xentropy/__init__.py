"""Fused softmax cross-entropy with label smoothing.

TPU-native equivalent of ``xentropy_cuda``
(ref: apex/contrib/xentropy/softmax_xentropy.py:1-28,
apex/contrib/csrc/xentropy/xentropy_kernel.cu).  The memory win the
reference's kernel provides — never materializing the [tokens, vocab]
softmax in the forward — is achieved with a custom VJP: forward keeps
only the per-row logsumexp; backward recomputes the softmax from logits
on the fly, where XLA fuses it into the gradient expression.
"""
from __future__ import annotations

__all__ = ["SoftmaxCrossEntropyLoss", "linear_cross_entropy_loss",
           "softmax_cross_entropy_loss"]

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits: jnp.ndarray,
                               labels: jnp.ndarray,
                               smoothing: float = 0.0,
                               half_to_float: bool = False,
                               padding_idx: int | None = None) -> jnp.ndarray:
    """Per-example CE loss over (tokens, vocab) logits with label smoothing.

    Rows whose label equals ``padding_idx`` contribute zero loss and zero
    gradient (ref: SoftmaxCrossEntropyLoss,
    apex/contrib/xentropy/softmax_xentropy.py:9 ``losses.masked_fill_``
    and :23 ``grad_loss.masked_fill_``).  ``None`` disables the mask.
    """
    return _xent_fwd(logits, labels, smoothing, half_to_float,
                     padding_idx)[0]


def _xent_fwd(logits, labels, smoothing, half_to_float, padding_idx):
    # Keep each fp32 view of the logits SINGLE-consumer so XLA fuses
    # the upcast into the reduction instead of materializing an fp32
    # copy of the whole (tokens, vocab) array (measured 2.1 ms/step of
    # pure convert+write at GPT-345M's 50k vocab).  jax's logsumexp
    # feeds the SAME fp32 view to both the max and the exp-sum, so the
    # convert materializes; computing the row max in the INPUT dtype
    # (exact — the max of bf16 values IS their bf16 max) leaves one
    # fp32 consumer: the exp-sum reduction.  The label logit is
    # gathered from the low-precision logits (tokens-sized, exact in
    # fp32 after the cast of just those elements).
    m = jnp.max(logits, axis=-1).astype(jnp.float32)
    lse = m + jnp.log(jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m[..., None]), axis=-1))
    x_label = jnp.take_along_axis(
        logits, labels[..., None], axis=-1).squeeze(-1)
    nll = lse - x_label.astype(jnp.float32)
    if smoothing > 0.0:
        # (1-eps)*nll + eps*mean_j(lse - x_j)
        # (ref: xentropy_kernel.cu label-smoothing path).
        smooth = lse - jnp.mean(logits.astype(jnp.float32), axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * smooth
    else:
        loss = nll
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss, (logits, labels, lse)


def _xent_bwd(smoothing, half_to_float, padding_idx, res, dloss):
    logits, labels, lse = res
    vocab = logits.shape[-1]
    dloss = dloss.astype(jnp.float32)
    if padding_idx is not None:
        dloss = jnp.where(labels == padding_idx, 0.0, dloss)
    # One fused elementwise pass: probs (exp of the inline-upcast
    # logits), the iota-compare one-hot, and the dloss scaling all
    # land in a single bf16-out kernel — no fp32 (tokens, vocab)
    # temporary (jax.nn.one_hot would materialize one).
    onehot = (jax.lax.broadcasted_iota(labels.dtype, logits.shape,
                                       logits.ndim - 1)
              == labels[..., None])
    target = jnp.where(onehot, 1.0 - smoothing + smoothing / vocab,
                       smoothing / vocab)
    probs = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    dx = (probs - target) * dloss[..., None]
    return dx.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class-style parity shim (ref: softmax_xentropy.py:6)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          half_to_float, padding_idx)


def linear_cross_entropy_loss(hidden, kernel, labels, smoothing=0.0,
                              padding_idx=None, chunks=8):
    """Mean CE of ``softmax(hidden @ kernel.T)`` vs ``labels`` without
    ever materializing the full (tokens, vocab) logits.

    The LM-head logits of a 50k-vocab model are the largest activation
    in the train step (GPT-345M batch 8: 2.5 GB of fp32+bf16 — the
    batch-16 OOM in BENCH notes).  Row-chunked: each chunk's logits are
    built, reduced to per-token losses, and rematerialized in the
    backward (``jax.checkpoint``), so peak logits memory drops by
    ``chunks``x at the cost of one extra chunk matmul each way.

    ``hidden`` (tokens, h); ``kernel`` (vocab, h) — the tied embedding
    table layout (``VocabParallelEmbedding.attend``); ``labels``
    (tokens,).  Returns the scalar mean loss over non-padding tokens.
    When ``chunks`` does not divide the token count, the largest
    divisor <= chunks is used instead (never a silent dense fallback —
    the caller asked for bounded logits memory).
    """
    t = hidden.shape[0]
    chunks = max(1, min(int(chunks), t))
    while t % chunks:
        chunks -= 1

    if chunks <= 1:
        total = jnp.sum(softmax_cross_entropy_loss(
            hidden @ kernel.T.astype(hidden.dtype), labels, smoothing,
            True, padding_idx))
    else:
        hs = hidden.reshape(chunks, t // chunks, hidden.shape[1])
        ls = labels.reshape(chunks, t // chunks)

        @jax.checkpoint
        def chunk_sum(h, l):
            logits = h @ kernel.T.astype(h.dtype)
            return jnp.sum(softmax_cross_entropy_loss(
                logits, l, smoothing, True, padding_idx))

        def body(acc, hl):
            return acc + chunk_sum(*hl), None

        total, _ = jax.lax.scan(body, jnp.float32(0.0), (hs, ls))

    if padding_idx is None:
        return total / t
    return total / jnp.maximum(jnp.sum(labels != padding_idx), 1)
