"""Fused ResNet bottleneck block (parity with ``apex/contrib/bottleneck``)."""
from .bottleneck import Bottleneck, FrozenBatchNorm2d, SpatialBottleneck

__all__ = ["Bottleneck", "FrozenBatchNorm2d", "SpatialBottleneck"]
