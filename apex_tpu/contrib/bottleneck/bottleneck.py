"""ResNet bottleneck block with frozen BN (inference-fused form).

Parity surface for ``apex/contrib/bottleneck/bottleneck.py:10-217``
(``FrozenBatchNorm2d`` :10-50, ``Bottleneck`` :112-217 — the ResNet v1.5
block with stride on the 1x1, frozen BN, built on cudnn-frontend fused
conv graphs) and ``SpatialBottleneck`` :386-500 (the same block with the
spatial (H) dimension sharded across a GPU group, halo-exchanged by
NCCL).

TPU design: the conv+scale+bias+relu chains are left to XLA, which fuses
them the way the cudnn-frontend graph API does on GPU — the module's job
is the exact arithmetic (frozen BN folds into a per-channel scale/bias
affine).  SpatialBottleneck's halo exchange maps onto GSPMD: shard H on
a mesh axis and XLA inserts the halo collectives for the 3x3 conv
automatically, so the module is the same code with a sharding
annotation, not a hand-written ppermute pipeline.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ... import parallel_state


class FrozenBatchNorm2d(nn.Module):
    """BatchNorm with fixed (non-trainable, non-updating) statistics —
    a per-channel affine ``scale * x + bias`` with
    ``scale = weight * rsqrt(running_var + eps)`` folded at call time
    (ref: bottleneck.py:10-50, get_scale_bias :25-31)."""

    num_features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        c = self.num_features
        weight = self.variable("batch_stats", "weight",
                               lambda: jnp.ones((c,), jnp.float32))
        bias = self.variable("batch_stats", "bias",
                             lambda: jnp.zeros((c,), jnp.float32))
        mean = self.variable("batch_stats", "running_mean",
                             lambda: jnp.zeros((c,), jnp.float32))
        var = self.variable("batch_stats", "running_var",
                            lambda: jnp.ones((c,), jnp.float32))
        scale = weight.value * jax.lax.rsqrt(var.value + self.eps)
        shift = bias.value - mean.value * scale
        return (x.astype(jnp.float32) * scale + shift).astype(x.dtype)


def _conv(ch_out, kernel, stride=1, name=None):
    # kaiming_uniform(a=1) as the reference initializes conv weights
    # (ref: bottleneck.py:158-160): gain = sqrt(2/(1+a^2)) = 1, bound =
    # sqrt(3/fan_in) == variance_scaling(scale=1.0, fan_in, uniform).
    return nn.Conv(ch_out, (kernel, kernel), strides=(stride, stride),
                   padding="SAME" if kernel > 1 else "VALID",
                   use_bias=False,
                   kernel_init=nn.initializers.variance_scaling(
                       1.0, "fan_in", "uniform"),
                   name=name)


class Bottleneck(nn.Module):
    """ResNet v1.5 bottleneck: 1x1(stride)-3x3-1x1 with frozen BN and
    residual relu (ref: bottleneck.py:112-217; stride placement comment
    :113-119 — this fork puts stride on the FIRST 1x1).  NHWC layout
    (TPU-native; the reference's ``explicit_nhwc`` fast path is the only
    path here)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    groups: int = 1
    dilation: int = 1
    use_cudnn: bool = False      # GPU knob, ignored
    explicit_nhwc: bool = True   # NHWC is native on TPU

    @nn.compact
    def __call__(self, x):
        if self.groups != 1:
            raise RuntimeError("Only support groups == 1")
        if self.dilation != 1:
            raise RuntimeError("Only support dilation == 1")

        out = _conv(self.bottleneck_channels, 1, self.stride,
                    name="conv1")(x)
        out = FrozenBatchNorm2d(self.bottleneck_channels, name="bn1")(out)
        out = jax.nn.relu(out)
        out = _conv(self.bottleneck_channels, 3, 1, name="conv2")(out)
        out = FrozenBatchNorm2d(self.bottleneck_channels, name="bn2")(out)
        out = jax.nn.relu(out)
        out = _conv(self.out_channels, 1, 1, name="conv3")(out)
        out = FrozenBatchNorm2d(self.out_channels, name="bn3")(out)

        if self.stride != 1 or self.in_channels != self.out_channels:
            identity = _conv(self.out_channels, 1, self.stride,
                             name="downsample_conv")(x)
            identity = FrozenBatchNorm2d(self.out_channels,
                                         name="downsample_bn")(identity)
        else:
            identity = x
        return jax.nn.relu(out + identity)


class SpatialBottleneck(Bottleneck):
    """Bottleneck with the H dimension sharded over a mesh axis
    (ref: bottleneck.py:386-500 — spatial_group_size GPUs exchange 3x3
    halos by NCCL p2p).  Under GSPMD the same computation is the parent
    block with a sharding constraint on H; XLA inserts the halo
    exchanges for the 3x3 conv.  ``spatial_axis`` names the mesh axis
    (None = unsharded, identical to :class:`Bottleneck`)."""

    spatial_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        if self.spatial_axis is not None:
            from jax.sharding import PartitionSpec as P

            mesh = parallel_state.get_mesh()
            x = jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(
                    mesh, P(None, self.spatial_axis, None, None)))
        return super().__call__(x)
