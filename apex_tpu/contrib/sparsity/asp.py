"""ASP — automatic structured (2:4) sparsity workflow.

Parity surface for ``apex/contrib/sparsity/asp.py:21-217``.  The
reference mutates the model in place (mask buffers on modules) and
monkey-patches ``optimizer.step`` to mask grads before and weights after
each step.  The functional equivalent: a :class:`SparsityState` pytree of
masks, :func:`wrap_optimizer` producing an optax transformation that
masks updates (so pruned weights, once zeroed, stay exactly zero through
any inner optimizer — same invariant as the reference's double masking),
and explicit :meth:`compute_sparse_masks` / :meth:`restore_pruned_weights`
workflow calls.  Checkpoint continuity matches the reference: masked
params carry literal zeros, and masks serialize via ``state_dict``
(the contrib checkpoint-continuity tests' contract,
ref: apex/contrib/sparsity/test/checkpointing_test_part1.py).

A classmethod facade mirrors the reference's global-singleton API
(``ASP.init_model_for_pruning`` / ``init_optimizer_for_pruning`` /
``compute_sparse_masks`` / ...) for drop-in-shaped migration.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from .sparse_masklib import create_mask


class SparsityState(NamedTuple):
    """Masks pytree: a mask array for sparse leaves, ``None`` for dense
    leaves.  ``enabled`` mirrors the reference's 'sparsity off by
    default until compute_sparse_masks' contract."""

    masks: Any
    enabled: bool = False


def default_whitelist(path, leaf) -> bool:
    """Eligible leaves: floating, rank >= 2, pattern-divisible columns —
    the functional analogue of the reference's
    Linear/Conv module-type whitelist (ref: asp.py:31,95-125 checks
    weights of whitelisted module classes with dims divisible by 4)."""
    arr = jnp.asarray(leaf)
    if not jnp.issubdtype(arr.dtype, jnp.floating) or arr.ndim < 2:
        return False
    return arr.shape[-1] % 4 == 0 and arr.shape[-2] % 4 == 0


def _path_name(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                    for k in path)


class ASPOptimizer:
    """Functional ASP session bound to one parameter tree."""

    def __init__(self, mask_calculator="m4n2_1d",
                 whitelist: Callable = default_whitelist,
                 allowed_layer_names: Optional[list] = None,
                 disallowed_layer_names: list = (),
                 verbosity: int = 0):
        if isinstance(mask_calculator, str):
            pattern = mask_calculator

            def calc(p):
                return create_mask(p, pattern)

            self.calculate_mask = calc
        else:
            self.calculate_mask = mask_calculator
        self.whitelist = whitelist
        self.allowed = allowed_layer_names
        self.disallowed = tuple(disallowed_layer_names)
        self.verbosity = verbosity

    def _eligible(self, path, leaf) -> bool:
        name = _path_name(path)
        if self.allowed is not None and not any(a in name
                                                for a in self.allowed):
            return False
        if any(d in name for d in self.disallowed):
            return False
        return self.whitelist(path, leaf)

    # -- workflow (ref: asp.py docstring recipe :36-50) ---------------------

    def init(self, params: Any) -> SparsityState:
        """Augment with all-ones masks — sparsity off until
        :meth:`compute_sparse_masks` (ref: asp.py:29-125)."""
        masks = jax.tree_util.tree_map_with_path(
            lambda path, p: jnp.ones_like(p) if self._eligible(path, p)
            else None, params)
        return SparsityState(masks=masks, enabled=False)

    def compute_sparse_masks(self, params: Any, state: SparsityState):
        """Search masks on current weights, zero pruned weights.

        Returns ``(masked_params, new_state)``
        (ref: asp.py:155-174; recompute is always possible here — the
        dense values live in ``params``/optimizer masters, not a
        side buffer, so ``allow_recompute_mask`` is implicitly True).
        """
        def mk(p, m):
            return None if m is None else self.calculate_mask(p)

        masks = jax.tree_util.tree_map(
            mk, params, state.masks, is_leaf=lambda x: x is None)
        new_params = self.apply_masks(params, masks)
        if self.verbosity >= 2:
            for path, m in jax.tree_util.tree_leaves_with_path(
                    masks, is_leaf=lambda x: x is None):
                if m is not None:
                    pct = 100.0 * float(jnp.sum(m)) / m.size
                    print(f"[ASP] Enabled {pct:.2f}% sparsity for "
                          f"{_path_name(path)} of size={tuple(m.shape)}")
        return new_params, SparsityState(masks=masks, enabled=True)

    def restore_pruned_weights(self, state: SparsityState
                               ) -> SparsityState:
        """Disable sparsity: masks back to ones (ref: asp.py:176-189).
        Pruned weight VALUES are zeros from the masking step — restoring
        dense values is the caller's job (reload a dense checkpoint), as
        the reference requires ``allow_recompute_mask`` for the same."""
        masks = jax.tree_util.tree_map(
            lambda m: None if m is None else jnp.ones_like(m),
            state.masks, is_leaf=lambda x: x is None)
        return SparsityState(masks=masks, enabled=False)

    @staticmethod
    def apply_masks(tree: Any, masks: Any) -> Any:
        """Elementwise mask; dense leaves (mask None) pass through."""
        return jax.tree_util.tree_map(
            lambda p, m: p if m is None else p * m.astype(p.dtype),
            tree, masks, is_leaf=lambda x: x is None)

    def is_sparsity_enabled(self, state: SparsityState) -> bool:
        """ref: asp.py:191-210 — consistent all-dense or all-50%."""
        total = sp100 = sp50 = 0
        for m in jax.tree_util.tree_leaves(state.masks):
            total += 1
            s = float(jnp.sum(m))
            if s == m.size:
                sp100 += 1
            elif 2 * s == m.size:
                sp50 += 1
        assert total in (sp100, sp50), "Inconsistent model sparsity"
        return total != sp100 if total else False

    def wrap_optimizer(self, tx: optax.GradientTransformation
                       ) -> optax.GradientTransformation:
        """The reference's patched ``optimizer.step``
        (ref: asp.py:127-153): grads masked before the inner update,
        updates masked after, so a weight pruned to zero can never move.
        State is ``(inner_state, SparsityState)``; thread the live
        SparsityState in by replacing it in the optax state after
        :meth:`compute_sparse_masks`."""
        def init(params):
            return (tx.init(params), self.init(params))

        def update(grads, state, params=None):
            inner_state, sp = state
            g = self.apply_masks(grads, sp.masks)
            updates, new_inner = tx.update(g, inner_state, params)
            updates = self.apply_masks(updates, sp.masks)
            return updates, (new_inner, sp)

        return optax.GradientTransformation(init, update)


# ---------------------------------------------------------------------------
# Reference-shaped classmethod facade (global singleton, ref: asp.py:21-27)
# ---------------------------------------------------------------------------

class ASP:
    __session: Optional[ASPOptimizer] = None
    __state: Optional[SparsityState] = None
    __params: Any = None

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator="m4n2_1d",
                               verbosity=0, whitelist=default_whitelist,
                               allowed_layer_names=None,
                               disallowed_layer_names=(),
                               allow_recompute_mask=False,
                               custom_layer_dict=None):
        """ref: asp.py:29-125.  ``params`` is the parameter pytree (the
        functional 'model'); returns the initial SparsityState."""
        assert cls.__session is None, "ASP has been initialized already."
        del allow_recompute_mask, custom_layer_dict  # implicit / n-a
        cls.__session = ASPOptimizer(
            mask_calculator, whitelist=whitelist,
            allowed_layer_names=allowed_layer_names,
            disallowed_layer_names=disallowed_layer_names,
            verbosity=verbosity)
        cls.__params = params
        cls.__state = cls.__session.init(params)
        return cls.__state

    @classmethod
    def init_optimizer_for_pruning(cls, tx: optax.GradientTransformation):
        """ref: asp.py:127-153 — returns the mask-aware transformation."""
        assert cls.__session is not None, \
            "Called ASP.init_optimizer_for_pruning before " \
            "ASP.init_model_for_pruning."
        return cls.__session.wrap_optimizer(tx)

    @classmethod
    def compute_sparse_masks(cls, params=None):
        """ref: asp.py:155-174 — returns (masked_params, state)."""
        params = cls.__params if params is None else params
        masked, cls.__state = cls.__session.compute_sparse_masks(
            params, cls.__state)
        cls.__params = masked
        return masked, cls.__state

    @classmethod
    def restore_pruned_weights(cls):
        cls.__state = cls.__session.restore_pruned_weights(cls.__state)
        return cls.__state

    @classmethod
    def is_sparsity_enabled(cls):
        return cls.__session.is_sparsity_enabled(cls.__state)

    @classmethod
    def prune_trained_model(cls, params, tx):
        """ref: asp.py:212-217 — one-call recipe."""
        cls.init_model_for_pruning(params, mask_calculator="m4n2_1d",
                                   verbosity=2)
        wrapped = cls.init_optimizer_for_pruning(tx)
        masked, state = cls.compute_sparse_masks()
        return masked, wrapped, state

    @classmethod
    def state_dict(cls) -> dict:
        """Mask checkpoint continuity
        (ref: contrib/sparsity/test/checkpointing_test_part1.py)."""
        return {"masks": cls.__state.masks,
                "enabled": cls.__state.enabled}

    @classmethod
    def load_state_dict(cls, d: dict):
        cls.__state = SparsityState(masks=d["masks"],
                                    enabled=d["enabled"])
        return cls.__state

    @classmethod
    def _reset(cls):
        """Testing hook (the reference singleton has no reset; tests
        re-import)."""
        cls.__session = None
        cls.__state = None
        cls.__params = None
