"""Structured m:n sparsity mask search.

Parity surface for ``apex/contrib/sparsity/sparse_masklib.py`` (fill :9,
reshape_1d :13, compute_valid_1d_patterns :25, mn_1d_best :37, m4n2_1d
:49, 2d greedy/best :67-143, create_mask :145-184).  The reference scores
every valid m:n pattern against |w| with a GEMM and picks the argmax per
group; that formulation is already TPU-shaped (one matmul + argmax), so
the port is direct jnp.  The 2-D variants (2:4 along rows AND columns of
each 4x4 tile, for transposed-weight DGRAD reuse) enumerate the valid
tile patterns once and score with one einsum.

TPU caveat (SURVEY §7): TPUs have no 2:4 sparse MMA; this library keeps
the *pruning workflow* capability (mask search, masked training,
checkpoint continuity) — the masks shape memory/regularization, not MXU
throughput.
"""
from __future__ import annotations

import itertools
from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np


def fill(x) -> float:
    """Density: fraction of nonzeros (ref :9-10)."""
    arr = np.asarray(x)
    return float(np.count_nonzero(arr)) / arr.size


def reshape_1d(matrix: jnp.ndarray, m: int
               ) -> Tuple[jnp.ndarray, Tuple[int, int]]:
    """(h, w) -> (h*w'/m, m), zero-padding w to a multiple of m
    (ref :13-21)."""
    h, w = matrix.shape
    pad = (-w) % m
    if pad:
        matrix = jnp.pad(matrix, ((0, 0), (0, pad)))
    shape = (h, w + pad)
    return matrix.reshape(-1, m), shape


_PATTERN_CACHE: Dict[Tuple[int, int], np.ndarray] = {}


def compute_valid_1d_patterns(m: int, n: int) -> np.ndarray:
    """All m-length binary vectors with exactly n ones (ref :25-34)."""
    key = (m, n)
    if key not in _PATTERN_CACHE:
        base = [1.0] * n + [0.0] * (m - n)
        pats = sorted(set(itertools.permutations(base)), reverse=True)
        _PATTERN_CACHE[key] = np.array(pats, np.float32)
    return _PATTERN_CACHE[key]


def mn_1d_best(matrix: jnp.ndarray, m: int, n: int) -> jnp.ndarray:
    """Best m:n pattern per m-group: argmax over pattern scores
    |w| @ P^T (ref :37-47 — the same one-GEMM-and-argmax form)."""
    patterns = jnp.asarray(compute_valid_1d_patterns(m, n))
    mat, shape = reshape_1d(matrix, m)
    scores = jnp.abs(mat.astype(jnp.float32)) @ patterns.T
    pmax = jnp.argmax(scores, axis=1)
    mask = patterns[pmax]
    h, w_padded = shape
    mask = mask.reshape(h, w_padded)[:, : matrix.shape[1]]
    return mask


def m4n2_1d(mat: jnp.ndarray, density: float = 0.5) -> jnp.ndarray:
    """2:4 along rows (ref :49-50; density arg is fixed by the pattern)."""
    return mn_1d_best(mat, 4, 2)


_PATTERN_2D_CACHE: Dict[Tuple[int, int], np.ndarray] = {}


def compute_valid_2d_patterns(m: int, n: int) -> np.ndarray:
    """All m x m binary tiles that are n:m along every row AND every
    column (ref :103-119)."""
    key = (m, n)
    if key not in _PATTERN_2D_CACHE:
        rows = compute_valid_1d_patterns(m, n)
        tiles = []
        for combo in itertools.product(range(len(rows)), repeat=m):
            tile = rows[list(combo)]
            if np.all(tile.sum(axis=0) == n):
                tiles.append(tile)
        _PATTERN_2D_CACHE[key] = np.stack(tiles).astype(np.float32)
    return _PATTERN_2D_CACHE[key]


def mn_2d_best(matrix: jnp.ndarray, m: int, n: int) -> jnp.ndarray:
    """Best n:m-in-both-directions tile per m x m block (ref :122-138)."""
    tiles = jnp.asarray(compute_valid_2d_patterns(m, n))  # (P, m, m)
    h, w = matrix.shape
    ph, pw = (-h) % m, (-w) % m
    mat = jnp.pad(matrix, ((0, ph), (0, pw))) if (ph or pw) else matrix
    H, W = mat.shape
    blocks = jnp.abs(
        mat.astype(jnp.float32).reshape(H // m, m, W // m, m)
        .transpose(0, 2, 1, 3))                           # (bh, bw, m, m)
    scores = jnp.einsum("xyij,pij->xyp", blocks, tiles)
    best = jnp.argmax(scores, axis=-1)                    # (bh, bw)
    mask = tiles[best]                                    # (bh, bw, m, m)
    mask = mask.transpose(0, 2, 1, 3).reshape(H, W)[:h, :w]
    return mask


def m4n2_2d_best(mat: jnp.ndarray, density: float = 0.5) -> jnp.ndarray:
    return mn_2d_best(mat, 4, 2)


# The reference's greedy 2d variant exists for speed on huge tensors; the
# vectorized best-search above is fast on TPU, so greedy aliases best
# (strictly better masks, ref :67-101 documents greedy as the fallback).
m4n2_2d_greedy = m4n2_2d_best

_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_best": m4n2_2d_best,
    "m4n2_2d_greedy": m4n2_2d_greedy,
}


def create_mask(tensor: jnp.ndarray, pattern: str = "m4n2_1d",
                density: float = 0.5) -> jnp.ndarray:
    """Mask a tensor of any rank by folding it to 2-D exactly as the
    reference does (ref :145-184): 1d -> (1, n); 2d as-is; 3d
    (b, i, o) -> (b*i, o); 4d convs (i, o, h, w) -> (h*w*i, o) via
    permute."""
    func = _PATTERNS.get(pattern)
    if func is None:
        raise ValueError(f"unknown sparsity pattern {pattern!r}")
    shape = tensor.shape
    dtype = tensor.dtype
    t = tensor.astype(jnp.float32)
    if len(shape) == 1:
        mask = func(t.reshape(1, shape[0]), density)
        return mask.reshape(shape).astype(dtype)
    if len(shape) == 2:
        return func(t, density).astype(dtype)
    if len(shape) == 3:
        mask = func(t.reshape(shape[0] * shape[1], shape[2]), density)
        return mask.reshape(shape).astype(dtype)
    if len(shape) == 4:
        perm = t.transpose(2, 3, 0, 1).reshape(
            shape[2] * shape[3] * shape[0], shape[1])
        mask = func(perm, density)
        mask = mask.reshape(shape[2], shape[3], shape[0],
                            shape[1]).transpose(2, 3, 0, 1)
        return mask.astype(dtype)
    raise ValueError(f"unsupported tensor rank {len(shape)}")
