"""Structured 2:4 sparsity (parity with ``apex/contrib/sparsity``)."""
from .asp import ASP, ASPOptimizer, SparsityState, default_whitelist
from .sparse_masklib import create_mask, fill, m4n2_1d, m4n2_2d_best

__all__ = [
    "ASP",
    "ASPOptimizer",
    "SparsityState",
    "default_whitelist",
    "create_mask",
    "fill",
    "m4n2_1d",
    "m4n2_2d_best",
]
