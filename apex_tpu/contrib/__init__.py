"""apex_tpu.contrib — contributed modules (ref: apex/contrib)."""
