"""Placeholder — populated by the build plan (SURVEY.md §7)."""
