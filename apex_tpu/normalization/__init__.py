"""apex_tpu.normalization — fused normalization layers."""
from .fused_layer_norm import (FusedLayerNorm, MixedFusedLayerNorm,
                               fused_layer_norm)

__all__ = ["FusedLayerNorm", "MixedFusedLayerNorm", "fused_layer_norm"]
