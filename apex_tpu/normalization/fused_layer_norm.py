"""FusedLayerNorm modules (TPU-native apex.normalization).

Parity with the reference's module API
(ref: apex/normalization/fused_layer_norm.py:15-218): ``FusedLayerNorm``
(elementwise_affine optional) and ``MixedFusedLayerNorm`` (low-precision
activations with fp32 gamma/beta, ref: fused_layer_norm.py:202).  Both
are thin flax wrappers over the Pallas kernel in
:mod:`apex_tpu.ops.layer_norm`; a pure-XLA fallback mirrors the
reference's torch fallback when the extension is unavailable.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from ..ops.layer_norm import layer_norm

Shape = Union[int, Sequence[int]]


def fused_layer_norm(x, weight=None, bias=None, eps: float = 1e-5):
    """Functional form (ref: fused_layer_norm_affine / fused_layer_norm
    autograd functions, apex/normalization/fused_layer_norm.py:15-96)."""
    return layer_norm(x, weight, bias, eps)


class FusedLayerNorm(nn.Module):
    """Layer norm over the trailing ``normalized_shape`` dimensions.

    Matches ``apex.normalization.FusedLayerNorm(normalized_shape, eps,
    elementwise_affine)``; parameters are created in ``param_dtype``
    (fp32 by default — set bf16 for a fully-low-precision layer).
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = self.normalized_shape
        if isinstance(shape, int):
            shape = (shape,)
        hidden = 1
        for s in shape:
            hidden *= s
        orig_shape = x.shape
        if tuple(orig_shape[-len(shape):]) != tuple(shape):
            raise ValueError(
                f"input trailing dims {orig_shape[-len(shape):]} != "
                f"normalized_shape {tuple(shape)}")
        x2 = x.reshape(*orig_shape[:-len(shape)], hidden)
        if self.elementwise_affine:
            gamma = self.param("weight", nn.initializers.ones,
                               (hidden,), self.param_dtype)
            beta = self.param("bias", nn.initializers.zeros,
                              (hidden,), self.param_dtype)
        else:
            gamma = beta = None
        y = layer_norm(x2, gamma, beta, self.eps)
        return y.reshape(orig_shape)


class MixedFusedLayerNorm(FusedLayerNorm):
    """bf16/fp16 activations with fp32 gamma/beta
    (ref: apex/normalization/fused_layer_norm.py:202 MixedFusedLayerNorm;
    kernel dispatch csrc/layer_norm_cuda.cpp:133-158)."""

    param_dtype: jnp.dtype = jnp.float32
