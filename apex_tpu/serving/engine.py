"""Continuous-batching serving engine.

The serving loop alternates two worlds on a fixed cadence:

* **between** jitted steps (host, this module): finished requests are
  evicted (their cache blocks return to the pool), queued requests are
  admitted (blocks allocated, prompt prefilled), and the next decode
  batch is assembled;
* **inside** jitted steps (:mod:`.model`): one prefill per admission,
  then one batched decode step per engine tick — cache donated through
  every call, greedy sampling in-graph, one int32 per row of output
  traffic.

Shapes are **bucketed**: the decode batch rounds up to a registered
batch bucket and the page span to a page bucket
(:class:`BucketLadder`, ``APEX_TPU_SERVE_BATCH_BUCKETS`` /
``APEX_TPU_SERVE_PAGE_BUCKETS``), prompt lengths to page-bucket
multiples of the block size — so the set of compiled programs is the
(small, finite) ladder product, every member AOT-compiled by
:meth:`ServingEngine.warmup` before traffic.  Steady-state serving
under :func:`apex_tpu.analysis.sanitize` therefore compiles exactly
once per bucket and never again — the same recompile budget the
training smoke enforces, now on the serving path (the tests and
tools/ci.sh step 11 prove it).

Admission control is **reservation-based**: a request is admitted only
when the pool can cover its whole worst case (prompt + max new
tokens), so a mid-flight decode can never exhaust the pool — eviction
is always "request finished", never "victim chosen".  Utilization-
optimistic admission (overcommit + preempt) layers on top of the same
pool primitives; this engine ships the safe policy.

Per-token latency is the engine tick wall (each active request gains
one token per tick); the run summary reports p50/p99 over every
generated token plus decode tokens/s — the rows ``standalone_gpt
--serve`` prints and bench.py's ``serving`` section commits.
"""
from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.flags import flag_int, flag_str
from .kv_cache import (DUMP_BLOCK, KVCacheConfig, KVCacheManager,
                       init_cache)
from .metrics import ServeMetrics
from .model import (GPTServingWeights, ServingModelConfig,
                    gpt_decode_step, gpt_prefill_step)

__all__ = ["Request", "BucketLadder", "ServingEngine", "ServeSummary",
           "default_cache_config"]

# per-token latency samples kept for the p50/p99 window (a lifetime
# list would grow without bound on a long-running serve)
_LATENCY_WINDOW = 100_000


def _parse_ladder(raw: str) -> Tuple[int, ...]:
    vals = tuple(sorted({int(x) for x in raw.split(",") if x.strip()}))
    if not vals or vals[0] < 1:
        raise ValueError(f"bucket ladder {raw!r} must name positive "
                         f"integers")
    return vals


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """The registered (batch, pages) shape ladder.  ``pick`` rounds a
    live size up to the smallest rung, so steady-state serving runs a
    finite, precompilable program set."""

    batch: Tuple[int, ...]
    pages: Tuple[int, ...]

    @classmethod
    def from_flags(cls) -> "BucketLadder":
        return cls(
            batch=_parse_ladder(flag_str("APEX_TPU_SERVE_BATCH_BUCKETS")),
            pages=_parse_ladder(flag_str("APEX_TPU_SERVE_PAGE_BUCKETS")))

    @staticmethod
    def _pick(rungs: Tuple[int, ...], n: int, what: str) -> int:
        for r in rungs:
            if n <= r:
                return r
        raise ValueError(f"{what} {n} exceeds the ladder {rungs} — "
                         f"register a bigger rung or admit less")

    def pick_batch(self, n: int) -> int:
        return self._pick(self.batch, n, "batch size")

    def pick_pages(self, n: int) -> int:
        return self._pick(self.pages, n, "page span")

    @property
    def max_batch(self) -> int:
        return self.batch[-1]

    @property
    def max_pages(self) -> int:
        return self.pages[-1]


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated results."""

    rid: Any
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    # engine-owned:
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    token_latency_s: List[float] = dataclasses.field(
        default_factory=list)
    admitted_at_step: Optional[int] = None
    preempted: bool = False

    @property
    def done(self) -> bool:
        if self.out_tokens and self.eos_token is not None \
                and self.out_tokens[-1] == self.eos_token:
            return True
        return len(self.out_tokens) >= self.max_new_tokens


@dataclasses.dataclass
class ServeSummary:
    """What a serve run measured (the --serve / bench row source)."""

    requests_done: int
    requests_preempted: int
    tokens_generated: int
    prefill_tokens: int
    wall_s: float
    decode_steps: int
    tokens_per_sec: float
    # decode ticks only (prefill wall excluded) — the honest basis for
    # kernel-vs-baseline decode comparisons
    decode_wall_s: float
    decode_tokens_per_sec: float
    latency_p50_ms: Optional[float]
    latency_p99_ms: Optional[float]
    compiles: Dict[str, int]
    drained: bool = False
    # per-request lifecycle distributions (serving/metrics.py, bounded
    # windows): admission queue wait, time-to-first-token, and
    # inter-token latency percentiles; None until a series has samples
    queue_wait_p50_ms: Optional[float] = None
    queue_wait_p99_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    itl_p50_ms: Optional[float] = None
    itl_p99_ms: Optional[float] = None
    # submits the engine refused, by reason (ladder_span / max_seq /
    # empty_prompt / max_new_tokens) — rejected requests never enter
    # the queue and never get lifecycle chains
    requests_rejected: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentile(xs: Sequence[float], q: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, np.float64), q))


class ServingEngine:
    """Continuous-batching driver over one model + one paged cache.

    ``weights``/``model_cfg`` come from :mod:`.model`;
    ``cache_cfg`` sizes the pool.  ``monitor`` is an optional
    :class:`apex_tpu.monitor.StepMonitor` (or anything with its
    ``event`` method) receiving ``serving`` events; ``autoresume`` an
    installed :class:`apex_tpu.resilience.AutoResume` polled between
    steps for the SIGTERM clean-drain path.

    Long-running serves: summary totals come from lifetime counters
    and latency percentiles from a bounded window of the most recent
    samples, so a caller may drain ``done`` (pop finished requests)
    at any time to keep host memory flat without corrupting the
    summary."""

    def __init__(self, weights: GPTServingWeights,
                 model_cfg: ServingModelConfig,
                 cache_cfg: KVCacheConfig, *,
                 ladder: Optional[BucketLadder] = None,
                 monitor=None, autoresume=None,
                 tick_every: Optional[int] = None,
                 snapshot=None,
                 clock: Callable[[], float] = time.perf_counter):
        self.weights = weights
        self.model_cfg = model_cfg
        self.cache_cfg = cache_cfg
        self.ladder = ladder if ladder is not None \
            else BucketLadder.from_flags()
        max_need = self.ladder.max_pages
        if max_need > cache_cfg.usable_blocks:
            raise ValueError(
                f"page ladder max {max_need} exceeds the pool's "
                f"{cache_cfg.usable_blocks} usable blocks")
        self.monitor = monitor
        self.autoresume = autoresume
        self._clock = clock
        # request-lifecycle + gauge telemetry (serving/metrics.py):
        # pure host bookkeeping through the monitor sinks — no device
        # traffic, so the one-fetch-per-tick budget is untouched.
        # ``snapshot`` is an optional metrics.SnapshotTrigger polled
        # at every tick boundary (the --serve driver wires SIGUSR1).
        self.metrics = ServeMetrics(monitor=monitor, clock=clock,
                                    tick_every=tick_every)
        self.snapshot = snapshot
        self.manager = KVCacheManager(cache_cfg)
        self.cache = init_cache(cache_cfg)
        self.queue: deque = deque()
        self.active: Dict[Any, Request] = {}
        self.done: List[Request] = []
        self.steps = 0
        self.prefill_tokens = 0
        self._run_wall_s = 0.0
        # bounded: a weeks-long serve must not grow host memory per
        # token — percentiles read the most recent window only
        self._latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self._done_count = 0
        self._preempted_count = 0
        self._done_tokens = 0
        self.decode_wall_s = 0.0
        self.decode_tokens = 0
        self._decode_exec: Dict[Tuple[int, int], Any] = {}
        self._prefill_exec: Dict[int, Any] = {}
        self._compiles: Dict[str, int] = {}

    # --- events -------------------------------------------------------

    def _event(self, name: str, value=None, **attrs) -> None:
        if self.monitor is not None:
            self.monitor.event("serving", name, value=value,
                               step=self.steps, **attrs)

    # --- compiled-program cache ---------------------------------------

    def _jit_decode(self):
        cfg, ccfg = self.model_cfg, self.cache_cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(weights, cache, tokens, positions, block_tables,
                 seq_lens, write_blocks, write_offsets):
            return gpt_decode_step(weights, cfg, ccfg, cache, tokens,
                                   positions, block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return step

    def _jit_prefill(self):
        cfg, ccfg = self.model_cfg, self.cache_cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(weights, cache, tokens, length, blocks):
            return gpt_prefill_step(weights, cfg, ccfg, cache, tokens,
                                    length, blocks)

        return step

    def _decode_args(self, bb: int, pb: int):
        z = jnp.zeros((bb,), jnp.int32)
        return (self.weights, self.cache, z, z,
                jnp.zeros((bb, pb), jnp.int32), z, z, z)

    def _prefill_args(self, s_pad: int):
        return (self.weights, self.cache,
                jnp.zeros((s_pad,), jnp.int32), jnp.int32(1),
                jnp.zeros((s_pad // self.cache_cfg.block_size,),
                          jnp.int32))

    def _compiled(self, cache: dict, key, jit_builder, args, label):
        ex = cache.get(key)
        if ex is None:
            t0 = self._clock()
            ex = jit_builder().lower(*args).compile()
            cache[key] = ex
            self._compiles[f"{label}:{key}"] = \
                self._compiles.get(f"{label}:{key}", 0) + 1
            self._event("serve_compile", value=round(
                (self._clock() - t0) * 1e3, 2), what=label,
                bucket=str(key))
        return ex

    def _decode_fn(self, bb: int, pb: int):
        return self._compiled(self._decode_exec, (bb, pb),
                              self._jit_decode,
                              self._decode_args(bb, pb), "decode")

    def _prefill_fn(self, s_pad: int):
        return self._compiled(self._prefill_exec, s_pad,
                              self._jit_prefill,
                              self._prefill_args(s_pad), "prefill")

    def warmup(self) -> Dict[str, float]:
        """AOT-compile every ladder bucket (decode: batch x pages;
        prefill: one program per page rung) BEFORE traffic, so a
        sanitized serve charges every compile to warmup and the
        steady state compiles exactly once per bucket.  Returns
        ``{bucket label: compile count}`` (all 1 after a fresh
        warmup)."""
        for pb in self.ladder.pages:
            self._prefill_fn(pb * self.cache_cfg.block_size)
        for bb in self.ladder.batch:
            for pb in self.ladder.pages:
                self._decode_fn(bb, pb)
        return dict(self._compiles)

    # --- request lifecycle --------------------------------------------

    def _reject(self, request: Request, reason: str,
                msg: str) -> None:
        """Refuse a submit: counted + emitted (``request_rejected``,
        the summary's ``requests_rejected`` reasons) before the raise,
        so a caller swallowing the ValueError still leaves an audit
        trail."""
        self.metrics.on_reject(request.rid, reason, self.steps)
        raise ValueError(msg)

    def submit(self, request: Request) -> None:
        if len(request.prompt) < 1:
            self._reject(request, "empty_prompt",
                         f"request {request.rid!r}: empty prompt")
        if request.max_new_tokens < 1:
            # prefill always emits one token, and a negative budget
            # would undercount the reservation _can_admit sizes —
            # admission could then exhaust the pool mid-decode
            self._reject(
                request, "max_new_tokens",
                f"request {request.rid!r}: max_new_tokens "
                f"{request.max_new_tokens} < 1")
        limit = self.ladder.max_pages * self.cache_cfg.block_size
        worst = len(request.prompt) + request.max_new_tokens
        if worst > limit:
            self._reject(
                request, "ladder_span",
                f"request {request.rid!r}: prompt + max_new_tokens = "
                f"{worst} exceeds the ladder's {limit}-token span")
        if worst > self.model_cfg.max_seq:
            self._reject(
                request, "max_seq",
                f"request {request.rid!r}: {worst} tokens exceed the "
                f"model's max_seq {self.model_cfg.max_seq}")
        self.queue.append(request)
        self.metrics.on_submit(request, self.steps)

    def _reserved_blocks(self) -> int:
        """Blocks the free pool already owes to active requests: each
        one may still grow to its worst case (prompt + max_new), and
        only the pages it has claimed so far left the free list."""
        total = 0
        for rid, req in self.active.items():
            worst = self.cache_cfg.blocks_for(
                len(req.prompt) + req.max_new_tokens)
            total += max(0, worst - self.manager.num_pages(rid))
        return total

    def _can_admit(self, req: Request) -> bool:
        # reservation policy lives in the manager — one build site
        # for the no-mid-decode-exhaustion contract
        return self.manager.can_admit(
            len(req.prompt), req.max_new_tokens,
            reserved_blocks=self._reserved_blocks())

    def _admit(self, req: Request) -> None:
        p_len = len(req.prompt)
        self.manager.alloc(req.rid, p_len)
        bs = self.cache_cfg.block_size
        pages_bucket = self.ladder.pick_pages(
            self.cache_cfg.blocks_for(p_len))
        s_pad = pages_bucket * bs
        bt = self.manager.block_table(req.rid, s_pad // bs)
        tokens = np.zeros(s_pad, np.int32)
        tokens[:p_len] = req.prompt
        fn = self._prefill_fn(s_pad)
        t0 = self._clock()
        self.cache, next_token = fn(
            self.weights, self.cache, jnp.asarray(tokens),
            jnp.int32(p_len), jnp.asarray(bt))
        first = int(next_token)          # explicit host sync: the
        # admission boundary needs the token to seed the decode batch
        dt = self._clock() - t0
        req.out_tokens.append(first)
        req.token_latency_s.append(dt)
        self._latencies.append(dt)
        req.admitted_at_step = self.steps
        self.active[req.rid] = req
        self.prefill_tokens += p_len
        # request_admitted (queue wait) + request_first_token (TTFT):
        # t0 is the instant queue wait ended and prefill began
        self.metrics.on_admit(req, self.steps, t0, dt,
                              prompt_len=p_len, s_pad=s_pad)

    def _finish(self, req: Request) -> None:
        self.manager.free(req.rid)
        del self.active[req.rid]
        self.done.append(req)
        if req.preempted:
            self._preempted_count += 1
        else:
            self._done_count += 1
        self._done_tokens += len(req.out_tokens)
        # terminal lifecycle event (request_done) with the full
        # queued/prefill/decode breakdown
        self.metrics.on_done(req, self.steps)

    def _terminating(self) -> bool:
        return (self.autoresume is not None
                and self.autoresume.termination_requested())

    # --- the engine tick ----------------------------------------------

    def step(self) -> int:
        """One continuous-batching tick: evict finished, admit (unless
        draining), run one bucketed decode step over every active
        request.  Returns the number of tokens generated this tick."""
        for rid in [r for r, q in self.active.items() if q.done]:
            self._finish(self.active[rid])
        if not self._terminating():
            while (self.queue
                   and len(self.active) < self.ladder.max_batch
                   and self._can_admit(self.queue[0])):
                self._admit(self.queue.popleft())
        # requests may finish at admission (max_new_tokens == 1)
        for rid in [r for r, q in self.active.items() if q.done]:
            self._finish(self.active[rid])
        if not self.active:
            return 0
        reqs = [self.active[r] for r in sorted(self.active,
                                               key=lambda r: str(r))]
        n = len(reqs)
        bb = self.ladder.pick_batch(n)
        slots = [self.manager.append(q.rid) for q in reqs]
        pb = self.ladder.pick_pages(
            max(self.manager.num_pages(q.rid) for q in reqs))
        tokens = np.zeros(bb, np.int32)
        positions = np.zeros(bb, np.int32)
        seq_lens = np.zeros(bb, np.int32)
        wb = np.full(bb, DUMP_BLOCK, np.int32)
        wo = np.zeros(bb, np.int32)
        bt = np.full((bb, pb), DUMP_BLOCK, np.int32)
        for i, (q, (blk, off)) in enumerate(zip(reqs, slots)):
            new_len = self.manager.seq_len(q.rid)   # post-append
            tokens[i] = q.out_tokens[-1]
            positions[i] = new_len - 1
            seq_lens[i] = new_len
            wb[i], wo[i] = blk, off
            bt[i] = self.manager.block_table(q.rid, pb)
        fn = self._decode_fn(bb, pb)
        t0 = self._clock()
        self.cache, next_tokens = fn(
            self.weights, self.cache, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt),
            jnp.asarray(seq_lens), jnp.asarray(wb), jnp.asarray(wo))
        out = np.asarray(next_tokens)    # the tick's ONE device fetch
        dt = self._clock() - t0
        for i, q in enumerate(reqs):
            q.out_tokens.append(int(out[i]))
            q.token_latency_s.append(dt)
            self._latencies.append(dt)
        self.decode_wall_s += dt
        self.decode_tokens += n
        self.steps += 1
        self._event("decode_step", value=round(dt * 1e3, 3),
                    batch=n, batch_bucket=bb, pages_bucket=pb)
        self._tick_tail(n, bb, pb)
        return n

    def _tick_tail(self, batch: int, bb: int, pb: int) -> None:
        """Per-tick telemetry boundary: engine gauges on the
        registered cadence, snapshot-trigger poll, and the watchdog
        stall heartbeat — all host bookkeeping the engine already
        holds, after the tick's one device fetch."""
        self.metrics.on_tick(
            self.steps, batch=batch, batch_bucket=bb,
            pages_bucket=pb,
            free_blocks=self.manager.free_blocks,
            used_blocks=self.manager.used_blocks,
            reserved_blocks=self._reserved_blocks(),
            pool_blocks=self.cache_cfg.usable_blocks,
            queue_depth=len(self.queue),
            compiles=sum(self._compiles.values()))
        if self.snapshot is not None:
            self.snapshot.poll(self.steps, self.snapshot_state,
                               self.monitor)
        # the serve loop's stall heartbeat: each tick feeds the same
        # Watchdog the training loops drive through StepMonitor, so a
        # wedged decode step raises the once-per-episode stall alarm
        # (with the optional jax.profiler capture) mid-serve
        wd = getattr(self.monitor, "watchdog", None)
        if wd is not None:
            wd.observe_step(self.steps)

    def snapshot_state(self) -> Dict[str, Any]:
        """Live engine state as one JSON-able dict — what the
        on-demand :class:`~apex_tpu.serving.metrics.SnapshotTrigger`
        dumps as an ``engine_snapshot`` event for a wedged serve."""
        return {
            "tick": self.steps,
            "active": len(self.active),
            "queued": len(self.queue),
            "done": self._done_count,
            "preempted": self._preempted_count,
            "free_blocks": self.manager.free_blocks,
            "used_blocks": self.manager.used_blocks,
            "reserved_blocks": self._reserved_blocks(),
            "used_blocks_high_water":
                self.metrics.gauges.used_blocks_hw,
            "pool_blocks": self.cache_cfg.usable_blocks,
            "compiles": sum(self._compiles.values()),
            "requests": [
                {"rid": str(rid),
                 "seq_len": self.manager.seq_len(rid),
                 "new_tokens": len(q.out_tokens),
                 "max_new_tokens": q.max_new_tokens}
                for rid, q in sorted(self.active.items(),
                                     key=lambda kv: str(kv[0]))],
        }

    def run(self, *, max_steps: Optional[int] = None,
            before_tick: Optional[Callable[[int], None]] = None,
            after_tick: Optional[Callable[[int], None]] = None
            ) -> ServeSummary:
        """Serve until every submitted request finishes (or a
        termination request / ``max_steps`` drains the run).  On
        SIGTERM (via ``autoresume``) the engine stops admitting,
        abandons in-flight generation cleanly (blocks freed, requests
        marked preempted) and still returns a complete summary — the
        clean-drain contract CI kills a serve mid-run to prove.
        ``before_tick``/``after_tick`` receive the tick index (fault
        injection and the sanitizer's step boundary in the smoke
        driver).

        The summary covers the engine's **lifetime**: token/request
        totals accumulate across every ``run()`` call on this engine,
        and ``wall_s`` accumulates the time spent inside ``run()`` —
        so a paused-and-resumed serve (``max_steps``, or bench's
        staggered tail admissions) reports the same honest tokens/s
        as a single uninterrupted run, never lifetime tokens over
        one run's wall."""
        t0 = self._clock()
        drained = False
        while self.queue or self.active:
            if self._terminating():
                drained = True
                for rid in list(self.active):
                    q = self.active[rid]
                    q.preempted = True
                    self._finish(q)
                while self.queue:
                    # accepted but never admitted: no blocks to free,
                    # but the drain still accounts for every request —
                    # preempted, in ``done``, with a complete
                    # lifecycle chain whose wall was all queue wait
                    q = self.queue.popleft()
                    q.preempted = True
                    self.done.append(q)
                    self._preempted_count += 1
                    self.metrics.on_done(q, self.steps)
                self._event("serve_preempt",
                            source=self.autoresume.source)
                break
            if max_steps is not None and self.steps >= max_steps:
                drained = True
                break
            if before_tick is not None:
                before_tick(self.steps)
            self.step()
            if after_tick is not None:
                after_tick(self.steps)
        self._run_wall_s += self._clock() - t0
        # a trailing partial gauge window (tick_every > 1) flushes so
        # the final engine state is always in the log
        self.metrics.flush_gauges(self.steps)
        wall = max(self._run_wall_s, 1e-9)
        gen = self._done_tokens \
            + sum(len(q.out_tokens) for q in self.active.values())
        pct = self.metrics.percentiles()
        summary = ServeSummary(
            requests_done=self._done_count,
            requests_preempted=self._preempted_count,
            tokens_generated=gen,
            prefill_tokens=self.prefill_tokens,
            wall_s=round(wall, 4),
            decode_steps=self.steps,
            tokens_per_sec=round(gen / wall, 2),
            decode_wall_s=round(self.decode_wall_s, 4),
            decode_tokens_per_sec=round(
                self.decode_tokens / max(self.decode_wall_s, 1e-9), 2)
            if self.decode_tokens else 0.0,
            latency_p50_ms=_round_ms(_percentile(self._latencies, 50)),
            latency_p99_ms=_round_ms(_percentile(self._latencies, 99)),
            compiles=dict(self._compiles),
            drained=drained,
            queue_wait_p50_ms=pct["queue_wait_p50_ms"],
            queue_wait_p99_ms=pct["queue_wait_p99_ms"],
            ttft_p50_ms=pct["ttft_p50_ms"],
            ttft_p99_ms=pct["ttft_p99_ms"],
            itl_p50_ms=pct["itl_p50_ms"],
            itl_p99_ms=pct["itl_p99_ms"],
            requests_rejected=dict(self.metrics.rejected))
        self._event("serve_done", value=summary.tokens_per_sec,
                    **{k: v for k, v in summary.as_dict().items()
                       if k not in ("compiles", "tokens_per_sec")})
        return summary


def _round_ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)


def default_cache_config(model_cfg: ServingModelConfig,
                         num_blocks: Optional[int] = None,
                         block_size: Optional[int] = None,
                         kv_dtype: Optional[str] = None) -> KVCacheConfig:
    """Cache plan from the registered serving flags
    (``APEX_TPU_SERVE_KV_BLOCK`` / ``APEX_TPU_SERVE_KV_DTYPE`` /
    ``APEX_TPU_SERVE_BLOCKS``); explicit arguments override."""
    return KVCacheConfig(
        num_layers=model_cfg.num_layers,
        num_heads=model_cfg.num_heads,
        head_dim=model_cfg.head_dim,
        num_blocks=(num_blocks if num_blocks is not None
                    else flag_int("APEX_TPU_SERVE_BLOCKS")),
        block_size=(block_size if block_size is not None
                    else flag_int("APEX_TPU_SERVE_KV_BLOCK")),
        kv_dtype=(kv_dtype if kv_dtype is not None
                  else flag_str("APEX_TPU_SERVE_KV_DTYPE")),
        model_dtype=model_cfg.dtype)
