"""Continuous-batching serving engine.

The serving loop alternates two worlds on a fixed cadence:

* **between** jitted steps (host, this module): finished requests are
  evicted (their cache blocks return to the pool), queued requests are
  admitted (blocks allocated, prompt prefilled), and the next decode
  batch is assembled;
* **inside** jitted steps (:mod:`.model`): one prefill per admission,
  then one batched decode step per engine tick — cache donated through
  every call, greedy sampling in-graph, one int32 per row of output
  traffic.

Shapes are **bucketed**: the decode batch rounds up to a registered
batch bucket and the page span to a page bucket
(:class:`BucketLadder`, ``APEX_TPU_SERVE_BATCH_BUCKETS`` /
``APEX_TPU_SERVE_PAGE_BUCKETS``), prompt lengths to page-bucket
multiples of the block size — so the set of compiled programs is the
(small, finite) ladder product, every member AOT-compiled by
:meth:`ServingEngine.warmup` before traffic.  Steady-state serving
under :func:`apex_tpu.analysis.sanitize` therefore compiles exactly
once per bucket and never again — the same recompile budget the
training smoke enforces, now on the serving path (the tests and
tools/ci.sh step 11 prove it).

Admission control is **reservation-based**: a request is admitted only
when the pool can cover its whole worst case (prompt + max new
tokens), so a mid-flight decode can never exhaust the pool — eviction
is always "request finished", never "victim chosen".  Utilization-
optimistic admission (overcommit + preempt) layers on top of the same
pool primitives; this engine ships the safe policy.

Per-token latency is the engine tick wall (each active request gains
one token per tick); the run summary reports p50/p99 over every
generated token plus decode tokens/s — the rows ``standalone_gpt
--serve`` prints and bench.py's ``serving`` section commits.
"""
from __future__ import annotations

import dataclasses
import functools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.flags import flag_bool, flag_float, flag_int, flag_str
from ..monitor.export import MetricsRegistry
from ..utils.log_util import get_logger
from .kv_cache import (DUMP_BLOCK, KVCacheConfig, KVCacheManager,
                       PrefixMatch, init_cache)
from .metrics import ServeMetrics, SLOTracker
from ..ops.quant_matmul import is_quantized_weights
from .model import (GPTServingWeights, ServingModelConfig,
                    copy_cache_block, gpt_decode_step,
                    gpt_extend_step, gpt_prefill_step)
from .resilience import RequestJournal, ShedPolicy, SpeculationGovernor

logger = get_logger(__name__)

__all__ = ["Request", "BucketLadder", "ServingEngine", "ServeSummary",
           "default_cache_config"]

# per-token latency samples kept for the p50/p99 window (a lifetime
# list would grow without bound on a long-running serve)
_LATENCY_WINDOW = 100_000


def _parse_ladder(raw: str) -> Tuple[int, ...]:
    vals = tuple(sorted({int(x) for x in raw.split(",") if x.strip()}))
    if not vals or vals[0] < 1:
        raise ValueError(f"bucket ladder {raw!r} must name positive "
                         f"integers")
    return vals


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """The registered (batch, pages[, prefill-chunk]) shape ladder.
    ``pick`` rounds a live size up to the smallest rung, so
    steady-state serving runs a finite, precompilable program set.
    ``chunks`` is the prefill-chunk token dimension (ISSUE-12): empty
    means "derive from the page rungs" (one whole-padded-prompt chunk
    per page rung — the warm-tail prefill shape when chunked prefill
    is off); the ``APEX_TPU_SERVE_PREFILL_CHUNK`` flag registers a
    single explicit rung."""

    batch: Tuple[int, ...]
    pages: Tuple[int, ...]
    chunks: Tuple[int, ...] = ()

    @classmethod
    def from_flags(cls) -> "BucketLadder":
        chunk = flag_int("APEX_TPU_SERVE_PREFILL_CHUNK")
        return cls(
            batch=_parse_ladder(flag_str("APEX_TPU_SERVE_BATCH_BUCKETS")),
            pages=_parse_ladder(flag_str("APEX_TPU_SERVE_PAGE_BUCKETS")),
            chunks=(chunk,) if chunk > 0 else ())

    @staticmethod
    def _pick(rungs: Tuple[int, ...], n: int, what: str) -> int:
        for r in rungs:
            if n <= r:
                return r
        raise ValueError(f"{what} {n} exceeds the ladder {rungs} — "
                         f"register a bigger rung or admit less")

    def pick_batch(self, n: int) -> int:
        return self._pick(self.batch, n, "batch size")

    def pick_pages(self, n: int) -> int:
        return self._pick(self.pages, n, "page span")

    def chunk_rungs(self, block_size: int) -> Tuple[int, ...]:
        """The effective prefill-chunk rungs: the registered ones, or
        (when none are) a derived set — one single-block rung (the
        common warm-prefix tail is a handful of tokens; padding it to
        the full page span would cost a whole prefill) plus one
        whole-padded-prompt rung per page bucket for long unshared
        tails — so a warm-tail prefill has a compiled shape even with
        chunked prefill disabled."""
        if self.chunks:
            return self.chunks
        return tuple(sorted({block_size}
                            | {p * block_size for p in self.pages}))

    def pick_chunk(self, n: int, block_size: int) -> int:
        """Round a chunk of ``n`` tokens up to the smallest chunk
        rung; a tail longer than every rung processes the largest
        rung per tick (the caller loops)."""
        rungs = self.chunk_rungs(block_size)
        for r in rungs:
            if n <= r:
                return r
        return rungs[-1]

    @property
    def max_batch(self) -> int:
        return self.batch[-1]

    @property
    def max_pages(self) -> int:
        return self.pages[-1]


@dataclasses.dataclass
class Request:
    """One generation request and its accumulated results.

    ``deadline_ms`` bounds the request's whole wall (submit → last
    token) relative to its submit instant: a queued request past its
    deadline is expired with terminal ``deadline_exceeded``; a running
    one is evicted with terminal ``deadline`` — both at tick
    boundaries, AFTER the expiring tick's tokens were delivered (the
    deadline-at-boundary semantics the tests pin).  ``None`` falls
    back to the engine default (``APEX_TPU_SERVE_DEADLINE_MS``, 0 =
    no deadline).  ``priority`` orders load shedding: under pool/queue
    pressure the :class:`~.resilience.ShedPolicy` sheds lowest
    priority, shortest progress first."""

    rid: Any
    prompt: List[int]
    max_new_tokens: int
    eos_token: Optional[int] = None
    deadline_ms: Optional[float] = None
    priority: int = 0
    # engine-owned:
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    token_latency_s: List[float] = dataclasses.field(
        default_factory=list)
    admitted_at_step: Optional[int] = None
    preempted: bool = False
    submit_t: Optional[float] = None     # engine-clock submit instant
    terminal: Optional[str] = None       # finished | preempted |
    # deadline | deadline_exceeded | shed — set exactly once

    @property
    def done(self) -> bool:
        if self.out_tokens and self.eos_token is not None \
                and self.out_tokens[-1] == self.eos_token:
            return True
        return len(self.out_tokens) >= self.max_new_tokens


@dataclasses.dataclass
class ServeSummary:
    """What a serve run measured (the --serve / bench row source)."""

    requests_done: int
    requests_preempted: int
    tokens_generated: int
    prefill_tokens: int
    wall_s: float
    decode_steps: int
    tokens_per_sec: float
    # decode ticks only (prefill wall excluded) — the honest basis for
    # kernel-vs-baseline decode comparisons
    decode_wall_s: float
    decode_tokens_per_sec: float
    latency_p50_ms: Optional[float]
    latency_p99_ms: Optional[float]
    compiles: Dict[str, int]
    drained: bool = False
    # per-request lifecycle distributions (serving/metrics.py, bounded
    # windows): admission queue wait, time-to-first-token, and
    # inter-token latency percentiles; None until a series has samples
    queue_wait_p50_ms: Optional[float] = None
    queue_wait_p99_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    itl_p50_ms: Optional[float] = None
    itl_p99_ms: Optional[float] = None
    # submits the engine refused, by reason (ladder_span / max_seq /
    # empty_prompt / max_new_tokens) — rejected requests never enter
    # the queue and never get lifecycle chains
    requests_rejected: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # ISSUE-12 decode fast path, all printed numbers (the ROADMAP
    # exit criteria), not derived ones: speculative-decode acceptance
    # (None when speculation is off), prompt-prefix sharing
    # (warm admissions, prefill tokens skipped, shared-block
    # high-water, copy-on-write count), and chunked-prefill volume
    spec_accept_rate: Optional[float] = None
    spec_tokens_proposed: int = 0
    spec_tokens_accepted: int = 0
    warm_prefix_admissions: int = 0
    prefix_hit_tokens: int = 0
    shared_blocks_hw: int = 0
    cow_copies: int = 0
    prefill_chunks: int = 0
    # ISSUE-13 serving resilience: requests expired past their
    # deadline (queued OR running), requests shed under pool/queue
    # pressure, how often the shed policy engaged, whether the
    # speculation governor degraded the run, and how many requests
    # entered through a journal replay (supervised crash recovery)
    requests_deadline: int = 0
    requests_shed: int = 0
    shed_engagements: int = 0
    spec_disabled: bool = False
    replayed_requests: int = 0
    # how many crash recoveries (engine.crash_reset) produced this
    # summary — counted on the engine itself so the serve_done event
    # carries the real value, not a post-hoc patch (0 = never crashed)
    restarts: int = 0
    # ISSUE-17 live metrics plane: SLO burn-rate episodes this engine
    # tripped (and recovered from), plus the class/dimension pairs
    # still burning when the summary was taken — the SERVE_DONE
    # surface of the SLOTracker (None objectives => all zeros)
    slo_burn_episodes: int = 0
    slo_recoveries: int = 0
    slo_burning: List[str] = dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentile(xs: Sequence[float], q: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, np.float64), q))


@dataclasses.dataclass
class _PrefillJob:
    """An admitted request whose prompt k/v is still being written —
    blocks already owned (alloc'd at admission, shared prefix mapped),
    ``written`` positions valid so far.  Advanced one chunk per engine
    tick (chunked prefill) or drained synchronously at admission (the
    warm-tail path when chunking is off)."""

    req: Request
    tokens: np.ndarray            # the whole prompt, int32
    written: int                  # k/v-valid positions so far
    start: int                    # prefix-shared positions (skipped)
    admit_t: float                # prefill-start instant


class ServingEngine:
    """Continuous-batching driver over one model + one paged cache.

    ``weights``/``model_cfg`` come from :mod:`.model`;
    ``cache_cfg`` sizes the pool.  ``monitor`` is an optional
    :class:`apex_tpu.monitor.StepMonitor` (or anything with its
    ``event`` method) receiving ``serving`` events; ``autoresume`` an
    installed :class:`apex_tpu.resilience.AutoResume` polled between
    steps for the SIGTERM clean-drain path.

    Long-running serves: summary totals come from lifetime counters
    and latency percentiles from a bounded window of the most recent
    samples, so a caller may drain ``done`` (pop finished requests)
    at any time to keep host memory flat without corrupting the
    summary."""

    def __init__(self, weights: GPTServingWeights,
                 model_cfg: ServingModelConfig,
                 cache_cfg: KVCacheConfig, *,
                 ladder: Optional[BucketLadder] = None,
                 monitor=None, autoresume=None,
                 tick_every: Optional[int] = None,
                 snapshot=None,
                 speculate_k: Optional[int] = None,
                 draft_weights: Optional[GPTServingWeights] = None,
                 draft_cfg: Optional[ServingModelConfig] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_share: Optional[bool] = None,
                 deadline_ms: Optional[float] = None,
                 shed: Optional[ShedPolicy] = None,
                 journal: Optional[RequestJournal] = None,
                 escalation=None, fault=None,
                 spec_governor="auto",
                 tp=None, ep=None,
                 replica_id: Optional[str] = None,
                 device=None,
                 slo="auto", exporter=None,
                 clock: Callable[[], float] = time.perf_counter):
        # --- ISSUE-14 fleet hooks -----------------------------------
        # ``tp`` is a serving.tp.TPContext: the engine swaps its jit
        # builders for the shard_map-wrapped TP ones, commits weights
        # and cache to the plan's shardings, and serves with the
        # tp-axis-carrying model config — the continuous-batching loop
        # is otherwise unchanged.  ``replica_id`` stamps every emitted
        # event with a stable fleet identity (ReplicaMonitor).
        # ``device`` pins a single-chip replica's weights and cache to
        # one device, so N fleet replicas execute on N device streams
        # CONCURRENTLY — without it every replica's arrays land on
        # device 0 and the fleet serializes behind one stream (mutually
        # exclusive with ``tp``, whose mesh already places the shards).
        # ``ep`` is a serving.ep.EPContext (ISSUE-19): same swap, but
        # the expert stacks shard and attention/cache replicate — the
        # MoE decode fast path.  tp/ep/device are mutually exclusive;
        # a context owns its device slice.
        self.tp = tp
        self.ep = ep
        self.device = device
        if sum(x is not None for x in (tp, ep, device)) > 1:
            raise ValueError("pass at most one of tp, ep, device — a "
                             "context owns its device slice")
        self.replica_id = (str(replica_id) if replica_id is not None
                           else None)
        if self.replica_id is not None and monitor is not None:
            from .metrics import ReplicaMonitor

            if not isinstance(monitor, ReplicaMonitor):
                monitor = ReplicaMonitor(monitor, self.replica_id)
        if tp is not None:
            if speculate_k or draft_weights is not None:
                raise ValueError(
                    "tensor-parallel serving does not compose with "
                    "speculative decoding yet — run the draft on its "
                    "own replica or drop one of the two")
            if tp.cache_cfg != cache_cfg:
                raise ValueError(
                    "TPContext was built for a different cache "
                    "config than the engine's")
            # int8 weights need the plan's scale-row specs armed; a
            # context built for the other weight format rebinds here
            # so callers never hand-sync the flag
            if tp.weight_quantized != is_quantized_weights(weights):
                self.tp = tp = tp.rebind(
                    weight_quantized=is_quantized_weights(weights))
            model_cfg = tp.model_cfg       # tp_axis armed
            weights = tp.shard_weights(weights)
        elif ep is not None:
            if speculate_k or draft_weights is not None:
                raise ValueError(
                    "expert-parallel serving does not compose with "
                    "speculative decoding yet — run the draft on its "
                    "own replica or drop one of the two")
            if ep.cache_cfg != cache_cfg:
                raise ValueError(
                    "EPContext was built for a different cache "
                    "config than the engine's")
            if is_quantized_weights(weights):
                raise ValueError(
                    "expert-parallel serving does not take int8 "
                    "weights yet — the Q8 kernel has no expert-stack "
                    "layout; serve bf16 or use tp")
            model_cfg = ep.model_cfg       # ep_axis armed
            weights = ep.shard_weights(weights)
        elif device is not None:
            weights = jax.device_put(weights, device)
        self.weights = weights
        self.model_cfg = model_cfg
        self.cache_cfg = cache_cfg
        self.ladder = ladder if ladder is not None \
            else BucketLadder.from_flags()
        max_need = self.ladder.max_pages
        if max_need > cache_cfg.usable_blocks:
            raise ValueError(
                f"page ladder max {max_need} exceeds the pool's "
                f"{cache_cfg.usable_blocks} usable blocks")
        self.monitor = monitor
        self.autoresume = autoresume
        self._clock = clock
        # --- ISSUE-12 decode fast path knobs (flags unless pinned) --
        self.speculate_k = speculate_k if speculate_k is not None \
            else flag_int("APEX_TPU_SERVE_SPECULATE_K")
        self.prefill_chunk = prefill_chunk if prefill_chunk is not None \
            else flag_int("APEX_TPU_SERVE_PREFILL_CHUNK")
        if self.prefill_chunk > 0 and not self.ladder.chunks:
            self.ladder = dataclasses.replace(
                self.ladder, chunks=(self.prefill_chunk,))
        self.prefix_share = prefix_share if prefix_share is not None \
            else flag_bool("APEX_TPU_SERVE_PREFIX_SHARE")
        # --- ISSUE-13 serving resilience ----------------------------
        # default request deadline (0/None = none), hysteresis shed
        # policy, crash-safe request journal, watchdog escalation
        # (serve default: stall -> snapshot-then-drain), and the
        # deterministic fault injector (reject_alloc / corrupt_journal
        # need the engine's cooperation; crash/stall/signal fire from
        # the driver's before_tick) — all host-side bookkeeping, so
        # the zero-steady-state-recompile ladder contract is untouched
        self.default_deadline_ms = deadline_ms if deadline_ms \
            is not None else flag_float("APEX_TPU_SERVE_DEADLINE_MS")
        self.shed = shed if shed is not None else ShedPolicy.from_flags()
        self.journal = journal
        self.escalation = escalation
        self.fault = fault
        self._esc_handled = False
        self._drain_reason: Optional[str] = None
        self.spec_disabled = False
        self._deadline_count = 0
        self._shed_count = 0
        self._replayed = 0
        self.restarts = 0
        # set on the first submit carrying a deadline: the per-tick
        # enforcement scan is skipped entirely while no request has one
        self._deadlines_active = False
        if self.speculate_k > 0 and draft_weights is None:
            raise ValueError(
                "speculate_k > 0 needs a draft model: pass "
                "draft_weights (+ draft_cfg) — e.g. "
                "extract_serving_weights of a narrower GPT")
        self.draft_weights = draft_weights
        self.draft_cfg = draft_cfg
        self.draft_cache_cfg: Optional[KVCacheConfig] = None
        self.draft_cache = None
        if draft_weights is not None:
            if draft_cfg is None:
                raise ValueError("draft_weights without draft_cfg")
            # the draft rides the SAME block pool geometry as the
            # target (same block ids, same tables, one manager), so
            # a (block, offset) slot means the same page in both
            # caches and prefix-shared / CoW'd pages mirror for free
            self.draft_cache_cfg = KVCacheConfig(
                num_layers=draft_cfg.num_layers,
                num_heads=draft_cfg.num_heads,
                head_dim=draft_cfg.head_dim,
                num_blocks=cache_cfg.num_blocks,
                block_size=cache_cfg.block_size,
                kv_dtype=cache_cfg.kv_dtype,
                model_dtype=draft_cfg.dtype)
            self.draft_cache = init_cache(self.draft_cache_cfg)
            if device is not None:
                self.draft_weights = jax.device_put(draft_weights,
                                                    device)
                self.draft_cache = jax.device_put(self.draft_cache,
                                                  device)
        # degraded mode for the fast path: sustained verify mismatch
        # auto-disables speculation (alarm + gauge, never a crash)
        if spec_governor == "auto":
            self.spec_governor = SpeculationGovernor() \
                if self.speculate_k > 0 else None
        else:
            self.spec_governor = spec_governor
        # --- ISSUE-17 live metrics plane ----------------------------
        # ``slo`` is an SLOTracker ("auto" builds one from the
        # APEX_TPU_SLO_* flags; None when every dimension is off) fed
        # by the metrics layer's lifecycle hooks and evaluated once
        # per tick; burn transitions route through the watchdog's
        # alarm machinery.  ``exporter`` is a monitor.export.
        # MetricsExporter receiving one lock-free published snapshot
        # per tick (registry + /healthz + /varz payloads) — all host
        # bookkeeping the engine already holds, no device traffic.
        self.slo = SLOTracker.from_flags() if slo == "auto" else slo
        self.exporter = exporter
        self._slo_defined = False
        # request-lifecycle + gauge telemetry (serving/metrics.py):
        # pure host bookkeeping through the monitor sinks — no device
        # traffic, so the one-fetch-per-tick budget is untouched.
        # ``snapshot`` is an optional metrics.SnapshotTrigger polled
        # at every tick boundary (the --serve driver wires SIGUSR1).
        self.metrics = ServeMetrics(monitor=monitor, clock=clock,
                                    tick_every=tick_every,
                                    slo=self.slo)
        self.snapshot = snapshot
        self.manager = KVCacheManager(cache_cfg,
                                      prefix_sharing=self.prefix_share)
        self.cache = self._fresh_cache()
        self.queue: deque = deque()
        self.active: Dict[Any, Request] = {}
        # admitted requests whose chunked prefill is still running:
        # rid -> _PrefillJob, advanced one chunk per engine tick
        self.prefilling: "Dict[Any, _PrefillJob]" = {}
        self.done: List[Request] = []
        self.steps = 0
        self.prefill_tokens = 0
        self.prefill_chunks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._warm_admissions = 0
        self._prefix_hit_tokens = 0
        self._run_wall_s = 0.0
        # bounded: a weeks-long serve must not grow host memory per
        # token — percentiles read the most recent window only
        self._latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self._done_count = 0
        self._preempted_count = 0
        self._done_tokens = 0
        self.decode_wall_s = 0.0
        self.decode_tokens = 0
        self._decode_exec: Dict[Tuple[int, int], Any] = {}
        self._prefill_exec: Dict[int, Any] = {}
        self._extend_exec: Dict[Tuple[int, int, int], Any] = {}
        self._draft_decode_exec: Dict[Tuple[int, int], Any] = {}
        self._draft_prefill_exec: Dict[int, Any] = {}
        self._draft_extend_exec: Dict[Tuple[int, int, int], Any] = {}
        self._cow_exec: Dict[str, Any] = {}
        self._compiles: Dict[str, int] = {}

    # --- events -------------------------------------------------------

    def _event(self, name: str, value=None, **attrs) -> None:
        if self.monitor is not None:
            self.monitor.event("serving", name, value=value,
                               step=self.steps, **attrs)

    # --- compiled-program cache ---------------------------------------

    def _ctx(self):
        """The engine's parallel serving context, if any — a
        TPContext or EPContext (mutually exclusive); both expose the
        same init_cache/shard_weights/jit_* surface."""
        return self.tp if self.tp is not None else self.ep

    def _fresh_cache(self):
        """A zeroed device cache — TP-sharded under a TPContext (the
        head axis committed to the plan), replicated across the
        expert axis under an EPContext, pinned to the replica's
        device when one was given, default placement otherwise.  Used
        at construction and by :meth:`swap_weights` (new weights mean
        every cached k/v row is stale)."""
        if self._ctx() is not None:
            return self._ctx().init_cache()
        cache = init_cache(self.cache_cfg)
        if self.device is not None:
            cache = jax.device_put(cache, self.device)
        return cache

    def _jit_decode(self, draft: bool = False):
        if self._ctx() is not None and not draft:
            return self._ctx().jit_decode(self.weights)
        cfg = self.draft_cfg if draft else self.model_cfg
        ccfg = self.draft_cache_cfg if draft else self.cache_cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(weights, cache, tokens, positions, block_tables,
                 seq_lens, write_blocks, write_offsets):
            return gpt_decode_step(weights, cfg, ccfg, cache, tokens,
                                   positions, block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return step

    def _jit_prefill(self, draft: bool = False):
        if self._ctx() is not None and not draft:
            return self._ctx().jit_prefill(self.weights)
        cfg = self.draft_cfg if draft else self.model_cfg
        ccfg = self.draft_cache_cfg if draft else self.cache_cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(weights, cache, tokens, length, blocks):
            return gpt_prefill_step(weights, cfg, ccfg, cache, tokens,
                                    length, blocks)

        return step

    def _jit_extend(self, draft: bool = False):
        if self._ctx() is not None and not draft:
            return self._ctx().jit_extend(self.weights)
        cfg = self.draft_cfg if draft else self.model_cfg
        ccfg = self.draft_cache_cfg if draft else self.cache_cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(weights, cache, tokens, block_tables, seq_lens,
                 write_blocks, write_offsets):
            return gpt_extend_step(weights, cfg, ccfg, cache, tokens,
                                   block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return step

    def _jit_cow(self):
        return functools.partial(jax.jit, donate_argnums=(0,))(
            copy_cache_block)

    def _wc(self, draft: bool):
        return (self.draft_weights, self.draft_cache) if draft \
            else (self.weights, self.cache)

    def _decode_args(self, bb: int, pb: int, draft: bool = False):
        z = jnp.zeros((bb,), jnp.int32)
        w, c = self._wc(draft)
        return (w, c, z, z, jnp.zeros((bb, pb), jnp.int32), z, z, z)

    def _prefill_args(self, s_pad: int, draft: bool = False):
        w, c = self._wc(draft)
        return (w, c, jnp.zeros((s_pad,), jnp.int32), jnp.int32(1),
                jnp.zeros((s_pad // self.cache_cfg.block_size,),
                          jnp.int32))

    def _extend_args(self, bb: int, t: int, pb: int,
                     draft: bool = False):
        w, c = self._wc(draft)
        return (w, c, jnp.zeros((bb, t), jnp.int32),
                jnp.zeros((bb, pb), jnp.int32),
                jnp.zeros((bb,), jnp.int32),
                jnp.zeros((bb, t), jnp.int32),
                jnp.zeros((bb, t), jnp.int32))

    def _compiled(self, cache: dict, key, jit_builder, args, label):
        ex = cache.get(key)
        if ex is None:
            t0 = self._clock()
            ex = jit_builder().lower(*args).compile()
            cache[key] = ex
            self._compiles[f"{label}:{key}"] = \
                self._compiles.get(f"{label}:{key}", 0) + 1
            self._event("serve_compile", value=round(
                (self._clock() - t0) * 1e3, 2), what=label,
                bucket=str(key))
            # compilation is progress: feed the stall heartbeat so a
            # multi-second AOT warmup cannot trip the watchdog (and,
            # under the serve escalation policy, drain the serve)
            # before the first tick ever runs
            wd = getattr(self.monitor, "watchdog", None)
            if wd is not None:
                wd.observe_step(self.steps)
        return ex

    def _decode_fn(self, bb: int, pb: int):
        return self._compiled(self._decode_exec, (bb, pb),
                              self._jit_decode,
                              self._decode_args(bb, pb), "decode")

    def _prefill_fn(self, s_pad: int):
        return self._compiled(self._prefill_exec, s_pad,
                              self._jit_prefill,
                              self._prefill_args(s_pad), "prefill")

    def _extend_fn(self, bb: int, t: int, pb: int):
        return self._compiled(self._extend_exec, (bb, t, pb),
                              self._jit_extend,
                              self._extend_args(bb, t, pb), "extend")

    def _draft_decode_fn(self, bb: int, pb: int):
        return self._compiled(
            self._draft_decode_exec, (bb, pb),
            functools.partial(self._jit_decode, True),
            self._decode_args(bb, pb, draft=True), "draft_decode")

    def _draft_prefill_fn(self, s_pad: int):
        return self._compiled(
            self._draft_prefill_exec, s_pad,
            functools.partial(self._jit_prefill, True),
            self._prefill_args(s_pad, draft=True), "draft_prefill")

    def _draft_extend_fn(self, bb: int, t: int, pb: int):
        return self._compiled(
            self._draft_extend_exec, (bb, t, pb),
            functools.partial(self._jit_extend, True),
            self._extend_args(bb, t, pb, draft=True), "draft_extend")

    def _cow_fn(self, which: str):
        cache = self.draft_cache if which == "draft" else self.cache
        return self._compiled(
            self._cow_exec, which, self._jit_cow,
            (cache, jnp.int32(0), jnp.int32(0)), "cow")

    @property
    def _chunking(self) -> bool:
        return self.prefill_chunk > 0

    def warmup(self) -> Dict[str, float]:
        """AOT-compile every ladder bucket BEFORE traffic, so a
        sanitized serve charges every compile to warmup and the
        steady state compiles exactly once per bucket, ever — across
        every enabled path: whole-prompt prefill (one program per page
        rung; skipped when chunked prefill replaces it), chunk/extend
        programs per (chunk rung x page rung) when chunked prefill or
        prefix sharing can route through them, decode per
        (batch x pages), and with speculation the draft's mirror
        programs plus the (batch x K+1 x pages) verify ladder and the
        copy-on-write block-copy program per cache.  Returns
        ``{bucket label: compile count}`` (all 1 after a fresh
        warmup)."""
        bs = self.cache_cfg.block_size
        spec = self.speculate_k > 0
        if not self._chunking:
            for pb in self.ladder.pages:
                self._prefill_fn(pb * bs)
                if spec:
                    self._draft_prefill_fn(pb * bs)
        if self._chunking or self.prefix_share:
            for ct in self.ladder.chunk_rungs(bs):
                for pb in self.ladder.pages:
                    self._extend_fn(1, ct, pb)
                    if spec:
                        self._draft_extend_fn(1, ct, pb)
        for bb in self.ladder.batch:
            for pb in self.ladder.pages:
                self._decode_fn(bb, pb)
                if spec:
                    self._draft_decode_fn(bb, pb)
                    self._extend_fn(bb, self.speculate_k + 1, pb)
        if self.prefix_share:
            self._cow_fn("target")
            if self.draft_cache is not None:
                self._cow_fn("draft")
        return dict(self._compiles)

    # --- request lifecycle --------------------------------------------

    def _reject(self, request: Request, reason: str,
                msg: str) -> None:
        """Refuse a submit: counted + emitted (``request_rejected``,
        the summary's ``requests_rejected`` reasons) before the raise,
        so a caller swallowing the ValueError still leaves an audit
        trail."""
        self.metrics.on_reject(request.rid, reason, self.steps)
        raise ValueError(msg)

    def submit(self, request: Request) -> None:
        if len(request.prompt) < 1:
            self._reject(request, "empty_prompt",
                         f"request {request.rid!r}: empty prompt")
        if request.max_new_tokens < 1:
            # prefill always emits one token, and a negative budget
            # would undercount the reservation can_admit sizes —
            # admission could then exhaust the pool mid-decode
            self._reject(
                request, "max_new_tokens",
                f"request {request.rid!r}: max_new_tokens "
                f"{request.max_new_tokens} < 1")
        limit = self.ladder.max_pages * self.cache_cfg.block_size
        worst = len(request.prompt) + request.max_new_tokens
        if worst > limit:
            self._reject(
                request, "ladder_span",
                f"request {request.rid!r}: prompt + max_new_tokens = "
                f"{worst} exceeds the ladder's {limit}-token span")
        if worst > self.model_cfg.max_seq:
            self._reject(
                request, "max_seq",
                f"request {request.rid!r}: {worst} tokens exceed the "
                f"model's max_seq {self.model_cfg.max_seq}")
        if request.deadline_ms is None and self.default_deadline_ms \
                and self.default_deadline_ms > 0:
            request.deadline_ms = float(self.default_deadline_ms)
        if request.deadline_ms:
            self._deadlines_active = True
        if request.submit_t is None:
            # a PRE-anchored submit instant is respected: the fleet
            # router stamps a disaggregated request when IT accepted
            # the submission, so queue-wait/TTFT/deadline all count
            # the prefill-replica probe and the KV handoff — the
            # clock must not restart at the decode-side submit
            request.submit_t = self._clock()
        self.queue.append(request)
        self.metrics.on_submit(request, self.steps)
        if self.journal is not None:
            self.journal.record_submit(request, self.steps)

    def resubmit(self, request: Request) -> None:
        """Re-enter a journal-replayed request (crash recovery) WITHOUT
        a second ``request_submitted`` lifecycle event: the chain the
        pre-crash submit opened stays open, its admission/first-token
        stamps are reset for the fresh incarnation, and the terminal
        event still fires exactly once — so ``trace_check --serve``'s
        N submitted ⇒ N terminal holds across the crash.  A replay in
        a fresh process (no open chain for the rid) opens one."""
        if request.deadline_ms:
            self._deadlines_active = True
        tr = self.metrics.reopen(str(request.rid))
        if tr is not None:
            # deadline stays anchored at the ORIGINAL submit: crash
            # downtime counts against the request's SLO, not for it
            request.submit_t = tr.submit_t
        else:
            request.submit_t = self._clock()
            self.metrics.on_submit(request, self.steps)
        self.queue.append(request)
        self._replayed += 1
        self._event("request_replayed", rid=str(request.rid),
                    prompt_len=len(request.prompt),
                    max_new_tokens=request.max_new_tokens)

    def crash_reset(self) -> Dict[str, int]:
        """Discard the tick loop's request bookkeeping the way a crash
        does, keeping what the supervisor owns: the device cache and
        the prefix-share index.  Every in-flight request's blocks are
        freed — registered prompt pages park in the idle LRU, still
        warm for the journal replay's readmission — and the open
        lifecycle chains stay open (the replayed incarnations close
        them).  Returns the lost-state counts for the replay event."""
        lost = {"active": len(self.active),
                "prefilling": len(self.prefilling),
                "queued": len(self.queue)}
        self.restarts += 1
        for rid in list(self.active):
            self.manager.free(rid)
        for rid in list(self.prefilling):
            self.manager.free(rid)
        self.active.clear()
        self.prefilling.clear()
        self.queue.clear()
        self._drain_reason = None
        # re-arm escalation for the recovered attempt (the training
        # loop's per-attempt escalation.reset() discipline): a stall
        # latched before the crash must not blind the next run, and a
        # NEW alarm there must escalate again
        self._esc_handled = False
        if self.escalation is not None:
            self.escalation.reset()
        self._event("crash_reset", **lost)
        return lost

    def _reserved_blocks(self) -> int:
        """Blocks the free pool already owes to in-flight requests
        (active AND mid-prefill): each may still grow to its worst
        case (prompt + max_new), only the pages it has claimed so far
        left the free list, and a request whose next append will
        copy-on-write a shared page owes one replacement block too."""
        total = 0
        in_flight = list(self.active.items()) \
            + [(rid, job.req) for rid, job in self.prefilling.items()]
        for rid, req in in_flight:
            worst = self.cache_cfg.blocks_for(
                len(req.prompt) + req.max_new_tokens)
            total += max(0, worst - self.manager.num_pages(rid))
            if self.prefix_share:
                total += self.manager.pending_cow_blocks(rid)
        return total

    def _cow_copy(self, src: int, dst: int) -> None:
        """Device-side copy-on-write of one page, mirrored into the
        draft cache (same block ids by construction)."""
        fn = self._cow_fn("target")
        self.cache = fn(self.cache, jnp.int32(src), jnp.int32(dst))
        if self.draft_cache is not None:
            fnd = self._cow_fn("draft")
            self.draft_cache = fnd(self.draft_cache, jnp.int32(src),
                                   jnp.int32(dst))
        self._event("cow_block", src=int(src), dst=int(dst))

    def _admit(self, req: Request,
               prefix: Optional[PrefixMatch] = None) -> None:
        p_len = len(req.prompt)
        t0 = self._clock()
        if prefix is None:          # step() passes its admission match
            prefix = self.manager.match_prefix(req.prompt)
        self.manager.alloc(req.rid, p_len,
                           shared_blocks=prefix.blocks)
        if prefix.warm:
            self._warm_admissions += 1
            self._prefix_hit_tokens += prefix.tokens
        if prefix.cow:
            # full-prompt warm hit: the tail (the final token) will be
            # re-written into the last mapped page — copy it private
            # before any write touches it
            cow = self.manager.make_private(req.rid,
                                            len(prefix.blocks) - 1)
            if cow is not None:
                self._cow_copy(*cow)
        req.admitted_at_step = self.steps
        if not prefix.warm and not self._chunking:
            # cold whole-prompt path: one flash-forward prefill (plus
            # the draft's, under speculation) covers the prompt
            bs = self.cache_cfg.block_size
            pages_bucket = self.ladder.pick_pages(
                self.cache_cfg.blocks_for(p_len))
            s_pad = pages_bucket * bs
            bt = self.manager.block_table(req.rid, s_pad // bs)
            tokens = np.zeros(s_pad, np.int32)
            tokens[:p_len] = req.prompt
            fn = self._prefill_fn(s_pad)
            self.cache, next_token = fn(
                self.weights, self.cache, jnp.asarray(tokens),
                jnp.int32(p_len), jnp.asarray(bt))
            if self.draft_cache is not None:
                dfn = self._draft_prefill_fn(s_pad)
                self.draft_cache, _ = dfn(
                    self.draft_weights, self.draft_cache,
                    jnp.asarray(tokens), jnp.int32(p_len),
                    jnp.asarray(bt))
            first = int(next_token)      # explicit host sync: the
            # admission boundary needs the token to seed the decode
            dt = self._clock() - t0
            req.out_tokens.append(first)
            req.token_latency_s.append(dt)
            self._latencies.append(dt)
            self.active[req.rid] = req
            self.prefill_tokens += p_len
            self.manager.register_prefix(req.rid, req.prompt)
            # request_admitted (queue wait) + request_first_token
            # (TTFT): t0 is the instant queue wait ended
            self.metrics.on_admit(req, self.steps, t0, dt,
                                  prompt_len=p_len, s_pad=s_pad)
            return
        # chunk path: warm tail, and/or chunked prefill.  The job owns
        # its blocks already; k/v streams in via extend-step chunks —
        # one per tick when chunking is on (interleaved with decode,
        # so a long admission cannot monopolize a tick), or drained
        # right here for a warm tail with chunking off.
        job = _PrefillJob(req=req,
                          tokens=np.asarray(req.prompt, np.int32),
                          written=prefix.tokens, start=prefix.tokens,
                          admit_t=t0)
        self.metrics.on_admit(req, self.steps, t0, None,
                              prompt_len=p_len,
                              warm_tokens=prefix.tokens)
        if self._chunking:
            self.prefilling[req.rid] = job
            return
        while not self._prefill_step(job):
            pass

    def _prefill_step(self, job: _PrefillJob) -> bool:
        """Write one prefill chunk of ``job``'s prompt (valid tokens
        back-aligned in the chunk bucket, front padding writing to the
        dump page); on the chunk that completes the prompt, fetch the
        first generated token and move the request into the decode
        set.  Returns True when the prefill finished."""
        req = job.req
        p_len = len(job.tokens)
        bs = self.cache_cfg.block_size
        rem = p_len - job.written
        ct = self.ladder.pick_chunk(rem, bs)
        n = min(rem, ct)
        pb = self.ladder.pick_pages(self.manager.num_pages(req.rid))
        bt = self.manager.block_table(req.rid, pb)
        table = self.manager.blocks(req.rid)
        toks = np.zeros(ct, np.int32)
        wb = np.full(ct, DUMP_BLOCK, np.int32)
        wo = np.zeros(ct, np.int32)
        toks[ct - n:] = job.tokens[job.written:job.written + n]
        for j in range(n):
            p = job.written + j
            wb[ct - n + j] = table[p // bs]
            wo[ct - n + j] = p % bs
        sl = np.asarray([job.written + n], np.int32)
        t0 = self._clock()
        fn = self._extend_fn(1, ct, pb)
        self.cache, out = fn(
            self.weights, self.cache, jnp.asarray(toks[None]),
            jnp.asarray(bt[None]), jnp.asarray(sl),
            jnp.asarray(wb[None]), jnp.asarray(wo[None]))
        if self.draft_cache is not None:
            dfn = self._draft_extend_fn(1, ct, pb)
            self.draft_cache, _ = dfn(
                self.draft_weights, self.draft_cache,
                jnp.asarray(toks[None]), jnp.asarray(bt[None]),
                jnp.asarray(sl), jnp.asarray(wb[None]),
                jnp.asarray(wo[None]))
        job.written += n
        self.prefill_chunks += 1
        done = job.written >= p_len
        first = int(np.asarray(out)[0, -1]) if done else None
        # ^ the only host sync: non-final chunks stay async
        dt = self._clock() - t0
        self._event("prefill_chunk", value=round(dt * 1e3, 3),
                    rid=str(req.rid), tokens=int(n),
                    written=int(job.written), prompt_len=p_len)
        if done:
            req.out_tokens.append(first)
            req.token_latency_s.append(dt)
            self._latencies.append(dt)
            self.active[req.rid] = req
            self.prefill_tokens += p_len - job.start
            self.manager.register_prefix(req.rid, job.tokens)
            self.metrics.on_first_token(req, self.steps,
                                        self._clock())
        return done

    def _terminate(self, req: Request, terminal: str, *,
                   where: str = "queued") -> None:
        """The ONE terminal transition: free owned blocks (``where`` in
        active/prefilling; queued requests own none), move the request
        into ``done``, bump the per-reason counter, emit the terminal
        ``request_done`` lifecycle event, and journal it — every
        terminal path (finished, drain-preempted, deadline, shed) goes
        through here, so none can skip the accounting."""
        req.terminal = terminal
        if where == "active":
            self.manager.free(req.rid)
            del self.active[req.rid]
        elif where == "prefilling":
            self.manager.free(req.rid)
            del self.prefilling[req.rid]
        self.done.append(req)
        if terminal == "finished":
            self._done_count += 1
        elif terminal == "preempted":
            self._preempted_count += 1
        elif terminal == "shed":
            self._shed_count += 1
        else:                       # deadline / deadline_exceeded
            self._deadline_count += 1
        self._done_tokens += len(req.out_tokens)
        # terminal lifecycle event (request_done) with the full
        # queued/prefill/decode breakdown
        self.metrics.on_done(req, self.steps)
        if self.journal is not None:
            self.journal.record_terminal(req, self.steps)

    def _finish(self, req: Request) -> None:
        self._terminate(req, "preempted" if req.preempted
                        else "finished", where="active")

    def _terminating(self) -> bool:
        return (self.autoresume is not None
                and self.autoresume.termination_requested())

    # --- deadlines, shedding, escalation (ISSUE-13) -------------------

    def _past_deadline(self, req: Request, now: float) -> bool:
        if req.deadline_ms is None or req.deadline_ms <= 0 \
                or req.submit_t is None:
            return False
        return (now - req.submit_t) * 1e3 >= req.deadline_ms

    def _expire_deadlines(self) -> None:
        """Tick-boundary deadline enforcement.  Runs at the START of a
        tick, so a deadline crossed during tick K's decode is noticed
        at the K+1 boundary — AFTER tick K's tokens were delivered
        (the deadline-at-boundary semantics the tests pin: expiry
        exactly on a boundary never claws back a delivered token)."""
        if not self._deadlines_active:
            return
        now = self._clock()
        if self.queue:
            keep: deque = deque()
            while self.queue:
                q = self.queue.popleft()
                if self._past_deadline(q, now):
                    self._event("deadline_exceeded", rid=str(q.rid),
                                where="queued",
                                deadline_ms=q.deadline_ms)
                    self._terminate(q, "deadline_exceeded")
                else:
                    keep.append(q)
            self.queue = keep
        for rid in [r for r, q in list(self.active.items())
                    if self._past_deadline(q, now)]:
            q = self.active[rid]
            self._event("deadline_exceeded", rid=str(rid),
                        where="active", deadline_ms=q.deadline_ms,
                        tokens=len(q.out_tokens))
            self._terminate(q, "deadline", where="active")
        for rid in [r for r, j in list(self.prefilling.items())
                    if self._past_deadline(j.req, now)]:
            q = self.prefilling[rid].req
            self._event("deadline_exceeded", rid=str(rid),
                        where="prefilling", deadline_ms=q.deadline_ms)
            self._terminate(q, "deadline", where="prefilling")

    def _load(self) -> Tuple[float, int]:
        """(pool pressure, admission backlog) for the shed policy.
        Pool pressure counts only what an allocation could NOT draw on
        — idle shared pages are reclaimable, so they are headroom, not
        pressure."""
        usable = max(1, self.cache_cfg.usable_blocks)
        frac = 1.0 - self.manager.available_blocks / usable
        return frac, len(self.queue) + len(self.prefilling)

    def _shed_victim(self, *, from_pool: bool):
        """The next victim under pressure: lowest priority first, then
        shortest progress.  Queue pressure sheds BACKLOG only — queued
        work (zero sunk cost) before mid-prefill jobs, never a running
        decode, which costs paid-for progress without moving the
        backlog signal at all.  Pool pressure must shed block OWNERS —
        mid-prefill jobs (no tokens yet) before running requests,
        fewest generated tokens first."""
        if not from_pool:
            if self.queue:
                # newest submission at equal priority: the latest
                # arrival has waited least
                victim = min(
                    enumerate(self.queue),
                    key=lambda iq: (iq[1].priority, -iq[0]))
                del self.queue[victim[0]]
                return "queued", victim[1]
            if self.prefilling:
                rid = min(self.prefilling,
                          key=lambda r: (
                              self.prefilling[r].req.priority,
                              self.prefilling[r].written
                              - self.prefilling[r].start))
                return "prefilling", self.prefilling[rid].req
            return None
        # progress = prefill chunks written for a mid-prefill job,
        # generated tokens for a running one — least paid-for work
        # dies first
        owners = [("prefilling", j.req, j.written - j.start)
                  for j in self.prefilling.values()] \
            + [("active", q, len(q.out_tokens))
               for q in self.active.values()]
        if not owners:
            return None
        where, req, _ = min(owners,
                            key=lambda w: (w[1].priority, w[2]))
        return where, req

    def _apply_shedding(self) -> bool:
        """Advance the shed policy's hysteresis state and, while
        engaged, shed lowest-priority / shortest-progress work until
        the load drops below the LOW-water marks.  Returns whether
        shedding is engaged (the engine admits nothing while it is —
        the no-flap half of the hysteresis contract)."""
        if self.shed is None or not self.shed.enabled:
            return False
        pf, qd = self._load()
        if not self.shed.update(pool_frac=pf, queue_depth=qd):
            return False
        while True:
            pf, qd = self._load()
            if not self.shed.over_low(pf, qd):
                break
            over_queue = self.shed.queue_hw > 0 \
                and qd > self.shed.queue_lw
            victim = self._shed_victim(from_pool=not over_queue)
            if victim is None:
                break
            where, req = victim
            self._event("request_shed", rid=str(req.rid), where=where,
                        priority=req.priority,
                        tokens=len(req.out_tokens),
                        pool_frac=round(pf, 4), queue_depth=qd)
            self._terminate(req, "shed", where=where)
        # shedding may have dropped the load through the band already
        pf, qd = self._load()
        self.shed.update(pool_frac=pf, queue_depth=qd)
        return self.shed.engaged

    def _poll_escalation(self) -> None:
        """Tick-boundary escalation poll: a watchdog alarm the serve
        policy maps to ``snapshot_then_drain`` (the serve default for
        ``stall`` — never ``ignore`` a wedged decode) dumps ONE
        structured engine snapshot and latches a drain for the next
        boundary; ``abort`` actions raise
        :class:`~apex_tpu.resilience.EscalationAbort` for the
        supervisor (:func:`~.resilience.run_serving`) to restart."""
        if self.escalation is None or self._esc_handled:
            return
        esc = self.escalation.pending()
        if esc is None:
            return
        self._esc_handled = True
        from ..resilience import SNAPSHOT_THEN_DRAIN, EscalationAbort

        if esc.action == SNAPSHOT_THEN_DRAIN:
            if self.monitor is not None:
                self.monitor.event(
                    "serving", "engine_snapshot", step=self.steps,
                    reason=f"escalation:{esc.alarm}",
                    **self.snapshot_state())
            self._event("escalation_drain", alarm=esc.alarm,
                        action=esc.action)
            self._drain_reason = f"escalation:{esc.alarm}"
            return
        raise EscalationAbort(esc.alarm, esc.action, step=self.steps)

    def _drain(self, source: str) -> None:
        """Stop serving NOW, accounting for every request: in-flight
        generation abandoned cleanly (blocks freed), mid-prefill jobs
        dropped (no first token — the whole post-admission wall reads
        as prefill), queued-never-admitted requests closed with
        queue-wait-only chains.  Every submitted request ends terminal
        ``preempted`` — nothing vanishes.  A request that already
        emitted its full token budget is evicted as ``finished``
        first: completing during the very tick that latched the drain
        must not read back as preemption."""
        for rid in [r for r, q in self.active.items() if q.done]:
            self._finish(self.active[rid])
        for rid in list(self.active):
            q = self.active[rid]
            q.preempted = True
            self._terminate(q, "preempted", where="active")
        for rid in list(self.prefilling):
            q = self.prefilling[rid].req
            q.preempted = True
            self._terminate(q, "preempted", where="prefilling")
        while self.queue:
            q = self.queue.popleft()
            q.preempted = True
            self._terminate(q, "preempted")
        self._drain_reason = None
        self._event("serve_preempt", source=source)

    # --- the engine tick ----------------------------------------------

    def step(self) -> int:
        """One continuous-batching tick: poll the escalation policy,
        enforce deadlines (boundary semantics: after the previous
        tick's tokens were delivered), evict finished, apply the shed
        policy, advance ONE pending prefill chunk (chunked prefill
        interleaves admission cost with decode — a long prompt never
        monopolizes a tick), admit (unless draining, shedding, or a
        ``reject_alloc`` fault simulates pool exhaustion), run one
        bucketed decode step — speculative when ``speculate_k > 0`` —
        over every active request.  Returns the number of tokens
        generated this tick."""
        self._poll_escalation()
        # finished requests leave BEFORE deadline enforcement: a
        # request whose last token arrived within its deadline must
        # end terminal "finished" even when the next boundary lands
        # past the deadline
        for rid in [r for r, q in self.active.items() if q.done]:
            self._finish(self.active[rid])
        self._expire_deadlines()
        shedding = self._apply_shedding()
        advanced_prefill = False
        if self.prefilling:
            # FIFO: the oldest admission's next chunk, exactly one
            # per tick
            rid = next(iter(self.prefilling))
            if self._prefill_step(self.prefilling[rid]):
                del self.prefilling[rid]
            advanced_prefill = True
        admit = (not self._terminating()
                 and self._drain_reason is None and not shedding)
        if admit and self.queue and self.fault is not None \
                and self.fault.reject_alloc(self.steps):
            # simulated pool exhaustion: this tick admits nothing,
            # exactly once per armed spec (the serve fault drill).
            # Polled only when work is actually queued, so a spec
            # landing on an idle tick defers to one it can affect.
            self._event("alloc_rejected", injected=True)
            admit = False
        if admit:
            while (self.queue
                   and (len(self.active) + len(self.prefilling)
                        < self.ladder.max_batch)):
                # one match per admission attempt: the PrefixMatch
                # feeds both the reservation check and the admission
                # itself (hashing the prompt every tick for a blocked
                # queue head would sit on the hot path for nothing)
                req = self.queue[0]
                prefix = self.manager.match_prefix(req.prompt)
                if not self.manager.can_admit(
                        len(req.prompt), req.max_new_tokens,
                        reserved_blocks=self._reserved_blocks(),
                        prefix=prefix):
                    break
                self._admit(self.queue.popleft(), prefix=prefix)
        # requests may finish at admission (max_new_tokens == 1)
        for rid in [r for r, q in self.active.items() if q.done]:
            self._finish(self.active[rid])
        if not self.active:
            if advanced_prefill:
                # a pure-prefill tick still crosses the telemetry
                # boundary: gauges, snapshot poll, and the watchdog
                # stall heartbeat must see chunked-prefill progress
                # even before anything decodes
                self._tick_tail(0, 0, 0)
            return 0
        reqs = [self.active[r] for r in sorted(self.active,
                                               key=lambda r: str(r))]
        if self.speculate_k > 0:
            return self._spec_tick(reqs)
        return self._decode_tick(reqs)

    def _append_slot(self, req: Request):
        """One KV append with the copy-on-write guard: a slot landing
        in a shared page (the owner's registered partial prompt
        block) copies the page private first — append never mutates
        a shared page."""
        if self.prefix_share:
            cow = self.manager.cow_for_append(req.rid)
            if cow is not None:
                self._cow_copy(*cow)
        return self.manager.append(req.rid)

    def _decode_tick(self, reqs: List[Request]) -> int:
        n = len(reqs)
        bb = self.ladder.pick_batch(n)
        slots = [self._append_slot(q) for q in reqs]
        pb = self.ladder.pick_pages(
            max(self.manager.num_pages(q.rid) for q in reqs))
        tokens = np.zeros(bb, np.int32)
        positions = np.zeros(bb, np.int32)
        seq_lens = np.zeros(bb, np.int32)
        wb = np.full(bb, DUMP_BLOCK, np.int32)
        wo = np.zeros(bb, np.int32)
        bt = np.full((bb, pb), DUMP_BLOCK, np.int32)
        for i, (q, (blk, off)) in enumerate(zip(reqs, slots)):
            new_len = self.manager.seq_len(q.rid)   # post-append
            tokens[i] = q.out_tokens[-1]
            positions[i] = new_len - 1
            seq_lens[i] = new_len
            wb[i], wo[i] = blk, off
            bt[i] = self.manager.block_table(q.rid, pb)
        fn = self._decode_fn(bb, pb)
        t0 = self._clock()
        self.cache, next_tokens = fn(
            self.weights, self.cache, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt),
            jnp.asarray(seq_lens), jnp.asarray(wb), jnp.asarray(wo))
        out = np.asarray(next_tokens)    # the tick's ONE device fetch
        dt = self._clock() - t0
        for i, q in enumerate(reqs):
            q.out_tokens.append(int(out[i]))
            q.token_latency_s.append(dt)
            self._latencies.append(dt)
        self.decode_wall_s += dt
        self.decode_tokens += n
        self.steps += 1
        self._event("decode_step", value=round(dt * 1e3, 3),
                    batch=n, batch_bucket=bb, pages_bucket=pb)
        self._tick_tail(n, bb, pb)
        return n

    def _spec_tick(self, reqs: List[Request]) -> int:
        """One speculative tick: the draft proposes K tokens row by
        row (K small decode dispatches), the target scores all K+1
        positions in ONE multi-token extend call, and greedy-match
        acceptance keeps the longest draft prefix agreeing with the
        target plus one corrected token — so every emitted token is
        exactly what non-speculative greedy decode would have
        produced, and a tick advances each row by 1..K+1 tokens.
        Rejected positions roll the KV write cursor back through the
        manager's (block, offset) slot accounting; the draft cache
        catches up its one unwritten position on full acceptance so
        the next tick's proposals stay on-policy."""
        K = self.speculate_k
        T = K + 1
        n = len(reqs)
        bb = self.ladder.pick_batch(n)
        base = np.zeros(bb, np.int32)
        caps = np.zeros(bb, np.int32)
        slots: List[List[Tuple[int, int]]] = []
        for i, q in enumerate(reqs):
            base[i] = self.manager.seq_len(q.rid)
            # a row near its token budget writes fewer real slots —
            # the reservation contract (prompt + max_new) caps the
            # pages a tick may claim, so overshoot positions go to
            # the dump page and their (unused) logits are garbage
            caps[i] = max(1, min(T, q.max_new_tokens
                                 - len(q.out_tokens)))
            row = []
            for j in range(int(caps[i])):
                if j == 0:
                    row.append(self._append_slot(q))
                else:
                    row.append(self.manager.append(q.rid))
            slots.append(row)
        pb = self.ladder.pick_pages(
            max(self.manager.num_pages(q.rid) for q in reqs))
        bt = np.full((bb, pb), DUMP_BLOCK, np.int32)
        for i, q in enumerate(reqs):
            bt[i] = self.manager.block_table(q.rid, pb)
        bt_j = jnp.asarray(bt)
        t0 = self._clock()
        # --- draft proposals: K sequential single-token steps -------
        d = np.zeros((bb, K), np.int32)
        prev = np.zeros(bb, np.int32)
        for i, q in enumerate(reqs):
            prev[i] = q.out_tokens[-1]
        for k in range(1, K + 1):
            toks = prev if k == 1 else d[:, k - 2]
            pos = np.zeros(bb, np.int32)
            sl = np.zeros(bb, np.int32)
            wbk = np.full(bb, DUMP_BLOCK, np.int32)
            wok = np.zeros(bb, np.int32)
            for i in range(n):
                pos[i] = base[i] + k - 1
                sl[i] = base[i] + k
                if k - 1 < caps[i]:
                    wbk[i], wok[i] = slots[i][k - 1]
            dfn = self._draft_decode_fn(bb, pb)
            self.draft_cache, nt = dfn(
                self.draft_weights, self.draft_cache,
                jnp.asarray(toks), jnp.asarray(pos), bt_j,
                jnp.asarray(sl), jnp.asarray(wbk), jnp.asarray(wok))
            d[:, k - 1] = np.asarray(nt)
        # --- target verification: ONE teacher-forced extend call ----
        vt = np.zeros((bb, T), np.int32)
        wbv = np.full((bb, T), DUMP_BLOCK, np.int32)
        wov = np.zeros((bb, T), np.int32)
        slv = np.zeros(bb, np.int32)
        for i in range(n):
            vt[i, 0] = prev[i]
            vt[i, 1:] = d[i]
            slv[i] = base[i] + T
            for j, (blk, off) in enumerate(slots[i]):
                wbv[i, j], wov[i, j] = blk, off
        fn = self._extend_fn(bb, T, pb)
        self.cache, out = fn(
            self.weights, self.cache, jnp.asarray(vt), bt_j,
            jnp.asarray(slv), jnp.asarray(wbv), jnp.asarray(wov))
        a = np.asarray(out)              # (bb, T) — the tick's fetch
        # --- greedy-match acceptance + rollback ---------------------
        gained = 0
        full_rows: List[int] = []
        keeps: List[int] = []
        tick_proposed = 0
        tick_accepted = 0
        for i, q in enumerate(reqs):
            cap = int(caps[i])
            emit = [int(a[i, 0])]
            j = 0
            while j < cap - 1 and int(d[i, j]) == emit[-1]:
                emit.append(int(a[i, j + 1]))
                j += 1
            tick_proposed += max(0, cap - 1)
            tick_accepted += j
            if q.eos_token is not None and q.eos_token in emit:
                emit = emit[:emit.index(q.eos_token) + 1]
            keep = len(emit)
            if keep < cap:
                self.manager.truncate(q.rid, int(base[i]) + keep)
            if keep == T:
                full_rows.append(i)
            q.out_tokens.extend(emit)
            keeps.append(keep)
            gained += keep
        dt = self._clock() - t0
        # amortize the tick wall over each row's gained tokens — the
        # tokens arrive together, so the honest per-token figure is
        # the tick cost split across them (the same population
        # ServeSummary.itl draws from)
        for q, keep in zip(reqs, keeps):
            share = dt / keep
            for _ in range(keep):
                q.token_latency_s.append(share)
                self._latencies.append(share)
        self.spec_proposed += tick_proposed
        self.spec_accepted += tick_accepted
        self.metrics.gauges.on_spec(tick_proposed, tick_accepted)
        if self.spec_governor is not None \
                and self.spec_governor.observe(tick_proposed,
                                               tick_accepted):
            # degraded mode: sustained verify mismatch (a drifted or
            # stalled draft) — turn speculation off for the rest of
            # the run.  Alarm + gauge, never a crash; output identity
            # is preserved (speculative greedy == greedy), so the only
            # observable change is ITL returning to one token/tick.
            self.spec_disabled = True
            self.speculate_k = 0
            if self.monitor is not None:
                self.monitor.event(
                    "alarm", "spec_disabled", step=self.steps,
                    low_streak=self.spec_governor.window,
                    min_accept=self.spec_governor.min_accept)
        # --- draft catch-up: on full acceptance the draft never wrote
        # position base + K (the target's verify did) — one masked
        # draft step fills it so next tick's proposals read real k/v
        if full_rows:
            toks = np.zeros(bb, np.int32)
            pos = np.zeros(bb, np.int32)
            sl = np.zeros(bb, np.int32)
            wbk = np.full(bb, DUMP_BLOCK, np.int32)
            wok = np.zeros(bb, np.int32)
            for i in full_rows:
                toks[i] = reqs[i].out_tokens[-2]     # the token AT
                pos[i] = base[i] + K                 # position base+K
                sl[i] = base[i] + T
                wbk[i], wok[i] = slots[i][K]
            dfn = self._draft_decode_fn(bb, pb)
            self.draft_cache, _ = dfn(
                self.draft_weights, self.draft_cache,
                jnp.asarray(toks), jnp.asarray(pos), bt_j,
                jnp.asarray(sl), jnp.asarray(wbk), jnp.asarray(wok))
        self.decode_wall_s += dt
        self.decode_tokens += gained
        self.steps += 1
        self._event("decode_step", value=round(dt * 1e3, 3),
                    batch=n, batch_bucket=bb, pages_bucket=pb,
                    spec_proposed=tick_proposed,
                    spec_accepted=tick_accepted, tokens=gained)
        self._tick_tail(n, bb, pb)
        return gained

    def _tick_tail(self, batch: int, bb: int, pb: int) -> None:
        """Per-tick telemetry boundary: engine gauges on the
        registered cadence, snapshot-trigger poll, and the watchdog
        stall heartbeat — all host bookkeeping the engine already
        holds, after the tick's one device fetch."""
        levels = dict(
            batch=batch, batch_bucket=bb, pages_bucket=pb,
            free_blocks=self.manager.free_blocks,
            used_blocks=self.manager.used_blocks,
            reserved_blocks=self._reserved_blocks(),
            shared_blocks=self.manager.shared_blocks,
            pool_blocks=self.cache_cfg.usable_blocks,
            queue_depth=len(self.queue),
            prefilling=len(self.prefilling),
            compiles=sum(self._compiles.values()))
        if self.shed is not None and self.shed.enabled:
            levels["shed_engaged"] = self.shed.engaged
        if self.spec_disabled:
            levels["spec_disabled"] = True
        self.metrics.on_tick(self.steps, **levels)
        if self.journal is not None and self.active:
            # ONE aggregated progress record per tick (not one write
            # per request — the journal flushes per line, and O(batch)
            # syscalls per generated token would tax ITL): the replay
            # ledger's observability record (replay correctness rides
            # the submit/terminal records — greedy decode regenerates)
            self.journal.record_progress(
                {rid: len(q.out_tokens)
                 for rid, q in self.active.items()}, self.steps)
        if self.snapshot is not None:
            self.snapshot.poll(self.steps, self.snapshot_state,
                               self.monitor)
        # the serve loop's stall heartbeat: each tick feeds the same
        # Watchdog the training loops drive through StepMonitor, so a
        # wedged decode step raises the once-per-episode stall alarm
        # (with the optional jax.profiler capture) mid-serve
        wd = getattr(self.monitor, "watchdog", None)
        if wd is not None:
            wd.observe_step(self.steps)
        # ISSUE-17: SLO burn evaluation, then one lock-free exporter
        # publish — SLO first so the published /healthz already
        # reflects an episode that opened this tick
        if self.slo is not None:
            self._poll_slo()
        if self.exporter is not None:
            self._publish_exporter()

    def _poll_slo(self) -> None:
        """Per-tick SLO boundary: lazily emit the objective-
        definition event (guaranteed to precede any burn — the
        pairing ``trace_check --serve`` asserts), then forward the
        tracker's episode transitions: ``burn`` through the
        watchdog's alarm machinery (sink + escalation hook, once per
        episode — the tracker latches), ``recovered`` as a plain
        ``slo`` event."""
        if not self._slo_defined:
            self._slo_defined = True
            if self.monitor is not None:
                self.monitor.event("slo", "slo_objectives",
                                   step=self.steps,
                                   **self.slo.objectives_attrs())
        wd = getattr(self.monitor, "watchdog", None)
        for tr in self.slo.evaluate(self.steps):
            action = tr.pop("action")
            if action == "burn":
                if wd is not None:
                    wd.alarm("slo_burn", value=tr["burn_fast"],
                             step=self.steps, **tr)
                elif self.monitor is not None:
                    self.monitor.event("alarm", "slo_burn",
                                       value=tr["burn_fast"],
                                       step=self.steps, **tr)
            elif self.monitor is not None:
                self.monitor.event("slo", "slo_recovered",
                                   value=tr["burn_fast"],
                                   step=self.steps, **tr)

    def health_state(self, *, drained: bool = False) -> Dict[str, Any]:
        """The /healthz payload: ``ok`` is False while the engine is
        draining (SIGTERM / escalation / API), after an escalation
        was handled, or while any SLO episode burns.  Shedding is
        DEGRADED-but-serving — reported, still 200 (the healthz
        semantics table in docs/api/observability.md)."""
        draining = bool(drained or self._drain_reason is not None
                        or self._terminating())
        shed = bool(self.shed.engaged) if (
            self.shed is not None and self.shed.enabled) else False
        burning = list(self.slo.burning) if self.slo is not None \
            else []
        ok = not (draining or self._esc_handled or burning)
        status = ("draining" if draining
                  else "escalated" if self._esc_handled
                  else "slo_burning" if burning
                  else "shedding" if shed else "ok")
        return {
            "ok": ok, "status": status, "tick": self.steps,
            "replica": self.replica_id,
            "draining": draining, "shed_engaged": shed,
            "escalated": self._esc_handled,
            "slo_burning": burning,
            "active": len(self.active), "queued": len(self.queue),
        }

    def export_registry(self,
                        registry: Optional[MetricsRegistry] = None
                        ) -> MetricsRegistry:
        """Adapter shim: fill a :class:`~apex_tpu.monitor.export.
        MetricsRegistry` from bookkeeping the engine already holds —
        the gauge layer's last-tick levels
        (``EngineGauges.router_snapshot()``, no cadence advance), the
        metrics layer's lifetime terminal/rejection tallies, cached
        latency quantiles, SLO episode counters, and the watchdog's
        fired-alarm counts.  No second bookkeeping path and no device
        fetch; counters mirror (``set``) the cumulative values, they
        never re-count.  A fleet passes a shared ``registry`` so N
        replicas land in one exposition document under their
        ``replica`` labels."""
        reg = registry if registry is not None else MetricsRegistry()
        lbl = ({"replica": self.replica_id}
               if self.replica_id is not None else {})
        m = self.metrics
        c = reg.counter("apex_tpu_serve_requests_total",
                        "Terminal requests by terminal reason.")
        for terminal, n in sorted(m.terminals.items()):
            c.set(n, terminal=terminal, **lbl)
        gen = self._done_tokens \
            + sum(len(q.out_tokens) for q in self.active.values())
        reg.counter("apex_tpu_serve_tokens_total",
                    "Generated tokens over terminal requests."
                    ).set(self._done_tokens, **lbl)
        reg.gauge("apex_tpu_serve_tokens_live",
                  "Generated tokens including in-flight requests."
                  ).set(gen, **lbl)
        reg.counter("apex_tpu_serve_prefill_tokens_total",
                    "Prompt tokens prefilled."
                    ).set(self.prefill_tokens, **lbl)
        rej = reg.counter("apex_tpu_serve_rejected_total",
                          "Submits the engine refused, by reason.")
        for reason, n in sorted(m.rejected.items()):
            rej.set(n, reason=reason, **lbl)
        snap = m.gauges.router_snapshot()
        for key, help_text in (
                ("queue_depth", "Admission queue depth at the last "
                                "tick."),
                ("free_blocks", "Free KV pool blocks at the last "
                                "tick."),
                ("used_blocks", "Used KV pool blocks at the last "
                                "tick."),
                ("reserved_blocks", "Blocks reserved by admitted "
                                    "requests at the last tick."),
                ("pool_blocks", "Usable KV pool blocks."),
                ("prefilling", "Requests mid-chunked-prefill at the "
                               "last tick."),
                ("batch", "Decode batch at the last tick."),
                ("used_blocks_high_water", "Used-block high water."),
                ("last_tick", "Engine tick of the last gauge "
                              "window.")):
            if key in snap:
                name = ("apex_tpu_serve_tick" if key == "last_tick"
                        else f"apex_tpu_serve_{key}")
                reg.gauge(name, help_text).set(
                    float(snap[key] or 0), **lbl)
        reg.counter("apex_tpu_serve_compiles_total",
                    "Cumulative compiled-program count."
                    ).set(sum(self._compiles.values()), **lbl)
        reg.gauge("apex_tpu_serve_shed_engaged",
                  "1 while the hysteresis shed policy is engaged."
                  ).set(1.0 if (self.shed is not None
                                and self.shed.engaged) else 0.0,
                        **lbl)
        pct = m.percentiles_cached()
        q = reg.gauge("apex_tpu_serve_latency_ms",
                      "Serving latency quantiles over the bounded "
                      "sample windows.")
        for series in ("queue_wait", "ttft", "itl"):
            for quant in ("p50", "p99"):
                v = pct.get(f"{series}_{quant}_ms")
                if v is not None:
                    q.set(v, series=series, quantile=quant, **lbl)
        if self.slo is not None:
            reg.counter("apex_tpu_slo_burn_episodes_total",
                        "SLO burn-rate episodes tripped."
                        ).set(self.slo.episodes, **lbl)
            reg.gauge("apex_tpu_slo_burning",
                      "Currently-burning SLO episodes."
                      ).set(len(self.slo.burning), **lbl)
        wd = getattr(self.monitor, "watchdog", None)
        if wd is not None and hasattr(wd, "alarm_counts"):
            a = reg.counter("apex_tpu_alarm_episodes_total",
                            "Watchdog alarm episodes fired, by "
                            "class.")
            for name, n in sorted(wd.alarm_counts().items()):
                a.set(n, alarm=name, **lbl)
        return reg

    def _publish_exporter(self, *, drained: bool = False) -> None:
        """One lock-free exporter publish: registry + health + varz,
        all frozen at this tick.  Telemetry must never kill the
        serve."""
        try:
            self.exporter.publish(
                self.export_registry(), tick=self.steps,
                health=self.health_state(drained=drained),
                varz=self.snapshot_state())
        except Exception as e:
            logger.warning("exporter publish failed: %s",
                           str(e)[:160])

    def tokens_digest(self) -> str:
        """Deterministic digest of every request's output token
        stream — the cheap cross-run identity proof the CI spec leg
        compares against the plain leg (same submitted trace + same
        digest == token-for-token identical output)."""
        import hashlib

        h = hashlib.md5()
        allq = list(self.done) + list(self.active.values())
        for q in sorted(allq, key=lambda q: str(q.rid)):
            h.update(f"{q.rid}:"
                     f"{','.join(map(str, q.out_tokens))};".encode())
        return h.hexdigest()[:12]

    def digest_rows(self) -> Dict[str, List[int]]:
        """The raw material of :meth:`tokens_digest` as data —
        ``{rid: output tokens}`` for every request this engine holds.
        The process-fleet supervisor (ISSUE-18) merges these rows
        across replicas AND across a restarted replica's journal
        terminals into ONE routing-invariant fleet digest: greedy
        decode is batching/interleaving-invariant (the PR 15 sweep's
        proof), so the merged digest is identical no matter which
        replica served which rid or how a crash reshuffled them."""
        allq = list(self.done) + list(self.active.values())
        return {str(q.rid): [int(t) for t in q.out_tokens]
                for q in allq}

    def router_snapshot(self) -> Dict[str, Any]:
        """The cheap per-replica struct a fleet router load-balances
        on (ISSUE-14): pool headroom (free + reclaimable-idle blocks,
        net reservations), backlog (queue depth + mid-prefill jobs +
        running batch), shed state, the shared-prefix index's chain
        keys for sticky warm routing, and the gauge layer's last-tick
        view — all host bookkeeping the engine already holds, one
        dict, no device traffic and no reaching into engine
        internals."""
        snap = {
            "replica": self.replica_id,
            "tick": self.steps,
            "free_blocks": self.manager.free_blocks,
            "available_blocks": self.manager.available_blocks,
            "reserved_blocks": self._reserved_blocks(),
            "pool_blocks": self.cache_cfg.usable_blocks,
            "queue_depth": len(self.queue),
            "active": len(self.active),
            "prefilling": len(self.prefilling),
            "shed_engaged": bool(self.shed.engaged
                                 if self.shed is not None else False),
            # active SLO burn episodes ("class/dimension" strings) —
            # the per-class QoS admission door (ISSUE-18) gates on
            # these fleet-wide, so they ride the same poll
            "slo_burning": (list(self.slo.burning())
                            if self.slo is not None else []),
            "warm_prefix_keys": self.manager.prefix_keys(),
            "gauges": self.metrics.gauges.router_snapshot(),
            # cumulative counters the FleetAggregator differentiates
            # into rate series (tokens/tick, compile deltas) against
            # the measured tick delta — same host bookkeeping, one
            # dict, still no device traffic
            "tokens_generated": self._done_tokens
            + sum(len(q.out_tokens) for q in self.active.values()),
            "compiles": sum(self._compiles.values()),
        }
        return snap

    def swap_weights(self, weights: GPTServingWeights, *,
                     draft_weights=None) -> None:
        """Replace the serving weights IN PLACE on an idle engine —
        the per-replica half of the fleet's rolling swap.  The engine
        must be fully drained (no active/queued/mid-prefill work):
        the fleet router guarantees that by admit-stopping the replica
        first.  Weights are ARGUMENTS of the compiled programs, not
        closures, so every AOT-compiled ladder bucket survives the
        swap untouched — zero recompiles, which the sanitized CI swap
        leg asserts.  The KV pool and the shared-prefix index reset
        (every cached k/v row was computed under the OLD weights;
        serving it would silently mix models), so the first
        post-swap admissions run cold by design.

        A **requantization swap** (bf16 ``GPTServingWeights`` ↔ int8
        :class:`~apex_tpu.ops.quant_matmul.QuantGPTServingWeights`)
        changes the weight pytree's structure, so the cached target
        executables cannot survive; the engine drops them and re-runs
        the AOT warmup inside the drained swap window instead — every
        retrace is charged to the swap, and the steady state after the
        replica rejoins is still zero-recompile (the fleet rollout
        test asserts the compile counter is flat from rejoin on)."""
        if self.active or self.prefilling or self.queue:
            raise RuntimeError(
                f"swap_weights on a busy engine ({len(self.active)} "
                f"active, {len(self.prefilling)} prefilling, "
                f"{len(self.queue)} queued) — drain first (the "
                f"router's admit-stop → drain → swap sequence)")
        requantized = (jax.tree_util.tree_structure(self.weights)
                       != jax.tree_util.tree_structure(weights))
        if not requantized:
            jax.tree_util.tree_map(
                lambda old, new: _check_swap_leaf(old, new),
                self.weights, weights)
        else:
            if is_quantized_weights(weights) \
                    == is_quantized_weights(self.weights) \
                    or len(self.weights.layers) != len(weights.layers):
                raise ValueError(
                    "swap_weights pytree mismatch that is not a "
                    "bf16<->int8 requantization — a swap must keep "
                    "the model geometry (same layer count, same "
                    "embedding shapes)")
            # the unquantized leaves still obey the strict leaf rule
            for old, new in ((self.weights.wte, weights.wte),
                             (self.weights.wpe, weights.wpe),
                             (self.weights.lnf_w, weights.lnf_w)):
                _check_swap_leaf(old, new)
            self._decode_exec.clear()
            self._prefill_exec.clear()
            self._extend_exec.clear()
        if self.tp is not None:
            if requantized:
                self.tp = self.tp.rebind(
                    weight_quantized=is_quantized_weights(weights))
            weights = self.tp.shard_weights(weights)
        elif self.ep is not None:
            if requantized:
                raise ValueError(
                    "expert-parallel serving does not take int8 "
                    "weights — requantization swap refused")
            weights = self.ep.shard_weights(weights)
        elif self.device is not None:
            weights = jax.device_put(weights, self.device)
        self.weights = weights
        if draft_weights is not None:
            if self.draft_weights is None:
                raise ValueError("draft_weights swap on an engine "
                                 "built without a draft")
            if self.device is not None:
                draft_weights = jax.device_put(draft_weights,
                                               self.device)
            self.draft_weights = draft_weights
        self.manager = KVCacheManager(
            self.cache_cfg, prefix_sharing=self.prefix_share)
        self.cache = self._fresh_cache()
        if self.draft_cache is not None:
            self.draft_cache = init_cache(self.draft_cache_cfg)
            if self.device is not None:
                self.draft_cache = jax.device_put(self.draft_cache,
                                                  self.device)
        if requantized:
            # restore the AOT ladder while still drained: the rebuild
            # is part of the swap's cost, not the steady state's
            self.warmup()
        self._event("weights_swapped", requantized=requantized,
                    compiles=sum(self._compiles.values()))

    def snapshot_state(self) -> Dict[str, Any]:
        """Live engine state as one JSON-able dict — what the
        on-demand :class:`~apex_tpu.serving.metrics.SnapshotTrigger`
        dumps as an ``engine_snapshot`` event for a wedged serve."""
        return {
            "tick": self.steps,
            "active": len(self.active),
            "queued": len(self.queue),
            "prefilling": [
                {"rid": str(rid), "written": job.written,
                 "prompt_len": len(job.tokens)}
                for rid, job in self.prefilling.items()],
            "done": self._done_count,
            "preempted": self._preempted_count,
            "free_blocks": self.manager.free_blocks,
            "used_blocks": self.manager.used_blocks,
            "reserved_blocks": self._reserved_blocks(),
            "shared_blocks": self.manager.shared_blocks,
            "idle_blocks": self.manager.idle_blocks,
            "used_blocks_high_water":
                self.metrics.gauges.used_blocks_hw,
            "pool_blocks": self.cache_cfg.usable_blocks,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "compiles": sum(self._compiles.values()),
            "requests": [
                {"rid": str(rid),
                 "seq_len": self.manager.seq_len(rid),
                 "new_tokens": len(q.out_tokens),
                 "max_new_tokens": q.max_new_tokens}
                for rid, q in sorted(self.active.items(),
                                     key=lambda kv: str(kv[0]))],
        }

    def run(self, *, max_steps: Optional[int] = None,
            before_tick: Optional[Callable[[int], None]] = None,
            after_tick: Optional[Callable[[int], None]] = None
            ) -> ServeSummary:
        """Serve until every submitted request finishes (or a
        termination request / ``max_steps`` drains the run).  On
        SIGTERM (via ``autoresume``) the engine stops admitting,
        abandons in-flight generation cleanly (blocks freed, requests
        marked preempted) and still returns a complete summary — the
        clean-drain contract CI kills a serve mid-run to prove.
        ``before_tick``/``after_tick`` receive the tick index (fault
        injection and the sanitizer's step boundary in the smoke
        driver).

        The summary covers the engine's **lifetime**: token/request
        totals accumulate across every ``run()`` call on this engine,
        and ``wall_s`` accumulates the time spent inside ``run()`` —
        so a paused-and-resumed serve (``max_steps``, or bench's
        staggered tail admissions) reports the same honest tokens/s
        as a single uninterrupted run, never lifetime tokens over
        one run's wall."""
        t0 = self._clock()
        drained = False
        try:
            while self.queue or self.active or self.prefilling:
                if self._terminating() or self._drain_reason is not None:
                    drained = True
                    self._drain(self._drain_reason
                                or (self.autoresume.source
                                    if self.autoresume else "api"))
                    break
                if max_steps is not None and self.steps >= max_steps:
                    drained = True
                    break
                if before_tick is not None:
                    before_tick(self.steps)
                self.step()
                if after_tick is not None:
                    after_tick(self.steps)
        except KeyboardInterrupt:
            # bare ^C with no AutoResume installed (the library-use
            # case): drain like SIGTERM — blocks freed, every chain
            # terminal, summary still returned — instead of unwinding
            # through the tick loop with blocks allocated.  A second
            # ^C during the drain propagates (the PR-3 double-signal
            # convention: the second one means NOW).
            drained = True
            self._drain("KeyboardInterrupt")
        # a drain request that became moot (everything finished in the
        # same tick that latched it, or max_steps broke first) must
        # not leak into a future run() on this engine and preempt a
        # fresh batch at its first tick
        self._drain_reason = None
        if self._esc_handled:
            # the handled episode ends with this run: consume the
            # policy latch and re-arm, so a future run() on this
            # engine escalates a NEW alarm instead of being deaf
            self._esc_handled = False
            if self.escalation is not None:
                self.escalation.reset()
        self._run_wall_s += self._clock() - t0
        # a trailing partial gauge window (tick_every > 1) flushes so
        # the final engine state is always in the log
        self.metrics.flush_gauges(self.steps)
        # final exporter publish: terminal counters complete, and the
        # published /healthz keeps reporting the drain until the
        # server stops (the CI flip probe reads this window)
        if self.exporter is not None:
            self._publish_exporter(drained=drained)
        summary = self.summary(drained=drained)
        self._event("serve_done", value=summary.tokens_per_sec,
                    **{k: v for k, v in summary.as_dict().items()
                       if k not in ("compiles", "tokens_per_sec")})
        return summary

    def summary(self, *, drained: bool = False) -> ServeSummary:
        """The engine's lifetime :class:`ServeSummary` from the
        counters it already holds — what :meth:`run` returns (and
        emits as ``serve_done``), exposed separately so a fleet can
        collect per-replica summaries without forcing an idle
        ``run()`` round per replica."""
        wall = max(self._run_wall_s, 1e-9)
        gen = self._done_tokens \
            + sum(len(q.out_tokens) for q in self.active.values())
        pct = self.metrics.percentiles()
        return ServeSummary(
            requests_done=self._done_count,
            requests_preempted=self._preempted_count,
            tokens_generated=gen,
            prefill_tokens=self.prefill_tokens,
            wall_s=round(wall, 4),
            decode_steps=self.steps,
            tokens_per_sec=round(gen / wall, 2),
            decode_wall_s=round(self.decode_wall_s, 4),
            decode_tokens_per_sec=round(
                self.decode_tokens / max(self.decode_wall_s, 1e-9), 2)
            if self.decode_tokens else 0.0,
            latency_p50_ms=_round_ms(_percentile(self._latencies, 50)),
            latency_p99_ms=_round_ms(_percentile(self._latencies, 99)),
            compiles=dict(self._compiles),
            drained=drained,
            queue_wait_p50_ms=pct["queue_wait_p50_ms"],
            queue_wait_p99_ms=pct["queue_wait_p99_ms"],
            ttft_p50_ms=pct["ttft_p50_ms"],
            ttft_p99_ms=pct["ttft_p99_ms"],
            itl_p50_ms=pct["itl_p50_ms"],
            itl_p99_ms=pct["itl_p99_ms"],
            requests_rejected=dict(self.metrics.rejected),
            spec_accept_rate=(
                round(self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed
                else (0.0 if self.speculate_k > 0 else None)),
            spec_tokens_proposed=self.spec_proposed,
            spec_tokens_accepted=self.spec_accepted,
            warm_prefix_admissions=self._warm_admissions,
            prefix_hit_tokens=self._prefix_hit_tokens,
            shared_blocks_hw=self.manager.shared_blocks_hw,
            cow_copies=self.manager.cow_copies,
            prefill_chunks=self.prefill_chunks,
            requests_deadline=self._deadline_count,
            requests_shed=self._shed_count,
            shed_engagements=(self.shed.engagements
                              if self.shed is not None else 0),
            spec_disabled=self.spec_disabled,
            replayed_requests=self._replayed,
            restarts=self.restarts,
            slo_burn_episodes=(self.slo.episodes
                               if self.slo is not None else 0),
            slo_recoveries=(self.slo.recoveries
                            if self.slo is not None else 0),
            slo_burning=(list(self.slo.burning)
                         if self.slo is not None else []))


def _check_swap_leaf(old, new) -> None:
    """One weight leaf of a rolling swap: shape and dtype must match
    the serving arrays exactly, or the cached executables would
    retrace (shape change) or silently cast (dtype change)."""
    if old.shape != new.shape or old.dtype != new.dtype:
        raise ValueError(
            f"swap_weights leaf mismatch: serving "
            f"{old.shape}/{old.dtype} vs replacement "
            f"{new.shape}/{new.dtype} — a swap must keep the "
            f"compiled ladder valid (same geometry, same dtype)")


def _round_ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)


def default_cache_config(model_cfg: ServingModelConfig,
                         num_blocks: Optional[int] = None,
                         block_size: Optional[int] = None,
                         kv_dtype: Optional[str] = None) -> KVCacheConfig:
    """Cache plan from the registered serving flags
    (``APEX_TPU_SERVE_KV_BLOCK`` / ``APEX_TPU_SERVE_KV_DTYPE`` /
    ``APEX_TPU_SERVE_BLOCKS``); explicit arguments override."""
    return KVCacheConfig(
        num_layers=model_cfg.num_layers,
        num_heads=model_cfg.num_heads,
        head_dim=model_cfg.head_dim,
        num_blocks=(num_blocks if num_blocks is not None
                    else flag_int("APEX_TPU_SERVE_BLOCKS")),
        block_size=(block_size if block_size is not None
                    else flag_int("APEX_TPU_SERVE_KV_BLOCK")),
        kv_dtype=(kv_dtype if kv_dtype is not None
                  else flag_str("APEX_TPU_SERVE_KV_DTYPE")),
        model_dtype=model_cfg.dtype)
