"""Pure-function GPT forward for serving: prefill + paged decode.

The training-side :class:`~apex_tpu.testing.standalone_gpt.GPTModel`
is a flax module built for whole-sequence teacher forcing; serving
needs the same math re-staged around a KV cache: a **prefill** that
runs the prompt once through the existing flash forward kernel while
writing every layer's k/v into the request's pages, and a **decode
step** that advances one token per sequence against the paged cache
through the :func:`~apex_tpu.ops.flash_decode.flash_decode` kernel.

Rather than threading mutable cache collections through flax, the
serving path extracts the model's parameters into a plain pytree
(:class:`GPTServingWeights` — same arrays, no copies beyond unboxing)
and runs an explicit forward whose math mirrors the flax stack
operation-for-operation: fp32 :func:`~apex_tpu.ops.layer_norm.
layer_norm` statistics, ``x @ kernel + bias`` in the model compute
dtype, fp32-softmax attention, gelu MLP, tied LM head.  The serving
tests pin this against ``GPTModel.apply`` so the two stacks cannot
drift.

Everything here is traced code (the engine jits these per bucket) —
shapes are static per call site, per-request dynamics ride data
(block tables, sequence lengths, write slots).
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.flash_decode import (flash_decode, flash_decode_multi,
                                paged_attention_multi_reference,
                                paged_attention_reference)
from ..ops.layer_norm import layer_norm
from ..ops.quant_matmul import (QuantGPTServingWeights,
                                QuantLayerWeights, quant_matmul,
                                quantize_weights)
from .kv_cache import (KVCacheConfig, PagedKVCache, write_prefill_kv,
                       write_token_kv)

__all__ = ["GPTServingWeights", "LayerWeights", "MoELayerWeights",
           "ServingModelConfig",
           "QuantGPTServingWeights", "QuantLayerWeights",
           "quantize_weights", "extract_serving_weights",
           "gpt_prefill_step", "gpt_decode_step", "gpt_extend_step",
           "gpt_sequence_logits", "copy_cache_block",
           "gather_cache_blocks", "scatter_cache_blocks"]


class LayerWeights(NamedTuple):
    """One transformer layer's parameters (plain arrays)."""

    ln1_w: jnp.ndarray
    ln1_b: jnp.ndarray
    qkv_k: jnp.ndarray        # (H, 3H)
    qkv_b: jnp.ndarray
    dense_k: jnp.ndarray      # (H, H)
    dense_b: jnp.ndarray
    ln2_w: jnp.ndarray
    ln2_b: jnp.ndarray
    fc1_k: jnp.ndarray        # (H, F)
    fc1_b: jnp.ndarray
    fc2_k: jnp.ndarray        # (F, H)
    fc2_b: jnp.ndarray


class MoELayerWeights(NamedTuple):
    """A transformer layer whose MLP is a Switch-style MoE (ISSUE-19).

    Attention/LN leaves match :class:`LayerWeights`; the dense fc1/fc2
    pair is replaced by a top-1 router and per-expert bias-free FFN
    stacks (the training-side :class:`~apex_tpu.transformer.
    layers_moe.MoEMLP` convention).  The step functions duck-type on
    ``router`` (like Q8 duck-types on the ``*_s`` scale rows), so
    dense and MoE layers mix freely in one model."""

    ln1_w: jnp.ndarray
    ln1_b: jnp.ndarray
    qkv_k: jnp.ndarray        # (H, 3H)
    qkv_b: jnp.ndarray
    dense_k: jnp.ndarray      # (H, H)
    dense_b: jnp.ndarray
    ln2_w: jnp.ndarray
    ln2_b: jnp.ndarray
    router: jnp.ndarray       # (H, E) fp32 — routing is precision-
    wi: jnp.ndarray           # (E, H, F)      # sensitive, stays fp32
    wo: jnp.ndarray           # (E, F, H)


class GPTServingWeights(NamedTuple):
    """The whole model as a pytree of plain arrays."""

    wte: jnp.ndarray          # (V, H) — tied LM head
    wpe: jnp.ndarray          # (S, H)
    layers: Tuple[LayerWeights, ...]
    lnf_w: jnp.ndarray
    lnf_b: jnp.ndarray


@dataclasses.dataclass(frozen=True)
class ServingModelConfig:
    """Static model geometry + serving knobs (hashable — safe to
    close over in jitted builders)."""

    vocab_size: int
    hidden_size: int
    num_heads: int
    num_layers: int
    max_seq: int
    dtype: Any = jnp.float32
    layernorm_eps: float = 1e-5
    # prefill attention: the existing flash fwd kernel, or the dense
    # reference (manual-axis contexts / debugging)
    prefill_flash: bool = True
    # decode attention: 'kernel' = the Pallas flash-decode kernel;
    # 'reference' = the dense gather twin — the naive full-attention
    # baseline bench.py's serving section measures the kernel against
    decode_attention: str = "kernel"
    # tensor-parallel axis name (serving/tp.py): when set, the step
    # functions run PER-SHARD math — heads/ffn columns local, hidden
    # residual global — and the two row-parallel linears (attention
    # dense, MLP fc2) all-reduce their partial sums over this axis
    # before the bias add (the Megatron forward, 2 psums per layer).
    # None (single chip) elides the collectives entirely, so the same
    # programs serve both topologies.
    tp_axis: Optional[str] = None
    # expert-parallel axis name (serving/ep.py): when set, MoE layers
    # (``MoELayerWeights``) run with the global experts sharded over
    # that axis — each rank routes its slice of the replicated token
    # rows, dispatch/return ride the capacity-chunked overlapped
    # all_to_all exchange, and the combined slice replicates through
    # one masked psum per MoE layer.  None runs all experts locally.
    ep_axis: Optional[str] = None
    # MoE geometry/knobs (ignored for all-dense weights): expert count
    # is recorded for context validation/describe (the math reads it
    # off the router leaf), capacity factor sizes the per-rank
    # dispatch buffer, a2a_chunks is the overlap depth (ISSUE-19;
    # 1 = legacy single-shot exchange)
    num_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_a2a_chunks: int = 2

    def __post_init__(self):
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden {self.hidden_size} not divisible by heads "
                f"{self.num_heads}")
        if self.decode_attention not in ("kernel", "reference"):
            raise ValueError(
                f"decode_attention {self.decode_attention!r} not in "
                f"('kernel', 'reference')")
        if self.moe_a2a_chunks < 1:
            raise ValueError(
                f"moe_a2a_chunks {self.moe_a2a_chunks} must be >= 1")
        if self.num_experts < 0:
            raise ValueError(
                f"num_experts {self.num_experts} must be >= 0")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_model(cls, model, **overrides) -> "ServingModelConfig":
        """Geometry from a :class:`~apex_tpu.testing.standalone_gpt.
        GPTModel` instance."""
        return cls(vocab_size=model.vocab_size,
                   hidden_size=model.hidden_size,
                   num_heads=model.num_attention_heads,
                   num_layers=model.num_layers,
                   max_seq=model.max_sequence_length,
                   dtype=model.dtype, **overrides)


def _unbox(tree):
    import flax.linen as nn

    return jax.tree.map(
        lambda l: l.unbox() if isinstance(l, nn.Partitioned) else l,
        tree, is_leaf=lambda l: isinstance(l, nn.Partitioned))


def extract_serving_weights(params,
                            num_layers: int) -> GPTServingWeights:
    """Flatten a ``GPTModel`` param tree (as returned by ``init`` /
    held by the train loop) into :class:`GPTServingWeights`.  Arrays
    are referenced, not copied — a freshly trained tree serves
    without a round-trip through a checkpoint."""
    p = _unbox(params)
    emb = p["embedding"]
    tr = p["transformer"]
    layers = []
    for i in range(num_layers):
        lp = tr[f"layer_{i}"]
        attn = lp["self_attention"]
        mlp = lp["mlp"]
        layers.append(LayerWeights(
            ln1_w=lp["input_layernorm"]["weight"],
            ln1_b=lp["input_layernorm"]["bias"],
            qkv_k=attn["query_key_value"]["kernel"],
            qkv_b=attn["query_key_value"]["bias"],
            dense_k=attn["dense"]["kernel"],
            dense_b=attn["dense"]["bias"],
            ln2_w=lp["post_attention_layernorm"]["weight"],
            ln2_b=lp["post_attention_layernorm"]["bias"],
            fc1_k=mlp["dense_h_to_4h"]["kernel"],
            fc1_b=mlp["dense_h_to_4h"]["bias"],
            fc2_k=mlp["dense_4h_to_h"]["kernel"],
            fc2_b=mlp["dense_4h_to_h"]["bias"]))
    return GPTServingWeights(
        wte=emb["word_embeddings"]["embedding"],
        wpe=emb["position_embeddings"]["embedding"],
        layers=tuple(layers),
        lnf_w=tr["final_layernorm"]["weight"],
        lnf_b=tr["final_layernorm"]["bias"])


def _matmul(x, kernel, dtype, scale):
    """The one matmul both linears share.  ``scale`` is None for a
    dense float kernel (compute-dtype matmul) or the per-output-channel
    fp32 scales of an int8 kernel (Q8: fp32-accumulated
    :func:`~apex_tpu.ops.quant_matmul.quant_matmul`, scale applied
    after the contraction, result cast back to compute dtype — the
    fp32 weight tensor never materializes, APX606's invariant)."""
    if scale is not None:
        return quant_matmul(x, kernel, scale, out_dtype=dtype)
    return x.astype(dtype) @ kernel.astype(dtype)


def _linear(x, kernel, bias, dtype, scale=None):
    """The ColumnParallelLinear single-device math: compute-dtype
    matmul, bias in compute dtype."""
    return _matmul(x, kernel, dtype, scale) + bias.astype(dtype)


def _row_linear(x, kernel, bias, dtype, tp_axis, scale=None):
    """RowParallelLinear: with ``tp_axis`` set the kernel rows are a
    contraction shard, so the partial product all-reduces over the
    axis BEFORE the (replicated) bias adds exactly once; single-chip
    (``tp_axis=None``) is plain ``_linear``.  Per-channel scales
    commute with the shard sum (each shard's partial covers every
    output channel), so Q8 scales apply pre-psum."""
    y = _matmul(x, kernel, dtype, scale)
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    return y + bias.astype(dtype)


def _moe_mlp(m_in, lw: MoELayerWeights, cfg):
    """Switch-style MoE FFN for serving: top-1 router (greedy serving
    is deterministic — no stochastic second-choice policy), the fused
    routing front (:func:`~apex_tpu.ops.moe_routing.
    moe_route_dispatch`), bias-free expert stacks.

    Single chip (``cfg.ep_axis`` None): every expert is local — route,
    batch the expert einsums over the ``(E, capacity, H)`` buffer,
    gate-combine.  Under ``cfg.ep_axis`` (serving/ep.py) the experts
    are weight-sharded over the axis while tokens/attention/cache stay
    replicated: each rank routes its ``T/n`` slice of the token rows,
    dispatch/return ride the capacity-chunked overlapped all_to_all
    exchange (``cfg.moe_a2a_chunks`` — the ISSUE-19 schedule APX704
    stays quiet on), and the combined slice replicates through ONE
    masked psum per MoE layer, so downstream math (residual, next
    layer, argmax) is shard-invariant exactly like the TP forward's
    post-psum activations.  Buckets whose row count doesn't divide the
    axis fall back to every rank routing the full batch (redundant
    expert FLOPs, weights still sharded — correctness never depends
    on bucket/axis alignment)."""
    from ..transformer.expert_parallel import moe_dispatch_combine_fused

    hdim = m_in.shape[-1]
    x2d = m_in.reshape(-1, hdim)
    t = x2d.shape[0]
    e = lw.router.shape[-1]
    dt = cfg.dtype
    logits = x2d.astype(jnp.float32) @ lw.router.astype(jnp.float32)

    def expert_fn(d):
        # d: (local_experts, rows, H) — the dispatched buffer (or its
        # arrived exchange chunk); wi/wo are the local expert stacks
        h1 = jax.nn.gelu(jnp.einsum(
            "ech,ehf->ecf", d.astype(dt), lw.wi.astype(dt),
            preferred_element_type=jnp.float32))
        return jnp.einsum(
            "ecf,efh->ech", h1.astype(dt), lw.wo.astype(dt),
            preferred_element_type=jnp.float32).astype(dt)

    axis = cfg.ep_axis
    if axis is None or t % _axis_size(axis) != 0:
        y, _ = moe_dispatch_combine_fused(
            x2d.astype(dt), logits, expert_fn, e,
            capacity_factor=cfg.moe_capacity_factor, axis_name=axis,
            a2a_chunks=cfg.moe_a2a_chunks)
        return y.reshape(m_in.shape)
    n = _axis_size(axis)
    tl = t // n
    r = jax.lax.axis_index(axis)
    xs = jax.lax.dynamic_slice_in_dim(x2d, r * tl, tl, axis=0)
    ls = jax.lax.dynamic_slice_in_dim(logits, r * tl, tl, axis=0)
    y_local, _ = moe_dispatch_combine_fused(
        xs.astype(dt), ls, expert_fn, e,
        capacity_factor=cfg.moe_capacity_factor, axis_name=axis,
        a2a_chunks=cfg.moe_a2a_chunks)
    pad = jnp.zeros((t, hdim), y_local.dtype)
    y = jax.lax.psum(
        jax.lax.dynamic_update_slice_in_dim(pad, y_local, r * tl,
                                            axis=0), axis)
    return y.reshape(m_in.shape)


def _axis_size(axis) -> int:
    from .._compat import axis_size

    return axis_size(axis) if axis is not None else 1


def _layer_tail(x, lw: LayerWeights, attn_out, cfg):
    """residual + LN + MLP + residual — shared by prefill and decode.
    fc1 is column-split under TP (local gelu), fc2 row-split (the
    layer's second all-reduce); an ``MoELayerWeights`` layer routes
    through the MoE FFN instead (duck-typed on ``router``)."""
    x = x + attn_out.astype(x.dtype)
    m_in = layer_norm(x, lw.ln2_w, lw.ln2_b,
                      cfg.layernorm_eps).astype(cfg.dtype)
    if getattr(lw, "router", None) is not None:
        return x + _moe_mlp(m_in, lw, cfg).astype(x.dtype)
    h1 = jax.nn.gelu(_linear(m_in, lw.fc1_k, lw.fc1_b, cfg.dtype,
                             getattr(lw, "fc1_s", None)))
    mlp_out = _row_linear(h1, lw.fc2_k, lw.fc2_b, cfg.dtype,
                          cfg.tp_axis, getattr(lw, "fc2_s", None))
    return x + mlp_out.astype(x.dtype)


def _lm_head(x, weights: GPTServingWeights, cfg):
    """Final LN + tied-embedding projection (GPTHead + attend)."""
    hf = layer_norm(x, weights.lnf_w, weights.lnf_b,
                    cfg.layernorm_eps).astype(cfg.dtype)
    return hf.astype(cfg.dtype) @ weights.wte.astype(cfg.dtype).T


def _embed(weights: GPTServingWeights, tokens, positions, cfg):
    dtype = cfg.dtype
    return (jnp.take(weights.wte.astype(dtype), tokens, axis=0)
            + jnp.take(weights.wpe.astype(dtype), positions, axis=0))


def gpt_prefill_step(weights: GPTServingWeights,
                     cfg: ServingModelConfig,
                     cache_cfg: KVCacheConfig, cache: PagedKVCache,
                     tokens: jnp.ndarray, length: jnp.ndarray,
                     blocks: jnp.ndarray):
    """Run one prompt through the model, writing every layer's k/v
    into the request's pages; returns ``(cache, next_token)``.

    ``tokens`` (s_pad,) int32, right-padded to the prompt-length
    bucket (``s_pad = len(blocks) * block_size``); ``length`` the true
    prompt length (traced — one compile covers the whole bucket);
    ``blocks`` (n_pages,) int32 with dump-page padding past the owned
    tail.  Attention is causal over the padded prompt — padded KEYS
    sit in the causal future of every real query, so the row at
    ``length - 1`` (whose argmax is the first generated token) never
    sees them; their own garbage rows land in pages the masked decode
    reads never weight.  The attention itself is the existing flash
    forward kernel (:func:`~apex_tpu.ops.flash_attention.
    flash_attention`) — prefill is exactly a training forward at
    batch 1."""
    from ..ops.flash_attention import flash_attention, mha_reference

    s_pad = tokens.shape[0]
    # head count comes from the CACHE config: under tensor parallelism
    # (serving/tp.py) each shard owns cfg.num_heads / tp heads and its
    # cache is sized to match — the math below is per-shard math
    h, d = cache_cfg.num_heads, cache_cfg.head_dim
    scale = d ** -0.5
    x = _embed(weights, tokens[None, :],
               jnp.arange(s_pad, dtype=jnp.int32)[None, :], cfg)
    for i, lw in enumerate(weights.layers):
        a_in = layer_norm(x, lw.ln1_w, lw.ln1_b,
                          cfg.layernorm_eps).astype(cfg.dtype)
        qkv = _linear(a_in, lw.qkv_k, lw.qkv_b, cfg.dtype,
                      getattr(lw, "qkv_s", None))
        qkv = qkv.reshape(1, s_pad, h, 3 * d)
        q, k, v = jnp.split(qkv, 3, axis=-1)      # (1, s, h, d)
        cache = write_prefill_kv(cache, cache_cfg, i, k[0], v[0],
                                 blocks)
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        attn = flash_attention if cfg.prefill_flash else mha_reference
        ctx = attn(qt, kt, vt, scale=scale, causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(1, s_pad, h * d)
        attn_out = _row_linear(ctx, lw.dense_k, lw.dense_b, cfg.dtype,
                               cfg.tp_axis,
                               getattr(lw, "dense_s", None))
        x = _layer_tail(x, lw, attn_out, cfg)
    logits = _lm_head(x, weights, cfg)[0]          # (s_pad, V)
    last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=0,
                                        keepdims=False)
    next_token = jnp.argmax(last, axis=-1).astype(jnp.int32)
    return cache, next_token


def gpt_decode_step(weights: GPTServingWeights,
                    cfg: ServingModelConfig,
                    cache_cfg: KVCacheConfig, cache: PagedKVCache,
                    tokens: jnp.ndarray, positions: jnp.ndarray,
                    block_tables: jnp.ndarray, seq_lens: jnp.ndarray,
                    write_blocks: jnp.ndarray,
                    write_offsets: jnp.ndarray):
    """Advance every batch row one token against the paged cache;
    returns ``(cache, next_tokens)``.

    Per row ``b``: ``tokens[b]`` is the token at position
    ``positions[b]`` (the previously sampled or last prompt token);
    its k/v is written to ``(write_blocks[b], write_offsets[b])``
    layer by layer *before* that layer's attention, so the token
    attends to itself through the cache; ``seq_lens[b] =
    positions[b] + 1`` bounds the attended span.  Inactive bucket
    rows carry ``seq_lens = 0``, point their writes at the dump page,
    and produce a (discarded) deterministic token.  Greedy argmax
    sampling happens in-graph — the step's only output traffic is the
    cache carry and one int32 per row.

    Every row's math touches only that row's pages and lanes, so a
    request's token stream is invariant to bucket shape and admission
    interleave — the continuous-batching determinism the serving
    tests prove.
    """
    h, d = cache_cfg.num_heads, cache_cfg.head_dim   # per-shard heads
    b = tokens.shape[0]
    scale = d ** -0.5
    x = _embed(weights, tokens, positions, cfg)   # (b, H)
    for i, lw in enumerate(weights.layers):
        a_in = layer_norm(x, lw.ln1_w, lw.ln1_b,
                          cfg.layernorm_eps).astype(cfg.dtype)
        qkv = _linear(a_in, lw.qkv_k, lw.qkv_b, cfg.dtype,
                      getattr(lw, "qkv_s", None))
        qkv = qkv.reshape(b, h, 3 * d)
        q, k, v = jnp.split(qkv, 3, axis=-1)       # (b, h, d)
        cache = write_token_kv(cache, cache_cfg, i, k, v,
                               write_blocks, write_offsets)
        kc, vc, ks, vs = cache.layer(i)
        if cfg.decode_attention == "kernel":
            ctx = flash_decode(q, kc, vc, block_tables, seq_lens,
                               scale=scale, k_scale=ks, v_scale=vs)
        else:
            ctx = paged_attention_reference(
                q, kc, vc, block_tables, seq_lens, scale=scale,
                k_scale=ks, v_scale=vs)
        ctx = ctx.reshape(b, h * d)
        attn_out = _row_linear(ctx, lw.dense_k, lw.dense_b, cfg.dtype,
                               cfg.tp_axis,
                               getattr(lw, "dense_s", None))
        x = _layer_tail(x, lw, attn_out, cfg)
    logits = _lm_head(x, weights, cfg)             # (b, V)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return cache, next_tokens


def gpt_extend_step(weights: GPTServingWeights,
                    cfg: ServingModelConfig,
                    cache_cfg: KVCacheConfig, cache: PagedKVCache,
                    tokens: jnp.ndarray, block_tables: jnp.ndarray,
                    seq_lens: jnp.ndarray,
                    write_blocks: jnp.ndarray,
                    write_offsets: jnp.ndarray):
    """Advance every batch row by a CHUNK of ``t`` tokens against the
    paged cache — the one program behind speculative verification,
    chunked prefill, and warm-prefix tail prefill; returns
    ``(cache, next_tokens)`` with one argmax token per chunk slot.

    ``tokens`` is (b, t): row ``b``'s chunk occupies the contiguous
    positions ``seq_lens[b] - t .. seq_lens[b] - 1`` (``seq_lens``
    counts every k/v-written token INCLUDING this chunk).  Each
    token's k/v goes to ``(write_blocks[b, j], write_offsets[b, j])``
    layer by layer before that layer's attention — the chunk attends
    to itself through the cache, exactly the decode step's discipline
    — and the per-row causal rule is
    :func:`~apex_tpu.ops.flash_decode.flash_decode_multi`'s.  Chunks
    shorter than ``t`` are FRONT-padded (valid tokens last, so the
    final row is always the newest position): padding rows carry
    negative positions, point their writes at the dump page, and emit
    a discarded deterministic token.  ``next_tokens[b, -1]`` after the
    chunk that completes a prompt is the request's first generated
    token; ``next_tokens[b, j]`` under verification is the target
    model's greedy choice after consuming position ``seq_lens[b] - t
    + j`` — the acceptance comparator.

    One compile per (batch bucket, t bucket, pages bucket) — the
    chunk/verify dimensions the engine's warmup adds to the ladder
    product."""
    h, d = cache_cfg.num_heads, cache_cfg.head_dim   # per-shard heads
    b, t = tokens.shape
    scale = d ** -0.5
    pos = seq_lens.astype(jnp.int32)[:, None] - t \
        + jnp.arange(t, dtype=jnp.int32)[None, :]       # (b, t)
    # padding rows sit at negative positions: clamp the embedding
    # lookup (their output is discarded; attention masks them to 0)
    x = _embed(weights, tokens, jnp.maximum(pos, 0), cfg)  # (b, t, H)
    wb = write_blocks.reshape(b * t)
    wo = write_offsets.reshape(b * t)
    for i, lw in enumerate(weights.layers):
        a_in = layer_norm(x, lw.ln1_w, lw.ln1_b,
                          cfg.layernorm_eps).astype(cfg.dtype)
        qkv = _linear(a_in, lw.qkv_k, lw.qkv_b, cfg.dtype,
                      getattr(lw, "qkv_s", None))
        qkv = qkv.reshape(b, t, h, 3 * d)
        q, k, v = jnp.split(qkv, 3, axis=-1)       # (b, t, h, d)
        cache = write_token_kv(cache, cache_cfg, i,
                               k.reshape(b * t, h, d),
                               v.reshape(b * t, h, d), wb, wo)
        kc, vc, ks, vs = cache.layer(i)
        if cfg.decode_attention == "kernel":
            ctx = flash_decode_multi(q, kc, vc, block_tables,
                                     seq_lens, scale=scale,
                                     k_scale=ks, v_scale=vs)
        else:
            ctx = paged_attention_multi_reference(
                q, kc, vc, block_tables, seq_lens, scale=scale,
                k_scale=ks, v_scale=vs)
        ctx = ctx.reshape(b, t, h * d)
        attn_out = _row_linear(ctx, lw.dense_k, lw.dense_b, cfg.dtype,
                               cfg.tp_axis,
                               getattr(lw, "dense_s", None))
        x = _layer_tail(x, lw, attn_out, cfg)
    logits = _lm_head(x, weights, cfg)             # (b, t, V)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return cache, next_tokens


def gpt_sequence_logits(weights, cfg: ServingModelConfig,
                        tokens: jnp.ndarray) -> jnp.ndarray:
    """Whole-sequence teacher-forced logits ``(b, s, V)`` — no KV
    cache, no paging: the training-forward view of the SAME serving
    math (same ``_linear``/``_row_linear`` dispatch, so Q8 weights run
    the quantized matmuls here too).  This is the oracle behind the
    bench's perplexity-delta row and the Q8-vs-O5 divergence tests;
    single-chip only (head counts come from ``cfg``, not a sharded
    cache config)."""
    from ..ops.flash_attention import flash_attention, mha_reference

    b, s = tokens.shape
    h, d = cfg.num_heads, cfg.head_dim
    scale = d ** -0.5
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                           (b, s))
    x = _embed(weights, tokens, pos, cfg)
    for lw in weights.layers:
        a_in = layer_norm(x, lw.ln1_w, lw.ln1_b,
                          cfg.layernorm_eps).astype(cfg.dtype)
        qkv = _linear(a_in, lw.qkv_k, lw.qkv_b, cfg.dtype,
                      getattr(lw, "qkv_s", None))
        qkv = qkv.reshape(b, s, h, 3 * d)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        attn = flash_attention if cfg.prefill_flash else mha_reference
        ctx = attn(qt, kt, vt, scale=scale, causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        attn_out = _row_linear(ctx, lw.dense_k, lw.dense_b, cfg.dtype,
                               cfg.tp_axis,
                               getattr(lw, "dense_s", None))
        x = _layer_tail(x, lw, attn_out, cfg)
    return _lm_head(x, weights, cfg)


def copy_cache_block(cache: PagedKVCache, src: jnp.ndarray,
                     dst: jnp.ndarray) -> PagedKVCache:
    """Device-side copy-on-write: duplicate block ``src`` (all layers,
    k+v+scales) into block ``dst``.  Traced code — the engine jits it
    once per cache (src/dst ride as data, so every CoW reuses the one
    compiled program) with the cache donated, making the copy an
    in-place page-sized DMA."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    k = cache.k.at[:, dst].set(cache.k[:, src])
    v = cache.v.at[:, dst].set(cache.v[:, src])
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if k_scale is not None:
        k_scale = k_scale.at[:, dst].set(k_scale[:, src])
        v_scale = v_scale.at[:, dst].set(v_scale[:, src])
    return PagedKVCache(k, v, k_scale, v_scale)


def gather_cache_blocks(cache: PagedKVCache, blocks: jnp.ndarray):
    """Pull ``blocks`` (n,) int32 out of the paged cache as one
    contiguous payload — the EXPORT half of the disaggregated
    prefill→decode KV handoff (serving/fleet.py).  Returns
    ``(k, v, k_scale, v_scale)`` with ``k``/``v`` shaped
    ``(L, n, hk, bs, dk)`` (the storage layout, bytes untouched — an
    int8 cache ships int8 rows + their fp32 scales, a bf16 cache
    ships bf16) and scales ``(L, n, h, bs)`` or None.  Traced code:
    the fleet jits it with the block list as data, padded to a page
    rung, so every export of a rung-sized span reuses one compiled
    program (dump-page padding gathers harmless zeros the importer
    drops)."""
    blocks = jnp.asarray(blocks, jnp.int32)
    k = jnp.take(cache.k, blocks, axis=1)
    v = jnp.take(cache.v, blocks, axis=1)
    ks = vs = None
    if cache.k_scale is not None:
        ks = jnp.take(cache.k_scale, blocks, axis=1)
        vs = jnp.take(cache.v_scale, blocks, axis=1)
    return k, v, ks, vs


def scatter_cache_blocks(cache: PagedKVCache, k: jnp.ndarray,
                         v: jnp.ndarray, k_scale, v_scale,
                         blocks: jnp.ndarray) -> PagedKVCache:
    """Write an exported payload into ``blocks`` of this cache — the
    IMPORT half of the KV handoff.  Shapes/dtypes must match this
    cache's storage layout exactly (the fleet validates the two
    replicas' :class:`~.kv_cache.KVCacheConfig` geometry before any
    transfer); the cache is donated by the jitted caller so the
    scatter is an in-place page-span DMA.  Padding entries pointing at
    the dump block overwrite only the dump page (never read
    unmasked)."""
    blocks = jnp.asarray(blocks, jnp.int32)
    ck = cache.k.at[:, blocks].set(k)
    cv = cache.v.at[:, blocks].set(v)
    cks, cvs = cache.k_scale, cache.v_scale
    if cks is not None:
        cks = cks.at[:, blocks].set(k_scale)
        cvs = cvs.at[:, blocks].set(v_scale)
    return PagedKVCache(ck, cv, cks, cvs)
