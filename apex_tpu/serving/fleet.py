"""apex_tpu.serving.fleet — multi-replica serving (ISSUE-14).

The "millions of users" story needs N engines behind a router, not
one.  This module is that host-side layer over the PR 9-13 serving
stack, four pieces:

* :class:`Replica` — one :class:`~.engine.ServingEngine` plus its
  fleet identity: a stable ``replica_id`` (stamped on every event the
  engine emits), a role (``serve`` decodes; ``prefill`` runs prompt
  admission only and streams finished KV to a decode replica), an
  optional per-replica :class:`~.resilience.RequestJournal` (a crashed
  replica recovers by crash_reset + replay, the PR-13 machinery), and
  the router's admit-stop latch (``routable``).
* :class:`FleetRouter` — the gauge-fed front: submissions are scored
  against each replica's :meth:`~.engine.ServingEngine.
  router_snapshot` (ONE cheap host struct per replica — free blocks
  net of in-flight reservations, backlog, shed state, and the shared
  prefix index's chain keys), with **sticky warm routing**: a prompt
  whose chain keys intersect a replica's warm-prefix keys routes
  there, so the CoW prefix machinery keeps paying across requests.
  ``APEX_TPU_SERVE_ROUTER`` picks the policy (``gauges`` default,
  ``round_robin`` the A/B control).
* **disaggregated prefill/decode** (:meth:`FleetRouter.submit` with
  prefill-role replicas) — the DistServe/Splitwise split: a prefill
  replica admits the prompt as a 1-token probe (the existing chunked-
  prefill/prefix-share path writes and registers every prompt page),
  then :func:`transfer_prefix` ships those pages —
  **block table as the wire format**, int8/bf16 storage bytes and
  scales preserved — into the decode replica's pool, registered into
  its shared index, so the real request's admission there is a WARM
  admission (``prefix_hit_tokens > 0``, the CI-asserted handoff
  proof).
* **rolling weight swap** (:meth:`FleetRouter.swap_weights`) — one
  replica at a time: admit-stop (the router routes around it), drain
  (in-flight requests finish normally — zero requests lost), swap
  (:meth:`~.engine.ServingEngine.swap_weights`: compiled ladder kept,
  KV pool reset), rejoin.  The fleet never drops below N−1 serving
  replicas and a sanitized fleet proves the swap compiles nothing.

Two drive modes: the deterministic **stepped** loop (one host thread
round-robins every replica's tick — CI, tests, disaggregation) and
the **threaded** mode (one thread per replica runs the engine's own
``run()``/supervised loop — the scaling measurement, since each
replica's jitted steps release the GIL and run concurrently on their
own device slice).  Driver: ``standalone_gpt --serve-fleet``;
aggregation: ``tools/trace_check.py --serve r0.jsonl r1.jsonl ...``
and the ``monitor_summary`` fleet digest.  Docs:
docs/api/serving.md#fleet-serving.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.flags import flag_str
from ..monitor.export import FleetAggregator, MetricsRegistry
from ..utils.log_util import get_logger
from .engine import Request, ServeSummary, ServingEngine
from .kv_cache import DUMP_BLOCK, prefix_chain_keys
from .model import gather_cache_blocks, scatter_cache_blocks
from .resilience import recover_engine, run_serving

logger = get_logger(__name__)

__all__ = ["FleetRouter", "FleetSummary", "Replica",
           "transfer_prefix", "export_prefix_payload",
           "import_prefix_payload"]

ROUTER_POLICIES = ("gauges", "round_robin")
# disaggregated prefill probes ride the normal request path under a
# namespaced rid so their lifecycle chains are ordinary, complete
# chains (N submitted => N terminal holds per replica log)
PREFILL_RID_PREFIX = "pf:"


@dataclasses.dataclass
class Replica:
    """One engine's seat in the fleet."""

    replica_id: str
    engine: ServingEngine
    role: str = "serve"               # 'serve' | 'prefill'
    journal: Any = None               # RequestJournal for recovery
    max_restarts: int = 3
    routable: bool = True             # router admit-stop latch
    restarts: int = 0                 # fleet-observed recoveries
    # deterministic fault injector (resilience.faults.FaultInjector)
    # fired at THIS replica's tick boundaries — how the CI fleet leg
    # crashes one replica while the others keep serving
    fault: Any = None

    def __post_init__(self):
        if self.role not in ("serve", "prefill"):
            raise ValueError(f"role {self.role!r} not in "
                             f"('serve', 'prefill')")
        if self.engine.replica_id is None:
            self.engine.replica_id = str(self.replica_id)
        if self.journal is not None and self.engine.journal is None:
            self.engine.journal = self.journal

    @property
    def busy(self) -> bool:
        e = self.engine
        return bool(e.queue or e.active or e.prefilling)

    def device_scope(self):
        """``jax.default_device`` pinned to this replica's device.

        The engine's per-tick input staging (``jnp.asarray`` of block
        tables, tokens, write slots) otherwise lands on the process
        default device — EVERY replica's every tick would then transit
        device 0's stream and the fleet serializes behind it (measured:
        flat aggregate tokens/s at any replica count).  Scoping each
        replica's ticks to its own device restores linear scaling; a
        replica without a pinned device (or a TP replica, whose mesh
        owns placement) gets a null scope."""
        dev = getattr(self.engine, "device", None)
        if dev is None:
            return contextlib.nullcontext()
        import jax as _jax

        return _jax.default_device(dev)


@dataclasses.dataclass
class FleetSummary:
    """What one fleet serve measured (the ``--serve-fleet`` /
    bench-row source).  Aggregates are over SERVE-role replicas
    (prefill probes are plumbing, not throughput); ``per_replica``
    carries every engine's full :class:`~.engine.ServeSummary`."""

    replicas: int
    prefill_replicas: int
    router_policy: str
    requests_submitted: int
    requests_done: int
    requests_preempted: int
    requests_deadline: int
    requests_shed: int
    lost_requests: int            # submitted - terminal; MUST be 0
    tokens_generated: int
    wall_s: float
    tokens_per_sec: float         # aggregate: fleet tokens over wall
    # capacity view: sum of per-replica decode-tick rates (each
    # replica's decode_wall counts only its own jitted steps)
    sum_decode_tokens_per_sec: float
    swaps: int = 0
    handoffs: int = 0             # disaggregated KV transfers done
    handoff_blocks: int = 0       # pages shipped (the wire volume)
    # worst serve-replica TTFT percentiles (each replica's bounded
    # window; the fleet reports the WORST replica — the SLO view)
    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    warm_prefix_admissions: int = 0
    prefix_hit_tokens: int = 0
    sticky_routes: int = 0        # submissions won by warm affinity
    replayed_requests: int = 0
    restarts: int = 0
    threaded: bool = False
    per_replica: Dict[str, dict] = dataclasses.field(
        default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Disaggregated KV handoff: block table as the wire format
# ---------------------------------------------------------------------------

# module-level jitted transfer pair: one compile per (cache shape,
# padded page count) across every handoff in the process — a fresh
# jax.jit per call would retrace per transfer
_gather_jit = jax.jit(gather_cache_blocks)
_scatter_jit = functools.partial(jax.jit, donate_argnums=(0,))(
    scatter_cache_blocks)


def _geometry_key(cfg) -> tuple:
    return (cfg.num_layers, cfg.num_heads, cfg.head_dim,
            cfg.block_size, cfg.kv_dtype, str(cfg.storage_dtype))


def transfer_prefix(src: ServingEngine, dst: ServingEngine,
                    prompt: Sequence[int], *,
                    monitor=None) -> Optional[int]:
    """Ship ``prompt``'s resident KV pages from ``src``'s pool into
    ``dst``'s — the disaggregated prefill→decode handoff.

    The wire format is the block table itself: ``src``'s shared index
    names the pages (every full block plus the partial tail),
    :func:`~.model.gather_cache_blocks` pulls them as one
    ``(L, n, hk, bs, dk)`` payload in storage layout (int8 rows ship
    with their fp32 scales, bf16 ships bf16 — nothing requantizes),
    ``dst`` claims ``n`` pool blocks via :meth:`~.kv_cache.
    KVCacheManager.register_external` (indexed shared, parked idle —
    exactly a finished local request's state), and
    :func:`~.model.scatter_cache_blocks` lands the payload.  The next
    admission of this prompt on ``dst`` maps the pages WARM.

    Both pools are padded to ``dst``'s page ladder, so repeated
    handoffs of rung-sized spans reuse one compiled gather/scatter
    pair per rung.  Returns the page count shipped, 0 when ``dst``
    already had the prompt resident (no device traffic), or None when
    ``src`` does not hold the whole prompt (the caller falls back to
    a cold admission)."""
    if _geometry_key(src.cache_cfg) != _geometry_key(dst.cache_cfg):
        raise ValueError(
            f"KV handoff across incompatible cache geometries: "
            f"{_geometry_key(src.cache_cfg)} -> "
            f"{_geometry_key(dst.cache_cfg)}")
    src_blocks = src.manager.resident_prefix(prompt)
    if src_blocks is None:
        return None
    n = len(src_blocks)
    dst_blocks = dst.manager.register_external(prompt, n)
    if dst_blocks is None:
        return 0                       # already resident — warm as-is
    # pad both tables to dst's page rung: the padding gathers dump-
    # page zeros and scatters them back into dst's dump page — dead
    # bytes into a dead page, and one compile covers the whole rung
    pn = dst.ladder.pick_pages(n)
    sb = np.full(pn, DUMP_BLOCK, np.int32)
    db = np.full(pn, DUMP_BLOCK, np.int32)
    sb[:n] = src_blocks
    db[:n] = dst_blocks
    k, v, ks, vs = _gather_jit(src.cache, jnp.asarray(sb))
    # the wire hop: the payload leaves src's device for dst's pool
    # (dst may be another device, or a TP shard layout — the dst
    # cache's own sharding describes both)
    sharding = dst.cache.k.sharding
    k, v = jax.device_put(k, sharding), jax.device_put(v, sharding)
    if ks is not None:
        ks_sh = dst.cache.k_scale.sharding
        ks = jax.device_put(ks, ks_sh)
        vs = jax.device_put(vs, ks_sh)
    with contextlib.ExitStack() as stack:
        dev = getattr(dst, "device", None)
        if dev is not None:
            stack.enter_context(jax.default_device(dev))
        dst.cache = _scatter_jit(dst.cache, k, v, ks, vs,
                                 jnp.asarray(db))
    if monitor is not None:
        monitor.event("fleet", "kv_handoff", value=n,
                      pages=n, padded=pn,
                      prompt_tokens=len(prompt),
                      src=str(src.replica_id),
                      dst=str(dst.replica_id))
    return n


def export_prefix_payload(src: ServingEngine, prompt: Sequence[int]
                          ) -> Optional[tuple]:
    """The source half of :func:`transfer_prefix` as HOST data — the
    process-fleet wire format (ISSUE-18).  Gathers ``prompt``'s
    resident pages exactly as the in-process handoff does (same
    ``_gather_jit``, same rung padding, int8 rows + fp32 scales
    verbatim) but lands them as numpy arrays a socket can carry.
    Returns ``(n, arrays)`` with ``arrays`` mapping ``k``/``v`` (and
    ``ks``/``vs`` for quantized storage) to host ndarrays padded to
    ``src.ladder.pick_pages(n)``, or None when ``src`` does not hold
    the whole prompt (the caller falls back to a cold admission)."""
    src_blocks = src.manager.resident_prefix(prompt)
    if src_blocks is None:
        return None
    n = len(src_blocks)
    pn = src.ladder.pick_pages(n)
    sb = np.full(pn, DUMP_BLOCK, np.int32)
    sb[:n] = src_blocks
    k, v, ks, vs = _gather_jit(src.cache, jnp.asarray(sb))
    arrays = {"k": np.asarray(k), "v": np.asarray(v)}
    if ks is not None:
        arrays["ks"] = np.asarray(ks)
        arrays["vs"] = np.asarray(vs)
    return n, arrays


def import_prefix_payload(dst: ServingEngine, prompt: Sequence[int],
                          n: int, arrays: Dict[str, Any]) -> int:
    """The destination half of :func:`transfer_prefix` from HOST data
    (ISSUE-18 socket handoff): claim ``n`` pool blocks via
    ``register_external`` and scatter the payload produced by
    :func:`export_prefix_payload`.  Both replicas must share the
    cache geometry AND the page ladder (one :class:`EngineSpec` per
    fleet guarantees it); a payload whose padded page count does not
    match this side's rung is rejected — the caller treats it as a
    torn handoff and admits cold.  Returns the page count landed, or
    0 when the prompt was already resident (no device traffic)."""
    pn = dst.ladder.pick_pages(int(n))
    if int(arrays["k"].shape[1]) != pn:
        raise ValueError(
            f"KV payload padded to {int(arrays['k'].shape[1])} "
            f"page(s) but this replica's ladder pads {n} -> {pn}: "
            f"mismatched page ladders across the fleet")
    dst_blocks = dst.manager.register_external(prompt, int(n))
    if dst_blocks is None:
        return 0                       # already resident — warm as-is
    db = np.full(pn, DUMP_BLOCK, np.int32)
    db[:n] = dst_blocks
    sharding = dst.cache.k.sharding
    k = jax.device_put(jnp.asarray(arrays["k"]), sharding)
    v = jax.device_put(jnp.asarray(arrays["v"]), sharding)
    ks = vs = None
    if "ks" in arrays:
        ks_sh = dst.cache.k_scale.sharding
        ks = jax.device_put(jnp.asarray(arrays["ks"]), ks_sh)
        vs = jax.device_put(jnp.asarray(arrays["vs"]), ks_sh)
    with contextlib.ExitStack() as stack:
        dev = getattr(dst, "device", None)
        if dev is not None:
            stack.enter_context(jax.default_device(dev))
        dst.cache = _scatter_jit(dst.cache, k, v, ks, vs,
                                 jnp.asarray(db))
    return int(n)


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------

class FleetRouter:
    """Host-side front over N replicas: scored submission, sticky
    warm routing, disaggregated prefill, rolling weight swap, and the
    stepped / threaded fleet drive loops.  See the module docstring
    for the architecture; ``docs/api/serving.md#fleet-serving`` for
    the worked walkthroughs."""

    def __init__(self, replicas: Sequence[Replica], *,
                 policy: Optional[str] = None, monitor=None,
                 aggregator: Optional[FleetAggregator] = None,
                 exporter=None,
                 clock: Callable[[], float] = time.perf_counter):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self.serve_replicas = [r for r in self.replicas
                               if r.role == "serve"]
        self.prefill_replicas = [r for r in self.replicas
                                 if r.role == "prefill"]
        if not self.serve_replicas:
            raise ValueError("a fleet needs at least one serve-role "
                             "replica (prefill replicas only feed)")
        sizes = {r.engine.cache_cfg.block_size for r in self.replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on the KV block size {sizes} — "
                f"prefix chain keys would not be comparable")
        self.block_size = sizes.pop()
        self.policy = policy if policy is not None \
            else (flag_str("APEX_TPU_SERVE_ROUTER") or "gauges")
        if self.policy not in ROUTER_POLICIES:
            raise ValueError(f"router policy {self.policy!r} not in "
                             f"{ROUTER_POLICIES}")
        if self.prefill_replicas:
            for r in self.replicas:
                if not r.engine.prefix_share:
                    raise ValueError(
                        f"disaggregated prefill needs "
                        f"prefix_share=True on every replica "
                        f"(replica {r.replica_id!r} has it off) — "
                        f"the handoff lands through the shared "
                        f"index")
        self.monitor = monitor
        # ISSUE-17 live metrics plane: the aggregator folds every
        # round's per-replica router_snapshot()s into fleet series
        # with trend windows (queue depth, free blocks net, backlog,
        # tokens/tick, compile deltas) and emits one ``fleet_tick``
        # event per router round; an attached exporter additionally
        # gets one published snapshot per round — per-replica series
        # under ``replica`` labels plus the fleet aggregates, all on
        # the router's single drive thread (no locks)
        self.aggregator = aggregator if aggregator is not None \
            else FleetAggregator()
        self.exporter = exporter
        self._clock = clock
        self._rr = 0
        self._pending: deque = deque()
        # submissions ROUTED but not yet engine-submitted (the
        # threaded drive plans every share before any engine sees a
        # request): counted into the backlog score, or every tied
        # snapshot would hand the whole batch to the first replica
        self._planned: Dict[str, int] = {}
        # rid -> (request, prefill replica): probes in flight
        self._handoffs: Dict[str, Any] = {}
        self.submitted = 0
        self.swaps = 0
        self.handoffs = 0
        self.handoff_blocks = 0
        self.sticky_routes = 0
        self.replayed = 0

    # --- events ---------------------------------------------------------

    def _event(self, name: str, value=None, **attrs) -> None:
        if self.monitor is not None:
            self.monitor.event("fleet", name, value=value, **attrs)

    # --- live metrics plane (ISSUE-17) ----------------------------------

    def fleet_tick(self, round_idx: int) -> Dict[str, Any]:
        """One aggregation round: gather every replica's
        ``router_snapshot()`` (the same cheap host struct routing
        already reads), fold it through the :class:`~apex_tpu.
        monitor.export.FleetAggregator`, emit ONE ``fleet_tick``
        event (step = router round, ``ticks`` = the measured engine-
        tick delta this window — the true rate denominator), and
        publish to the attached exporter.  Called once per stepped-
        loop round; the threaded drive calls it once after the join
        (its workers own their engines' ticks — aggregating from the
        drive thread only is the APX801 discipline)."""
        snapshots = {r.replica_id: r.engine.router_snapshot()
                     for r in self.replicas}
        attrs = self.aggregator.observe(round_idx, snapshots)
        if self.monitor is not None:
            self.monitor.event("fleet_tick", "fleet_tick",
                               value=attrs.get("queue_depth"),
                               step=round_idx, **attrs)
        if self.exporter is not None:
            try:
                self.exporter.publish(
                    self.fleet_registry(snapshots), tick=round_idx,
                    health=self.fleet_health(),
                    varz=self.fleet_varz())
            except Exception as e:  # telemetry must never kill serve
                logger.warning("fleet exporter publish failed: %s",
                               str(e)[:160])
        return attrs

    def fleet_registry(self,
                       snapshots: Optional[Dict[str, Dict[str, Any]]]
                       = None) -> "MetricsRegistry":
        """One exposition document for the whole fleet: every
        replica's engine series under its ``replica`` label plus the
        fleet-aggregate gauges and trend series."""
        reg = MetricsRegistry()
        for r in self.replicas:
            r.engine.export_registry(reg)
        if snapshots is None:
            snapshots = {r.replica_id: r.engine.router_snapshot()
                         for r in self.replicas}
        qd = sum(int(s.get("queue_depth", 0))
                 for s in snapshots.values())
        free_net = sum(int(s.get("available_blocks", 0))
                       - int(s.get("reserved_blocks", 0))
                       for s in snapshots.values())
        backlog = sum(int(s.get("queue_depth", 0))
                      + int(s.get("prefilling", 0))
                      + int(s.get("active", 0))
                      for s in snapshots.values())
        reg.gauge("apex_tpu_fleet_replicas",
                  "Serve-role replicas in the fleet."
                  ).set(len(self.serve_replicas))
        reg.gauge("apex_tpu_fleet_queue_depth",
                  "Fleet-wide admission queue depth.").set(qd)
        reg.gauge("apex_tpu_fleet_free_blocks_net",
                  "Fleet free+idle KV blocks net of reservations."
                  ).set(free_net)
        reg.gauge("apex_tpu_fleet_backlog",
                  "Fleet queued + prefilling + active requests."
                  ).set(backlog)
        c = reg.counter("apex_tpu_fleet_requests_routed_total",
                        "Requests the router submitted.")
        c.set(self.submitted)
        reg.counter("apex_tpu_fleet_kv_handoffs_total",
                    "Disaggregated prefill->decode KV handoffs."
                    ).set(self.handoffs)
        reg.counter("apex_tpu_fleet_swaps_total",
                    "Rolling weight swaps completed."
                    ).set(self.swaps)
        trend = reg.gauge("apex_tpu_fleet_trend",
                          "Windowed trend per fleet series "
                          "(least-squares slope / EWMA).")
        for series, t in self.aggregator.trends().items():
            trend.set(t["slope"], series=series, stat="slope")
            trend.set(t["ewma"], series=series, stat="ewma")
        return reg

    def fleet_health(self) -> Dict[str, Any]:
        """Fleet /healthz: ok iff every serve replica is ok; the
        worst replica's status wins the headline."""
        order = ("draining", "escalated", "slo_burning", "shedding",
                 "ok")
        per = {r.replica_id: r.engine.health_state()
               for r in self.replicas}
        ok = all(h["ok"] for h in per.values())
        worst = min((h["status"] for h in per.values()),
                    key=lambda s: order.index(s)
                    if s in order else 0, default="ok")
        return {"ok": ok, "status": worst,
                "replicas": {rid: h["status"]
                             for rid, h in sorted(per.items())}}

    def fleet_varz(self) -> Dict[str, Any]:
        return {rid: snap for rid, snap in sorted(
            (r.replica_id, r.engine.snapshot_state())
            for r in self.replicas)}

    # --- routing --------------------------------------------------------

    def _warm_tokens(self, snap: Dict[str, Any],
                     keys: List[bytes], pkey) -> int:
        """Prompt tokens a replica's warm-prefix keys already cover:
        consecutive full-block chain hits from the front (the chain
        property makes any later hit imply these), plus the partial
        tail when every full block hit."""
        index = snap.get("warm_prefix_keys") or ()
        tokens = 0
        hit_all = True
        for key in keys:
            if key in index:
                tokens += self.block_size
            else:
                hit_all = False
                break
        if hit_all and pkey is not None and pkey in index:
            tokens += 1               # partial tail resident too
        return tokens

    def route(self, request: Request) -> Replica:
        """Pick the serve replica for one submission.  ``gauges``
        policy: sticky warm affinity first (most prompt tokens already
        resident in a replica's prefix index), then pool headroom
        (free + idle blocks net of in-flight reservations), then the
        smallest backlog; shed-engaged replicas are avoided while any
        alternative exists.  ``round_robin`` ignores all signals (the
        A/B control the bench row compares against)."""
        candidates = [r for r in self.serve_replicas if r.routable]
        if not candidates:
            raise RuntimeError(
                "no routable serve replica (every replica is "
                "admit-stopped) — rolling swap drains one at a time "
                "precisely so this cannot happen")
        if self.policy == "round_robin" or len(candidates) == 1:
            r = candidates[self._rr % len(candidates)]
            self._rr += 1
            return r
        keys, pkey = prefix_chain_keys(request.prompt,
                                       self.block_size)
        best = None
        best_score = None
        warm_best = 0
        for r in candidates:
            snap = r.engine.router_snapshot()
            warm = self._warm_tokens(snap, keys, pkey)
            headroom = (snap["available_blocks"]
                        - snap["reserved_blocks"])
            backlog = (snap["queue_depth"] + snap["prefilling"]
                       + snap["active"]
                       + self._planned.get(r.replica_id, 0))
            score = (0 if snap["shed_engaged"] else 1, warm,
                     headroom, -backlog)
            if best_score is None or score > best_score:
                best, best_score, warm_best = r, score, warm
        if warm_best > 0:
            self.sticky_routes += 1
        return best

    def submit(self, request: Request) -> Replica:
        """Route one request into the fleet.  With prefill-role
        replicas the submission disaggregates: the prompt runs on a
        prefill replica first (a 1-token probe under a ``pf:`` rid);
        its finished pages hand off to the decode replica this method
        already chose, and the REAL request submits there on arrival
        — a warm admission.  (Single-token prompts skip the split:
        there is nothing to transfer that the decode replica would
        not immediately rewrite.)"""
        target = self.route(request)
        if self.prefill_replicas and len(request.prompt) > 1:
            # anchor the request's clock NOW: its TTFT must count the
            # prefill-probe wait and the KV handoff, not restart at
            # the decode-side submit rounds later (the router and the
            # engines share the perf_counter timebase)
            if request.submit_t is None:
                request.submit_t = self._clock()
            pf = min(self.prefill_replicas,
                     key=lambda r: (len(r.engine.queue)
                                    + len(r.engine.prefilling)
                                    + len(r.engine.active)))
            probe = Request(rid=f"{PREFILL_RID_PREFIX}{request.rid}",
                            prompt=list(request.prompt),
                            max_new_tokens=1,
                            priority=request.priority)
            pf.engine.submit(probe)
            self._handoffs[probe.rid] = (request, pf, target)
            self.submitted += 1
            self._event("request_routed", rid=str(request.rid),
                        replica=target.replica_id,
                        prefill_replica=pf.replica_id,
                        disaggregated=True)
            return target
        target.engine.submit(request)
        self.submitted += 1
        self._event("request_routed", rid=str(request.rid),
                    replica=target.replica_id)
        return target

    def _advance_handoffs(self) -> None:
        """Complete any prefill probes whose prompt pages are fully
        written: transfer the pages to the chosen decode replica and
        submit the real request there (warm).  A probe that ended
        without registering its prompt (preempted/shed/deadline on
        the prefill side) falls back to a COLD submission — the
        request is never lost, it just pays the prefill again."""
        if not self._handoffs:
            return
        finished = []
        for pf_rid, (req, pf, target) in self._handoffs.items():
            probe = next((q for q in pf.engine.done
                          if str(q.rid) == pf_rid), None)
            if probe is None:
                continue
            finished.append(pf_rid)
            if not target.routable:
                target = self.route(req)
            shipped = transfer_prefix(pf.engine, target.engine,
                                      req.prompt,
                                      monitor=self.monitor)
            if shipped is not None:
                self.handoffs += 1
                self.handoff_blocks += shipped
            else:
                logger.warning(
                    "prefill probe %s finished but its prompt is not "
                    "resident on %s — cold fallback", pf_rid,
                    pf.replica_id)
            target.engine.submit(req)
        for pf_rid in finished:
            del self._handoffs[pf_rid]

    # --- rolling weight swap --------------------------------------------

    def swap_weights(self, weights, *,
                     drain_step: Optional[Callable[[], None]] = None
                     ) -> int:
        """Zero-downtime rolling swap: one serve replica at a time is
        admit-stopped, drained (its in-flight work finishes normally
        — ``drain_step`` advances the WHOLE fleet once per wait
        round, so the other N−1 replicas keep serving), swapped
        (compiled ladder kept, pool reset), and rejoined.  Prefill
        replicas swap after the serve side (their probes only feed).
        Returns the number of replicas swapped."""
        swapped = 0
        for r in self.serve_replicas + self.prefill_replicas:
            r.routable = False
            self._event("swap_drain", replica=r.replica_id,
                        active=len(r.engine.active),
                        queued=len(r.engine.queue))
            guard = 0
            while r.busy:
                if drain_step is not None:
                    drain_step()
                else:
                    self._step_replica(r)
                guard += 1
                if guard > 1_000_000:   # defensive: a wedged replica
                    raise RuntimeError(  # must not hang the swap
                        f"replica {r.replica_id} did not drain")
            r.engine.swap_weights(weights)
            swapped += 1
            self.swaps += 1
            r.routable = True
            self._event("swap_done", replica=r.replica_id,
                        swapped=swapped)
        return swapped

    # --- stepped drive loop ----------------------------------------------

    def _step_replica(self, r: Replica) -> None:
        """One engine tick with fleet-level crash supervision: a
        journaled replica that raises recovers in place
        (crash_reset + journal replay, bounded by ``max_restarts``);
        an unjournaled one propagates — the fleet must not silently
        eat an engine bug."""
        t0 = self._clock()
        try:
            with r.device_scope():
                if r.fault is not None:
                    r.fault.before_tick(
                        r.engine.steps,
                        journal_path=(r.journal.path
                                      if r.journal is not None
                                      else None))
                r.engine.step()
        except KeyboardInterrupt:
            raise
        except Exception as e:
            if r.journal is None or r.restarts >= r.max_restarts:
                raise
            logger.warning("replica %s crashed (%s: %s) — recovering "
                           "from its journal", r.replica_id,
                           type(e).__name__, str(e)[:120])
            r.restarts += 1
            self._event("replica_restart", replica=r.replica_id,
                        error=type(e).__name__,
                        message=str(e)[:160],
                        restarts=r.restarts)
            # the replica's OWN (replica-stamped) monitor carries the
            # replay events, so per-replica logs attribute correctly
            stats = recover_engine(r.engine, r.journal,
                                   monitor=r.engine.monitor)
            self.replayed += stats.replayed
        finally:
            # the stepped loop never enters engine.run(), which is
            # where _run_wall_s normally accrues — charge each tick's
            # wall here so per-replica ServeSummary wall_s and
            # tokens_per_sec stay honest in stepped fleets too
            r.engine._run_wall_s += self._clock() - t0

    def serve(self, requests: Sequence[Request] = (), *,
              swap_after: Optional[int] = None,
              swap_weights=None,
              max_rounds: Optional[int] = None,
              before_round: Optional[Callable[[int], None]] = None
              ) -> FleetSummary:
        """Drive the fleet to completion in the deterministic stepped
        loop: each round dispatches pending submissions (scored),
        completes ripe prefill→decode handoffs, then ticks every busy
        replica once.  ``swap_after`` triggers ONE rolling weight
        swap (to ``swap_weights``) after that many rounds — the other
        replicas keep ticking while each drains, which is the
        zero-downtime property the CI leg asserts.  Returns the
        aggregate :class:`FleetSummary`."""
        if swap_after is not None and swap_weights is None:
            raise ValueError("swap_after needs swap_weights")
        self._pending.extend(requests)
        t0 = self._clock()
        rounds = 0
        swapped = swap_after is None

        def tick_all():
            for r in self.replicas:
                if r.busy:
                    self._step_replica(r)

        while True:
            while self._pending:
                self.submit(self._pending.popleft())
            self._advance_handoffs()
            if not swapped and rounds >= swap_after:
                swapped = True
                self.swap_weights(swap_weights, drain_step=tick_all)
            busy = any(r.busy for r in self.replicas)
            if not busy and not self._pending and not self._handoffs:
                break
            if before_round is not None:
                before_round(rounds)
            tick_all()
            # one fleet_tick per router round, after the replicas
            # ticked: the aggregation window's ``ticks`` stamp counts
            # the engine ticks that actually elapsed (swap drains
            # advance engines without advancing rounds — the measured
            # delta, not the nominal cadence, is the rate denominator)
            self.fleet_tick(rounds)
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return self._summary(self._clock() - t0, threaded=False)

    # --- threaded drive loop ---------------------------------------------

    def serve_threaded(self, requests: Sequence[Request], *,
                       max_restarts: Optional[int] = None,
                       scheduler=None) -> FleetSummary:
        """One thread per serve replica, each running its engine's own
        ``run()`` (or the supervised :func:`~.resilience.run_serving`
        when the replica carries a journal).  Requests are routed
        up-front; each replica then serves its share concurrently —
        jitted steps release the GIL, so on a multi-core host the
        fleet's aggregate tokens/s scales with replica count (the
        bench's scaling row).  Disaggregation needs the stepped
        loop's handoff sequencing and is rejected here.

        ``scheduler`` (an :class:`apex_tpu.analysis.schedule.
        DeterministicScheduler`) gates every replica's tick boundary
        through a seeded permuted hand-off, serializing the threads
        in a reproducible interleaving — the race-hunting stress mode
        (``python -m apex_tpu.analysis.schedule``).  Worker threads
        write NO shared attributes: each deposits its supervised-run
        stats in its own slot of ``results`` and the main thread
        aggregates after ``join()`` (a cross-thread ``self.x += y``
        is exactly the APX801 lost-update race)."""
        if self.prefill_replicas:
            raise ValueError("disaggregated prefill runs in the "
                             "stepped loop (serve()), not threads")
        shares: Dict[str, List[Request]] = {
            r.replica_id: [] for r in self.serve_replicas}
        self._planned = {}
        for req in requests:
            target = self.route(req)
            shares[target.replica_id].append(req)
            self._planned[target.replica_id] = \
                self._planned.get(target.replica_id, 0) + 1
            self.submitted += 1
            self._event("request_routed", rid=str(req.rid),
                        replica=target.replica_id)
        self._planned = {}
        errors: List[BaseException] = []
        # one slot per replica id, one writer each; read after join()
        results: Dict[str, Tuple[int, int]] = {}
        workers = [r for r in self.serve_replicas
                   if shares[r.replica_id]]
        if scheduler is not None:
            for r in workers:
                scheduler.expect(r.replica_id)

        def worker(r: Replica, share: List[Request]) -> None:
            try:
                hooks = []
                if r.fault is not None:
                    jp = r.journal.path if r.journal is not None \
                        else None
                    hooks.append(lambda tick, _f=r.fault, _jp=jp:
                                 _f.before_tick(tick,
                                                journal_path=_jp))
                if scheduler is not None:
                    hooks.append(lambda tick, _rid=r.replica_id:
                                 scheduler.gate(_rid))
                before = None
                if hooks:
                    def before(tick, _hooks=tuple(hooks)):
                        for h in _hooks:
                            h(tick)
                no_retry: tuple = ()
                if scheduler is not None:
                    # a starved schedule gate is the HARNESS failing,
                    # not an engine crash: retrying it as one would
                    # mask the starvation behind max_restarts journal
                    # replays (each gating and starving again)
                    from ..analysis.schedule import ScheduleTimeout

                    no_retry = (ScheduleTimeout,)
                with r.device_scope():
                    if r.journal is not None:
                        res = run_serving(
                            r.engine, share, journal=r.journal,
                            max_restarts=(max_restarts
                                          if max_restarts is not None
                                          else r.max_restarts),
                            monitor=self.monitor,
                            before_tick=before,
                            no_retry_on=no_retry)
                        results[r.replica_id] = (res.restarts,
                                                 res.replayed)
                    else:
                        for req in share:
                            r.engine.submit(req)
                        r.engine.run(before_tick=before)
            except BaseException as e:
                # surfaced after the join: the fleet must collect
                # every worker before re-raising the first failure
                logger.error("replica %s worker failed: %s: %s",
                             r.replica_id, type(e).__name__,
                             str(e)[:160])
                errors.append(e)
            finally:
                if scheduler is not None:
                    scheduler.finish(r.replica_id)

        t0 = self._clock()
        threads = [threading.Thread(
            target=worker, args=(r, shares[r.replica_id]),
            name=f"replica-{r.replica_id}", daemon=True)
            for r in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = self._clock() - t0
        for r in self.serve_replicas:
            got = results.get(r.replica_id)
            if got is not None:
                r.restarts += got[0]
                self.replayed += got[1]
        if errors:
            raise errors[0]
        # threaded mode has no router rounds — the workers owned
        # their engines' ticks.  One terminal aggregation round from
        # the drive thread (after the join: workers write no shared
        # state, the APX801 discipline) records the fleet's final
        # series and publishes the exporter's end state.
        self.fleet_tick(max((r.engine.steps for r in self.replicas),
                            default=0))
        return self._summary(wall, threaded=True)

    # --- aggregation ------------------------------------------------------

    def _summary(self, wall_s: float, *, threaded: bool
                 ) -> FleetSummary:
        per: Dict[str, ServeSummary] = {
            r.replica_id: r.engine.summary() for r in self.replicas}
        serve_ids = [r.replica_id for r in self.serve_replicas]
        tokens = sum(per[i].tokens_generated for i in serve_ids)
        terminal = sum(per[i].requests_done + per[i].requests_preempted
                       + per[i].requests_deadline
                       + per[i].requests_shed for i in serve_ids)
        wall = max(wall_s, 1e-9)
        summary = FleetSummary(
            replicas=len(self.serve_replicas),
            prefill_replicas=len(self.prefill_replicas),
            router_policy=self.policy,
            requests_submitted=self.submitted,
            requests_done=sum(per[i].requests_done
                              for i in serve_ids),
            requests_preempted=sum(per[i].requests_preempted
                                   for i in serve_ids),
            requests_deadline=sum(per[i].requests_deadline
                                  for i in serve_ids),
            requests_shed=sum(per[i].requests_shed
                              for i in serve_ids),
            lost_requests=self.submitted - terminal
            - len(self._handoffs),
            tokens_generated=tokens,
            wall_s=round(wall, 4),
            tokens_per_sec=round(tokens / wall, 2),
            sum_decode_tokens_per_sec=round(
                sum(per[i].decode_tokens_per_sec
                    for i in serve_ids), 2),
            swaps=self.swaps,
            handoffs=self.handoffs,
            handoff_blocks=self.handoff_blocks,
            ttft_p50_ms=max(
                (per[i].ttft_p50_ms for i in serve_ids
                 if per[i].ttft_p50_ms is not None),
                default=None),
            ttft_p99_ms=max(
                (per[i].ttft_p99_ms for i in serve_ids
                 if per[i].ttft_p99_ms is not None),
                default=None),
            warm_prefix_admissions=sum(
                per[i].warm_prefix_admissions for i in serve_ids),
            prefix_hit_tokens=sum(per[i].prefix_hit_tokens
                                  for i in serve_ids),
            sticky_routes=self.sticky_routes,
            replayed_requests=sum(per[i].replayed_requests
                                  for i in per),
            restarts=sum(r.restarts for r in self.replicas),
            threaded=threaded,
            per_replica={i: s.as_dict() for i, s in per.items()})
        self._event("fleet_done", value=summary.tokens_per_sec,
                    **{k: v for k, v in summary.as_dict().items()
                       if k != "per_replica"})
        return summary
