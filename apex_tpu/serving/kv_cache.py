"""Block-paged KV cache: device layout + host block-pool bookkeeping.

The serving cache is a fixed pool of ``num_blocks`` blocks of
``block_size`` tokens each, shared by every in-flight request.  A
request owns an ordered list of block ids (its *block table*); growing
a sequence past a block boundary appends one block from the free list,
finishing a request returns its blocks.  Nothing is ever moved or
compacted — **defrag-free paging**: the flash-decode kernel gathers
pages through the block table (scalar-prefetched index map), so block
ids need no spatial locality, and admission/eviction cost is O(pages
touched), never O(cache).

Two cleanly separated halves:

* :class:`PagedKVCache` — the DEVICE state: per-layer k/v block arrays
  stacked over layers, ``(L, nb, hk, bs, dk)``, plus optional int8
  per-row scales ``(L, nb, h, bs)``.  A pytree, threaded through the
  jitted prefill/decode steps and **donated** every step (the same
  carry discipline as the scan driver's amp state — the cache is the
  largest buffer in the serving process, double-buffering it halves
  capacity).  ``hk``/``dk`` follow the d=64 head-pair packing decision
  (:func:`apex_tpu.ops.flash_decode.use_decode_head_packing`) so the
  kernel and the layout can never disagree.
* :class:`KVCacheManager` — the HOST bookkeeping: free list, per-
  request tables and lengths.  Pure Python, no device work; the engine
  consults it between jitted steps (the continuous-batching boundary).

Block 0 is reserved as the **dump page**: it is never handed to a
request, block-table padding points at it, and inactive batch rows
write their (masked-out) k/v there — so a bucketed decode step needs
no write masking and a dead page read contributes exactly 0.

Storage dtype (``APEX_TPU_SERVE_KV_DTYPE``): ``model`` stores k/v in
the model compute dtype, ``bf16`` forces bfloat16 (the O4/O5-native
choice), ``int8`` stores weight-only-quantized rows with per-token,
per-head fp32 scales — appending never requantizes history, and the
kernel dequantizes per page in VMEM (docs/api/serving.md#kv-dtype).
"""
from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..ops.flash_decode import use_decode_head_packing

__all__ = ["KVCacheConfig", "PagedKVCache", "KVCacheManager",
           "PrefixMatch", "CachePoolExhausted", "init_cache",
           "write_token_kv", "write_prefill_kv", "quantize_kv_rows",
           "prefix_chain_keys", "DUMP_BLOCK"]

# block 0: never allocated, pads every block table, absorbs inactive
# rows' writes.  Reads of it are always masked to an exact 0 weight.
DUMP_BLOCK = 0

_KV_DTYPES = ("model", "bf16", "int8")


class CachePoolExhausted(RuntimeError):
    """The block pool cannot cover a requested allocation — the
    admission-control signal (callers check :meth:`KVCacheManager.
    can_admit` first; racing past it raises this)."""


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape/dtype plan for one paged cache."""

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int          # INCLUDING the reserved dump block
    block_size: int
    kv_dtype: str = "model"  # 'model' | 'bf16' | 'int8'
    model_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.kv_dtype not in _KV_DTYPES:
            raise ValueError(f"kv_dtype {self.kv_dtype!r} not in "
                             f"{_KV_DTYPES}")
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved dump page)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @property
    def packed(self) -> bool:
        return use_decode_head_packing(self.num_heads, self.head_dim)

    @property
    def storage_dtype(self):
        if self.kv_dtype == "int8":
            return jnp.int8
        if self.kv_dtype == "bf16":
            return jnp.bfloat16
        return self.model_dtype

    @property
    def quantized(self) -> bool:
        return self.kv_dtype == "int8"

    @property
    def kv_shape(self):
        """(L, nb, hk, bs, dk) — the packed storage head axes."""
        h, d = self.num_heads, self.head_dim
        hk, dk = (h // 2, 2 * d) if self.packed else (h, d)
        return (self.num_layers, self.num_blocks, hk,
                self.block_size, dk)

    @property
    def scale_shape(self):
        """(L, nb, h, bs) — scales keep GLOBAL head order."""
        return (self.num_layers, self.num_blocks, self.num_heads,
                self.block_size)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    def blocks_for(self, length: int) -> int:
        return -(-max(int(length), 1) // self.block_size)

    def cache_nbytes(self) -> int:
        per = np.dtype(self.storage_dtype).itemsize
        n = 2 * int(np.prod(self.kv_shape)) * per
        if self.quantized:
            n += 2 * int(np.prod(self.scale_shape)) * 4
        return n


class PagedKVCache(NamedTuple):
    """Device half of the cache (a pytree — jit/donation friendly)."""

    k: jnp.ndarray                     # (L, nb, hk, bs, dk)
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray]     # (L, nb, h, bs) fp32 | None
    v_scale: Optional[jnp.ndarray]

    def layer(self, i: int):
        """(k, v, k_scale, v_scale) views of layer ``i``."""
        return (self.k[i], self.v[i],
                None if self.k_scale is None else self.k_scale[i],
                None if self.v_scale is None else self.v_scale[i])


def init_cache(config: KVCacheConfig) -> PagedKVCache:
    """All-zero cache (zeros are the safe dead-page filler: even an
    unmasked read of a never-written row contributes finite values)."""
    k = jnp.zeros(config.kv_shape, config.storage_dtype)
    v = jnp.zeros(config.kv_shape, config.storage_dtype)
    if config.quantized:
        # k/v scales must be DISTINCT buffers: the cache pytree is
        # donated every step, and aliased leaves would donate the same
        # buffer twice
        return PagedKVCache(k, v,
                            jnp.zeros(config.scale_shape, jnp.float32),
                            jnp.zeros(config.scale_shape, jnp.float32))
    return PagedKVCache(k, v, None, None)


def quantize_kv_rows(x: jnp.ndarray):
    """Per-row symmetric int8: ``x`` (..., d) -> (int8 values,
    (...,) fp32 scales).  Each cached token row quantizes against its
    own amax, so appends never touch history."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _to_storage(x, config: KVCacheConfig):
    """(..., h, d) new rows -> (storage values (..., hk, dk),
    scales (..., h) | None) per the cache layout."""
    if config.quantized:
        q, scale = quantize_kv_rows(x)
        if config.packed:
            q = q.reshape(*q.shape[:-2], config.num_heads // 2,
                          2 * config.head_dim)
        return q, scale
    if config.packed:
        x = x.reshape(*x.shape[:-2], config.num_heads // 2,
                      2 * config.head_dim)
    return x.astype(config.storage_dtype), None


def write_token_kv(cache: PagedKVCache, config: KVCacheConfig,
                   layer: int, k_new: jnp.ndarray, v_new: jnp.ndarray,
                   blocks: jnp.ndarray,
                   offsets: jnp.ndarray) -> PagedKVCache:
    """Scatter ONE token's k/v per batch row into layer ``layer``'s
    page slots.

    ``k_new``/``v_new`` (b, h, d) in model dtype; ``blocks``/
    ``offsets`` (b,) int32 address each row's current page and in-page
    slot (inactive rows point at the dump block).  Per-layer because
    the decode step interleaves write -> attend inside its layer loop
    (the new token attends to itself through the cache).  Traced code
    — runs inside the jitted decode step; the cache argument is
    donated by the caller so the scatter is in-place on device."""
    kq, ks = _to_storage(k_new, config)
    vq, vs = _to_storage(v_new, config)
    # scalar layer index collapses axis 0; the (blocks@0, offsets@2)
    # advanced pair around the head slice selects (b, hk, dk) rows
    k = cache.k.at[layer, blocks, :, offsets, :].set(kq)
    v = cache.v.at[layer, blocks, :, offsets, :].set(vq)
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if config.quantized:
        k_scale = k_scale.at[layer, blocks, :, offsets].set(ks)
        v_scale = v_scale.at[layer, blocks, :, offsets].set(vs)
    return PagedKVCache(k, v, k_scale, v_scale)


def write_prefill_kv(cache: PagedKVCache, config: KVCacheConfig,
                     layer: int, k_all: jnp.ndarray,
                     v_all: jnp.ndarray,
                     blocks: jnp.ndarray) -> PagedKVCache:
    """Scatter a prefilled prompt's whole k/v for one layer into its
    pages.

    ``k_all``/``v_all`` (s_pad, h, d) with ``s_pad = len(blocks) *
    block_size``; ``blocks`` (n_pages,) int32 — pages past the
    request's owned tail point at the dump block (duplicate dump
    writes race harmlessly: the dump page is never read unmasked)."""
    s_pad, h, d = k_all.shape
    bs = config.block_size
    n_pages = s_pad // bs

    def paged(x):
        q, scale = _to_storage(x, config)
        # (P*bs, hk, dk) -> (P, hk, bs, dk)
        q = q.reshape(n_pages, bs, *q.shape[-2:]).transpose(0, 2, 1, 3)
        if scale is not None:
            scale = scale.reshape(n_pages, bs, h).transpose(0, 2, 1)
        return q, scale

    kq, ks = paged(k_all)
    vq, vs = paged(v_all)
    k = cache.k.at[layer, blocks].set(kq)
    v = cache.v.at[layer, blocks].set(vq)
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if config.quantized:
        k_scale = k_scale.at[layer, blocks].set(ks)
        v_scale = v_scale.at[layer, blocks].set(vs)
    return PagedKVCache(k, v, k_scale, v_scale)


def prefix_chain_keys(prompt: Sequence[int], block_size: int):
    """(full-block chain keys, partial-tail key or None) for a prompt.
    Key ``i`` commits to tokens ``[0, (i+1)*bs)`` — a chain, so
    matching key ``i`` implies matching every earlier block too.  The
    ONE hashing convention for the whole serving stack: the manager's
    shared-prefix index, the fleet router's sticky-warm probe
    (:meth:`KVCacheManager.prefix_keys` against a prompt's keys), and
    the disaggregated KV handoff's registration all speak it — so a
    key computed on one replica addresses the same content on any
    other."""
    bs = int(block_size)
    h = hashlib.blake2b(b"apex-prefix", digest_size=16)
    keys: List[bytes] = []
    full = len(prompt) // bs
    for i in range(full):
        h.update(np.asarray(prompt[i * bs:(i + 1) * bs],
                            np.int64).tobytes())
        keys.append(h.digest())
    pkey = None
    tail = prompt[full * bs:]
    if len(tail):
        hp = h.copy()
        hp.update(b"partial")
        hp.update(np.asarray(tail, np.int64).tobytes())
        pkey = hp.digest()
    return keys, pkey


class PrefixMatch(NamedTuple):
    """What :meth:`KVCacheManager.match_prefix` found for a prompt.

    ``blocks`` are the shared page ids to map (in page order),
    ``tokens`` the prompt positions their cached k/v covers (the
    prefill-skipped span — always ``<= len(prompt) - 1``, so at least
    one tail token runs through the model to produce the first
    generated token), ``cow`` whether the LAST mapped block must be
    copied-on-write before the tail prefill (the tail's first write
    lands inside it — the full-prompt warm-hit case)."""

    blocks: Tuple[int, ...]
    tokens: int
    cow: bool

    @property
    def warm(self) -> bool:
        return bool(self.blocks)


_NO_MATCH = PrefixMatch(blocks=(), tokens=0, cow=False)


class KVCacheManager:
    """Host-side block pool + per-request block tables, with optional
    copy-on-write prompt-prefix sharing.

    Free blocks form a LIFO stack: an evict-then-readmit cycle hands
    the same ids back (the tests' bitwise block-reuse proof), and hot
    blocks stay hot.  All methods are O(pages touched).

    **Prefix sharing** (``prefix_sharing=True``): full prompt blocks
    are chain-content-hashed into ``_index`` (hash of block ``i``
    commits to every token before it, so a hit is a hit on the whole
    prefix, not one block's bytes), plus one entry for the prompt's
    final partial block.  A shared block carries a refcount = number
    of request tables mapping it; it is **read-only** while mapped —
    a write into it (the owner's first decode append into its partial
    prompt block, or a warm full-prompt hit's tail re-prefill) must go
    through :meth:`cow_for_append` / :meth:`make_private`, which swap
    in a fresh private block and hand the caller the (src, dst) pair
    to device-copy.  Eviction decrements refcounts; a block reaching
    zero moves to an **idle LRU** (still cached, OFF the free list) so
    a later identical prompt still hits warm — idle blocks are
    reclaimed (unregistered) only when an allocation finds the free
    list empty.  ``can_admit`` counts idle blocks as available and a
    warm request's need as only its unshared tail."""

    def __init__(self, config: KVCacheConfig, *,
                 prefix_sharing: bool = False):
        self.config = config
        # stack: pop() from the end; ids descend so the FIRST blocks
        # handed out are 1, 2, 3, ... (stable, test-friendly)
        self._free: List[int] = list(range(config.num_blocks - 1, 0,
                                           -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        self.prefix_sharing = bool(prefix_sharing)
        self._index: Dict[bytes, int] = {}       # chain key -> block
        self._block_key: Dict[int, bytes] = {}   # reverse
        self._refs: Dict[int, int] = {}          # active mappings
        self._idle: "OrderedDict[int, None]" = OrderedDict()
        self._shared_of: Dict[object, set] = {}
        # lifetime stats (the ServeSummary / gauge feed; the engine
        # owns the token-level warm-hit accounting)
        self.prefix_hits = 0
        self.cow_copies = 0
        self.shared_blocks_hw = 0

    # --- capacity -----------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def idle_blocks(self) -> int:
        """Shared blocks no live request maps (cached, reclaimable)."""
        return len(self._idle)

    @property
    def available_blocks(self) -> int:
        """What an allocation can actually draw on: the free list
        plus idle shared blocks (reclaimed LRU-first on demand)."""
        return len(self._free) + len(self._idle)

    @property
    def shared_blocks(self) -> int:
        return len(self._block_key)

    @property
    def used_blocks(self) -> int:
        return self.config.usable_blocks - len(self._free)

    def can_admit(self, prompt_len: int, max_new_tokens: int, *,
                  reserved_blocks: int = 0,
                  prefix: Optional[PrefixMatch] = None) -> bool:
        """Reservation admission: the request's WHOLE worst case
        (``prompt_len + max_new_tokens``) must fit the pool right
        now, net of ``reserved_blocks`` the pool already owes
        in-flight requests (their own worst cases minus the pages
        they hold) — so a later :meth:`append` can never exhaust the
        pool mid-decode.  Admitting on anything weaker (e.g. prompt
        plus one token of headroom) re-opens exactly that crash.

        A warm ``prefix`` (from :meth:`match_prefix`) shrinks the
        bill: mapped shared pages come from the index, not the pool,
        so only the unshared tail (plus one replacement block when
        ``prefix.cow`` says the last mapped page will be
        copied-on-write) counts against the free list — warm prefixes
        admit more load, not just faster.  Matched blocks currently
        parked idle are excluded from the available count (mapping
        them consumes their idle slot, not a free block)."""
        s = len(prefix.blocks) if prefix is not None else 0
        cow = prefix.cow if prefix is not None else False
        idle_matched = sum(1 for b in (prefix.blocks if prefix
                                       else ()) if b in self._idle)
        need = self.config.blocks_for(prompt_len + max_new_tokens) \
            - s + (1 if cow else 0)
        return need <= self.available_blocks - idle_matched \
            - reserved_blocks

    # --- prefix index -------------------------------------------------

    def _chain_keys(self, prompt: Sequence[int]):
        """(full-block chain keys, partial-tail key or None) — see
        :func:`prefix_chain_keys`."""
        return prefix_chain_keys(prompt, self.config.block_size)

    def match_prefix(self, prompt: Sequence[int]) -> PrefixMatch:
        """Longest warm prefix of ``prompt`` in the shared index.
        Never covers the final token (the tail prefill must emit the
        first generated token); a match reaching the whole prompt maps
        every page and flags the last one for copy-on-write instead."""
        if not self.prefix_sharing or len(prompt) < 2:
            return _NO_MATCH
        keys, pkey = self._chain_keys(prompt)
        blocks: List[int] = []
        for key in keys:
            blk = self._index.get(key)
            if blk is None:
                break
            blocks.append(blk)
        tokens = len(blocks) * self.config.block_size
        cow = False
        if len(blocks) == len(keys) and pkey is not None:
            blk = self._index.get(pkey)
            if blk is not None:
                blocks.append(blk)
                tokens = len(prompt)
        if not blocks:
            return _NO_MATCH
        if tokens >= len(prompt):
            # full-prompt hit: the tail is the final token, whose page
            # is the last mapped block — copy-on-write before writing
            tokens = len(prompt) - 1
            cow = True
        return PrefixMatch(blocks=tuple(blocks), tokens=tokens,
                           cow=cow)

    def register_prefix(self, rid, prompt: Sequence[int]) -> int:
        """Index ``rid``'s freshly prefilled prompt pages as shared:
        every full block plus the final partial block, keyed by the
        content chain.  Pages already mapped from the index stay as
        they are; content another block already owns is not
        re-registered (two identical cold admissions race — first
        writer wins, the second's pages stay private).  Returns the
        number of newly registered blocks.  Call only after the
        prompt's k/v is fully written (a concurrent warm admission
        must never map unwritten pages)."""
        if not self.prefix_sharing:
            return 0
        keys, pkey = self._chain_keys(prompt)
        table = self._tables[rid]
        shared = self._shared_of.setdefault(rid, set())
        new = 0
        entries = list(enumerate(keys))
        if pkey is not None:
            entries.append((len(keys), pkey))
        for page, key in entries:
            blk = table[page]
            owner = self._index.get(key)
            if owner is not None:
                continue                  # mapped warm, or a duplicate
            if blk in self._block_key:
                continue                  # block already shared as
            self._index[key] = blk        # different content (cannot
            self._block_key[blk] = key    # happen via alloc, belt+
            self._refs[blk] = 1           # braces)
            shared.add(blk)
            new += 1
        self.shared_blocks_hw = max(self.shared_blocks_hw,
                                    len(self._block_key))
        return new

    def prefix_keys(self):
        """The shared index's chain keys (bytes digests) as a LIVE
        read-only set view — the cheap warm-prefix probe surface
        :meth:`~apex_tpu.serving.engine.ServingEngine.router_snapshot`
        exports.  A router hashes a candidate prompt ONCE
        (:func:`prefix_chain_keys`) and membership-probes each
        replica's view (O(1) per key, no index copy per poll — the
        index can hold thousands of chains on a warm replica); it
        must not mutate or retain the view across engine mutations."""
        return self._index.keys()

    def resident_prefix(self, prompt: Sequence[int]
                        ) -> Optional[List[int]]:
        """The block list holding ``prompt``'s ENTIRE k/v in this
        pool's shared index (every full block plus the partial tail),
        in page order — the export unit of the disaggregated KV
        handoff — or None when any page is missing.  Unlike
        :meth:`match_prefix` this includes the final token's page
        unconditionally: an exporter ships content, it does not admit
        a request."""
        if not self.prefix_sharing or not len(prompt):
            return None
        keys, pkey = self._chain_keys(prompt)
        blocks: List[int] = []
        for key in keys + ([pkey] if pkey is not None else []):
            blk = self._index.get(key)
            if blk is None:
                return None
            blocks.append(blk)
        return blocks

    def register_external(self, prompt: Sequence[int],
                          payload_pages: int) -> Optional[List[int]]:
        """Claim pool blocks for an IMPORTED prompt's k/v (the decode
        side of the disaggregated handoff) and index them as shared
        with zero live mappings — parked in the idle LRU, exactly the
        state a finished local request's prompt pages land in — so the
        next admission of this prompt maps them warm.  Returns the
        claimed block ids (in page order, the scatter destination), or
        None when the prompt (or a block-content collision) is already
        resident — the importer then skips the device scatter
        entirely.  Raises :class:`CachePoolExhausted` when the pool
        cannot cover ``payload_pages`` blocks."""
        if not self.prefix_sharing:
            raise ValueError(
                "register_external needs prefix_sharing=True — "
                "imported pages are addressed through the shared "
                "index (the warm-admission machinery)")
        keys, pkey = self._chain_keys(prompt)
        entries = keys + ([pkey] if pkey is not None else [])
        if len(entries) != int(payload_pages):
            raise ValueError(
                f"payload covers {payload_pages} page(s) but the "
                f"prompt chains into {len(entries)} — block_size "
                f"mismatch between the replicas?")
        if all(k in self._index for k in entries):
            return None                       # already resident
        if payload_pages > self.available_blocks:
            raise CachePoolExhausted(
                f"import needs {payload_pages} block(s), pool has "
                f"{self.available_blocks} available")
        blocks: List[int] = []
        fresh: List[int] = []
        # resident owners this import reuses leave the idle LRU for
        # the duration of the claim loop: _take_block reclaims LRU
        # idle blocks when the free list is dry, and stealing a page
        # that is already on this import's block list would both
        # unregister its chain entry and alias two payload pages into
        # one block (one silently lost)
        shelved: List[int] = []
        for key in entries:
            owner = self._index.get(key)
            if owner is not None and owner in self._idle:
                del self._idle[owner]
                shelved.append(owner)
        try:
            for key in entries:
                owner = self._index.get(key)
                if owner is not None:
                    # chain prefix already cached here: reuse the
                    # resident page (the scatter rewrites it with
                    # identical bytes — content-addressed no-op)
                    blocks.append(owner)
                    continue
                blk = self._take_block("import: pool drained "
                                       "mid-claim")
                self._index[key] = blk
                self._block_key[blk] = key
                self._refs[blk] = 0
                blocks.append(blk)
                fresh.append(blk)
        finally:
            for blk in shelved:
                self._idle[blk] = None        # back in the LRU
        for blk in fresh:
            # parked idle only AFTER every claim, same hazard as above
            self._idle[blk] = None            # cached, reclaimable
        self.shared_blocks_hw = max(self.shared_blocks_hw,
                                    len(self._block_key))
        return blocks

    def _map_shared(self, rid, blk: int) -> None:
        self._refs[blk] = self._refs.get(blk, 0) + 1
        self._idle.pop(blk, None)
        self._shared_of.setdefault(rid, set()).add(blk)

    def _unmap_shared(self, blk: int) -> None:
        self._refs[blk] -= 1
        if self._refs[blk] == 0:
            # cached but unmapped: off the free list, reclaimable LRU
            self._idle[blk] = None

    def _take_block(self, why: str) -> int:
        """One block off the free list, reclaiming the LRU idle shared
        block (unregistering its prefix entry) when the list is dry."""
        if self._free:
            return self._free.pop()
        if self._idle:
            blk, _ = self._idle.popitem(last=False)
            key = self._block_key.pop(blk)
            del self._index[key]
            del self._refs[blk]
            return blk
        raise CachePoolExhausted(why)

    def is_shared(self, rid, block: int) -> bool:
        """Whether ``block`` is a read-only shared mapping in
        ``rid``'s table (a write must CoW it first)."""
        return block in self._shared_of.get(rid, ())

    # --- lifecycle ----------------------------------------------------

    def alloc(self, rid, length: int, *,
              shared_blocks: Sequence[int] = ()) -> List[int]:
        """Claim blocks covering ``length`` tokens for a new request.
        ``shared_blocks`` (from :meth:`match_prefix`) are mapped
        read-only as the table's leading pages — refcounted, never
        drawn from the pool — and only the tail is allocated."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        if length < 1:
            raise ValueError("length must be >= 1")
        need = self.config.blocks_for(length) - len(shared_blocks)
        if need < 0:
            raise ValueError(
                f"request {rid!r}: {len(shared_blocks)} shared pages "
                f"exceed the {self.config.blocks_for(length)} pages "
                f"length {length} occupies")
        idle_matched = sum(1 for b in shared_blocks
                           if b in self._idle)
        if need > self.available_blocks - idle_matched:
            raise CachePoolExhausted(
                f"request {rid!r} needs {need} block(s) for length "
                f"{length}, pool has {self.available_blocks} "
                f"available of {self.config.usable_blocks}")
        blocks = list(shared_blocks)
        for blk in shared_blocks:
            self._map_shared(rid, blk)
        blocks.extend(self._take_block(
            f"request {rid!r}: pool drained mid-alloc")
            for _ in range(need))
        self._tables[rid] = blocks
        self._lens[rid] = int(length)
        if shared_blocks:
            self.prefix_hits += 1
        return list(blocks)

    def cow_for_append(self, rid):
        """Copy-on-write guard for the next :meth:`append`: when the
        slot the next token lands in sits inside a shared (read-only)
        page — the owner's first append into its registered partial
        prompt block — swap in a fresh private block and return
        ``(src, dst)`` for the caller to device-copy.  Returns None
        when the next write is already private."""
        pos = self._lens[rid]
        page = pos // self.config.block_size
        if page >= len(self._tables[rid]):
            return None                       # append opens a new page
        return self.make_private(rid, page)

    def make_private(self, rid, page: int):
        """CoW page ``page`` of ``rid``'s table if it is a shared
        mapping: allocate a private replacement, swap the table entry,
        release the shared ref.  Returns ``(src_block, dst_block)``
        to device-copy, or None if the page is already private."""
        blocks = self._tables[rid]
        src = blocks[page]
        if not self.is_shared(rid, src):
            return None
        dst = self._take_block(
            f"request {rid!r}: no block for the copy-on-write of "
            f"shared page {page}")
        blocks[page] = dst
        self._shared_of[rid].discard(src)
        self._unmap_shared(src)
        self.cow_copies += 1
        return src, dst

    def pending_cow_blocks(self, rid) -> int:
        """1 when ``rid``'s next append will CoW a shared page (the
        reservation math must hold that block back), else 0."""
        pos = self._lens[rid]
        page = pos // self.config.block_size
        blocks = self._tables[rid]
        if page < len(blocks) and self.is_shared(rid, blocks[page]):
            return 1
        return 0

    def append(self, rid):
        """Grow ``rid`` by one token, allocating a fresh block when
        the token starts a new page.  Returns ``(block_id, offset)``
        — the page slot the new token's k/v must be written to (its
        position is the pre-append ``seq_len``).  Writing into a
        shared page is a contract violation: call
        :meth:`cow_for_append` first (the engine does, copying the
        block on device)."""
        blocks = self._tables[rid]
        pos = self._lens[rid]
        page, off = divmod(pos, self.config.block_size)
        if page == len(blocks):
            blocks.append(self._take_block(
                f"request {rid!r} crossed a block edge at length "
                f"{pos + 1} with the pool empty — admission "
                f"control must keep headroom (can_admit)"))
        elif self.is_shared(rid, blocks[page]):
            raise RuntimeError(
                f"request {rid!r}: append would write into shared "
                f"page {page} (block {blocks[page]}) — the caller "
                f"must cow_for_append() first")
        self._lens[rid] = pos + 1
        return blocks[page], off

    def truncate(self, rid, new_len: int) -> List[int]:
        """Roll ``rid``'s write cursor back to ``new_len`` tokens
        (speculative-decode rejection), returning pages past the new
        end to the pool.  Only ever sheds private blocks the same
        tick's appends claimed — a rollback never reaches below the
        prompt, so shared pages are untouchable by construction."""
        if not 1 <= new_len <= self._lens[rid]:
            raise ValueError(
                f"request {rid!r}: truncate to {new_len} outside "
                f"[1, {self._lens[rid]}]")
        blocks = self._tables[rid]
        keep = self.config.blocks_for(new_len)
        freed: List[int] = []
        while len(blocks) > keep:
            blk = blocks.pop()
            if blk in self._block_key:
                raise RuntimeError(
                    f"request {rid!r}: truncate would free shared "
                    f"block {blk} — rollback crossed the prompt")
            self._free.append(blk)
            freed.append(blk)
        self._lens[rid] = int(new_len)
        return freed

    def free(self, rid) -> List[int]:
        """Return ``rid``'s blocks to the pool (LIFO, reverse order so
        a readmit walks them back out first-block-first).  Shared
        mappings are unref'd instead — a block another table still
        maps stays live, and one reaching zero refs parks in the idle
        LRU (still indexed, warm for the next identical prompt)."""
        blocks = self._tables.pop(rid)
        del self._lens[rid]
        shared = self._shared_of.pop(rid, set())
        for blk in reversed(blocks):
            if blk in shared:
                self._unmap_shared(blk)
            else:
                self._free.append(blk)
        return blocks

    # --- views --------------------------------------------------------

    def requests(self):
        return list(self._tables)

    def seq_len(self, rid) -> int:
        return self._lens[rid]

    def blocks(self, rid) -> List[int]:
        return list(self._tables[rid])

    def block_table(self, rid, max_pages: int) -> np.ndarray:
        """(max_pages,) int32, padded with the dump block."""
        blocks = self._tables[rid]
        if len(blocks) > max_pages:
            raise ValueError(
                f"request {rid!r} owns {len(blocks)} pages > bucket "
                f"max_pages {max_pages} — the ladder pick is wrong")
        bt = np.full(max_pages, DUMP_BLOCK, np.int32)
        bt[:len(blocks)] = blocks
        return bt

    def num_pages(self, rid) -> int:
        return len(self._tables[rid])
