"""Block-paged KV cache: device layout + host block-pool bookkeeping.

The serving cache is a fixed pool of ``num_blocks`` blocks of
``block_size`` tokens each, shared by every in-flight request.  A
request owns an ordered list of block ids (its *block table*); growing
a sequence past a block boundary appends one block from the free list,
finishing a request returns its blocks.  Nothing is ever moved or
compacted — **defrag-free paging**: the flash-decode kernel gathers
pages through the block table (scalar-prefetched index map), so block
ids need no spatial locality, and admission/eviction cost is O(pages
touched), never O(cache).

Two cleanly separated halves:

* :class:`PagedKVCache` — the DEVICE state: per-layer k/v block arrays
  stacked over layers, ``(L, nb, hk, bs, dk)``, plus optional int8
  per-row scales ``(L, nb, h, bs)``.  A pytree, threaded through the
  jitted prefill/decode steps and **donated** every step (the same
  carry discipline as the scan driver's amp state — the cache is the
  largest buffer in the serving process, double-buffering it halves
  capacity).  ``hk``/``dk`` follow the d=64 head-pair packing decision
  (:func:`apex_tpu.ops.flash_decode.use_decode_head_packing`) so the
  kernel and the layout can never disagree.
* :class:`KVCacheManager` — the HOST bookkeeping: free list, per-
  request tables and lengths.  Pure Python, no device work; the engine
  consults it between jitted steps (the continuous-batching boundary).

Block 0 is reserved as the **dump page**: it is never handed to a
request, block-table padding points at it, and inactive batch rows
write their (masked-out) k/v there — so a bucketed decode step needs
no write masking and a dead page read contributes exactly 0.

Storage dtype (``APEX_TPU_SERVE_KV_DTYPE``): ``model`` stores k/v in
the model compute dtype, ``bf16`` forces bfloat16 (the O4/O5-native
choice), ``int8`` stores weight-only-quantized rows with per-token,
per-head fp32 scales — appending never requantizes history, and the
kernel dequantizes per page in VMEM (docs/api/serving.md#kv-dtype).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.flash_decode import use_decode_head_packing

__all__ = ["KVCacheConfig", "PagedKVCache", "KVCacheManager",
           "CachePoolExhausted", "init_cache", "write_token_kv",
           "write_prefill_kv", "quantize_kv_rows", "DUMP_BLOCK"]

# block 0: never allocated, pads every block table, absorbs inactive
# rows' writes.  Reads of it are always masked to an exact 0 weight.
DUMP_BLOCK = 0

_KV_DTYPES = ("model", "bf16", "int8")


class CachePoolExhausted(RuntimeError):
    """The block pool cannot cover a requested allocation — the
    admission-control signal (callers check :meth:`KVCacheManager.
    can_admit` first; racing past it raises this)."""


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape/dtype plan for one paged cache."""

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int          # INCLUDING the reserved dump block
    block_size: int
    kv_dtype: str = "model"  # 'model' | 'bf16' | 'int8'
    model_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.kv_dtype not in _KV_DTYPES:
            raise ValueError(f"kv_dtype {self.kv_dtype!r} not in "
                             f"{_KV_DTYPES}")
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved dump page)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @property
    def packed(self) -> bool:
        return use_decode_head_packing(self.num_heads, self.head_dim)

    @property
    def storage_dtype(self):
        if self.kv_dtype == "int8":
            return jnp.int8
        if self.kv_dtype == "bf16":
            return jnp.bfloat16
        return self.model_dtype

    @property
    def quantized(self) -> bool:
        return self.kv_dtype == "int8"

    @property
    def kv_shape(self):
        """(L, nb, hk, bs, dk) — the packed storage head axes."""
        h, d = self.num_heads, self.head_dim
        hk, dk = (h // 2, 2 * d) if self.packed else (h, d)
        return (self.num_layers, self.num_blocks, hk,
                self.block_size, dk)

    @property
    def scale_shape(self):
        """(L, nb, h, bs) — scales keep GLOBAL head order."""
        return (self.num_layers, self.num_blocks, self.num_heads,
                self.block_size)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    def blocks_for(self, length: int) -> int:
        return -(-max(int(length), 1) // self.block_size)

    def cache_nbytes(self) -> int:
        per = np.dtype(self.storage_dtype).itemsize
        n = 2 * int(np.prod(self.kv_shape)) * per
        if self.quantized:
            n += 2 * int(np.prod(self.scale_shape)) * 4
        return n


class PagedKVCache(NamedTuple):
    """Device half of the cache (a pytree — jit/donation friendly)."""

    k: jnp.ndarray                     # (L, nb, hk, bs, dk)
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray]     # (L, nb, h, bs) fp32 | None
    v_scale: Optional[jnp.ndarray]

    def layer(self, i: int):
        """(k, v, k_scale, v_scale) views of layer ``i``."""
        return (self.k[i], self.v[i],
                None if self.k_scale is None else self.k_scale[i],
                None if self.v_scale is None else self.v_scale[i])


def init_cache(config: KVCacheConfig) -> PagedKVCache:
    """All-zero cache (zeros are the safe dead-page filler: even an
    unmasked read of a never-written row contributes finite values)."""
    k = jnp.zeros(config.kv_shape, config.storage_dtype)
    v = jnp.zeros(config.kv_shape, config.storage_dtype)
    if config.quantized:
        # k/v scales must be DISTINCT buffers: the cache pytree is
        # donated every step, and aliased leaves would donate the same
        # buffer twice
        return PagedKVCache(k, v,
                            jnp.zeros(config.scale_shape, jnp.float32),
                            jnp.zeros(config.scale_shape, jnp.float32))
    return PagedKVCache(k, v, None, None)


def quantize_kv_rows(x: jnp.ndarray):
    """Per-row symmetric int8: ``x`` (..., d) -> (int8 values,
    (...,) fp32 scales).  Each cached token row quantizes against its
    own amax, so appends never touch history."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _to_storage(x, config: KVCacheConfig):
    """(..., h, d) new rows -> (storage values (..., hk, dk),
    scales (..., h) | None) per the cache layout."""
    if config.quantized:
        q, scale = quantize_kv_rows(x)
        if config.packed:
            q = q.reshape(*q.shape[:-2], config.num_heads // 2,
                          2 * config.head_dim)
        return q, scale
    if config.packed:
        x = x.reshape(*x.shape[:-2], config.num_heads // 2,
                      2 * config.head_dim)
    return x.astype(config.storage_dtype), None


def write_token_kv(cache: PagedKVCache, config: KVCacheConfig,
                   layer: int, k_new: jnp.ndarray, v_new: jnp.ndarray,
                   blocks: jnp.ndarray,
                   offsets: jnp.ndarray) -> PagedKVCache:
    """Scatter ONE token's k/v per batch row into layer ``layer``'s
    page slots.

    ``k_new``/``v_new`` (b, h, d) in model dtype; ``blocks``/
    ``offsets`` (b,) int32 address each row's current page and in-page
    slot (inactive rows point at the dump block).  Per-layer because
    the decode step interleaves write -> attend inside its layer loop
    (the new token attends to itself through the cache).  Traced code
    — runs inside the jitted decode step; the cache argument is
    donated by the caller so the scatter is in-place on device."""
    kq, ks = _to_storage(k_new, config)
    vq, vs = _to_storage(v_new, config)
    # scalar layer index collapses axis 0; the (blocks@0, offsets@2)
    # advanced pair around the head slice selects (b, hk, dk) rows
    k = cache.k.at[layer, blocks, :, offsets, :].set(kq)
    v = cache.v.at[layer, blocks, :, offsets, :].set(vq)
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if config.quantized:
        k_scale = k_scale.at[layer, blocks, :, offsets].set(ks)
        v_scale = v_scale.at[layer, blocks, :, offsets].set(vs)
    return PagedKVCache(k, v, k_scale, v_scale)


def write_prefill_kv(cache: PagedKVCache, config: KVCacheConfig,
                     layer: int, k_all: jnp.ndarray,
                     v_all: jnp.ndarray,
                     blocks: jnp.ndarray) -> PagedKVCache:
    """Scatter a prefilled prompt's whole k/v for one layer into its
    pages.

    ``k_all``/``v_all`` (s_pad, h, d) with ``s_pad = len(blocks) *
    block_size``; ``blocks`` (n_pages,) int32 — pages past the
    request's owned tail point at the dump block (duplicate dump
    writes race harmlessly: the dump page is never read unmasked)."""
    s_pad, h, d = k_all.shape
    bs = config.block_size
    n_pages = s_pad // bs

    def paged(x):
        q, scale = _to_storage(x, config)
        # (P*bs, hk, dk) -> (P, hk, bs, dk)
        q = q.reshape(n_pages, bs, *q.shape[-2:]).transpose(0, 2, 1, 3)
        if scale is not None:
            scale = scale.reshape(n_pages, bs, h).transpose(0, 2, 1)
        return q, scale

    kq, ks = paged(k_all)
    vq, vs = paged(v_all)
    k = cache.k.at[layer, blocks].set(kq)
    v = cache.v.at[layer, blocks].set(vq)
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if config.quantized:
        k_scale = k_scale.at[layer, blocks].set(ks)
        v_scale = v_scale.at[layer, blocks].set(vs)
    return PagedKVCache(k, v, k_scale, v_scale)


class KVCacheManager:
    """Host-side block pool + per-request block tables.

    Free blocks form a LIFO stack: an evict-then-readmit cycle hands
    the same ids back (the tests' bitwise block-reuse proof), and hot
    blocks stay hot.  All methods are O(pages touched)."""

    def __init__(self, config: KVCacheConfig):
        self.config = config
        # stack: pop() from the end; ids descend so the FIRST blocks
        # handed out are 1, 2, 3, ... (stable, test-friendly)
        self._free: List[int] = list(range(config.num_blocks - 1, 0,
                                           -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}

    # --- capacity -----------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.config.usable_blocks - len(self._free)

    def can_admit(self, prompt_len: int, max_new_tokens: int, *,
                  reserved_blocks: int = 0) -> bool:
        """Reservation admission: the request's WHOLE worst case
        (``prompt_len + max_new_tokens``) must fit the pool right
        now, net of ``reserved_blocks`` the pool already owes
        in-flight requests (their own worst cases minus the pages
        they hold) — so a later :meth:`append` can never exhaust the
        pool mid-decode.  Admitting on anything weaker (e.g. prompt
        plus one token of headroom) re-opens exactly that crash."""
        need = self.config.blocks_for(prompt_len + max_new_tokens)
        return need <= len(self._free) - reserved_blocks

    # --- lifecycle ----------------------------------------------------

    def alloc(self, rid, length: int) -> List[int]:
        """Claim blocks covering ``length`` tokens for a new request."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        if length < 1:
            raise ValueError("length must be >= 1")
        need = self.config.blocks_for(length)
        if need > len(self._free):
            raise CachePoolExhausted(
                f"request {rid!r} needs {need} block(s) for length "
                f"{length}, pool has {len(self._free)} free of "
                f"{self.config.usable_blocks}")
        blocks = [self._free.pop() for _ in range(need)]
        self._tables[rid] = blocks
        self._lens[rid] = int(length)
        return list(blocks)

    def append(self, rid):
        """Grow ``rid`` by one token, allocating a fresh block when
        the token starts a new page.  Returns ``(block_id, offset)``
        — the page slot the new token's k/v must be written to (its
        position is the pre-append ``seq_len``)."""
        blocks = self._tables[rid]
        pos = self._lens[rid]
        page, off = divmod(pos, self.config.block_size)
        if page == len(blocks):
            if not self._free:
                raise CachePoolExhausted(
                    f"request {rid!r} crossed a block edge at length "
                    f"{pos + 1} with the pool empty — admission "
                    f"control must keep headroom (can_admit)")
            blocks.append(self._free.pop())
        self._lens[rid] = pos + 1
        return blocks[page], off

    def free(self, rid) -> List[int]:
        """Return ``rid``'s blocks to the pool (LIFO, reverse order so
        a readmit walks them back out first-block-first)."""
        blocks = self._tables.pop(rid)
        del self._lens[rid]
        self._free.extend(reversed(blocks))
        return blocks

    # --- views --------------------------------------------------------

    def requests(self):
        return list(self._tables)

    def seq_len(self, rid) -> int:
        return self._lens[rid]

    def blocks(self, rid) -> List[int]:
        return list(self._tables[rid])

    def block_table(self, rid, max_pages: int) -> np.ndarray:
        """(max_pages,) int32, padded with the dump block."""
        blocks = self._tables[rid]
        if len(blocks) > max_pages:
            raise ValueError(
                f"request {rid!r} owns {len(blocks)} pages > bucket "
                f"max_pages {max_pages} — the ladder pick is wrong")
        bt = np.full(max_pages, DUMP_BLOCK, np.int32)
        bt[:len(blocks)] = blocks
        return bt

    def num_pages(self, rid) -> int:
        return len(self._tables[rid])
