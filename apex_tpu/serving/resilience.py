"""Serving-side resilience: deadlines/shedding policy, the request
journal, and the supervised serve driver (ISSUE-13).

PR 3 gave the *training* loop its fault-tolerance story (SIGTERM-safe
checkpoints, torn-restore fallback, ``run_resumable`` bounded retry,
deterministic fault injectors).  This module is the serving mirror —
the pieces a single replica needs before a fleet router (ROADMAP item
1) can load-balance over it, because a router can only fail over
between engines that fail *predictably*:

* :class:`ShedPolicy` — hysteresis load shedding: when the block pool
  or the admission queue crosses a configured high-water mark the
  engine stops admitting and sheds lowest-priority / shortest-progress
  work first, and keeps shedding state latched until the load drops
  below the low-water mark — so the engine cannot flap between admit
  and shed around one threshold.  Every shed decision is a terminal
  ``request_done`` lifecycle event (``terminal="shed"``), so
  ``trace_check --serve`` still proves N submitted ⇒ N terminal.
* :class:`RequestJournal` — crash-safe append-only JSONL (the
  :class:`~apex_tpu.monitor.events.JsonlSink` machinery: one record
  per line, flushed per line, torn trailing lines tolerated on load)
  recording every request's submit / progress / terminal transitions.
  :func:`RequestJournal.load` reconstructs the request ledger;
  :func:`recover_engine` replays it — every non-terminal request is
  re-submitted (no duplicate ``request_submitted`` event: the
  lifecycle chain stays open across the crash), and with PR-12 prefix
  sharing on, the crashed requests' prompt pages survive in the idle
  LRU so the readmission hits warm (``prefix_hit_tokens`` grows —
  the measured warm-readmit win).  Replaying a fully-terminal journal
  is a no-op.
* :func:`run_serving` — the supervised serve driver: the PR-3
  bounded-backoff restart semantics (:func:`apex_tpu.resilience.
  run_resumable` drives the attempts, so the ``attempt_start`` /
  ``attempt_error`` / ``attempt_backoff`` event trail is identical)
  around one :class:`~.engine.ServingEngine`.  A crashed engine loop
  is recovered in-process: request bookkeeping is rebuilt from the
  journal while the device cache — owned by the supervisor, not the
  loop — survives, which is exactly why the warm readmit works.
  Greedy decode is deterministic, so a replayed request regenerates
  token-for-token what the uninterrupted run would have produced
  (the CI crash leg proves the digests match).
* :class:`SpeculationGovernor` — degraded mode for the PR-12 fast
  path: a run of consecutive low-acceptance speculative ticks
  (mismatching draft, stalled verify) auto-disables speculation for
  the rest of the run — alarm + gauge, never a crash, and output
  identity is preserved because speculative greedy == greedy.

Deterministic serve faults (``crash@tick`` / ``stall@tick`` /
``reject_alloc@tick`` / ``corrupt_journal@tick``) live in
:mod:`apex_tpu.resilience.faults`; ``standalone_gpt --serve --fault``
wires them.  Worked crash-replay walkthrough: docs/api/resilience.md.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis.flags import flag_float, flag_int
from ..monitor.events import Event, JsonlSink
from ..utils.log_util import get_logger

logger = get_logger(__name__)

__all__ = ["ShedPolicy", "RequestJournal", "SpeculationGovernor",
           "ServeRunResult", "recover_engine", "run_serving"]


# ---------------------------------------------------------------------------
# Load shedding
# ---------------------------------------------------------------------------

class ShedPolicy:
    """Hysteresis admission/shed control for one serving engine.

    Two independent pressure signals, each with a high-water mark that
    *engages* shedding and a low-water mark that *disengages* it:

    * ``pool_hw`` — used-block fraction of the pool (0 disables);
      ``pool_lw`` defaults to ``pool_hw - 0.15``.
    * ``queue_hw`` — queued + mid-prefill request count (0 disables);
      ``queue_lw`` defaults to ``queue_hw // 2``.

    While engaged the engine admits nothing and sheds lowest-priority,
    shortest-progress work first (queued requests before running
    ones — zero sunk cost beats evicting paid-for decode) until both
    signals are below their LOW-water marks.  The gap between the two
    marks is the hysteresis band: load hovering exactly at the
    high-water mark cannot flap admit/shed/admit, because disengaging
    requires dropping all the way through the band
    (tests/test_serving_resilience.py proves no-flap around the mark).
    """

    def __init__(self, *, pool_hw: float = 0.0,
                 pool_lw: Optional[float] = None,
                 queue_hw: int = 0,
                 queue_lw: Optional[int] = None,
                 class_queue_hw: Optional[Dict[str, int]] = None):
        if pool_hw and not 0.0 < pool_hw <= 1.0:
            raise ValueError(f"pool_hw {pool_hw} must be in (0, 1]")
        self.pool_hw = float(pool_hw)
        self.pool_lw = (max(0.0, self.pool_hw - 0.15)
                        if pool_lw is None else float(pool_lw))
        self.queue_hw = int(queue_hw)
        self.queue_lw = (self.queue_hw // 2 if queue_lw is None
                         else int(queue_lw))
        # per-priority-class high-water overrides (ISSUE-18): the
        # process-fleet QoS door admits per class, so each class can
        # carry its own backlog ceiling ("p0" may queue deep, "p2"
        # sheds early to protect its latency SLO).  Engine-level
        # hysteresis is untouched — these gate ADMISSION fleet-wide,
        # before a request ever reaches an engine queue.
        self.class_queue_hw: Dict[str, int] = {}
        for cls, hw in (class_queue_hw or {}).items():
            hw = int(hw)
            if hw < 1:
                raise ValueError(
                    f"class_queue_hw[{cls!r}] must be >= 1, got {hw}")
            self.class_queue_hw[str(cls)] = hw
        if self.pool_hw and self.pool_lw >= self.pool_hw:
            raise ValueError("pool_lw must sit below pool_hw "
                             "(the hysteresis band)")
        if self.queue_hw and self.queue_lw >= self.queue_hw:
            raise ValueError("queue_lw must sit below queue_hw")
        self.engaged = False
        self.engagements = 0

    @classmethod
    def from_flags(cls) -> "ShedPolicy":
        return cls(pool_hw=flag_float("APEX_TPU_SERVE_SHED_POOL_HW"),
                   queue_hw=flag_int("APEX_TPU_SERVE_SHED_QUEUE_HW"))

    @property
    def enabled(self) -> bool:
        return bool(self.pool_hw or self.queue_hw)

    def queue_hw_for(self, priority_class: str) -> int:
        """The queue high-water mark for one priority class — the
        per-class override when present, else the global mark (0 =
        unlimited).  The QoS admission door polls this per submit."""
        return self.class_queue_hw.get(str(priority_class),
                                       self.queue_hw)

    def _over_high(self, pool_frac: float, queue_depth: int) -> bool:
        return ((self.pool_hw > 0 and pool_frac >= self.pool_hw)
                or (self.queue_hw > 0 and queue_depth > self.queue_hw))

    def over_low(self, pool_frac: float, queue_depth: int) -> bool:
        """Still above the LOW-water marks — while engaged, shedding
        continues until this goes False."""
        return ((self.pool_hw > 0 and pool_frac > self.pool_lw)
                or (self.queue_hw > 0 and queue_depth > self.queue_lw))

    def update(self, *, pool_frac: float, queue_depth: int) -> bool:
        """Advance the hysteresis state with this tick's load; returns
        whether shedding is engaged for the tick."""
        if not self.enabled:
            return False
        if not self.engaged:
            if self._over_high(pool_frac, queue_depth):
                self.engaged = True
                self.engagements += 1
        elif not self.over_low(pool_frac, queue_depth):
            self.engaged = False
        return self.engaged


# ---------------------------------------------------------------------------
# Request journal
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JournalState:
    """One journal's reconstructed ledger (:meth:`RequestJournal.load`).

    ``submitted`` maps rid -> the submit record's attrs (prompt,
    budget, deadline, priority — everything needed to rebuild the
    :class:`~.engine.Request`); ``progress`` the last journaled token
    count; ``terminal`` rid -> the terminal record's attrs (reason +
    the full output token list, so completed requests' digests are
    reconstructable without re-running them)."""

    submitted: Dict[str, Dict[str, Any]]
    progress: Dict[str, int]
    terminal: Dict[str, Dict[str, Any]]
    malformed: int = 0

    @property
    def open_rids(self) -> List[str]:
        """Submitted-but-not-terminal rids, in submit order — exactly
        the set a replay must re-enter."""
        return [rid for rid in self.submitted
                if rid not in self.terminal]


class RequestJournal:
    """Crash-safe append-only request ledger for one serve.

    Rides the monitor's :class:`~apex_tpu.monitor.events.JsonlSink`
    (append-only, one record per line, flushed per line — a kill at
    any instant loses at most one torn trailing line, which
    :meth:`load` tolerates).  Records are ``kind="journal"`` events:

    * ``submit`` — rid, prompt, max_new_tokens, eos/deadline/priority
      (enough to rebuild the request), stamped with the engine tick;
    * ``progress`` — ONE record per tick mapping each active rid to
      its generated-token count (observability + post-mortem; replay
      correctness does not depend on it — greedy decode regenerates);
    * ``terminal`` — rid, terminal reason, and the full output token
      list (the exactly-once ledger: a rid with a terminal record is
      never replayed, and its tokens survive the crash);
    * ``replay`` — one record per recovery naming the re-entered rids.
    """

    def __init__(self, path: str, *,
                 wall_clock: Callable[[], float] = time.time):
        self.path = path
        self._wall = wall_clock
        self._sink = JsonlSink(path)

    def _record(self, name: str, tick: Optional[int] = None,
                **attrs) -> None:
        self._sink.emit(Event(time=self._wall(), step=tick,
                              kind="journal", name=name, attrs=attrs))

    def record_submit(self, request, tick: int) -> None:
        self._record(
            "submit", tick, rid=str(request.rid),
            prompt=[int(t) for t in request.prompt],
            max_new_tokens=int(request.max_new_tokens),
            eos_token=request.eos_token,
            deadline_ms=request.deadline_ms,
            priority=int(request.priority))

    def record_progress(self, progress: Dict[Any, int],
                        tick: int) -> None:
        """One aggregated record: ``{rid: generated-token count}`` for
        every active request this tick."""
        self._record("progress", tick,
                     progress={str(rid): int(n)
                               for rid, n in progress.items()})

    def record_terminal(self, request, tick: int) -> None:
        self._record(
            "terminal", tick, rid=str(request.rid),
            terminal=request.terminal or "finished",
            tokens=[int(t) for t in request.out_tokens])

    def record_replay(self, rids: List[str], tick: int) -> None:
        self._record("replay", tick, rids=[str(r) for r in rids])

    def close(self) -> None:
        self._sink.close()

    @staticmethod
    def load(path: str) -> JournalState:
        """Reconstruct the ledger from disk.  Torn trailing lines (a
        truncate-style crash or the ``corrupt_journal`` injector) are
        counted, not fatal.  Submit records are incarnation-aware: a
        submit while the rid is open keeps the FIRST record (the
        original request definition is the replay contract — recovery
        never re-records submits), but a submit arriving AFTER the
        rid's terminal record REOPENS it with the new definition — a
        journal reused across serves (an append-only file outliving
        one run) must not let a finished previous-run rid mask the
        new run's live request."""
        from ..monitor.summary import load_events

        events, malformed = load_events(path)
        state = JournalState(submitted={}, progress={}, terminal={},
                             malformed=malformed)
        for e in events:
            if e.kind != "journal":
                continue
            if e.name == "submit":
                rid = str(e.attrs.get("rid"))
                if rid in state.terminal:
                    del state.terminal[rid]
                    state.submitted[rid] = dict(e.attrs)
                else:
                    state.submitted.setdefault(rid, dict(e.attrs))
            elif e.name == "progress":
                for rid, n in (e.attrs.get("progress") or {}).items():
                    state.progress[str(rid)] = int(n)
            elif e.name == "terminal":
                state.terminal[str(e.attrs.get("rid"))] = dict(e.attrs)
        return state


# ---------------------------------------------------------------------------
# Degraded mode: speculative-decode governor
# ---------------------------------------------------------------------------

class SpeculationGovernor:
    """Auto-disable speculation after sustained verify mismatch.

    Observes every speculative tick's (proposed, accepted) pair; when
    ``window`` *consecutive* ticks each land below ``min_accept``
    acceptance, :meth:`observe` returns True once and the engine turns
    speculation off for the rest of the run (alarm + gauge, never a
    crash — speculative greedy and plain greedy emit identical tokens,
    so degrading is output-invisible).  A draft that has stalled into
    garbage proposals and a verify path that rejects everything look
    the same from here, which is the point: either way every tick is
    paying K draft dispatches for nothing."""

    def __init__(self, *, min_accept: float = 0.05, window: int = 4):
        if not 0.0 <= min_accept <= 1.0:
            raise ValueError(f"min_accept {min_accept} not in [0, 1]")
        if window < 1:
            raise ValueError(f"window {window} must be >= 1")
        self.min_accept = float(min_accept)
        self.window = int(window)
        self.low_streak = 0
        self.tripped = False

    def observe(self, proposed: int, accepted: int) -> bool:
        """Feed one speculative tick; True exactly once, on the tick
        that trips the governor."""
        if self.tripped or proposed <= 0:
            return False
        if accepted / proposed < self.min_accept:
            self.low_streak += 1
        else:
            self.low_streak = 0
        if self.low_streak >= self.window:
            self.tripped = True
            return True
        return False


# ---------------------------------------------------------------------------
# Supervised recovery
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReplayStats:
    """What one journal replay did (:func:`recover_engine`).

    The replayed requests are only *queued* by the recovery — their
    (possibly warm) admissions happen inside the next ``run()``, so
    the warm-readmit win is measured by the caller as the delta of
    the engine's warm counters across that run
    (:func:`run_serving` does this and reports it on
    :class:`ServeRunResult`)."""

    replayed: int = 0            # non-terminal rids re-entered
    skipped_terminal: int = 0    # rids the ledger already closed
    lost_active: int = 0         # in-flight state the crash destroyed
    lost_queued: int = 0


@dataclasses.dataclass
class ServeRunResult:
    """What :func:`run_serving` supervised end to end."""

    summary: Any                 # the final attempt's ServeSummary
    attempts: int                # 1 = no crash
    restarts: int                # attempts - 1
    replayed: int                # total re-entered requests
    warm_readmits: int           # replayed admissions that hit warm
    prefix_hit_tokens: int       # prefill tokens replay skipped


def recover_engine(engine, journal: RequestJournal,
                   monitor=None) -> ReplayStats:
    """Rebuild a crashed engine loop's request state from its journal.

    The supervisor owns the device cache and the prefix-share index;
    the crash destroyed only the *loop's* request bookkeeping.  So:
    :meth:`~.engine.ServingEngine.crash_reset` frees every in-flight
    request's blocks (registered prompt pages park in the idle LRU —
    still warm), then every non-terminal rid in the journal is
    re-entered through :meth:`~.engine.ServingEngine.resubmit` (no
    second ``request_submitted`` event — the lifecycle chain opened
    before the crash stays open and closes exactly once).  With prefix
    sharing on, the readmission maps the surviving pages instead of
    re-prefilling them; the stats record the measured win.  Replaying
    a fully-terminal journal is a no-op (idempotency test)."""
    from .engine import Request

    stats = ReplayStats()
    lost = engine.crash_reset()
    stats.lost_active = lost["active"] + lost["prefilling"]
    stats.lost_queued = lost["queued"]
    state = RequestJournal.load(journal.path)
    open_rids = state.open_rids
    for rid in open_rids:
        a = state.submitted[rid]
        req = Request(
            rid=rid, prompt=[int(t) for t in a.get("prompt", [])],
            max_new_tokens=int(a.get("max_new_tokens", 1)),
            eos_token=a.get("eos_token"),
            deadline_ms=a.get("deadline_ms"),
            priority=int(a.get("priority", 0)))
        engine.resubmit(req)
        stats.replayed += 1
    stats.skipped_terminal = len(state.terminal)
    journal.record_replay(open_rids, engine.steps)
    if monitor is not None:
        monitor.event("serving", "journal_replay", step=engine.steps,
                      value=stats.replayed, replayed=stats.replayed,
                      skipped_terminal=stats.skipped_terminal,
                      lost_active=stats.lost_active,
                      lost_queued=stats.lost_queued,
                      malformed_lines=state.malformed)
    return stats


def run_serving(engine, requests, *, journal: RequestJournal,
                max_restarts: int = 3,
                backoff_base: float = 0.05,
                backoff_max: float = 5.0,
                jitter: float = 0.25,
                monitor=None, sink=None,
                before_tick: Optional[Callable[[int], None]] = None,
                after_tick: Optional[Callable[[int], None]] = None,
                max_steps: Optional[int] = None,
                sleep: Callable[[float], None] = time.sleep,
                rng=None,
                no_retry_on: tuple = ()) -> ServeRunResult:
    """Supervise one engine's serve with bounded-backoff restarts.

    The serving twin of PR-3's :func:`~apex_tpu.resilience.
    run_resumable` — and literally built on it, so the restart event
    trail (``attempt_start`` / ``attempt_error`` / ``attempt_backoff``
    / ``attempt_done`` / ``run_giveup``) is the same one training
    post-mortems already read.  ``requests`` are submitted (each
    journaled) BEFORE the retry loop, so a submit-time validation
    error raises straight to the caller instead of being retried as
    a crash; a crashed attempt is recovered via
    :func:`recover_engine` — crash_reset + journal replay — and the
    next attempt serves the replayed queue to completion.  The same
    engine (and its device cache) is reused across attempts, which is
    what makes replayed admissions hit the prefix index warm.

    ``monitor`` receives the serving-side events (``journal_replay``);
    ``sink`` the resilience attempt trail (pass the same monitor for
    one unified log).  Exhausting ``max_restarts`` re-raises through
    :class:`~apex_tpu.resilience.GiveUp` — a replica that cannot
    recover must die loudly, not serve garbage."""
    from ..resilience import run_resumable

    # the engine must journal THROUGH the supervisor's journal, or a
    # recovery would load an empty ledger and silently drop every
    # in-flight request; wiring it here makes the common call shape
    # (engine built without journal=) just work
    if engine.journal is None:
        engine.journal = journal
    elif engine.journal is not journal:
        raise ValueError(
            "engine.journal and run_serving's journal differ — "
            "recovery would replay a ledger the engine never wrote")
    stats = {"replayed": 0, "restarts": 0,
             "replay_warm0": None, "replay_hit0": None}
    # submit BEFORE the retry loop: a submit-time validation error
    # (ladder span, empty prompt, ...) is the caller's bug and must
    # raise to them directly — retrying it as a crash would swallow
    # the error and silently drop every request after the bad one
    for r in requests:
        engine.submit(r)

    def attempt(k: int):
        if k > 0:
            stats["restarts"] = k
            if stats["replay_warm0"] is None:
                # warm-hit counters at the FIRST recovery: everything
                # above this after the run is replay-earned
                stats["replay_warm0"] = engine._warm_admissions
                stats["replay_hit0"] = engine._prefix_hit_tokens
            rs = recover_engine(engine, journal, monitor=monitor)
            stats["replayed"] += rs.replayed
        return engine.run(max_steps=max_steps,
                          before_tick=before_tick,
                          after_tick=after_tick)

    summary = run_resumable(
        attempt, max_restarts=max_restarts, backoff_base=backoff_base,
        backoff_max=backoff_max, jitter=jitter,
        sink=sink if sink is not None else monitor,
        sleep=sleep, rng=rng,
        no_retry_on=no_retry_on,
        autoresume=engine.autoresume)
    warm0 = stats["replay_warm0"]
    hit0 = stats["replay_hit0"]
    return ServeRunResult(
        summary=summary,
        attempts=stats["restarts"] + 1,
        restarts=stats["restarts"],
        replayed=stats["replayed"],
        warm_readmits=(engine._warm_admissions - warm0
                       if warm0 is not None else 0),
        prefix_hit_tokens=(engine._prefix_hit_tokens - hit0
                           if hit0 is not None else 0))
