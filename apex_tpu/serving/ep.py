"""Expert-parallel serving: MoE decode sharded along a MeshPlan
``expert`` axis (ISSUE-19 tentpole, piece 3).

The serving step functions (:mod:`.model`) duck-type MoE layers on
``MoELayerWeights.router`` and route through ``_moe_mlp``, whose
collective points arm when ``ServingModelConfig.ep_axis`` is set.
This module supplies the topology as data, the same way
:mod:`.tp` does for tensor parallelism:

* :func:`serving_ep_plan` — the :class:`~apex_tpu.mesh_plan.MeshPlan`
  contract: one ``expert``-kind axis; ONLY the expert stacks
  (``wi``/``wo``) shard (leading expert dim), everything else —
  attention, router, layer norms, embeddings, the paged KV cache —
  stays replicated; and the collective budget: **2·chunks all_to_all
  plus 1 psum per MoE layer** (the capacity-chunked overlapped
  dispatch/return exchange of
  :func:`~apex_tpu.transformer.expert_parallel.
  moe_dispatch_combine_fused`, then one masked psum replicating the
  combined token slice), a CEILING the SPMD auditor holds the
  compiled artifact to.
* :class:`EPContext` — binds a plan to devices and builds the
  shard_map-wrapped, donation-preserving jitted step builders the
  :class:`~.engine.ServingEngine` swaps in: same signatures, same
  bucket ladder, same AOT warmup — expert parallelism is invisible
  to the continuous-batching loop.

Unlike TP (which shards per-token work), EP shards per-EXPERT work:
each rank holds ``E/ep`` expert FFNs and the full attention stack, so
attention/cache math is redundantly replicated while the dominant MoE
FFN FLOPs and weights split.  Tokens slice ``T/ep`` per rank before
routing; the post-psum combined activations are shard-invariant, so
greedy argmax samples the same token everywhere and the engine's one
fetch per tick is unchanged.  The audited entry
(``gpt_decode_step_ep`` in :mod:`apex_tpu.testing.entry_points`)
carries this plan, so APX701/703/705 guard the serving topology and
tests pin the EP engine's greedy output token-identical to the
single-chip engine on a duplicated-expert config.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Sequence

from ..mesh_plan import MeshPlan
from .kv_cache import KVCacheConfig, init_cache
from .model import (GPTServingWeights, MoELayerWeights,
                    ServingModelConfig, gpt_decode_step,
                    gpt_extend_step, gpt_prefill_step)

__all__ = ["SERVING_EP_AXIS", "EPContext", "expand_moe_weights",
           "serving_ep_plan"]

# the canonical serving expert-axis name (MeshPlan kind "expert")
SERVING_EP_AXIS = "expert"


def serving_ep_plan(ep: int, num_layers: int, *,
                    axis: str = SERVING_EP_AXIS,
                    a2a_chunks: int = 2) -> MeshPlan:
    """The EP serving topology contract for the audited decode entry:
    expert stacks sharded on their leading (expert) dim under ``in0``,
    the router and every dense/attention tensor replicated by
    omission, the paged cache replicated in AND out, and the
    per-layer collective ceiling — ``2·a2a_chunks`` all_to_all (the
    overlapped dispatch + return hops of the capacity-chunked
    exchange) plus one masked psum (slice replication).  The runtime
    (:class:`EPContext`) derives its shard_map in/out specs and jit
    in_shardings from THIS object, so plan drift is an APX703
    finding, not a silent reshard."""
    if a2a_chunks < 1:
        raise ValueError(f"a2a_chunks {a2a_chunks} must be >= 1")
    specs = {
        r"^in0.*\.wi$": (axis,),
        r"^in0.*\.wo$": (axis,),
    }
    n_layers = int(num_layers)
    return MeshPlan.build(
        axes=((axis, int(ep), "expert"),),
        tensor_specs=specs,
        collective_budget={
            "all_to_all": 2 * int(a2a_chunks) * n_layers,
            "psum": n_layers,
        })


def expand_moe_weights(weights: GPTServingWeights, num_experts: int,
                       rng=None) -> GPTServingWeights:
    """Convert dense serving weights into a ``num_experts``-way MoE
    model: every layer's fc1/fc2 kernel is TILED into the
    ``(E, H, F)`` / ``(E, F, H)`` expert stacks (all experts start
    identical — the dense function, which is what the token-parity
    tests rely on) and a small random router is drawn per layer
    (``rng`` a PRNGKey; zeros when None, making routing uniform and
    the expansion fully deterministic).  fc biases are dropped — the
    serving MoE expert stacks are bias-free (matching
    :class:`~apex_tpu.transformer.layers_moe.MoEMLP`) — so exact
    dense equivalence needs zero fc biases in the source weights."""
    import jax
    import jax.numpy as jnp

    e = int(num_experts)
    if e < 1:
        raise ValueError(f"num_experts {e} must be >= 1")
    layers = []
    for i, lw in enumerate(weights.layers):
        h = lw.fc1_k.shape[0]
        if rng is None:
            router = jnp.zeros((h, e), jnp.float32)
        else:
            router = 0.02 * jax.random.normal(
                jax.random.fold_in(rng, i), (h, e), jnp.float32)
        layers.append(MoELayerWeights(
            ln1_w=lw.ln1_w, ln1_b=lw.ln1_b,
            qkv_k=lw.qkv_k, qkv_b=lw.qkv_b,
            dense_k=lw.dense_k, dense_b=lw.dense_b,
            ln2_w=lw.ln2_w, ln2_b=lw.ln2_b,
            router=router,
            wi=jnp.broadcast_to(lw.fc1_k[None], (e,) + lw.fc1_k.shape
                                ).copy(),
            wo=jnp.broadcast_to(lw.fc2_k[None], (e,) + lw.fc2_k.shape
                                ).copy(),
        ))
    return weights._replace(layers=tuple(layers))


def _keystr(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


class EPContext:
    """One expert-parallel serving topology, bound to real devices.

    Validates the geometry (``model_cfg.num_experts`` must be set and
    divide by ``ep``; the cache must match the model's head layout —
    it is replicated, never split), builds the mesh from ``devices``
    (default: the first ``ep`` of ``jax.devices()``), and exposes
    exactly what the engine needs:

    * :meth:`shard_weights` / :meth:`init_cache` — commit the global
      arrays to their plan shardings once (expert stacks split,
      everything else replicated), so every step call runs
      reshard-free;
    * :meth:`jit_decode` / :meth:`jit_prefill` / :meth:`jit_extend` —
      drop-in replacements for the engine's single-chip jit builders:
      same signatures, cache donated, shard_map inside with in/out
      specs derived from the plan.

    ``model_cfg`` is the context's ep-axis-carrying config — the
    engine serves with it so ``_moe_mlp``'s token slicing, overlapped
    exchange, and masked psum are armed."""

    def __init__(self, model_cfg: ServingModelConfig,
                 cache_cfg: KVCacheConfig, ep: int, *,
                 axis: str = SERVING_EP_AXIS,
                 devices: Optional[Sequence[Any]] = None):
        if ep < 2:
            raise ValueError(f"ep {ep} must be >= 2 (ep=1 is the "
                             f"single-chip engine, no context needed)")
        if model_cfg.num_experts < 1:
            raise ValueError(
                "EPContext needs an MoE model: "
                f"model_cfg.num_experts={model_cfg.num_experts}")
        if model_cfg.num_experts % ep:
            raise ValueError(
                f"num_experts {model_cfg.num_experts} not divisible "
                f"by ep {ep}")
        if model_cfg.tp_axis is not None:
            raise ValueError(
                "EPContext does not compose with tp_axis "
                f"{model_cfg.tp_axis!r} — expert parallelism "
                "replicates the attention stack")
        if cache_cfg.num_heads != model_cfg.num_heads \
                or cache_cfg.head_dim != model_cfg.head_dim:
            raise ValueError(
                "cache_cfg head geometry "
                f"({cache_cfg.num_heads}x{cache_cfg.head_dim}) does "
                f"not match the model "
                f"({model_cfg.num_heads}x{model_cfg.head_dim})")
        self.ep = int(ep)
        self.axis = axis
        self.cache_cfg = cache_cfg
        # the cache is replicated over the expert axis — per-shard
        # geometry IS the global geometry (contrast TPContext's
        # head-split local_cache_cfg)
        self.local_cache_cfg = cache_cfg
        self.model_cfg = dataclasses.replace(model_cfg, ep_axis=axis)
        self.plan = serving_ep_plan(
            ep, model_cfg.num_layers, axis=axis,
            a2a_chunks=model_cfg.moe_a2a_chunks)
        self.mesh = self.plan.make_mesh(devices)

    # --- spec trees -----------------------------------------------------

    def _replicated(self):
        from jax.sharding import PartitionSpec as P

        return P()

    def _spec_tree(self, tree, prefix: str):
        """PartitionSpec pytree for ``tree`` from the plan's declared
        specs under ``prefix`` — the ONE derivation both shard_map
        in/out_specs and jit in/out_shardings use."""
        import jax

        return jax.tree_util.tree_map_with_path(
            lambda path, _: self.plan.partition_spec(
                prefix + _keystr(path)), tree)

    def weight_specs(self, weights: GPTServingWeights):
        return self._spec_tree(weights, "in0")

    def cache_specs(self, cache=None):
        """PartitionSpec pytree for the paged cache — every leaf
        replicated (the plan declares no ``in1`` patterns): each
        expert shard holds the full cache and runs the full attention
        stack."""
        if cache is None:
            cache = init_cache(self.cache_cfg)
        return self._spec_tree(cache, "in1")

    def _named(self, spec_tree):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree, is_leaf=lambda s: isinstance(s, P))

    # --- committed placement -------------------------------------------

    def shard_weights(self, weights: GPTServingWeights
                      ) -> GPTServingWeights:
        """Commit the (global) weight arrays to their plan shardings —
        expert stacks split on their leading dim, everything else
        replicated — once at engine construction and once per weight
        swap, so steps never pay a per-call reshard."""
        import jax

        for lw in weights.layers:
            if getattr(lw, "router", None) is None:
                raise ValueError(
                    "EPContext weights must be MoE layers "
                    f"(got {type(lw).__name__}; run "
                    "expand_moe_weights first)")
        return jax.device_put(weights,
                              self._named(self.weight_specs(weights)))

    def init_cache(self):
        """A zeroed paged cache committed replicated — every shard
        writes/reads the full cache (attention is redundant under
        EP)."""
        import jax

        cache = init_cache(self.cache_cfg)
        return jax.device_put(cache,
                              self._named(self.cache_specs(cache)))

    # --- jitted step builders (engine drop-ins) -------------------------

    def _wrap(self, body, weights, n_data: int):
        """shard_map-wrapped jit: ``body(weights, cache, *data)`` with
        the expert stacks sharded per plan, cache and the ``n_data``
        trailing args replicated, every output replicated (post-psum
        values are shard-invariant), and the cache donated.
        ``check_vma=False`` — the overlapped exchange's custom_vjp and
        the masked psum predate the replication-rewrite trace (see
        ``_chunked_expert_exchange``)."""
        import jax

        from .._compat import shard_map

        rep = self._replicated()
        w_specs = self.weight_specs(weights)
        c_specs = self.cache_specs()
        in_specs = (w_specs, c_specs) + (rep,) * n_data
        out_specs = (c_specs, rep)
        in_sh = (self._named(w_specs), self._named(c_specs)) \
            + (self._named(rep),) * n_data
        out_sh = (self._named(c_specs), self._named(rep))
        mesh = self.mesh

        @functools.partial(jax.jit, donate_argnums=(1,),
                           in_shardings=in_sh, out_shardings=out_sh)
        def step(weights, cache, *data):
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(weights, cache, *data)

        return step

    def jit_decode(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, positions, block_tables,
                 seq_lens, write_blocks, write_offsets):
            return gpt_decode_step(weights, cfg, ccfg, cache, tokens,
                                   positions, block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return self._wrap(body, weights, 6)

    def jit_prefill(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, length, blocks):
            return gpt_prefill_step(weights, cfg, ccfg, cache, tokens,
                                    length, blocks)

        return self._wrap(body, weights, 3)

    def jit_extend(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, block_tables, seq_lens,
                 write_blocks, write_offsets):
            return gpt_extend_step(weights, cfg, ccfg, cache, tokens,
                                   block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return self._wrap(body, weights, 5)

    def describe(self) -> str:
        devs = ",".join(str(getattr(d, "id", d))
                        for d in self.mesh.devices.flat)
        b = self.plan.budget()
        return (f"ep={self.ep} axis={self.axis!r} devices=[{devs}] "
                f"experts={self.model_cfg.num_experts} "
                f"a2a_budget={b.get('all_to_all')} "
                f"psum_budget={b.get('psum')}")
