"""apex_tpu.serving.control_plane — the process-isolated fleet (ISSUE-18).

PR 14's fleet is N engine threads in ONE address space: a segfault,
OOM, or wedged XLA call in any replica takes down all of them.  This
module promotes every fleet boundary that is already *data* — the
request journal, the block-table KV wire format, ``router_snapshot()``
gauges — into a process/socket boundary:

* :class:`EngineSpec` — a picklable recipe for one replica's engine
  (builder entry point + model kwargs + device index + paths).  The
  parent never builds an engine; each **replica subprocess** does,
  pinned to its device, and speaks a length-prefixed-JSON(+binary)
  protocol over an AF_UNIX socket.
* :class:`ReplicaProcess` — the parent-side handle: spawn (``spawn``
  start method — fork after jax init is unsafe), hello handshake,
  sequenced RPCs with **explicit timeouts + bounded-backoff retry**
  (idempotent ops retry in place; non-idempotent ops — tick, submit,
  scatter — escalate to SIGKILL + respawn + journal replay, which the
  journal makes safe), and SIGKILL + join for the reap.
* :class:`ProcessFleet` — the supervisor: scored routing from gauge
  polls (a timed-out poll degrades that replica's score — it never
  blocks the tick), **heartbeat-supervised liveness** (missed polls ⇒
  SIGKILL + bounded-backoff restart, the PR 3 ``run_resumable``
  discipline), crash recovery by replaying the on-disk
  :class:`~.resilience.RequestJournal` into the fresh process (fleet
  digest token-identical to an uninterrupted run — greedy decode is
  batching-invariant, the PR 15 sweep's proof), disaggregated-prefill
  KV handoff over the socket (:func:`~.fleet.export_prefix_payload`
  blobs; a torn handoff falls back to cold prefill, never losing the
  request), **autoscaling** from FleetAggregator trend slopes
  (scale-up on backlog, drain-then-reap scale-down — zero lost
  requests), and **per-class QoS admission** tied to SLOTracker burn
  rates (:class:`QoSPolicy` over ShedPolicy's per-class thresholds).

The supervisor module itself imports no jax (importing the
``apex_tpu.serving`` package does pull jax into the parent
interpreter, but the parent creates no engines, no arrays, no device
state — all of that lives in the children, so one replica dying takes
nothing else with it).  KV blobs transit the parent as opaque bytes:
only children serialize/deserialize arrays.

Drive modes mirror the in-process fleet: the deterministic **stepped**
loop (faults, autoscale, QoS, handoffs; one supervisor round ticks
every replica once over RPC) and **freerun** (submit everything, send
one ``run`` RPC per replica, children decode concurrently in their own
processes — the scaling mode the bench row measures).

Supervision tree and the worked kill-9 walkthrough:
docs/api/resilience.md#distributed-control-plane.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import importlib
import json
import os
import random
import signal
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis.flags import flag_float, flag_int
from ..monitor.events import Event, JsonlSink
from ..monitor.export import (FleetAggregator, MetricsExporter,
                              MetricsRegistry, MetricsServer,
                              replica_metrics_port)
from ..resilience.driver import backoff_delay
from ..resilience.faults import parse_fault, split_fault
from ..utils.log_util import get_logger
from .resilience import RequestJournal

logger = get_logger(__name__)

__all__ = [
    "AutoscalePolicy", "EngineSpec", "FleetGiveUp", "FrameError",
    "PROTOCOL", "ProcessFleet", "ProcessFleetSummary",
    "ProtocolSpec", "ProtocolViolation", "QoSClass", "QoSPolicy",
    "ReplicaProcess", "RpcError", "RpcRemoteError", "RpcTimeout",
    "ReplicaDead", "fleet_rows_digest", "recv_frame", "send_frame",
]

# disaggregated prefill probes ride the normal request path under this
# rid prefix (same convention as the in-process fleet) — probes are
# plumbing, excluded from fleet accounting and the fleet digest
PREFILL_RID_PREFIX = "pf:"

# one frame's JSON header may not exceed this (the KV payload rides
# separate binary blobs, so headers stay small; a corrupt length
# prefix must fail fast, not allocate gigabytes)
MAX_HEADER_BYTES = 64 << 20
MAX_BLOB_BYTES = 1 << 31


# ---------------------------------------------------------------------------
# Wire protocol: length-prefixed JSON header + raw binary blobs
# ---------------------------------------------------------------------------

class RpcError(RuntimeError):
    """Base class for control-plane RPC failures."""


class RpcTimeout(RpcError):
    """The peer did not answer within the per-op timeout.  For
    idempotent ops the caller retries with backoff; for the rest the
    supervisor escalates to SIGKILL + respawn + journal replay."""


class ReplicaDead(RpcError):
    """The socket died mid-conversation (peer closed, ECONNRESET) —
    the subprocess is gone or unreachable.  Supervisor restarts it."""


class RpcRemoteError(RpcError):
    """The child executed the op and reported a Python-level error.
    The connection is still healthy — this is a REQUEST-level failure
    (e.g. an engine admission reject), not a replica failure."""


class FrameError(RpcError):
    """The length prefix was honest but the header inside it was not
    JSON (or not a JSON object).  Crucially the stream is still
    FRAME-ALIGNED — exactly the declared bytes were consumed — so a
    receiver may answer with a structured error frame and keep
    serving instead of tearing the socket down."""


class ProtocolViolation(RpcError):
    """A frame that decodes fine but violates :data:`PROTOCOL`: an
    op nobody declared, a missing required header field, or a retry
    requested for a non-idempotent op.  Raised on the side that can
    see the violation — locally before a send, or remotely as a
    structured error reply."""


def send_frame(sock: socket.socket, header: Dict[str, Any],
               blobs: Sequence[bytes] = ()) -> None:
    """One wire frame: ``>I`` length + JSON header, then each binary
    blob verbatim (lengths announced in ``header['blobs']``).  KV
    payloads ride the blobs — int8 rows and fp32 scales as raw bytes,
    never JSON-escaped."""
    header = dict(header)
    if blobs:
        header["blobs"] = [len(b) for b in blobs]
    payload = json.dumps(header, separators=(",", ":")).encode()
    try:
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        for b in blobs:
            sock.sendall(b)
    except socket.timeout as e:
        raise RpcTimeout(f"send timed out: {e}") from e
    except OSError as e:
        raise ReplicaDead(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks: List[bytes] = []
    remaining = int(n)
    while remaining:
        try:
            chunk = sock.recv(min(remaining, 1 << 20))
        except socket.timeout as e:
            raise RpcTimeout(
                f"recv timed out with {remaining} byte(s) "
                f"outstanding") from e
        except OSError as e:
            raise ReplicaDead(f"recv failed: {e}") from e
        if not chunk:
            raise ReplicaDead("peer closed the socket"
                              + (" mid-frame" if chunks or
                                 remaining != n else ""))
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket
               ) -> Tuple[Dict[str, Any], List[bytes]]:
    """Receive one frame; returns ``(header, blobs)``.  Raises
    :class:`RpcTimeout` on the socket timeout, :class:`ReplicaDead`
    on EOF/reset, :class:`FrameError` on an undecodable header (the
    stream stays frame-aligned — the worker loop answers and keeps
    serving), and plain :class:`RpcError` when the framing itself is
    untrustworthy (corrupt length prefix, junk blob lengths — the
    only cure is a new socket)."""
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > MAX_HEADER_BYTES:
        raise RpcError(f"frame header of {n} bytes exceeds "
                       f"{MAX_HEADER_BYTES} — corrupt length prefix?")
    try:
        header = json.loads(_recv_exact(sock, n).decode())
    except ValueError as e:
        raise FrameError(f"malformed frame header: {e}") from e
    if not isinstance(header, dict):
        raise FrameError(f"frame header is "
                         f"{type(header).__name__}, not an object")
    blobs = []
    lengths = header.get("blobs", [])
    if not isinstance(lengths, list):
        raise RpcError(f"blob lengths {lengths!r} are not a list")
    for m in lengths:
        if not isinstance(m, int) or not 0 <= m <= MAX_BLOB_BYTES:
            raise RpcError(f"blob length {m!r} out of range")
        blobs.append(_recv_exact(sock, m))
    return header, blobs


# ---------------------------------------------------------------------------
# The protocol, as data: every op both sides are generated from
# ---------------------------------------------------------------------------

#: Header fields the FRAMING layer owns on every message — senders
#: may always set them, receivers may always read them, and no
#: :class:`ProtocolSpec` re-declares them: ``op``/``seq`` address the
#: frame, ``blobs`` carries the binary lengths (``send_frame`` adds
#: it), ``error``/``message`` are the structured error-reply shape.
FRAME_FIELDS = ("op", "seq", "blobs", "error", "message")

#: The timeout classes call sites must route through (never literal
#: floats): ``rpc`` = APEX_TPU_CP_RPC_TIMEOUT_S, ``poll`` =
#: APEX_TPU_CP_POLL_TIMEOUT_S, ``spawn`` = APEX_TPU_CP_SPAWN_TIMEOUT_S.
TIMEOUT_CLASSES = ("rpc", "poll", "spawn")


@dataclasses.dataclass(frozen=True)
class ProtocolSpec:
    """One wire op's declared contract.  The child's dispatch table,
    the parent's retry policy, and the per-op timeouts are all
    derived from these — and ``apex_tpu.analysis.protocol`` audits
    both sides against them statically (APX901–APX905).

    ``required``/``optional`` are the request header fields beyond
    :data:`FRAME_FIELDS`; ``reply`` the success-reply fields.
    ``request_blobs``/``reply_blobs`` declare which direction may
    carry binary payloads.  ``idempotent`` gates in-place retry:
    a non-idempotent op never re-sends — it escalates to
    SIGKILL + respawn + journal replay."""

    op: str
    direction: str = "parent_to_child"
    required: Tuple[str, ...] = ()
    optional: Tuple[str, ...] = ()
    reply: Tuple[str, ...] = ()
    request_blobs: bool = False
    reply_blobs: bool = False
    timeout_class: str = "rpc"
    idempotent: bool = False

    def __post_init__(self):
        if self.direction not in ("parent_to_child",
                                  "child_to_parent"):
            raise ValueError(f"bad direction {self.direction!r}")
        if self.timeout_class not in TIMEOUT_CLASSES:
            raise ValueError(
                f"timeout class {self.timeout_class!r} not in "
                f"{TIMEOUT_CLASSES}")
        clash = (set(self.required) | set(self.optional)
                 | set(self.reply)) & set(FRAME_FIELDS)
        if clash:
            raise ValueError(f"op {self.op!r} re-declares framing "
                             f"field(s) {sorted(clash)}")


#: op name → spec.  THE registry: ``_OP_HANDLERS`` must cover exactly
#: the parent→child ops here (checked at import), ``ReplicaProcess``
#: refuses undeclared ops and non-idempotent retries, and
#: ``ProcessFleet`` maps ``timeout_class``/``idempotent`` to its
#: configured deadlines and retry budgets.
PROTOCOL: Dict[str, ProtocolSpec] = {s.op: s for s in (
    ProtocolSpec("hello", direction="child_to_parent",
                 required=("rid", "pid"),
                 optional=("replayed", "tick", "block_size"),
                 timeout_class="spawn"),
    ProtocolSpec("snapshot", reply=("snapshot",),
                 timeout_class="poll", idempotent=True),
    ProtocolSpec("tick", reply=("tick", "busy", "finished")),
    ProtocolSpec("submit", required=("req",), reply=("ok",)),
    ProtocolSpec("gather_kv", required=("prompt",),
                 reply=("resident", "names", "shapes", "dtypes",
                        "geometry"),
                 reply_blobs=True, idempotent=True),
    ProtocolSpec("scatter_kv",
                 required=("names", "shapes", "dtypes", "prompt",
                           "n"),
                 optional=("geometry",), reply=("landed",),
                 request_blobs=True),
    ProtocolSpec("run", reply=("summary", "finished", "busy"),
                 timeout_class="spawn"),
    ProtocolSpec("summary",
                 reply=("summary", "digest", "rows", "replayed",
                        "tick"),
                 idempotent=True),
    ProtocolSpec("shutdown", idempotent=True),
)}


# ---------------------------------------------------------------------------
# EngineSpec: the picklable recipe a subprocess builds its engine from
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EngineSpec:
    """Everything a replica subprocess needs to build its engine.

    ``builder`` is a ``"module:function"`` entry point resolved IN THE
    CHILD (the parent never imports it); it receives this spec as a
    plain dict and returns ``{"engine": ..., "monitor": ..., or None,
    "journal": ... or None}``.  ``model`` carries the builder's
    kwargs verbatim.  ``fault`` is a child-side injector spec string
    (``kill9@K`` etc.) fired at the engine's tick boundaries;
    ``replay`` makes the fresh process re-enter its journal's open
    rids before serving (the crash-recovery spawn).
    ``connect_timeout_s`` is how long the child keeps retrying its
    rendezvous connect — :meth:`ReplicaProcess.begin_spawn` stamps
    it with the SAME ``spawn_timeout_s`` deadline the listener
    honors, so the two sides of the handshake can never race two
    different clocks (None falls back to the registered
    ``APEX_TPU_CP_CONNECT_TIMEOUT_S`` flag)."""

    replica_id: str
    role: str = "serve"                   # 'serve' | 'prefill'
    builder: str = ("apex_tpu.testing.standalone_gpt:"
                    "build_fleet_engine")
    model: Dict[str, Any] = dataclasses.field(default_factory=dict)
    device_index: Optional[int] = None
    jsonl_path: Optional[str] = None
    journal_path: Optional[str] = None
    metrics_port: Optional[int] = None
    fault: Optional[str] = None
    replay: bool = False
    connect_timeout_s: Optional[float] = None

    def __post_init__(self):
        if self.role not in ("serve", "prefill"):
            raise ValueError(f"role {self.role!r} not in "
                             f"('serve', 'prefill')")

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "EngineSpec":
        return EngineSpec(**d)


def _resolve_builder(path: str) -> Callable[[Dict[str, Any]],
                                            Dict[str, Any]]:
    mod, _, fn = path.partition(":")
    if not mod or not fn:
        raise ValueError(f"builder {path!r} is not 'module:function'")
    return getattr(importlib.import_module(mod), fn)


def fleet_rows_digest(rows: Dict[str, List[int]]) -> str:
    """The routing-invariant fleet digest: md5 over ``rid:tokens;``
    in sorted rid order, prefill probes excluded.  Identical row
    format to :meth:`~.engine.ServingEngine.tokens_digest`, but
    merged across every replica AND across a restarted replica's
    journal terminals — so a kill-9'd fleet and an uninterrupted one
    digest the same no matter how the crash reshuffled routing."""
    h = hashlib.md5()
    for rid in sorted(rows):
        if str(rid).startswith(PREFILL_RID_PREFIX):
            continue
        h.update(f"{rid}:"
                 f"{','.join(map(str, rows[rid]))};".encode())
    return h.hexdigest()[:12]


# ---------------------------------------------------------------------------
# Autoscale + QoS policies (pure host logic, unit-testable)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AutoscalePolicy:
    """Scale decisions from the signals PR 17's FleetAggregator
    already computes.  Scale UP when backlog-per-serve-replica crosses
    ``up_backlog`` while the ``queue_depth`` trend slope is
    non-improving (``>= up_slope``); scale DOWN after
    ``down_rounds`` consecutive rounds below ``down_backlog`` per
    replica.  ``cooldown`` rounds separate consecutive actions so one
    burst cannot thrash spawn/reap."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_backlog: float = 4.0
    up_slope: float = 0.0
    down_backlog: float = 0.5
    down_rounds: int = 3
    cooldown: int = 3
    _idle_rounds: int = dataclasses.field(default=0, init=False)
    _last_action: int = dataclasses.field(default=-(10 ** 9),
                                          init=False)

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")

    def decide(self, round_idx: int, n_serve: int, backlog: int,
               trends: Optional[Dict[str, Dict[str, float]]]
               ) -> Optional[str]:
        """``'up'`` / ``'down'`` / None for this round."""
        per = float(backlog) / max(1, n_serve)
        slope = float(((trends or {}).get("queue_depth") or {})
                      .get("slope", 0.0))
        if per < self.down_backlog:
            self._idle_rounds += 1
        else:
            self._idle_rounds = 0
        if round_idx - self._last_action < self.cooldown:
            return None
        if (n_serve < self.max_replicas and per >= self.up_backlog
                and slope >= self.up_slope):
            self._last_action = round_idx
            self._idle_rounds = 0
            return "up"
        if (n_serve > self.min_replicas
                and self._idle_rounds >= self.down_rounds):
            self._last_action = round_idx
            self._idle_rounds = 0
            return "down"
        return None


@dataclasses.dataclass
class QoSClass:
    """One priority class's admission contract: ``max_open`` caps the
    class's fleet-wide in-flight requests (0 = defer to the
    ShedPolicy's per-class queue high-water mark), ``shed_on_burn``
    refuses new admissions while the class has an active SLO burn
    episode (the PR 17 SLOTracker signal, polled off the gauge
    snapshots)."""

    name: str
    max_open: int = 0
    shed_on_burn: bool = False


class QoSPolicy:
    """Per-priority-class admission at the fleet door.

    Classes are the engine's own naming (``p<priority>``,
    :meth:`~.metrics.ServeMetrics.priority_class`).  A refused request
    is SHED AT THE DOOR — it never reaches an engine, opens no
    lifecycle chain, and is accounted as ``shed_admission`` (so
    ``trace_check --serve``'s N submitted ⇒ N terminal still holds
    over what WAS submitted)."""

    def __init__(self, classes: Sequence[QoSClass] = (),
                 shed=None):
        self.classes: Dict[str, QoSClass] = {}
        for c in classes:
            if c.name in self.classes:
                raise ValueError(f"duplicate QoS class {c.name!r}")
            self.classes[c.name] = c
        self.shed = shed                  # ShedPolicy (queue_hw_for)

    @staticmethod
    def class_of(priority) -> str:
        return f"p{int(priority or 0)}"

    def admit(self, cls: str, open_count: int,
              burning: Sequence[str]) -> Tuple[bool, str]:
        """Admission verdict for one request of class ``cls`` given
        the class's fleet-wide open count and the active SLO burn
        episodes (``class/dimension`` strings)."""
        qc = self.classes.get(cls)
        cap = qc.max_open if qc is not None and qc.max_open else 0
        if not cap and self.shed is not None:
            cap = int(self.shed.queue_hw_for(cls))
        if cap and open_count >= cap:
            return False, "class_backlog"
        if qc is not None and qc.shed_on_burn and any(
                str(b).partition("/")[0] == cls for b in burning):
            return False, "slo_burn"
        return True, ""


class FleetGiveUp(RuntimeError):
    """A replica exhausted its restart budget (the bounded half of
    bounded-backoff restart — mirrors :class:`~..resilience.driver.
    GiveUp`)."""


# ---------------------------------------------------------------------------
# Child side: the replica worker
# ---------------------------------------------------------------------------

def _np_dtype(name: str):
    """Resolve a dtype string in the CHILD (numpy available there).
    ``bfloat16`` needs the ml_dtypes registration jax ships."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _connect_child(path: str, timeout_s: float) -> socket.socket:
    deadline = time.monotonic() + timeout_s
    attempt = 0
    while True:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(path)
            return s
        except OSError:
            s.close()
            if time.monotonic() >= deadline:
                raise
            time.sleep(backoff_delay(attempt, base=0.02,
                                     maximum=0.5))
            attempt += 1


class _WorkerState:
    """Everything the child's RPC loop owns (ALL jax state lives
    here, in the subprocess)."""

    def __init__(self, spec: EngineSpec, built: Dict[str, Any]):
        self.spec = spec
        self.engine = built["engine"]
        self.monitor = built.get("monitor")
        self.journal = built.get("journal")
        self.closer = built.get("close")
        self.fault = parse_fault(spec.fault)
        self.replayed = 0
        self.done_mark = 0           # engine.done watermark
        self.exporter = None
        self.metrics_server = None

    def new_finished(self) -> List[List[str]]:
        """Terminal rids since the last report (the tick-reply
        delta the supervisor's ledger is built from)."""
        out = []
        done = self.engine.done
        while self.done_mark < len(done):
            q = done[self.done_mark]
            out.append([str(q.rid), str(q.terminal or "finished")])
            self.done_mark += 1
        return out

    def close(self) -> None:
        if self.metrics_server is not None:
            with contextlib.suppress(Exception):
                self.metrics_server.stop()
        if self.closer is not None:
            with contextlib.suppress(Exception):
                self.closer()
        for obj in (self.journal, self.monitor):
            if obj is not None:
                with contextlib.suppress(Exception):
                    obj.close()


def _build_worker(spec: EngineSpec) -> _WorkerState:
    builder = _resolve_builder(spec.builder)
    state = _WorkerState(spec, builder(spec.as_dict()))
    if spec.replay and state.journal is not None \
            and spec.journal_path \
            and os.path.exists(spec.journal_path):
        # the crash-recovery spawn: re-enter every open rid from the
        # on-disk ledger (PR 13 machinery — crash_reset on a fresh
        # engine is a no-op, resubmit opens a new lifecycle chain as
        # documented).  Probes replay like any request.
        from .resilience import recover_engine

        stats = recover_engine(state.engine, state.journal,
                               state.monitor)
        state.replayed = int(stats.replayed)
    if spec.metrics_port:
        state.exporter = MetricsExporter()
        state.metrics_server = MetricsServer(
            state.exporter, port=int(spec.metrics_port),
            monitor=state.monitor)
        state.metrics_server.start()
    return state


def _worker_publish(state: _WorkerState, tick: int) -> None:
    if state.exporter is None:
        return
    try:
        reg = MetricsRegistry()
        state.engine.export_registry(reg)
        state.exporter.publish(reg, tick=tick)
    except Exception as e:      # telemetry must never kill the serve
        logger.warning("replica exporter publish failed: %s",
                       str(e)[:160])


def _op_snapshot(state: _WorkerState, header: Dict[str, Any],
                 blobs: List[bytes]
                 ) -> Tuple[Dict[str, Any], List[bytes]]:
    snap = dict(state.engine.router_snapshot())
    # chain keys are bytes digests; hex them for the JSON header
    snap["warm_prefix_keys"] = [k.hex()
                                for k in snap["warm_prefix_keys"]]
    e = state.engine
    snap["busy"] = bool(e.queue or e.active or e.prefilling)
    _worker_publish(state, e.steps)
    return {"snapshot": snap}, []


def _op_tick(state: _WorkerState, header: Dict[str, Any],
             blobs: List[bytes]
             ) -> Tuple[Dict[str, Any], List[bytes]]:
    e = state.engine
    if state.fault is not None:
        state.fault.before_tick(e.steps,
                                journal_path=state.spec.journal_path)
    if e.queue or e.active or e.prefilling:
        e.step()
    return ({"tick": e.steps,
             "busy": bool(e.queue or e.active or e.prefilling),
             "finished": state.new_finished()}, [])


def _op_submit(state: _WorkerState, header: Dict[str, Any],
               blobs: List[bytes]
               ) -> Tuple[Dict[str, Any], List[bytes]]:
    from .engine import Request

    req = header["req"]
    state.engine.submit(Request(
        rid=str(req["rid"]),
        prompt=[int(t) for t in req["prompt"]],
        max_new_tokens=int(req.get("max_new_tokens", 1)),
        eos_token=req.get("eos_token"),
        deadline_ms=req.get("deadline_ms"),
        priority=int(req.get("priority", 0) or 0)))
    return {"ok": 1}, []


def _op_gather_kv(state: _WorkerState, header: Dict[str, Any],
                  blobs: List[bytes]
                  ) -> Tuple[Dict[str, Any], List[bytes]]:
    from .fleet import _geometry_key, export_prefix_payload

    out = export_prefix_payload(
        state.engine, [int(t) for t in header["prompt"]])
    if out is None:
        return {"resident": -1}, []
    n, arrays = out
    names = sorted(arrays)
    return ({"resident": int(n), "names": names,
             "shapes": [list(arrays[k].shape) for k in names],
             "dtypes": [str(arrays[k].dtype) for k in names],
             "geometry": list(map(str, _geometry_key(
                 state.engine.cache_cfg)))},
            [arrays[k].tobytes() for k in names])


def _op_scatter_kv(state: _WorkerState, header: Dict[str, Any],
                   blobs: List[bytes]
                   ) -> Tuple[Dict[str, Any], List[bytes]]:
    import numpy as np

    from .fleet import _geometry_key, import_prefix_payload

    geo = list(map(str, _geometry_key(state.engine.cache_cfg)))
    if list(header.get("geometry", geo)) != geo:
        raise ValueError(
            f"KV handoff across incompatible cache geometries: "
            f"{header.get('geometry')} -> {geo}")
    arrays = {}
    for name, shape, dtype, blob in zip(
            header["names"], header["shapes"], header["dtypes"],
            blobs):
        arrays[name] = np.frombuffer(
            blob, dtype=_np_dtype(dtype)).reshape(shape)
    landed = import_prefix_payload(
        state.engine, [int(t) for t in header["prompt"]],
        int(header["n"]), arrays)
    return {"landed": int(landed)}, []


def _op_run(state: _WorkerState, header: Dict[str, Any],
            blobs: List[bytes]
            ) -> Tuple[Dict[str, Any], List[bytes]]:
    e = state.engine

    def before_tick(tick):
        if state.fault is not None:
            state.fault.before_tick(
                tick, journal_path=state.spec.journal_path)

    summary = e.run(before_tick=before_tick)
    _worker_publish(state, e.steps)
    return ({"summary": summary.as_dict(),
             "finished": state.new_finished(),
             "busy": bool(e.queue or e.active or e.prefilling)}, [])


def _op_summary(state: _WorkerState, header: Dict[str, Any],
                blobs: List[bytes]
                ) -> Tuple[Dict[str, Any], List[bytes]]:
    e = state.engine
    return ({"summary": e.summary().as_dict(),
             "digest": e.tokens_digest(),
             "rows": e.digest_rows(),
             "replayed": state.replayed,
             "tick": e.steps}, [])


def _op_shutdown(state: _WorkerState, header: Dict[str, Any],
                 blobs: List[bytes]
                 ) -> Tuple[Dict[str, Any], List[bytes]]:
    # the loop special-cases shutdown (reply THEN return); the
    # handler exists so the dispatch table covers the whole registry
    return {}, []


#: The child dispatch, generated against :data:`PROTOCOL` — every
#: parent→child op maps to one uniform ``(state, header, blobs) →
#: (reply_fields, reply_blobs)`` handler.  ``_validate_protocol``
#: fails the import on any drift between this table and the registry.
_OP_HANDLERS: Dict[str, Callable[
    [_WorkerState, Dict[str, Any], List[bytes]],
    Tuple[Dict[str, Any], List[bytes]]]] = {
    "snapshot": _op_snapshot,
    "tick": _op_tick,
    "submit": _op_submit,
    "gather_kv": _op_gather_kv,
    "scatter_kv": _op_scatter_kv,
    "run": _op_run,
    "summary": _op_summary,
    "shutdown": _op_shutdown,
}


def _validate_protocol() -> None:
    """Import-time drift check: the dispatch table and the declared
    registry must cover exactly the same parent→child op set."""
    declared = {op for op, s in PROTOCOL.items()
                if s.direction == "parent_to_child"}
    handled = set(_OP_HANDLERS)
    if declared != handled:
        raise AssertionError(
            f"PROTOCOL/_OP_HANDLERS drift: declared-not-handled="
            f"{sorted(declared - handled)} handled-not-declared="
            f"{sorted(handled - declared)}")


_validate_protocol()


def _check_required(spec: ProtocolSpec,
                    header: Dict[str, Any]) -> None:
    missing = [f for f in spec.required if f not in header]
    if missing:
        raise ProtocolViolation(
            f"op {spec.op!r} frame is missing required header "
            f"field(s) {missing}")


def _worker_loop(conn: socket.socket, state: _WorkerState) -> None:
    from ..resilience.faults import InjectedFault

    while True:
        try:
            header, blobs = recv_frame(conn)
        except ReplicaDead:
            return                      # supervisor went away
        except FrameError as e:
            # undecodable header inside an honest length prefix: the
            # stream is still frame-aligned, so answer structurally
            # and keep serving — never tear the socket on a request
            # that merely failed to decode
            logger.warning("worker dropped malformed frame: %s", e)
            send_frame(conn, {"seq": None,
                              "error": type(e).__name__,
                              "message": str(e)[:500]})
            continue
        op = header.get("op")
        seq = header.get("seq")
        reply: Dict[str, Any] = {"seq": seq}
        rblobs: List[bytes] = []
        try:
            spec = PROTOCOL.get(op)
            if spec is None or spec.direction != "parent_to_child":
                raise ProtocolViolation(f"unknown op {op!r}")
            _check_required(spec, header)
            out, rblobs = _OP_HANDLERS[op](state, header, blobs)
            reply.update(out)
            if op == "shutdown":
                send_frame(conn, reply)
                return
        except (InjectedFault, KeyboardInterrupt, SystemExit):
            # an injected crash kills the PROCESS — that is the
            # drill.  The socket dies with us; the supervisor's
            # recv raises ReplicaDead and the restart path runs.
            raise
        except Exception as e:
            # request-level failures become an error REPLY, not a dead
            # child: the supervisor decides whether to retry or shed
            logger.warning("worker op %r failed: %s: %s",
                           op, type(e).__name__, e)
            reply = {"seq": seq, "error": type(e).__name__,
                     "message": str(e)[:500]}
            rblobs = []
        send_frame(conn, reply, rblobs)


def _worker_entry(spec_dict: Dict[str, Any],
                  socket_path: str) -> None:
    """Subprocess main.  Connects FIRST (so the parent's accept
    returns as soon as the interpreter is up), then builds the engine
    (jax import + warmup — the slow part the spawn timeout covers),
    then says hello and serves RPCs until shutdown or parent exit."""
    spec = EngineSpec.from_dict(spec_dict)
    # the connect deadline is the LISTENER's deadline (begin_spawn
    # stamps spawn_timeout_s into the spec) — one clock, two sides
    connect_timeout = (float(spec.connect_timeout_s)
                       if spec.connect_timeout_s is not None
                       else flag_float("APEX_TPU_CP_CONNECT_TIMEOUT_S"))
    conn = _connect_child(socket_path, timeout_s=connect_timeout)
    try:
        try:
            state = _build_worker(spec)
        except BaseException as e:
            with contextlib.suppress(Exception):
                send_frame(conn, {
                    "op": "hello", "rid": spec.replica_id,
                    "pid": os.getpid(),
                    "error": type(e).__name__,
                    "message": str(e)[:500]})
            raise
        try:
            send_frame(conn, {
                "op": "hello", "rid": spec.replica_id,
                "pid": os.getpid(), "replayed": state.replayed,
                "tick": state.engine.steps,
                "block_size": int(state.engine.cache_cfg.block_size)})
            _worker_loop(conn, state)
        finally:
            state.close()
    finally:
        with contextlib.suppress(Exception):
            conn.close()


# ---------------------------------------------------------------------------
# Parent side: one replica subprocess's handle
# ---------------------------------------------------------------------------

class ReplicaProcess:
    """Supervisor-side handle for one replica subprocess: spawn +
    hello handshake, sequenced RPCs with per-op timeout and bounded-
    backoff retry, SIGKILL + join for the reap, and the restart
    bookkeeping (incarnation counter, suspect-heartbeat count, restart
    budget).  Holds no engine — only the socket, the pid, and the
    :class:`EngineSpec` to respawn from."""

    def __init__(self, spec: EngineSpec, sock_dir: str, *,
                 max_restarts: int = 3,
                 spawn_timeout_s: float = 300.0,
                 backoff_base: float = 0.05,
                 backoff_max: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.spec = spec
        self.sock_dir = sock_dir
        self.max_restarts = int(max_restarts)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._rng = rng or random.Random(0)
        self.proc = None
        self.conn: Optional[socket.socket] = None
        self.pid: Optional[int] = None
        self.incarnation = 0
        self.restarts = 0
        self.suspect = 0              # consecutive missed heartbeats
        self.stale = False            # last poll failed — score floor
        self.inflight = 0             # submits since the last fresh
        #                               snapshot (router reservation)
        self.last_snap: Optional[Dict[str, Any]] = None
        self.block_size: Optional[int] = None
        self.replayed_total = 0
        self.routable = True
        self.reaped = False
        self._seq = 0
        self._listener: Optional[socket.socket] = None
        self._sock_path: Optional[str] = None

    @property
    def replica_id(self) -> str:
        return self.spec.replica_id

    @property
    def role(self) -> str:
        return self.spec.role

    def alive(self) -> bool:
        return (self.proc is not None and self.proc.is_alive()
                and self.conn is not None)

    # -- spawn ----------------------------------------------------------

    def begin_spawn(self, *, replay: bool = False) -> None:
        """Phase 1: bind the listener and start the subprocess (the
        jax import + warmup runs concurrently across replicas;
        :meth:`finish_spawn` collects the hello)."""
        import multiprocessing as mp

        path = os.path.join(self.sock_dir,
                            f"{self.spec.replica_id}"
                            f".{self.incarnation}.sock")
        with contextlib.suppress(OSError):
            os.unlink(path)
        lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            lst.bind(path)
            lst.listen(1)
            lst.settimeout(self.spawn_timeout_s)
            spec = self._spawn_spec(replay)
            ctx = mp.get_context("spawn")
            self.proc = ctx.Process(
                target=_worker_entry, args=(spec.as_dict(), path),
                name=f"apex-replica-{self.spec.replica_id}",
                daemon=True)
            self.proc.start()
        except BaseException:
            # a failed bind/spawn must not leak the listener fd
            lst.close()
            raise
        self._listener = lst
        self._sock_path = path

    def _spawn_spec(self, replay: bool) -> EngineSpec:
        """The spec one incarnation actually receives: a
        respawn-for-replay strips the fault spec entirely (injected
        faults are once-per-serve by contract, and a fresh process's
        tick counter would otherwise re-reach K and re-fire forever
        — see faults.PROCESS_FATAL_KINDS), and the child's connect
        deadline is stamped with the listener's own
        ``spawn_timeout_s`` so both halves of the rendezvous run on
        one clock."""
        return dataclasses.replace(
            self.spec, replay=replay,
            fault=None if replay else self.spec.fault,
            connect_timeout_s=self.spawn_timeout_s)

    def finish_spawn(self) -> Dict[str, Any]:
        """Phase 2: accept + hello.  Raises :class:`RpcError` when
        the child failed to build (its hello carries the error)."""
        lst, path = self._listener, self._sock_path
        self._listener = self._sock_path = None
        try:
            try:
                conn, _ = lst.accept()
            except socket.timeout as e:
                raise RpcTimeout(
                    f"replica {self.replica_id} did not connect "
                    f"within {self.spawn_timeout_s}s") from e
        finally:
            lst.close()
            with contextlib.suppress(OSError):
                os.unlink(path)
        try:
            conn.settimeout(self.spawn_timeout_s)
            hello, _ = recv_frame(conn)
        except BaseException:
            # a child that died mid-hello must not leak the accepted
            # socket: close it, reap the corpse, then escalate
            conn.close()
            self.kill()
            raise
        if hello.get("error"):
            conn.close()
            self.kill()
            raise RpcError(
                f"replica {self.replica_id} failed to build: "
                f"{hello['error']}: {hello.get('message', '')}")
        self.conn = conn
        self.pid = int(hello["pid"])
        self.block_size = hello.get("block_size")
        self.incarnation += 1
        self.suspect = 0
        self.stale = False
        self.inflight = 0
        self.reaped = False
        replayed = int(hello.get("replayed", 0))
        self.replayed_total += replayed
        return hello

    def spawn(self, *, replay: bool = False) -> Dict[str, Any]:
        self.begin_spawn(replay=replay)
        return self.finish_spawn()

    # -- RPC ------------------------------------------------------------

    def post(self, op: str, header: Optional[Dict[str, Any]] = None,
             blobs: Sequence[bytes] = (), *,
             timeout: float) -> int:
        """Send one request without waiting (the freerun fan-out);
        returns the sequence number for :meth:`wait`."""
        spec = PROTOCOL.get(op)
        if spec is None or spec.direction != "parent_to_child":
            raise ProtocolViolation(
                f"op {op!r} is not a declared parent->child op")
        if blobs and not spec.request_blobs:
            raise ProtocolViolation(
                f"op {op!r} does not carry request blobs")
        _check_required(spec, header or {})
        if self.conn is None:
            raise ReplicaDead(f"replica {self.replica_id} has no "
                              f"connection")
        self._seq += 1
        frame = dict(header or {})
        frame["op"] = op
        frame["seq"] = self._seq
        self.conn.settimeout(timeout)
        send_frame(self.conn, frame, blobs)
        return self._seq

    def wait(self, seq: int, *, timeout: float
             ) -> Tuple[Dict[str, Any], List[bytes]]:
        """Collect the reply for ``seq``, draining stale replies from
        earlier timed-out calls (every reply echoes its seq, so a
        late answer can never be mistaken for the current one)."""
        if self.conn is None:
            raise ReplicaDead(f"replica {self.replica_id} has no "
                              f"connection")
        self.conn.settimeout(timeout)
        for _ in range(32):
            reply, rblobs = recv_frame(self.conn)
            if reply.get("seq") == seq:
                if "error" in reply:
                    raise RpcRemoteError(
                        f"replica {self.replica_id} op failed: "
                        f"{reply['error']}: "
                        f"{reply.get('message', '')}")
                return reply, rblobs
        raise RpcError(f"replica {self.replica_id}: no reply for "
                       f"seq {seq} after draining 32 stale frames")

    def call(self, op: str,
             header: Optional[Dict[str, Any]] = None,
             blobs: Sequence[bytes] = (), *, timeout: float,
             retries: int = 0
             ) -> Tuple[Dict[str, Any], List[bytes]]:
        """One RPC with explicit timeout and bounded-backoff retry.
        Retries re-SEND under a fresh seq — safe only for ops the
        registry marks idempotent, and refused otherwise: the
        callers escalate tick/submit/scatter to restart+replay
        instead, which the journal makes exactly-once."""
        spec = PROTOCOL.get(op)
        if retries and spec is not None and not spec.idempotent:
            raise ProtocolViolation(
                f"op {op!r} is not idempotent — it may not retry "
                f"in place (escalate to restart + journal replay)")
        last: Optional[RpcError] = None
        for attempt in range(int(retries) + 1):
            try:
                seq = self.post(op, header, blobs, timeout=timeout)
                return self.wait(seq, timeout=timeout)
            except RpcTimeout as e:
                last = e
                if attempt < retries:
                    time.sleep(backoff_delay(
                        attempt, base=self.backoff_base,
                        maximum=self.backoff_max, rng=self._rng))
                    continue
                raise
        raise last  # pragma: no cover — loop always returns/raises

    # -- reap -----------------------------------------------------------

    def kill(self, *, join_timeout_s: float = 10.0) -> None:
        """SIGKILL + join + close the socket.  Idempotent."""
        if self.proc is not None and self.proc.is_alive() \
                and self.proc.pid:
            with contextlib.suppress(OSError):
                os.kill(self.proc.pid, signal.SIGKILL)
        if self.proc is not None:
            self.proc.join(join_timeout_s)
        if self.conn is not None:
            with contextlib.suppress(Exception):
                self.conn.close()
            self.conn = None

    def shutdown(self, *, timeout_s: float = 10.0) -> bool:
        """Graceful stop: the shutdown RPC, then join.  Falls back to
        :meth:`kill` on any failure.  Returns True when the child
        exited on its own."""
        ok = False
        try:
            self.call("shutdown", timeout=timeout_s)
            if self.proc is not None:
                self.proc.join(timeout_s)
                ok = not self.proc.is_alive()
        except RpcError:
            ok = False
        self.kill()
        return ok


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProcessFleetSummary:
    """What one process-fleet serve measured (the ``--serve-fleet
    --procs`` / bench-row source).  ``lost_requests`` is the
    accounting identity the whole design defends:
    ``offered - shed_admission - terminal`` MUST be 0 — every request
    the door admitted reached exactly one terminal state, across any
    number of kill-9s, torn handoffs, and scale events."""

    replicas: int
    prefill_replicas: int
    offered: int
    submitted: int               # reached an engine (offered - shed)
    shed_admission: int          # refused at the QoS door
    rejected: int                # engine-side admission rejects
    requests_done: int
    lost_requests: int
    tokens_generated: int
    wall_s: float
    tokens_per_sec: float
    rounds: int
    restarts: int
    rpc_timeouts: int
    handoffs: int
    handoff_blocks: int
    handoff_retries: int         # torn handoffs that went cold
    autoscale_ups: int
    autoscale_downs: int
    replayed_requests: int
    digest: str
    freerun: bool = False
    terminal_by_reason: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    per_replica: Dict[str, dict] = dataclasses.field(
        default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class _SupervisorLog:
    """The supervisor's event emitter: same ``event(kind, name,
    value=None, step=None, **attrs)`` shape as StepMonitor (so
    trace_check / monitor_summary read the merged JSONLs uniformly),
    backed by a JsonlSink plus an in-memory list for tests."""

    def __init__(self, jsonl_path: Optional[str] = None):
        self.events: List[Event] = []
        self._sink = JsonlSink(jsonl_path) if jsonl_path else None

    def event(self, kind: str, name: str, value=None,
              step: Optional[int] = None, **attrs) -> None:
        ev = Event(time=time.time(), step=step, kind=kind,
                   name=name, value=value, attrs=attrs)
        self.events.append(ev)
        if self._sink is not None:
            self._sink.emit(ev)

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


@dataclasses.dataclass
class _Handoff:
    """One disaggregated prefill in flight: probe on the prefill
    replica, then gather → scatter → warm submit on a serve replica
    (any failure after the probe goes COLD, never lost)."""

    req: Dict[str, Any]
    probe_rid: str
    stage: str = "probe"          # probe -> ready


class ProcessFleet:
    """The supervising parent over N replica subprocesses.  See the
    module docstring for the architecture; construction takes the
    specs, the policies, and the fault plumbing — :meth:`start`
    spawns, :meth:`serve` drives, :meth:`close` reaps.  Usable as a
    context manager."""

    def __init__(self, specs: Sequence[EngineSpec], *,
                 jsonl_path: Optional[str] = None,
                 qos: Optional[QoSPolicy] = None,
                 autoscale: Optional[AutoscalePolicy] = None,
                 spec_factory: Optional[
                     Callable[[str, int], EngineSpec]] = None,
                 aggregator: Optional[FleetAggregator] = None,
                 exporter: Optional[MetricsExporter] = None,
                 metrics_port: Optional[int] = None,
                 fault: Optional[str] = None,
                 fault_replica: str = "r0",
                 max_restarts: int = 3,
                 backoff_base: float = 0.05,
                 backoff_max: float = 2.0,
                 rpc_timeout_s: Optional[float] = None,
                 poll_timeout_s: Optional[float] = None,
                 rpc_retries: Optional[int] = None,
                 spawn_timeout_s: Optional[float] = None,
                 heartbeat_misses: Optional[int] = None,
                 tick_seed: int = 0):
        if not specs:
            raise ValueError("a fleet needs at least one replica")
        ids = [s.replica_id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.log = _SupervisorLog(jsonl_path)
        self.qos = qos
        self.autoscale = autoscale
        self.spec_factory = spec_factory
        self.aggregator = aggregator or FleetAggregator()
        self.exporter = exporter
        self.metrics_port = metrics_port
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.rpc_timeout_s = (float(rpc_timeout_s)
                              if rpc_timeout_s is not None else
                              flag_float("APEX_TPU_CP_RPC_TIMEOUT_S"))
        self.poll_timeout_s = (
            float(poll_timeout_s) if poll_timeout_s is not None
            else flag_float("APEX_TPU_CP_POLL_TIMEOUT_S"))
        self.rpc_retries = (int(rpc_retries)
                            if rpc_retries is not None else
                            flag_int("APEX_TPU_CP_RPC_RETRIES"))
        self.spawn_timeout_s = (
            float(spawn_timeout_s) if spawn_timeout_s is not None
            else flag_float("APEX_TPU_CP_SPAWN_TIMEOUT_S"))
        self.heartbeat_misses = (
            int(heartbeat_misses) if heartbeat_misses is not None
            else flag_int("APEX_TPU_CP_HEARTBEAT_MISSES"))
        self._rng = random.Random(20180 + int(tick_seed))
        child_fault, parent_fault = split_fault(fault)
        self._fault_replica = str(fault_replica)
        self._parent_fault = parse_fault(parent_fault)
        self._sock_dir: Optional[str] = None
        self._next_index = len(specs)
        self._metrics_server: Optional[MetricsServer] = None
        self._sigchld = threading.Event()
        self._prev_sigchld = None
        self.replicas: List[ReplicaProcess] = []
        self._specs = []
        base = int(metrics_port) if metrics_port else 0
        for i, spec in enumerate(specs):
            spec = dataclasses.replace(
                spec,
                fault=(child_fault
                       if spec.replica_id == self._fault_replica
                       else spec.fault),
                metrics_port=(spec.metrics_port
                              or (replica_metrics_port(base, i)
                                  if base else None)))
            self._specs.append(spec)
        # the supervisor's authoritative ledger
        self._routed: Dict[str, str] = {}       # rid -> replica_id
        self._terminal: Dict[str, str] = {}     # rid -> reason
        self._rows: Dict[str, List[int]] = {}   # rid -> out tokens
        self._class_open: Dict[str, set] = {}
        self._handoffs: Dict[str, _Handoff] = {}
        self.offered = 0
        self.shed_admission = 0
        self.rejected = 0
        self.restarts = 0
        self.rpc_timeouts = 0
        self.handoffs_done = 0
        self.handoff_blocks = 0
        self.handoff_retries = 0
        self.autoscale_ups = 0
        self.autoscale_downs = 0
        self._closed = False

    # -- lifecycle ------------------------------------------------------

    def __enter__(self) -> "ProcessFleet":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _on_sigchld(self, signum, frame) -> None:
        # APX803 flag-only discipline: a signal handler may only set
        # a flag the loop polls — the reap itself runs at a round
        # boundary, never in handler context
        self._sigchld.set()

    def start(self) -> None:
        """Spawn every replica (two-phase: all processes start, THEN
        all hellos are collected — the jax imports and warmups run
        concurrently), install the flag-only SIGCHLD handler, and
        bind the aggregated metrics server on the base port."""
        # AF_UNIX sun_path is ~108 bytes; pytest tmpdirs routinely
        # blow it, so the rendezvous sockets live under /tmp
        self._sock_dir = tempfile.mkdtemp(prefix="apexcp-")
        try:
            self._prev_sigchld = signal.signal(
                signal.SIGCHLD, self._on_sigchld)
        except ValueError:        # not the main thread — poll-only
            self._prev_sigchld = None
        for spec in self._specs:
            self.replicas.append(ReplicaProcess(
                spec, self._sock_dir,
                max_restarts=self.max_restarts,
                spawn_timeout_s=self.spawn_timeout_s,
                backoff_base=self.backoff_base,
                backoff_max=self.backoff_max, rng=self._rng))
        for rp in self.replicas:
            rp.begin_spawn()
        for rp in self.replicas:
            hello = rp.finish_spawn()
            self._emit_spawned(rp, hello)
        if self.metrics_port is not None:
            if self.exporter is None:
                self.exporter = MetricsExporter()
            self._metrics_server = MetricsServer(
                self.exporter, port=int(self.metrics_port),
                monitor=self.log)
            self._metrics_server.start()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for rp in self.replicas:
            if not rp.reaped:
                self._reap(rp, reason="shutdown", graceful=True)
        if self._metrics_server is not None:
            with contextlib.suppress(Exception):
                self._metrics_server.stop()
        if self._prev_sigchld is not None:
            with contextlib.suppress(ValueError):
                signal.signal(signal.SIGCHLD, self._prev_sigchld)
            self._prev_sigchld = None
        if self._sock_dir is not None:
            import shutil

            shutil.rmtree(self._sock_dir, ignore_errors=True)
            self._sock_dir = None
        self.log.close()

    # -- event helpers --------------------------------------------------

    def _emit_spawned(self, rp: ReplicaProcess,
                      hello: Dict[str, Any]) -> None:
        self.log.event("fleet", "replica_spawned",
                       replica=rp.replica_id, role=rp.role,
                       pid=rp.pid, incarnation=rp.incarnation,
                       replayed=int(hello.get("replayed", 0)))

    def _reap(self, rp: ReplicaProcess, *, reason: str,
              graceful: bool = False) -> None:
        """Reap ONE incarnation exactly once: (optionally graceful)
        stop, absorb the journal's terminals, emit the paired
        ``replica_reaped``."""
        if rp.reaped:
            return
        rp.reaped = True
        if graceful and rp.alive():
            rp.shutdown(timeout_s=min(10.0, self.rpc_timeout_s))
        else:
            rp.kill()
        self._absorb_journal(rp)
        self.log.event("fleet", "replica_reaped",
                       replica=rp.replica_id, pid=rp.pid,
                       incarnation=rp.incarnation, reason=reason)

    def _absorb_journal(self, rp: ReplicaProcess):
        """Fold the replica's on-disk ledger into the supervisor's:
        terminal records carry the full output token list, so
        requests that finished BEFORE a kill keep their tokens (and
        their digest rows) even though the fresh process never saw
        them.  Returns the JournalState (the submit-failure path
        checks ownership against it)."""
        path = rp.spec.journal_path
        if not path or not os.path.exists(path):
            return None
        state = RequestJournal.load(path)
        for rid, attrs in state.terminal.items():
            if str(rid).startswith(PREFILL_RID_PREFIX):
                continue
            self._record_terminal(
                str(rid), str(attrs.get("terminal", "finished")))
            self._rows.setdefault(
                str(rid),
                [int(t) for t in attrs.get("tokens", [])])
        return state

    def _record_terminal(self, rid: str, reason: str) -> None:
        if rid in self._terminal:
            return
        self._terminal[rid] = reason
        for open_set in self._class_open.values():
            open_set.discard(rid)

    def _mark_routed(self, rid: str, rp: ReplicaProcess,
                     cls: str) -> None:
        self._routed[rid] = rp.replica_id
        self._class_open.setdefault(cls, set()).add(rid)

    # -- per-op RPC policy, derived from the PROTOCOL registry ----------

    def _op_timeout(self, op: str) -> float:
        """The configured deadline for ``op``'s declared timeout
        class — call sites never carry their own float."""
        cls = PROTOCOL[op].timeout_class
        return {"rpc": self.rpc_timeout_s,
                "poll": self.poll_timeout_s,
                "spawn": self.spawn_timeout_s}[cls]

    def _op_retries(self, op: str) -> int:
        """The retry budget ``op`` is allowed: the configured budget
        when the registry marks it idempotent, zero otherwise (those
        escalate to restart + journal replay)."""
        return self.rpc_retries if PROTOCOL[op].idempotent else 0

    # -- restart (the heartbeat ⇒ SIGKILL ⇒ replay discipline) ----------

    def _restart(self, rp: ReplicaProcess, *, reason: str,
                 round_idx: int):
        """SIGKILL + bounded-backoff respawn + journal replay for one
        replica.  Returns the absorbed JournalState (None without a
        journal).  Raises :class:`FleetGiveUp` past the budget —
        bounded restarts, same contract as ``run_resumable``."""
        self.restarts += 1
        rp.restarts += 1
        self._reap(rp, reason=reason)
        state = (RequestJournal.load(rp.spec.journal_path)
                 if rp.spec.journal_path
                 and os.path.exists(rp.spec.journal_path) else None)
        if rp.restarts > rp.max_restarts:
            raise FleetGiveUp(
                f"replica {rp.replica_id} exhausted its restart "
                f"budget ({rp.max_restarts}); last reason: {reason}")
        delay = backoff_delay(rp.restarts - 1,
                              base=self.backoff_base,
                              maximum=self.backoff_max,
                              rng=self._rng)
        self.log.event("fleet", "replica_restart", step=round_idx,
                       replica=rp.replica_id, restarts=rp.restarts,
                       reason=reason, backoff_s=round(delay, 4))
        time.sleep(delay)
        hello = rp.spawn(replay=True)
        self._emit_spawned(rp, hello)
        rp.last_snap = None
        return state

    def _check_processes(self, round_idx: int) -> None:
        """The SIGCHLD flag's poll point (plus a liveness sweep — a
        child that died without a signal reaching us is still
        caught): every dead, unreaped replica restarts here."""
        self._sigchld.clear()
        for rp in list(self.replicas):
            if not rp.reaped and not rp.alive():
                self._restart(rp, reason="process_exit",
                              round_idx=round_idx)

    # -- gauge polls (heartbeats) ---------------------------------------

    def _poll_round(self, round_idx: int
                    ) -> Dict[str, Dict[str, Any]]:
        """One snapshot poll per replica.  A timeout (real or the
        ``rpc_timeout@K`` injector's dropped response) degrades the
        replica to its STALE snapshot and floors its router score —
        it never blocks the round.  ``heartbeat_misses`` consecutive
        misses ⇒ the replica is presumed wedged ⇒ SIGKILL + restart."""
        snaps: Dict[str, Dict[str, Any]] = {}
        for rp in list(self.replicas):
            if rp.reaped:
                continue
            inj = (self._parent_fault
                   if rp.replica_id == self._fault_replica else None)
            if inj is not None and inj.drop_rpc(round_idx):
                self.rpc_timeouts += 1
                rp.suspect += 1
                rp.stale = True
                self.log.event("fleet", "rpc_timeout",
                               step=round_idx,
                               replica=rp.replica_id, op="snapshot",
                               injected=True)
                if rp.last_snap is not None:
                    snaps[rp.replica_id] = rp.last_snap
                continue
            try:
                reply, _ = rp.call(
                    "snapshot",
                    timeout=self._op_timeout("snapshot"),
                    retries=self._op_retries("snapshot"))
                rp.last_snap = reply["snapshot"]
                rp.suspect = 0
                rp.stale = False
                rp.inflight = 0   # the fresh snapshot counts them
                snaps[rp.replica_id] = rp.last_snap
            except RpcTimeout:
                self.rpc_timeouts += 1
                rp.suspect += 1
                rp.stale = True
                self.log.event("fleet", "rpc_timeout",
                               step=round_idx,
                               replica=rp.replica_id, op="snapshot",
                               injected=False)
                if rp.suspect >= self.heartbeat_misses:
                    self._restart(rp, reason="missed_heartbeat",
                                  round_idx=round_idx)
                elif rp.last_snap is not None:
                    snaps[rp.replica_id] = rp.last_snap
            except (ReplicaDead, RpcRemoteError) as e:
                self._restart(
                    rp,
                    reason=f"poll_failed:{type(e).__name__}",
                    round_idx=round_idx)
        return snaps

    # -- routing --------------------------------------------------------

    def _serve_replicas(self) -> List[ReplicaProcess]:
        return [rp for rp in self.replicas
                if rp.role == "serve" and not rp.reaped]

    def _prefill_replica(self) -> Optional[ReplicaProcess]:
        for rp in self.replicas:
            if rp.role == "prefill" and not rp.reaped:
                return rp
        return None

    @staticmethod
    def _warm_keys(prompt: List[int], block_size: Optional[int]
                   ) -> List[str]:
        """The prompt's chain keys (hex), for sticky warm routing
        against each snapshot's ``warm_prefix_keys``.  Lazy import —
        the hashing itself is pure host code."""
        if not block_size:
            return []
        try:
            from .kv_cache import prefix_chain_keys

            return [k.hex() for k in
                    prefix_chain_keys(prompt, int(block_size))]
        except Exception:  # apex-lint: disable=APX202 -- warm-key hashing is best-effort routing affinity; any failure degrades to cold routing, never fails the submit
            return []

    def _route(self, req: Dict[str, Any]
               ) -> Optional[ReplicaProcess]:
        """Best serve replica for one request: fresh-over-stale,
        unshedded-over-shedding, warm-over-cold, then pool headroom
        and backlog — the FleetRouter scoring over RPC'd snapshots.
        A stale (timed-out) poll floors the score instead of
        excluding the replica: degraded, never stalled."""
        best = None
        best_score = None
        for rp in self._serve_replicas():
            if not rp.routable:
                continue
            snap = rp.last_snap or {}
            warm = 0
            keys = self._warm_keys(req["prompt"], rp.block_size)
            if keys:
                snap_keys = set(snap.get("warm_prefix_keys", []))
                if snap_keys.intersection(keys):
                    warm = 1
            headroom = (int(snap.get("available_blocks", 0))
                        - int(snap.get("reserved_blocks", 0)))
            # inflight = submits the snapshot predates — without the
            # reservation term one admission round dumps EVERY pending
            # request on the round-start-emptiest replica
            backlog = (int(snap.get("queue_depth", 0))
                       + int(snap.get("prefilling", 0))
                       + int(snap.get("active", 0))
                       + rp.inflight)
            score = (0 if (rp.stale or snap == {}) else 1,
                     0 if snap.get("shed_engaged") else 1,
                     warm, headroom, -backlog, rp.replica_id)
            if best_score is None or score > best_score:
                best, best_score = rp, score
        return best

    def _submit(self, rp: ReplicaProcess, req: Dict[str, Any],
                cls: str, round_idx: int, *,
                track: bool = True) -> bool:
        """Submit one request, surviving a replica death mid-submit:
        after the restart, the journal says whether the dead
        incarnation journaled the submit (⇒ the replay owns it) or
        never saw it (⇒ re-route).  Never double-submits, never
        drops."""
        rid = str(req["rid"])
        for _ in range(self.max_restarts + 2):
            try:
                rp.call("submit", {"req": req},
                        timeout=self._op_timeout("submit"))
                rp.inflight += 1
                if track:
                    self._mark_routed(rid, rp, cls)
                return True
            except RpcRemoteError as e:
                self.rejected += 1
                if track:
                    self._record_terminal(rid, "rejected")
                self.log.event("fleet", "request_rejected",
                               step=round_idx, rid=rid,
                               replica=rp.replica_id,
                               error=str(e)[:200])
                return False
            except RpcError as e:
                state = self._restart(
                    rp,
                    reason=f"submit_failed:{type(e).__name__}",
                    round_idx=round_idx)
                if state is not None and rid in state.submitted \
                        and rid not in state.terminal:
                    # the dead incarnation journaled it — the replay
                    # just re-entered it; it is routed, not lost
                    if track:
                        self._mark_routed(rid, rp, cls)
                    return True
                nxt = self._route(req)
                if nxt is None:
                    continue
                rp = nxt
        raise FleetGiveUp(f"could not place request {rid}")

    # -- QoS admission + disaggregated handoff --------------------------

    def _burning(self) -> List[str]:
        out: set = set()
        for rp in self.replicas:
            if rp.last_snap:
                out.update(rp.last_snap.get("slo_burning", []))
        return sorted(out)

    def _admit(self, pending: deque, round_idx: int) -> None:
        pf = self._prefill_replica()
        while pending:
            req = pending[0]
            cls = QoSPolicy.class_of(req.get("priority"))
            if self.qos is not None:
                open_count = len(self._class_open.get(cls, ()))
                ok, why = self.qos.admit(cls, open_count,
                                         self._burning())
                if not ok:
                    pending.popleft()
                    self.shed_admission += 1
                    self.log.event(
                        "fleet", "request_shed_admission",
                        step=round_idx, rid=str(req["rid"]),
                        priority_class=cls, reason=why)
                    continue
            if pf is not None and pf.block_size \
                    and len(req["prompt"]) >= int(pf.block_size):
                probe_rid = f"{PREFILL_RID_PREFIX}{req['rid']}"
                probe = dict(req, rid=probe_rid, max_new_tokens=1,
                             deadline_ms=None)
                pending.popleft()
                # probes are untracked plumbing — the real rid is
                # owned by the handoff until its warm/cold submit
                if self._submit(pf, probe, cls, round_idx,
                                track=False):
                    self._handoffs[probe_rid] = _Handoff(
                        req=req, probe_rid=probe_rid)
                else:
                    # probe rejected — admit the real request cold
                    self._submit_cold(req, cls, round_idx,
                                      stage="probe_rejected")
                continue
            rp = self._route(req)
            if rp is None:
                return            # nothing routable — retry next round
            pending.popleft()
            self._submit(rp, req, cls, round_idx)

    def _submit_cold(self, req: Dict[str, Any], cls: str,
                     round_idx: int, *, stage: str) -> None:
        """The torn-handoff fallback: the request admits cold on the
        best serve replica.  Degraded (no warm pages), never lost."""
        self.handoff_retries += 1
        self.log.event("fleet", "kv_handoff_retry", step=round_idx,
                       rid=str(req["rid"]), stage=stage)
        rp = self._route(req)
        if rp is None:
            rp = next(iter(self._serve_replicas()), None)
        if rp is None:
            raise FleetGiveUp("no serve replica for cold fallback")
        self._submit(rp, req, cls, round_idx)

    def _advance_handoffs(self, round_idx: int) -> None:
        """Drive every finished probe through gather → scatter →
        warm submit.  EVERY rpc failure in the chain — timeout, dead
        replica, payload mismatch — lands in :meth:`_submit_cold`."""
        ready = [h for h in self._handoffs.values()
                 if h.stage == "ready"]
        for h in ready:
            del self._handoffs[h.probe_rid]
            cls = QoSPolicy.class_of(h.req.get("priority"))
            pf = self._prefill_replica()
            if pf is None:
                self._submit_cold(h.req, cls, round_idx,
                                  stage="prefill_gone")
                continue
            try:
                reply, blobs = pf.call(
                    "gather_kv", {"prompt": h.req["prompt"]},
                    timeout=self._op_timeout("gather_kv"),
                    retries=self._op_retries("gather_kv"))
            except RpcError:
                self._submit_cold(h.req, cls, round_idx,
                                  stage="gather")
                continue
            n = int(reply.get("resident", -1))
            if n <= 0:
                self._submit_cold(h.req, cls, round_idx,
                                  stage="not_resident")
                continue
            dst = self._route(h.req)
            if dst is None:
                self._submit_cold(h.req, cls, round_idx,
                                  stage="no_dst")
                continue
            try:
                # a literal header — the auditor checks these keys
                # against PROTOCOL["scatter_kv"] field for field
                scatter = {"names": reply["names"],
                           "shapes": reply["shapes"],
                           "dtypes": reply["dtypes"],
                           "geometry": reply["geometry"],
                           "prompt": h.req["prompt"], "n": n}
                dst.call("scatter_kv", scatter, blobs,
                         timeout=self._op_timeout("scatter_kv"))
            except RpcError:
                self._submit_cold(h.req, cls, round_idx,
                                  stage="scatter")
                continue
            self.handoffs_done += 1
            self.handoff_blocks += n
            self.log.event("fleet", "kv_handoff", value=n,
                           step=round_idx, pages=n,
                           rid=str(h.req["rid"]),
                           src=pf.replica_id, dst=dst.replica_id)
            self._submit(dst, h.req, cls, round_idx)

    # -- the tick round -------------------------------------------------

    def _tick_round(self, round_idx: int) -> bool:
        """Tick every live replica once, in a seed-permuted order
        (the PR 15 schedule-stress surface: the fleet digest must not
        care).  Any tick failure escalates to restart+replay — a tick
        is not idempotent, so it never retries in place."""
        order = list(self.replicas)
        self._rng.shuffle(order)
        busy = False
        for rp in order:
            if rp.reaped:
                continue
            try:
                reply, _ = rp.call("tick",
                                   timeout=self._op_timeout("tick"))
            except RpcError as e:
                self._restart(
                    rp, reason=f"tick_failed:{type(e).__name__}",
                    round_idx=round_idx)
                busy = True       # the replay re-entered its work
                continue
            busy = busy or bool(reply.get("busy"))
            for rid, reason in reply.get("finished", []):
                if str(rid).startswith(PREFILL_RID_PREFIX):
                    h = self._handoffs.get(str(rid))
                    if h is not None and h.stage == "probe":
                        h.stage = "ready"
                    continue
                self._record_terminal(str(rid), str(reason))
        return busy

    # -- observe / autoscale --------------------------------------------

    def _observe(self, round_idx: int,
                 snaps: Dict[str, Dict[str, Any]]) -> None:
        if not snaps:
            return
        attrs = self.aggregator.observe(round_idx, snaps)
        self.log.event("fleet_tick", "fleet_tick",
                       value=attrs.get("queue_depth"),
                       step=round_idx, **attrs)
        if self.exporter is not None:
            try:
                self.exporter.publish(self._registry(snaps),
                                      tick=round_idx)
            except Exception as e:
                logger.warning("fleet exporter publish failed: %s",
                               str(e)[:160])

    def _registry(self, snaps: Dict[str, Dict[str, Any]]
                  ) -> MetricsRegistry:
        """The aggregated fleet view the BASE metrics port serves
        (each replica's own exporter lives in its subprocess on
        ``base + 1 + k``)."""
        reg = MetricsRegistry()
        reg.gauge("apex_tpu_fleet_replicas",
                  "Serve-role replica subprocesses."
                  ).set(len(self._serve_replicas()))
        reg.gauge("apex_tpu_fleet_restarts",
                  "Replica subprocess restarts (supervisor)."
                  ).set(self.restarts)
        reg.gauge("apex_tpu_fleet_rpc_timeouts",
                  "Timed-out control-plane RPCs."
                  ).set(self.rpc_timeouts)
        qd = reg.gauge("apex_tpu_replica_queue_depth",
                       "Per-replica queue depth (gauge poll).")
        tok = reg.gauge("apex_tpu_replica_tokens_generated",
                        "Per-replica generated tokens (gauge poll).")
        for rid, snap in sorted(snaps.items()):
            qd.set(int(snap.get("queue_depth", 0)), replica=rid)
            tok.set(int(snap.get("tokens_generated", 0)),
                    replica=rid)
        return reg

    def _autoscale_round(self, round_idx: int,
                         snaps: Dict[str, Dict[str, Any]]) -> None:
        if self.autoscale is None:
            return
        backlog = sum(int(s.get("queue_depth", 0))
                      + int(s.get("prefilling", 0))
                      + int(s.get("active", 0))
                      for s in snaps.values())
        action = self.autoscale.decide(
            round_idx, len(self._serve_replicas()), backlog,
            self.aggregator.trends())
        if action == "up":
            self._scale_up(round_idx, backlog)
        elif action == "down":
            self._scale_down(round_idx, backlog)

    def _scale_up(self, round_idx: int, backlog: int) -> None:
        if self.spec_factory is None:
            logger.warning("autoscale up skipped: no spec_factory")
            return
        idx = self._next_index
        self._next_index += 1
        spec = self.spec_factory(f"r{idx}", idx)
        rp = ReplicaProcess(spec, self._sock_dir,
                            max_restarts=self.max_restarts,
                            spawn_timeout_s=self.spawn_timeout_s,
                            backoff_base=self.backoff_base,
                            backoff_max=self.backoff_max,
                            rng=self._rng)
        hello = rp.spawn()
        self.replicas.append(rp)
        self._emit_spawned(rp, hello)
        self.autoscale_ups += 1
        self.log.event("fleet", "autoscale", step=round_idx,
                       action="up", reason="backlog_trend",
                       replica=rp.replica_id, backlog=backlog,
                       replicas=len(self._serve_replicas()))

    def _scale_down(self, round_idx: int, backlog: int) -> None:
        """Drain-then-reap: admit-stop the emptiest serve replica;
        the reap happens in :meth:`_maybe_reap_draining` once its
        open requests finish — zero lost, the swap_weights
        contract."""
        victims = [rp for rp in self._serve_replicas()
                   if rp.routable]
        if len(victims) <= (self.autoscale.min_replicas
                            if self.autoscale else 1):
            return
        victim = min(victims, key=lambda rp: (
            sum(1 for rid, owner in self._routed.items()
                if owner == rp.replica_id
                and rid not in self._terminal),
            rp.replica_id))
        victim.routable = False
        self.autoscale_downs += 1
        self.log.event("fleet", "autoscale", step=round_idx,
                       action="down", reason="idle_trend",
                       replica=victim.replica_id, backlog=backlog,
                       replicas=len(self._serve_replicas()) - 1)

    def _maybe_reap_draining(self, round_idx: int) -> None:
        for rp in list(self.replicas):
            if rp.routable or rp.reaped or rp.role != "serve":
                continue
            open_rids = [rid for rid, owner in self._routed.items()
                         if owner == rp.replica_id
                         and rid not in self._terminal]
            if open_rids:
                continue
            self._reap(rp, reason="scale_down", graceful=True)
            self.replicas.remove(rp)

    # -- the serve loops ------------------------------------------------

    @staticmethod
    def _req_dict(r) -> Dict[str, Any]:
        """Accept engine Requests OR plain dicts (the parent never
        imports the engine class)."""
        if isinstance(r, dict):
            d = dict(r)
        else:
            d = {k: getattr(r, k, None)
                 for k in ("rid", "prompt", "max_new_tokens",
                           "eos_token", "deadline_ms", "priority")}
        d["rid"] = str(d["rid"])
        d["prompt"] = [int(t) for t in d["prompt"]]
        d["max_new_tokens"] = int(d.get("max_new_tokens") or 1)
        return d

    def serve(self, requests: Sequence[Any], *,
              freerun: bool = False,
              max_rounds: int = 100000) -> ProcessFleetSummary:
        """Drive the fleet over ``requests`` to completion.  The
        default stepped loop supervises round by round (polls, QoS
        admission, handoffs, ticks, heartbeats, aggregation,
        autoscale); ``freerun`` submits everything up front and lets
        every subprocess decode concurrently under one ``run`` RPC —
        the scaling mode (no autoscale/QoS/parent-fault support
        there)."""
        reqs = [self._req_dict(r) for r in requests]
        self.offered += len(reqs)
        t0 = time.perf_counter()
        if freerun:
            if self.autoscale is not None or self.qos is not None \
                    or self._parent_fault is not None:
                raise ValueError(
                    "freerun supports neither autoscale, QoS, nor "
                    "parent-side fault injection — use the stepped "
                    "loop")
            rounds = self._serve_freerun(reqs)
        else:
            rounds = self._serve_stepped(reqs, max_rounds)
        wall = time.perf_counter() - t0
        return self._summarize(rounds, wall, freerun=freerun)

    def _serve_stepped(self, reqs: List[Dict[str, Any]],
                       max_rounds: int) -> int:
        pending = deque(reqs)
        rounds = 0
        while True:
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError(
                    f"fleet did not drain within {max_rounds} "
                    f"rounds: {len(pending)} pending, "
                    f"{len(self._handoffs)} handoff(s) in flight")
            self._check_processes(rounds)
            snaps = self._poll_round(rounds)
            self._admit(pending, rounds)
            self._advance_handoffs(rounds)
            busy = self._tick_round(rounds)
            self._observe(rounds, snaps)
            self._autoscale_round(rounds, snaps)
            self._maybe_reap_draining(rounds)
            open_left = any(rid not in self._terminal
                            for rid in self._routed)
            if not pending and not self._handoffs \
                    and not open_left and not busy:
                return rounds

    def _serve_freerun(self, reqs: List[Dict[str, Any]]) -> int:
        serve_rps = self._serve_replicas()
        if not serve_rps:
            raise FleetGiveUp("no serve replicas")
        for i, req in enumerate(reqs):
            rp = serve_rps[i % len(serve_rps)]
            self._submit(rp, req,
                         QoSPolicy.class_of(req.get("priority")),
                         round_idx=0)
        pending_seq: Dict[str, int] = {}
        for rp in self.replicas:
            if not rp.reaped:
                pending_seq[rp.replica_id] = rp.post(
                    "run", timeout=self._op_timeout("run"))
        for rp in list(self.replicas):
            if rp.reaped:
                continue
            for attempt in range(self.max_restarts + 1):
                try:
                    reply, _ = rp.wait(
                        pending_seq[rp.replica_id],
                        timeout=self._op_timeout("run"))
                    for rid, reason in reply.get("finished", []):
                        if not str(rid).startswith(
                                PREFILL_RID_PREFIX):
                            self._record_terminal(str(rid),
                                                  str(reason))
                    break
                except RpcError as e:
                    self._restart(
                        rp,
                        reason=f"run_failed:{type(e).__name__}",
                        round_idx=attempt)
                    pending_seq[rp.replica_id] = rp.post(
                        "run", timeout=self._op_timeout("run"))
        snaps = self._poll_round(1)
        self._observe(1, snaps)
        return 1

    # -- the verdict ----------------------------------------------------

    def fleet_rows(self) -> Dict[str, List[int]]:
        """The merged ``{rid: tokens}`` ledger: live engines' rows
        (over RPC) layered over journal-absorbed terminals.  The
        digest over these is the cross-run identity proof."""
        rows = dict(self._rows)
        for rp in self.replicas:
            if rp.reaped or not rp.alive():
                continue
            try:
                reply, _ = rp.call(
                    "summary",
                    timeout=self._op_timeout("summary"),
                    retries=self._op_retries("summary"))
            except RpcError:
                continue
            for rid, toks in reply.get("rows", {}).items():
                if not str(rid).startswith(PREFILL_RID_PREFIX):
                    rows[str(rid)] = [int(t) for t in toks]
        return rows

    def _summarize(self, rounds: int, wall: float, *,
                   freerun: bool) -> ProcessFleetSummary:
        per_replica: Dict[str, dict] = {}
        rows = dict(self._rows)
        for rp in self.replicas:
            if rp.reaped or not rp.alive():
                continue
            try:
                reply, _ = rp.call(
                    "summary",
                    timeout=self._op_timeout("summary"),
                    retries=self._op_retries("summary"))
            except RpcError:
                continue
            per_replica[rp.replica_id] = reply.get("summary", {})
            for rid, toks in reply.get("rows", {}).items():
                if not str(rid).startswith(PREFILL_RID_PREFIX):
                    rows[str(rid)] = [int(t) for t in toks]
        for rp in self.replicas:
            self._absorb_journal(rp)
            rows.update({rid: t for rid, t in self._rows.items()
                         if rid not in rows})
        by_reason: Dict[str, int] = {}
        for reason in self._terminal.values():
            by_reason[reason] = by_reason.get(reason, 0) + 1
        tokens = sum(len(t) for t in rows.values())
        done = sum(1 for rid in self._routed
                   if self._terminal.get(rid) == "finished")
        # Rejected requests are terminal-but-never-routed, so count the
        # terminal ledger directly: every offered request must end up
        # either shed at the door or with a terminal record.
        lost = (self.offered - self.shed_admission
                - len(self._terminal))
        digest = fleet_rows_digest(rows)
        summary = ProcessFleetSummary(
            replicas=len(self._serve_replicas()),
            prefill_replicas=sum(
                1 for rp in self.replicas
                if rp.role == "prefill" and not rp.reaped),
            offered=self.offered,
            submitted=len(self._routed),
            shed_admission=self.shed_admission,
            rejected=self.rejected,
            requests_done=done,
            lost_requests=lost,
            tokens_generated=tokens,
            wall_s=wall,
            tokens_per_sec=(tokens / wall if wall > 0 else 0.0),
            rounds=rounds,
            restarts=self.restarts,
            rpc_timeouts=self.rpc_timeouts,
            handoffs=self.handoffs_done,
            handoff_blocks=self.handoff_blocks,
            handoff_retries=self.handoff_retries,
            autoscale_ups=self.autoscale_ups,
            autoscale_downs=self.autoscale_downs,
            replayed_requests=sum(rp.replayed_total
                                  for rp in self.replicas),
            digest=digest,
            freerun=freerun,
            terminal_by_reason=by_reason,
            per_replica=per_replica)
        self.log.event("fleet", "fleet_done",
                       value=summary.tokens_per_sec,
                       **{k: v for k, v in summary.as_dict().items()
                          if k not in ("per_replica",
                                       "terminal_by_reason")})
        return summary
