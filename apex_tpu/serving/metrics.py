"""Per-request serving telemetry: request lifecycle, engine gauges,
and the on-demand engine snapshot.

PR 9's :class:`~apex_tpu.serving.engine.ServeSummary` reports lifetime
totals — a request that waited 800 ms in the admission queue and one
admitted instantly are indistinguishable.  This module gives the
engine the Orca/vLLM serving vocabulary (queue wait, time-to-first-
token, inter-token latency) with the same sync-free discipline as the
PR-7 tracer: every number here is host bookkeeping the engine already
holds, so the one-fetch-per-tick budget and the zero-recompile
contract are untouched.  Three pieces:

* :class:`RequestTrace` / :class:`ServeMetrics` — every request emits
  a monotonic lifecycle chain through the monitor sinks
  (``request_submitted → request_admitted → request_first_token →
  request_done``; a rejected submit emits ``request_rejected``
  instead, and a drained request ends in ``request_done`` with
  ``preempted=true``), each event stamped with host wall time and the
  engine tick index.  The terminal event carries the whole per-request
  timing breakdown (``queue_wait_ms + prefill_ms + decode_ms ==
  wall_ms`` by construction, from one clock), from which the summary
  derives queue-wait / TTFT / ITL / decode-tokens-per-sec
  distributions over a bounded window, and from which the Chrome
  export (:func:`apex_tpu.monitor.tracing.serve_lanes_from_events`)
  rebuilds one Perfetto lane per request with queued/prefill/decode
  phases.
* :class:`EngineGauges` — one ``kind="serve_tick"`` event per engine
  tick (or every K ticks, ``APEX_TPU_SERVE_TICK_EVERY``): running
  batch, active bucket shape, free/reserved blocks, queue depth,
  admissions/evictions/preemptions this window, compile count — the
  feed a fleet router load-balances on (ROADMAP item 1).
* :class:`SnapshotTrigger` — file-touch or SIGUSR1 dumps the live
  engine state as ONE ``engine_snapshot`` JSON event at the next tick
  boundary (exactly one per trigger; the same flag-only-handler
  discipline as :class:`~apex_tpu.monitor.tracing.CaptureTrigger`) —
  the wedged-serve post-mortem hook.

All clocks are injectable (fake-clock tests in
tests/test_serving_metrics.py); the read side — ``monitor_summary``'s
serving section and ``tools/trace_check.py --serve`` — lives in
:mod:`apex_tpu.monitor`.  Worked example: docs/api/serving.md.
"""
from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..analysis.flags import flag_float, flag_int, flag_str
from ..monitor.summary import _pct
from ..monitor.tracing import serve_chrome_trace
from ..utils.log_util import get_logger

logger = get_logger(__name__)

__all__ = ["RequestTrace", "ServeMetrics", "EngineGauges",
           "ReplicaMonitor", "SnapshotTrigger", "SLObjective",
           "SLOTracker"]

# distribution samples kept per series (queue-wait / ttft / itl /
# per-request decode tok/s) — same bound as the engine's per-token
# latency window, so a weeks-long serve keeps host memory flat
_SAMPLE_WINDOW = 100_000
# completed RequestTrace records kept for the Chrome lane export (the
# JSONL event log is the complete record; the in-memory list backs
# the artifact a driver writes at close)
_TRACE_WINDOW = 10_000


@dataclasses.dataclass
class RequestTrace:
    """One request's lifecycle timestamps, all on the engine clock.

    The phase boundaries are shared instants — queue wait ends exactly
    where prefill starts, prefill where decode starts — so
    ``queue_wait_s + prefill_s + decode_s == wall_s`` holds by
    construction (the 2% tolerance in the checkers covers float
    rounding of the exported milliseconds, nothing else)."""

    rid: str
    prompt_len: int
    submit_t: float
    submit_tick: int
    admit_t: Optional[float] = None
    admit_tick: Optional[int] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    done_tick: Optional[int] = None
    done_wall: Optional[float] = None   # epoch seconds (Chrome anchor)
    new_tokens: int = 0
    preempted: bool = False
    # terminal reason (ISSUE-13): finished | preempted | deadline |
    # deadline_exceeded | shed — the lifecycle chains' new terminal
    # paths all close through request_done, just with a reason
    terminal: str = "finished"

    @property
    def admitted(self) -> bool:
        return self.admit_t is not None

    @property
    def queue_wait_s(self) -> float:
        """Submit → admission start (for a never-admitted request the
        whole wall was queue wait)."""
        end = self.admit_t if self.admitted else self.done_t
        return max(0.0, (end or self.submit_t) - self.submit_t)

    @property
    def prefill_s(self) -> float:
        """Admission start → first token.  A request preempted while
        its (possibly chunked) prefill was still running has no first
        token: its whole post-admission wall counts as prefill, so
        the parts still sum to the wall."""
        if not self.admitted:
            return 0.0
        end = self.first_token_t if self.first_token_t is not None \
            else (self.done_t if self.done_t is not None
                  else self.admit_t)
        return max(0.0, end - self.admit_t)

    @property
    def decode_s(self) -> float:
        if not self.admitted or self.done_t is None \
                or self.first_token_t is None:
            return 0.0
        return max(0.0, self.done_t - self.first_token_t)

    @property
    def wall_s(self) -> float:
        return max(0.0, (self.done_t or self.submit_t) - self.submit_t)

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit → first generated token (the prefill output token);
        None for a request preempted before admission or before its
        chunked prefill produced the token."""
        if not self.admitted or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def decode_tokens_per_sec(self) -> Optional[float]:
        """Steady-state decode rate (tokens after the first over the
        decode span); None until >= 2 tokens exist."""
        if self.new_tokens < 2 or self.decode_s <= 0.0:
            return None
        return (self.new_tokens - 1) / self.decode_s

    def lane_row(self) -> Dict[str, Any]:
        """The Chrome-lane row shape
        :func:`apex_tpu.monitor.tracing.serve_lane_events` consumes."""
        return {
            "rid": self.rid,
            "end": self.done_wall,
            "queue_wait_ms": self.queue_wait_s * 1e3,
            "prefill_ms": self.prefill_s * 1e3 if self.admitted
            else None,
            "decode_ms": self.decode_s * 1e3 if self.admitted
            else None,
            "new_tokens": self.new_tokens,
            "preempted": self.preempted,
            "terminal": self.terminal,
            "tick": self.done_tick,
        }


def _percentile(xs, q: float) -> Optional[float]:
    """Empty-tolerant facade over the summary renderer's
    linear-interpolation percentile (one implementation of the math,
    same method as np.percentile's default — the engine's latency
    series and these stay comparable)."""
    s = list(xs)
    if not s:
        return None
    return float(_pct(s, q))


class EngineGauges:
    """Tick-gauge accumulator + cadence: the engine reports every tick,
    one ``serve_tick`` event leaves every ``every`` ticks (counters —
    admissions/evictions/preemptions/compiles — accumulate across the
    window; level gauges — batch, buckets, pool, queue — carry the
    window's last tick).  A trailing partial window flushes at run
    end, so the final engine state is always in the log."""

    def __init__(self, every: int = 1):
        self.every = max(1, int(every))
        self.emitted = 0
        self.used_blocks_hw = 0
        self.shared_blocks_hw = 0
        self._ticks = 0
        self._admitted = 0
        self._warm_admitted = 0
        self._finished = 0
        self._preempted = 0
        self._shed = 0
        self._deadline = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._compiles_seen = 0
        self._last: Optional[Dict[str, Any]] = None

    def on_admit(self, warm: bool = False) -> None:
        self._admitted += 1
        if warm:
            self._warm_admitted += 1

    def on_spec(self, proposed: int, accepted: int) -> None:
        """One speculative tick's draft bookkeeping: ``proposed``
        draft tokens scored, ``accepted`` kept by the greedy match —
        the window's acceptance feed (``spec_accept_rate`` on the
        rolled gauge event)."""
        self._spec_proposed += int(proposed)
        self._spec_accepted += int(accepted)

    def on_finish(self, terminal="finished", *,
                  preempted: Optional[bool] = None) -> None:
        """One terminal request this window.  ``terminal`` is the
        reason string; the pre-ISSUE-13 signature (a bool, positional
        or as the ``preempted`` keyword) still works."""
        if preempted is not None:
            terminal = "preempted" if preempted else "finished"
        elif isinstance(terminal, bool):
            terminal = "preempted" if terminal else "finished"
        if terminal == "finished":
            self._finished += 1
        elif terminal == "preempted":
            self._preempted += 1
        elif terminal == "shed":
            self._shed += 1
        else:                       # deadline / deadline_exceeded
            self._deadline += 1

    def observe(self, tick: int, **levels) -> Optional[Dict[str, Any]]:
        """Record one engine tick's level gauges; returns the event
        attrs when the cadence says this tick emits, else None."""
        self._ticks += 1
        self.used_blocks_hw = max(self.used_blocks_hw,
                                  int(levels.get("used_blocks", 0)))
        self.shared_blocks_hw = max(self.shared_blocks_hw,
                                    int(levels.get("shared_blocks",
                                                   0)))
        self._last = dict(levels, last_tick=tick)
        if self._ticks >= self.every:
            return self._roll()
        return None

    def router_snapshot(self) -> Dict[str, Any]:
        """The last observed tick's level gauges plus the high-water
        counters, WITHOUT advancing the cadence window — the cheap
        read a fleet router polls between its own dispatch rounds
        (:meth:`~apex_tpu.serving.engine.ServingEngine.
        router_snapshot` composes this with the pool's live state)."""
        snap = dict(self._last or {})
        snap["used_blocks_high_water"] = self.used_blocks_hw
        snap["shared_blocks_high_water"] = self.shared_blocks_hw
        return snap

    def flush(self) -> Optional[Dict[str, Any]]:
        """Close a trailing partial window (None when nothing is
        pending).  A window may hold counters but zero ticks: the
        run's final evictions happen in a tick that decodes nothing,
        so the flush is how they reach the log."""
        if self._ticks == 0 and not (self._admitted or self._finished
                                     or self._preempted or self._shed
                                     or self._deadline
                                     or self._spec_proposed):
            return None
        return self._roll()

    def _roll(self) -> Dict[str, Any]:
        attrs = dict(self._last or {})
        compiles = int(attrs.get("compiles", self._compiles_seen))
        attrs.update(
            ticks=self._ticks,
            admitted=self._admitted,
            warm_admitted=self._warm_admitted,
            finished=self._finished,
            preempted=self._preempted,
            new_compiles=compiles - self._compiles_seen,
            used_blocks_high_water=self.used_blocks_hw,
        )
        if self._shed:
            attrs["shed"] = self._shed
        if self._deadline:
            attrs["deadline_exceeded"] = self._deadline
        if self.shared_blocks_hw:
            attrs["shared_blocks_high_water"] = self.shared_blocks_hw
        if self._spec_proposed:
            attrs["spec_proposed"] = self._spec_proposed
            attrs["spec_accepted"] = self._spec_accepted
            attrs["spec_accept_rate"] = round(
                self._spec_accepted / self._spec_proposed, 4)
        self._compiles_seen = compiles
        self._ticks = 0
        self._admitted = self._warm_admitted = 0
        self._finished = self._preempted = 0
        self._shed = self._deadline = 0
        self._spec_proposed = self._spec_accepted = 0
        self.emitted += 1
        return attrs


class ReplicaMonitor:
    """Monitor facade stamping ``replica=<id>`` on every event.

    A fleet's replicas may share one JSONL sink or write one file
    each; either way every event a replica's engine, metrics layer, or
    supervisor emits must carry a stable replica id so the aggregation
    side (``monitor_summary`` fleet digest, ``trace_check --serve``
    over per-replica logs) can attribute chains without parsing rids.
    Wraps anything with the ``StepMonitor.event`` signature; every
    other attribute (``watchdog``, ``close``, sinks) passes through,
    so the engine's heartbeat and teardown paths see the real
    monitor.  An explicit ``replica=`` in an event's attrs wins — the
    stamp is a default, not an override."""

    def __init__(self, monitor, replica_id: str):
        self._monitor = monitor
        self.replica_id = str(replica_id)

    def event(self, kind: str, name: str, value=None, **attrs) -> None:
        attrs.setdefault("replica", self.replica_id)
        self._monitor.event(kind, name, value=value, **attrs)

    def __getattr__(self, name):
        return getattr(self._monitor, name)


# ---------------------------------------------------------------------------
# Per-priority-class SLOs with multi-window burn-rate alerting
# ---------------------------------------------------------------------------

# a p99 latency objective budgets 1% violations by definition
_P99_BUDGET = 0.01
# terminals the availability objective counts as bad: the engine
# failed the request (shed under pressure, or past its deadline).
# preempted is NOT bad — a clean drain is operator-initiated.
_UNAVAILABLE_TERMINALS = ("shed", "deadline", "deadline_exceeded")


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One priority class's declarative objectives (0 disables a
    dimension).  ``priority_class`` is ``"p<priority>"`` matching
    :class:`~apex_tpu.serving.engine.Request.priority`, or ``"*"``
    for one class-agnostic objective over all traffic (what the
    ``APEX_TPU_SLO_*`` flags build).  ``availability`` is the target
    good fraction (e.g. 0.99): a request is *bad* when its terminal
    is shed / deadline / deadline_exceeded — the non-shed/non-
    deadline fraction must stay above the target."""

    priority_class: str = "*"
    ttft_p99_ms: float = 0.0
    itl_p99_ms: float = 0.0
    availability: float = 0.0

    def matches(self, cls: str) -> bool:
        return self.priority_class in ("*", cls)

    def dimensions(self):
        """``(dimension, threshold, error budget)`` triples for the
        enabled dimensions."""
        if self.ttft_p99_ms > 0:
            yield "ttft", self.ttft_p99_ms, _P99_BUDGET
        if self.itl_p99_ms > 0:
            yield "itl", self.itl_p99_ms, _P99_BUDGET
        if self.availability > 0:
            yield ("availability", self.availability,
                   max(1e-9, 1.0 - self.availability))

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class SLOTracker:
    """Multi-window burn-rate alerting over declarative objectives.

    The SRE recipe, tick-denominated: each enabled (objective,
    dimension) pair keeps a bounded deque of ``(tick, bad)`` samples;
    :meth:`evaluate` computes the burn rate — bad fraction over the
    error budget — over a fast window (~1 min equivalent in engine
    ticks) and a slow window (~1 hr equivalent) and trips when BOTH
    exceed ``burn_threshold`` (a fast blip alone or a long-decayed
    stain alone never pages).  Episodes latch: one ``burn``
    transition when the condition first holds, one ``recovered`` when
    the fast window drops back under — the watchdog's once-per-
    episode discipline, enforced here so the alarm machinery stays a
    pass-through.  Everything is driven by the engine tick (injected,
    fake-clock tests in tests/test_serving_slo.py) and touched only
    from the engine thread — no locks.

    Feeds: :class:`ServeMetrics` records TTFT/ITL samples and
    terminal availability per priority class; the engine calls
    :meth:`evaluate` once per tick from its telemetry boundary and
    routes ``burn`` transitions through the watchdog
    (:meth:`~apex_tpu.monitor.watchdog.Watchdog.alarm`) so the
    escalation hook sees them like any other alarm."""

    def __init__(self, objectives: "List[SLObjective]", *,
                 fast_window: int = 64, slow_window: int = 1024,
                 burn_threshold: float = 2.0):
        self.objectives = [o for o in objectives
                           if any(True for _ in o.dimensions())]
        self.fast_window = max(1, int(fast_window))
        self.slow_window = max(self.fast_window, int(slow_window))
        self.burn_threshold = float(burn_threshold)
        # (objective idx, dimension) -> deque[(tick, bad)]
        self._samples: Dict[tuple, deque] = {}
        # latched episodes: key -> attrs of the burn that opened it
        self._burning: Dict[tuple, Dict[str, Any]] = {}
        self.episodes = 0
        self.recoveries = 0

    @classmethod
    def from_flags(cls) -> "Optional[SLOTracker]":
        """One class-agnostic objective from the ``APEX_TPU_SLO_*``
        flags; None when every dimension is disabled (the default —
        no tracker, no per-tick evaluation cost)."""
        obj = SLObjective(
            priority_class="*",
            ttft_p99_ms=flag_float("APEX_TPU_SLO_TTFT_P99_MS"),
            itl_p99_ms=flag_float("APEX_TPU_SLO_ITL_P99_MS"),
            availability=flag_float("APEX_TPU_SLO_AVAILABILITY"))
        if not any(True for _ in obj.dimensions()):
            return None
        return cls([obj])

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    # -- sample feeds (called by ServeMetrics) ---------------------------

    def _record(self, dimension: str, cls_name: str, bad: bool,
                tick: int) -> None:
        for i, obj in enumerate(self.objectives):
            if not obj.matches(cls_name):
                continue
            if not any(d == dimension for d, _, _ in
                       obj.dimensions()):
                continue
            dq = self._samples.setdefault((i, dimension), deque())
            dq.append((int(tick), 1 if bad else 0))

    def record_ttft(self, cls_name: str, ttft_ms: float,
                    tick: int) -> None:
        for i, obj in enumerate(self.objectives):
            if obj.matches(cls_name) and obj.ttft_p99_ms > 0:
                dq = self._samples.setdefault((i, "ttft"), deque())
                dq.append((int(tick),
                           1 if ttft_ms > obj.ttft_p99_ms else 0))

    def record_itl(self, cls_name: str, itl_ms: float,
                   tick: int) -> None:
        for i, obj in enumerate(self.objectives):
            if obj.matches(cls_name) and obj.itl_p99_ms > 0:
                dq = self._samples.setdefault((i, "itl"), deque())
                dq.append((int(tick),
                           1 if itl_ms > obj.itl_p99_ms else 0))

    def record_terminal(self, cls_name: str, terminal: str,
                        tick: int) -> None:
        bad = terminal in _UNAVAILABLE_TERMINALS
        self._record("availability", cls_name, bad, tick)

    # -- evaluation ------------------------------------------------------

    def _burn(self, dq: deque, tick: int, window: int,
              budget: float) -> "tuple":
        lo = tick - window
        n = bad = 0
        for t, b in dq:
            if t > lo:
                n += 1
                bad += b
        if n == 0:
            return 0.0, 0, 0
        return (bad / n) / budget, n, bad

    def evaluate(self, tick: int) -> "List[Dict[str, Any]]":
        """Advance to ``tick``: evict samples past the slow window,
        recompute every pair's dual-window burn, and return the
        episode TRANSITIONS (``action`` = ``burn`` | ``recovered``)
        — at most one of each per pair per episode, the once-per-
        episode contract the engine forwards to the alarm path."""
        transitions: List[Dict[str, Any]] = []
        for i, obj in enumerate(self.objectives):
            for dimension, threshold, budget in obj.dimensions():
                key = (i, dimension)
                dq = self._samples.get(key)
                if dq is None:
                    continue
                lo = tick - self.slow_window
                while dq and dq[0][0] <= lo:
                    dq.popleft()
                burn_slow, n_slow, bad_slow = self._burn(
                    dq, tick, self.slow_window, budget)
                burn_fast, n_fast, bad_fast = self._burn(
                    dq, tick, self.fast_window, budget)
                attrs = {
                    "priority_class": obj.priority_class,
                    "dimension": dimension,
                    "objective": threshold,
                    "budget": budget,
                    "burn_threshold": self.burn_threshold,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "bad_fast": bad_fast, "n_fast": n_fast,
                    "bad_slow": bad_slow, "n_slow": n_slow,
                }
                tripping = (n_fast > 0
                            and burn_fast >= self.burn_threshold
                            and burn_slow >= self.burn_threshold)
                if tripping and key not in self._burning:
                    self._burning[key] = attrs
                    self.episodes += 1
                    transitions.append(dict(attrs, action="burn"))
                elif key in self._burning and not tripping \
                        and burn_fast < self.burn_threshold:
                    del self._burning[key]
                    self.recoveries += 1
                    transitions.append(dict(attrs,
                                            action="recovered"))
        return transitions

    # -- surfaces --------------------------------------------------------

    @property
    def burning(self) -> "List[str]":
        """Active episodes as ``class/dimension`` strings (the
        /healthz payload)."""
        return sorted(
            f"{self.objectives[i].priority_class}/{dim}"
            for i, dim in self._burning)

    def objectives_attrs(self) -> Dict[str, Any]:
        """The objective-definition event payload (``kind="slo"``,
        ``name="slo_objectives"``) — the schema every ``slo_burn``
        must pair with (``trace_check --serve`` asserts it)."""
        return {
            "fast_window": self.fast_window,
            "slow_window": self.slow_window,
            "burn_threshold": self.burn_threshold,
            "objectives": [o.as_dict() for o in self.objectives],
        }

    def summary_attrs(self) -> Dict[str, Any]:
        return {
            "slo_burn_episodes": self.episodes,
            "slo_recoveries": self.recoveries,
            "slo_burning": self.burning,
        }


class ServeMetrics:
    """The engine's request-lifecycle + gauge telemetry layer.

    Owned by :class:`~apex_tpu.serving.engine.ServingEngine`; every
    hook is host-only bookkeeping (clock reads + dict/deque updates)
    and emission goes through the engine's monitor (anything with the
    ``StepMonitor.event`` signature; None records distributions but
    emits nothing — the bench path).  Timestamps use the engine's
    injectable monotonic clock, wall-anchored once at construction
    (the :class:`~apex_tpu.monitor.tracing.SpanTracer` trick) so
    exported Chrome lanes line up with device traces captured in the
    same process."""

    def __init__(self, *, monitor=None,
                 clock: Callable[[], float] = time.perf_counter,
                 wall_clock: Callable[[], float] = time.time,
                 tick_every: Optional[int] = None,
                 window: int = _SAMPLE_WINDOW,
                 trace_window: int = _TRACE_WINDOW,
                 slo: Optional[SLOTracker] = None):
        self._monitor = monitor
        self._clock = clock
        self._perf0 = clock()
        self._wall0 = wall_clock()
        self.gauges = EngineGauges(
            tick_every if tick_every is not None
            else flag_int("APEX_TPU_SERVE_TICK_EVERY"))
        # optional SLO layer: the lifecycle hooks below feed it
        # per-class samples; the engine evaluates it per tick
        self.slo = slo
        self._open: Dict[str, RequestTrace] = {}
        self.completed: deque = deque(maxlen=trace_window)
        self.rejected: Dict[str, int] = {}
        # lifetime terminal counts by reason — the exporter's
        # requests_total counter source (same on_done hook, no second
        # bookkeeping path)
        self.terminals: Dict[str, int] = {}
        self._queue_wait_ms: deque = deque(maxlen=window)
        self._ttft_ms: deque = deque(maxlen=window)
        self._itl_ms: deque = deque(maxlen=window)
        self._decode_tps: deque = deque(maxlen=window)
        # percentile cache: recomputed only when a series grew (the
        # per-tick exporter publish must not re-sort idle windows);
        # the mark is a monotone append count, not lengths — a
        # saturated bounded deque keeps its length while its contents
        # roll
        self._pct_cache: Optional[Dict[str, Optional[float]]] = None
        self._pct_appends = 0
        self._pct_mark = -1

    @staticmethod
    def priority_class(request) -> str:
        """The SLO bucket a request belongs to: ``p<priority>``."""
        return f"p{int(getattr(request, 'priority', 0) or 0)}"

    # -- emission ------------------------------------------------------------

    def _emit(self, kind: str, name: str, value=None,
              tick: Optional[int] = None, **attrs) -> None:
        if self._monitor is not None:
            self._monitor.event(kind, name, value=value, step=tick,
                                **attrs)

    def _wall_at(self, t: float) -> float:
        return self._wall0 + (t - self._perf0)

    # -- request lifecycle ---------------------------------------------------

    def on_reject(self, rid, reason: str, tick: int) -> None:
        """A submit the engine refused (before it entered the queue)."""
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        self._emit("serving", "request_rejected", tick=tick,
                   rid=str(rid), reason=reason)

    def on_submit(self, request, tick: int) -> None:
        # the engine stamps request.submit_t just before this hook
        # (respecting a pre-anchored instant — the fleet router's
        # disaggregated submissions); the lifecycle chain must share
        # that anchor or queue-wait/TTFT would silently exclude the
        # pre-engine wait
        t = getattr(request, "submit_t", None)
        if t is None:
            t = self._clock()
        self._open[str(request.rid)] = RequestTrace(
            rid=str(request.rid), prompt_len=len(request.prompt),
            submit_t=t, submit_tick=tick)
        self._emit("serving", "request_submitted", tick=tick,
                   rid=str(request.rid),
                   prompt_len=len(request.prompt))

    def on_admit(self, request, tick: int, admit_t: float,
                 prefill_s: Optional[float] = None, **attrs) -> None:
        """Admission happened: ``admit_t`` is the engine-clock instant
        queue wait ended and prefill began.  With ``prefill_s`` (the
        synchronous whole-prompt path) the first generated token
        exists at ``admit_t + prefill_s`` and both lifecycle events
        emit here; a chunked prefill passes ``prefill_s=None`` and
        reports the token later through :meth:`on_first_token` — TTFT
        is always measured to the REAL first token, however many
        ticks the prefill spans."""
        tr = self._open.get(str(request.rid))
        if tr is None:  # engine-internal admit without a submit record
            tr = RequestTrace(rid=str(request.rid),
                              prompt_len=len(request.prompt),
                              submit_t=admit_t, submit_tick=tick)
            self._open[tr.rid] = tr
        tr.admit_t = admit_t
        tr.admit_tick = tick
        qw_ms = tr.queue_wait_s * 1e3
        self._queue_wait_ms.append(qw_ms)
        self._pct_appends += 1
        self.gauges.on_admit(warm=bool(attrs.get("warm_tokens")))
        self._emit("serving", "request_admitted",
                   value=(None if prefill_s is None
                          else round(prefill_s * 1e3, 3)), tick=tick,
                   rid=tr.rid, queue_wait_ms=round(qw_ms, 3), **attrs)
        if prefill_s is not None:
            self.on_first_token(request, tick, admit_t + prefill_s)

    def on_first_token(self, request, tick: int, t: float) -> None:
        """The request's first generated token exists at engine-clock
        instant ``t`` (the end of its last prefill chunk, or of the
        synchronous prefill).  Emits ``request_first_token`` and
        records the TTFT sample."""
        tr = self._open.get(str(request.rid))
        if tr is None or tr.admit_t is None \
                or tr.first_token_t is not None:
            return
        tr.first_token_t = t
        qw_ms = tr.queue_wait_s * 1e3
        ttft_ms = tr.ttft_s * 1e3
        prefill_ms = tr.prefill_s * 1e3
        self._ttft_ms.append(ttft_ms)
        self._pct_appends += 1
        if self.slo is not None:
            self.slo.record_ttft(self.priority_class(request),
                                 ttft_ms, tick)
        self._emit("serving", "request_first_token",
                   value=round(ttft_ms, 3), tick=tick, rid=tr.rid,
                   ttft_ms=round(ttft_ms, 3),
                   queue_wait_ms=round(qw_ms, 3),
                   prefill_ms=round(prefill_ms, 3))

    def reopen(self, rid: str) -> Optional[RequestTrace]:
        """Reset an open chain's admission/first-token stamps for a
        journal-replayed incarnation (crash recovery): queue wait runs
        from the ORIGINAL submit through the crash downtime to the
        fresh admission, prefill/decode measure the incarnation that
        actually finishes — so the terminal parts still sum to the
        rid's full wall.  Returns the trace, or None when no chain is
        open (a fresh-process replay re-submits normally)."""
        tr = self._open.get(str(rid))
        if tr is None:
            return None
        tr.admit_t = None
        tr.admit_tick = None
        tr.first_token_t = None
        return tr

    def on_done(self, request, tick: int) -> None:
        """Terminal — every submitted rid ends in exactly one of
        these, whatever the reason: ``request.terminal`` names it
        (finished / preempted / deadline / deadline_exceeded / shed;
        absent falls back to the ``request.preempted`` flag)."""
        tr = self._open.pop(str(request.rid), None)
        if tr is None:
            tr = RequestTrace(rid=str(request.rid),
                              prompt_len=len(request.prompt),
                              submit_t=self._clock(), submit_tick=tick)
        t = self._clock()
        tr.done_t = t
        tr.done_tick = tick
        tr.done_wall = self._wall_at(t)
        tr.new_tokens = len(request.out_tokens)
        tr.terminal = getattr(request, "terminal", None) \
            or ("preempted" if request.preempted else "finished")
        tr.preempted = bool(request.preempted)
        # the first latency sample is the prefill; the rest are decode
        # ticks — the per-request inter-token latencies
        cls_name = self.priority_class(request)
        for itl in getattr(request, "token_latency_s", [])[1:]:
            itl_ms = itl * 1e3
            self._itl_ms.append(itl_ms)
            self._pct_appends += 1
            if self.slo is not None:
                self.slo.record_itl(cls_name, itl_ms, tick)
        tps = tr.decode_tokens_per_sec
        if tps is not None:
            self._decode_tps.append(tps)
        self.completed.append(tr)
        self.gauges.on_finish(tr.terminal)
        self.terminals[tr.terminal] = \
            self.terminals.get(tr.terminal, 0) + 1
        if self.slo is not None:
            self.slo.record_terminal(cls_name, tr.terminal, tick)
        attrs: Dict[str, Any] = {
            "rid": tr.rid, "new_tokens": tr.new_tokens,
            "preempted": tr.preempted,
            "terminal": tr.terminal,
            "wall_ms": round(tr.wall_s * 1e3, 3),
            "queue_wait_ms": round(tr.queue_wait_s * 1e3, 3),
            "prefill_ms": round(tr.prefill_s * 1e3, 3),
            "decode_ms": round(tr.decode_s * 1e3, 3),
            "submit_tick": tr.submit_tick,
        }
        if tr.admitted:
            attrs["admit_tick"] = tr.admit_tick
            if tr.ttft_s is not None:
                attrs["ttft_ms"] = round(tr.ttft_s * 1e3, 3)
        if tps is not None:
            attrs["decode_tokens_per_sec"] = round(tps, 2)
        self._emit("serving", "request_done", tick=tick, **attrs)

    # -- engine gauges -------------------------------------------------------

    def on_tick(self, tick: int, **levels) -> None:
        """Called once per engine tick with the level gauges (batch,
        buckets, pool, queue, cumulative compile count); emits on the
        registered cadence."""
        attrs = self.gauges.observe(tick, **levels)
        if attrs is not None:
            self._emit("serve_tick", "serve_tick",
                       value=attrs.get("batch"), tick=tick, **attrs)

    def flush_gauges(self, tick: int) -> None:
        """Emit a trailing partial gauge window (run teardown)."""
        attrs = self.gauges.flush()
        if attrs is not None:
            self._emit("serve_tick", "serve_tick",
                       value=attrs.get("batch"), tick=tick, **attrs)

    # -- derived distributions ----------------------------------------------

    def percentiles(self) -> Dict[str, Optional[float]]:
        """The ServeSummary fields: p50/p99 over the bounded sample
        windows (None until a series has samples)."""
        out: Dict[str, Optional[float]] = {}
        for name, xs in (("queue_wait", self._queue_wait_ms),
                         ("ttft", self._ttft_ms),
                         ("itl", self._itl_ms)):
            for q in (50, 99):
                v = _percentile(xs, q)
                out[f"{name}_p{q}_ms"] = (None if v is None
                                          else round(v, 3))
        return out

    def percentiles_cached(self) -> Dict[str, Optional[float]]:
        """:meth:`percentiles`, recomputed only when a series grew —
        the per-tick exporter publish calls this so idle decode ticks
        never re-sort the sample windows (latency quantiles cost
        amortizes per completed request, not per tick)."""
        if self._pct_cache is None \
                or self._pct_appends != self._pct_mark:
            self._pct_cache = self.percentiles()
            self._pct_mark = self._pct_appends
        return self._pct_cache

    def distributions(self) -> Dict[str, Dict[str, float]]:
        """Full p50/p90/p99 digest for every series (the bench row /
        docs surface; richer than the summary fields)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, xs in (("queue_wait_ms", self._queue_wait_ms),
                         ("ttft_ms", self._ttft_ms),
                         ("itl_ms", self._itl_ms),
                         ("decode_tokens_per_sec", self._decode_tps)):
            if not xs:
                continue
            out[name] = {
                "p50": round(_percentile(xs, 50), 3),
                "p90": round(_percentile(xs, 90), 3),
                "p99": round(_percentile(xs, 99), 3),
                "n": len(xs),
            }
        return out

    # -- Chrome export -------------------------------------------------------

    def lane_rows(self) -> List[Dict[str, Any]]:
        return [tr.lane_row() for tr in self.completed]

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON: one lane per completed request
        with queued/prefill/decode phases — loads in Perfetto next to
        a device trace (write with :func:`apex_tpu.monitor.tracing.
        write_chrome_trace`)."""
        return serve_chrome_trace(self.lane_rows())


class SnapshotTrigger:
    """On-demand live-engine-state dump, exactly once per trigger.

    Two sources, mirroring :class:`~apex_tpu.monitor.tracing.
    CaptureTrigger`: a trigger file
    (``APEX_TPU_SERVE_SNAPSHOT_FILE``) existing at a tick boundary
    (consumed), or a signal (SIGUSR1 in the ``--serve`` driver) whose
    handler only sets a flag.  The consuming :meth:`poll` emits ONE
    ``engine_snapshot`` event whose attrs are the engine's
    ``snapshot_state()`` dict — queue depth, active requests and
    their progress, pool/reservation state, compile bookkeeping — the
    post-mortem for a wedged serve (docs/api/serving.md)."""

    def __init__(self, *, trigger_file: Optional[str] = None,
                 signum: Optional[int] = None):
        self.trigger_file = trigger_file
        self.snapshots = 0
        self._pending: Optional[str] = None
        self._signum = signum
        self._prev_handler = None
        if signum is not None:
            import signal as _signal

            try:
                self._prev_handler = _signal.signal(
                    signum, lambda *_: self.request("signal"))
            except ValueError as e:
                # signal.signal only works on the main thread — a
                # trigger built elsewhere keeps its file source
                logger.warning("snapshot signal trigger unavailable: "
                               "%s", str(e)[:120])
                self._signum = None

    @classmethod
    def from_flags(cls, signum: Optional[int] = None
                   ) -> "SnapshotTrigger":
        return cls(trigger_file=flag_str("APEX_TPU_SERVE_SNAPSHOT_FILE"),
                   signum=signum)

    def request(self, reason: str) -> None:
        """Arm a snapshot; consumed at the next :meth:`poll`."""
        if self._pending is None:
            self._pending = reason

    def poll(self, tick: int, state_fn: Callable[[], Dict[str, Any]],
             monitor=None) -> bool:
        """Call once per tick boundary: consume a pending trigger and
        emit the snapshot event.  Returns True iff a snapshot was
        taken by *this* call."""
        if (self.trigger_file is not None and self._pending is None
                and os.path.exists(self.trigger_file)):
            try:
                os.unlink(self.trigger_file)
            except OSError as e:
                # the file cannot be consumed, so it would re-arm on
                # every tick — take this one snapshot, then retire
                # the file source (exactly-once must survive a
                # read-only trigger directory)
                logger.warning("snapshot trigger file unlink failed "
                               "(disabling the file trigger): %s",
                               str(e)[:120])
                self.trigger_file = None
            self._pending = "file"
        if self._pending is None:
            return False
        reason, self._pending = self._pending, None
        try:
            state = dict(state_fn())
        except Exception as e:  # telemetry must never kill the serve
            logger.warning("engine snapshot state failed: %s",
                           str(e)[:160])
            state = {"error": str(e)[:200]}
        self.snapshots += 1
        if monitor is not None:
            monitor.event("serving", "engine_snapshot", step=tick,
                          reason=reason, **state)
        return True

    def close(self) -> None:
        """Restore the signal handler."""
        if self._signum is not None and self._prev_handler is not None:
            import signal as _signal

            _signal.signal(self._signum, self._prev_handler)
            self._prev_handler = None
