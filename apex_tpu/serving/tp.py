"""Tensor-parallel serving: the decode/prefill/extend programs sharded
along a MeshPlan ``tensor`` axis (ISSUE-14 tentpole, piece 1).

The single-chip serving programs (:mod:`.model`) are already written
as per-shard math with the collective points marked: head count and
head dim come from the CACHE config, and the two row-parallel linears
(attention dense, MLP fc2) go through ``_row_linear`` whose psum is
elided when ``ServingModelConfig.tp_axis`` is None.  This module
supplies the other half — the topology as *data*:

* :func:`serving_tp_plan` — the :class:`~apex_tpu.mesh_plan.MeshPlan`
  contract: one ``tensor``-kind axis; qkv/fc1 column-split (heads and
  ffn columns local), dense/fc2 row-split, embeddings / layernorms /
  biases-after-psum replicated; the paged KV cache sharded on its
  head axis; and the collective budget — **2 psums per layer** (the
  Megatron forward: one after the attention dense, one after fc2),
  a CEILING the SPMD auditor holds the compiled artifact to.
* :class:`TPContext` — binds a plan to a mesh and builds the
  shard_map-wrapped, donation-preserving jitted step builders the
  :class:`~.engine.ServingEngine` swaps in for its single-chip ones:
  same argument signatures, same bucket ladder, same AOT warmup —
  tensor parallelism is invisible to the continuous-batching loop.

Everything per-request stays host-side and replicated (block tables,
write slots, sampled tokens); only weights and cache shard.  Greedy
argmax runs on the post-psum (replicated) logits, so every shard
samples the same token and the engine's one fetch per tick is
unchanged.  The audited entry (``gpt_decode_step_tp`` in
:mod:`apex_tpu.testing.entry_points`) carries this plan, so
APX701/703/705 guard the serving topology exactly as they guard
training, and tests pin the TP engine's greedy output token-identical
to the single-chip engine.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Sequence

from ..mesh_plan import MeshPlan
from .kv_cache import KVCacheConfig, init_cache
from .model import (GPTServingWeights, ServingModelConfig,
                    gpt_decode_step, gpt_extend_step, gpt_prefill_step)

__all__ = ["SERVING_TP_AXIS", "TPContext", "serving_tp_plan",
           "serving_weight_specs"]

# the canonical serving tensor-axis name (MeshPlan kind "tensor")
SERVING_TP_AXIS = "tensor"


def serving_weight_specs(axis: str = SERVING_TP_AXIS, *,
                         weight_quantized: bool = False):
    """Path-pattern → :data:`~apex_tpu.mesh_plan.Spec` for
    :class:`~.model.GPTServingWeights` leaves, as the SPMD auditor
    names them under an ``in0`` prefix (``in0.layers[0].qkv_k``).

    Column-parallel kernels shard their OUTPUT columns (qkv by head —
    the ``(h, 3d)`` column layout groups a head's 3d columns
    contiguously, so an even head split is an even column split; fc1
    by ffn column) along with their biases; row-parallel kernels
    (dense, fc2) shard their INPUT rows and keep the bias replicated
    (added once, after the psum).  Embeddings and every layer norm
    stay replicated — the residual stream is global hidden.

    ``weight_quantized`` (Q8 int8 weights,
    :class:`~apex_tpu.ops.quant_matmul.QuantGPTServingWeights`) adds
    the per-output-channel scale rows: a column-split kernel's scales
    split with its columns (``qkv_s``/``fc1_s``), while a row-split
    kernel's scales index GLOBAL output channels — applied to the
    pre-psum partial, which covers every channel on every shard — so
    ``dense_s``/``fc2_s`` stay replicated like the post-psum biases.
    The patterns are gated so a bf16 plan never declares a spec that
    matches no tensor (APX703)."""
    specs = {
        r"\.qkv_k$": (None, axis),
        r"\.qkv_b$": (axis,),
        r"\.dense_k$": (axis, None),
        r"\.fc1_k$": (None, axis),
        r"\.fc1_b$": (axis,),
        r"\.fc2_k$": (axis, None),
    }
    if weight_quantized:
        specs[r"\.qkv_s$"] = (axis,)
        specs[r"\.fc1_s$"] = (axis,)
    return specs


def serving_tp_plan(tp: int, num_layers: int, *,
                    axis: str = SERVING_TP_AXIS,
                    quantized: bool = False,
                    weight_quantized: bool = False) -> MeshPlan:
    """The TP serving topology contract for the audited decode entry:
    weight specs under ``in0``, the paged cache's head axis (storage
    axis 2 of ``(L, nb, hk, bs, dk)``) under ``in1`` and on the
    returned-cache outputs (``out0``/``out1``; int8 caches add the
    scale leaves), and the 2-psums-per-layer ceiling.  The runtime
    (:class:`TPContext`) derives its shard_map in/out specs and jit
    in_shardings from THIS object, so plan drift is an APX703
    finding, not a silent reshard."""
    specs = {}
    for pat, spec in serving_weight_specs(
            axis, weight_quantized=weight_quantized).items():
        specs[r"^in0.*" + pat] = spec
    cache_spec = (None, None, axis)
    if quantized:
        specs[r"^in1\.(k|v)_scale$"] = cache_spec
        specs[r"^in1\.(k|v)$"] = cache_spec
        # flat output order of (PagedKVCache, tokens): k, v, k_scale,
        # v_scale, next_tokens
        specs[r"^out[0-3]$"] = cache_spec
        specs[r"^out4$"] = ()
    else:
        specs[r"^in1\.(k|v)$"] = cache_spec
        specs[r"^out[01]$"] = cache_spec
        specs[r"^out2$"] = ()
    return MeshPlan.build(
        axes=((axis, int(tp), "tensor"),),
        tensor_specs=specs,
        collective_budget={"psum": 2 * int(num_layers)})


def _keystr(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


class TPContext:
    """One tensor-parallel serving topology, bound to real devices.

    Validates the geometry (heads, ffn columns, packed head pairs, and
    int8 scale rows must all divide by ``tp``), builds the mesh from
    ``devices`` (default: the first ``tp`` of ``jax.devices()`` — a
    fleet places each replica's context on its own device slice), and
    exposes exactly what the engine needs:

    * :meth:`shard_weights` / :meth:`init_cache` — commit the global
      arrays to their plan shardings once, so every step call runs
      reshard-free;
    * :meth:`jit_decode` / :meth:`jit_prefill` / :meth:`jit_extend` —
      drop-in replacements for the engine's single-chip jit builders:
      same signatures, cache donated, shard_map inside with in/out
      specs derived from the plan.

    ``model_cfg`` is the context's tp-axis-carrying config — the
    engine serves with it so the step functions' psums are armed."""

    def __init__(self, model_cfg: ServingModelConfig,
                 cache_cfg: KVCacheConfig, tp: int, *,
                 axis: str = SERVING_TP_AXIS,
                 devices: Optional[Sequence[Any]] = None,
                 weight_quantized: bool = False):
        if tp < 2:
            raise ValueError(f"tp {tp} must be >= 2 (tp=1 is the "
                             f"single-chip engine, no context needed)")
        if model_cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {model_cfg.num_heads} not divisible by "
                f"tp {tp}")
        if (4 * model_cfg.hidden_size) % tp:
            raise ValueError(
                f"ffn width {4 * model_cfg.hidden_size} not divisible "
                f"by tp {tp}")
        if cache_cfg.num_heads != model_cfg.num_heads \
                or cache_cfg.head_dim != model_cfg.head_dim:
            raise ValueError(
                "cache_cfg head geometry "
                f"({cache_cfg.num_heads}x{cache_cfg.head_dim}) does "
                f"not match the model "
                f"({model_cfg.num_heads}x{model_cfg.head_dim})")
        local = dataclasses.replace(
            cache_cfg, num_heads=cache_cfg.num_heads // tp)
        if local.packed != cache_cfg.packed:
            raise ValueError(
                f"tp {tp} breaks the d=64 head-pair packing: the "
                f"global layout is packed={cache_cfg.packed} but a "
                f"{local.num_heads}-head shard packs={local.packed} — "
                f"choose tp so heads/tp stays even (or disable "
                f"APEX_TPU_FLASH_PACK_D64)")
        if cache_cfg.kv_shape[2] % tp:
            raise ValueError(
                f"cache head axis {cache_cfg.kv_shape[2]} not "
                f"divisible by tp {tp}")
        self.tp = int(tp)
        self.axis = axis
        self.cache_cfg = cache_cfg            # GLOBAL geometry
        self.local_cache_cfg = local          # per-shard geometry
        self.weight_quantized = bool(weight_quantized)
        self.model_cfg = dataclasses.replace(model_cfg, tp_axis=axis)
        self.plan = serving_tp_plan(tp, model_cfg.num_layers,
                                    axis=axis,
                                    quantized=cache_cfg.quantized,
                                    weight_quantized=weight_quantized)
        self.mesh = self.plan.make_mesh(devices)

    def rebind(self, *, weight_quantized: bool) -> "TPContext":
        """The same topology re-planned for the other weight format —
        the engine's requantization swap calls this so the bf16→int8
        rollout reuses the context's devices and geometry while the
        plan gains (or drops) the int8 scale-row specs."""
        if bool(weight_quantized) == self.weight_quantized:
            return self
        return TPContext(
            self.model_cfg, self.cache_cfg, self.tp, axis=self.axis,
            devices=list(self.mesh.devices.flat),
            weight_quantized=weight_quantized)

    # --- spec trees -----------------------------------------------------

    def _replicated(self):
        from jax.sharding import PartitionSpec as P

        return P()

    def _spec_tree(self, tree, prefix: str):
        """PartitionSpec pytree for ``tree`` from the plan's declared
        specs under ``prefix`` — the ONE derivation both shard_map
        in/out_specs and jit in/out_shardings use."""
        import jax

        return jax.tree_util.tree_map_with_path(
            lambda path, _: self.plan.partition_spec(
                prefix + _keystr(path)), tree)

    def weight_specs(self, weights: GPTServingWeights):
        return self._spec_tree(weights, "in0")

    def cache_specs(self, cache=None):
        """PartitionSpec pytree for the paged cache, derived from the
        plan's ``in1`` patterns — the SAME object the auditor checks,
        so a plan change cannot leave the runtime sharding with a
        stale literal (the drift the design promises is impossible)."""
        if cache is None:
            cache = init_cache(self.cache_cfg)
        return self._spec_tree(cache, "in1")

    def _named(self, spec_tree):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            spec_tree, is_leaf=lambda s: isinstance(s, P))

    # --- committed placement -------------------------------------------

    def shard_weights(self, weights: GPTServingWeights
                      ) -> GPTServingWeights:
        """Commit the (global) weight arrays to their plan shardings —
        done once at engine construction and once per weight swap, so
        steps never pay a per-call reshard."""
        import jax

        return jax.device_put(weights,
                              self._named(self.weight_specs(weights)))

    def init_cache(self):
        """A zeroed paged cache committed to the plan's head-axis
        sharding (each shard holds its heads' pages for every block)."""
        import jax

        cache = init_cache(self.cache_cfg)
        return jax.device_put(cache,
                              self._named(self.cache_specs(cache)))

    # --- jitted step builders (engine drop-ins) -------------------------

    def _wrap(self, body, weights, n_data: int, cache_out_index=0):
        """shard_map-wrapped jit: ``body(weights, cache, *data)`` with
        weights/cache sharded per plan, the ``n_data`` trailing args
        replicated, the cache output sharded, everything else
        replicated (post-psum values are shard-invariant), and the
        cache donated."""
        import jax

        from .._compat import shard_map

        rep = self._replicated()
        w_specs = self.weight_specs(weights)
        c_specs = self.cache_specs()
        in_specs = (w_specs, c_specs) + (rep,) * n_data
        out_specs = (c_specs, rep)
        in_sh = (self._named(w_specs), self._named(c_specs)) \
            + (self._named(rep),) * n_data
        out_sh = (self._named(c_specs), self._named(rep))
        mesh = self.mesh

        @functools.partial(jax.jit, donate_argnums=(1,),
                           in_shardings=in_sh, out_shardings=out_sh)
        def step(weights, cache, *data):
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(weights, cache, *data)

        return step

    def jit_decode(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, positions, block_tables,
                 seq_lens, write_blocks, write_offsets):
            return gpt_decode_step(weights, cfg, ccfg, cache, tokens,
                                   positions, block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return self._wrap(body, weights, 6)

    def jit_prefill(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, length, blocks):
            return gpt_prefill_step(weights, cfg, ccfg, cache, tokens,
                                    length, blocks)

        return self._wrap(body, weights, 3)

    def jit_extend(self, weights: GPTServingWeights):
        cfg, ccfg = self.model_cfg, self.local_cache_cfg

        def body(weights, cache, tokens, block_tables, seq_lens,
                 write_blocks, write_offsets):
            return gpt_extend_step(weights, cfg, ccfg, cache, tokens,
                                   block_tables, seq_lens,
                                   write_blocks, write_offsets)

        return self._wrap(body, weights, 5)

    def describe(self) -> str:
        devs = ",".join(str(getattr(d, "id", d))
                        for d in self.mesh.devices.flat)
        return (f"tp={self.tp} axis={self.axis!r} devices=[{devs}] "
                f"psum_budget={self.plan.budget().get('psum')}")
