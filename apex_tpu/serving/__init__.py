"""apex_tpu.serving — flash-decode inference stack.

The serving counterpart of the training pipeline (ROADMAP item 1):

* :mod:`.kv_cache` — block-paged KV cache: device layout
  (:class:`PagedKVCache`), host block pool
  (:class:`KVCacheManager`), bf16/int8 storage.
* :mod:`.model` — pure-function GPT prefill + paged decode over the
  extracted :class:`GPTServingWeights`.
* :mod:`.engine` — continuous batching: bucket-laddered jitted steps,
  reservation admission, SIGTERM clean drain, tokens/s + p50/p99
  metrics (:class:`ServingEngine`), plus the decode fast path
  (ISSUE-12): copy-on-write prompt-prefix sharing, speculative
  decoding (draft-propose / multi-token verify, greedy-match
  acceptance — token-identical to plain greedy), and chunked
  prefill interleaved with decode ticks.
* :mod:`.metrics` — per-request lifecycle telemetry (queue wait /
  TTFT / ITL distributions, Perfetto request lanes), per-tick engine
  gauges (``serve_tick``), the on-demand engine snapshot
  (:class:`ServeMetrics`, :class:`EngineGauges`,
  :class:`SnapshotTrigger`), and per-priority-class SLOs with
  multi-window burn-rate alerting (ISSUE-17: :class:`SLObjective`,
  :class:`SLOTracker` — ``slo_burn`` episodes through the watchdog
  alarm machinery, surfaced in ``/healthz`` and ``SERVE_DONE``).
* :mod:`.resilience` — serving fault-tolerance (ISSUE-13): request
  deadlines + hysteresis load shedding (:class:`ShedPolicy`), the
  crash-safe :class:`RequestJournal` with supervised
  restart-and-replay (:func:`run_serving`, the PR-3 bounded-backoff
  semantics around one engine), and degraded modes
  (:class:`SpeculationGovernor` auto-disabling a mismatching draft,
  watchdog stall → snapshot-then-drain).

* :mod:`.control_plane` — process-isolated fleet (ISSUE-18): each
  replica is a supervised subprocess pinned to its device, speaking
  a length-prefixed JSON+binary protocol over local sockets for
  submits, gauge polls and KV handoff; heartbeat liveness (missed
  polls ⇒ SIGKILL + bounded-backoff restart with journal replay,
  fleet digest token-identical to an uninterrupted run), autoscaling
  from queue-depth trends and per-class QoS admission
  (:class:`ProcessFleet`, :class:`ReplicaProcess`,
  :class:`AutoscalePolicy`, :class:`QoSPolicy`).

Entry point: ``python -m apex_tpu.testing.standalone_gpt --serve``;
docs/api/serving.md walks the architecture.
"""
from .control_plane import (AutoscalePolicy, EngineSpec, FleetGiveUp,
                            ProcessFleet, ProcessFleetSummary,
                            QoSClass, QoSPolicy, ReplicaDead,
                            ReplicaProcess, RpcError, RpcRemoteError,
                            RpcTimeout, fleet_rows_digest, recv_frame,
                            send_frame)
from .engine import (BucketLadder, Request, ServeSummary,
                     ServingEngine, default_cache_config)
from .fleet import FleetRouter, FleetSummary, Replica, transfer_prefix
from .kv_cache import (DUMP_BLOCK, CachePoolExhausted, KVCacheConfig,
                       KVCacheManager, PagedKVCache, PrefixMatch,
                       init_cache, prefix_chain_keys,
                       quantize_kv_rows, write_prefill_kv,
                       write_token_kv)
from .metrics import (EngineGauges, ReplicaMonitor, RequestTrace,
                      ServeMetrics, SLObjective, SLOTracker,
                      SnapshotTrigger)
from .ep import (SERVING_EP_AXIS, EPContext, expand_moe_weights,
                 serving_ep_plan)
from .model import (GPTServingWeights, LayerWeights, MoELayerWeights,
                    QuantGPTServingWeights, QuantLayerWeights,
                    ServingModelConfig, copy_cache_block,
                    extract_serving_weights, gather_cache_blocks,
                    gpt_decode_step, gpt_extend_step,
                    gpt_prefill_step, gpt_sequence_logits,
                    quantize_weights, scatter_cache_blocks)
from .resilience import (RequestJournal, ServeRunResult, ShedPolicy,
                         SpeculationGovernor, recover_engine,
                         run_serving)
from .tp import SERVING_TP_AXIS, TPContext, serving_tp_plan

__all__ = [
    "AutoscalePolicy", "EngineSpec", "FleetGiveUp", "ProcessFleet",
    "ProcessFleetSummary", "QoSClass", "QoSPolicy", "ReplicaDead",
    "ReplicaProcess", "RpcError", "RpcRemoteError", "RpcTimeout",
    "fleet_rows_digest", "recv_frame", "send_frame",
    "BucketLadder", "Request", "ServeSummary", "ServingEngine",
    "default_cache_config",
    "FleetRouter", "FleetSummary", "Replica", "transfer_prefix",
    "DUMP_BLOCK", "CachePoolExhausted", "KVCacheConfig",
    "KVCacheManager", "PagedKVCache", "PrefixMatch", "init_cache",
    "prefix_chain_keys", "quantize_kv_rows", "write_prefill_kv",
    "write_token_kv",
    "GPTServingWeights", "LayerWeights", "MoELayerWeights",
    "QuantGPTServingWeights", "QuantLayerWeights",
    "ServingModelConfig",
    "copy_cache_block", "extract_serving_weights",
    "gather_cache_blocks", "gpt_decode_step", "gpt_extend_step",
    "gpt_prefill_step", "gpt_sequence_logits", "quantize_weights",
    "scatter_cache_blocks",
    "EngineGauges", "ReplicaMonitor", "RequestTrace", "ServeMetrics",
    "SLObjective", "SLOTracker", "SnapshotTrigger",
    "RequestJournal", "ServeRunResult", "ShedPolicy",
    "SpeculationGovernor", "recover_engine", "run_serving",
    "SERVING_TP_AXIS", "TPContext", "serving_tp_plan",
    "SERVING_EP_AXIS", "EPContext", "expand_moe_weights",
    "serving_ep_plan",
]
