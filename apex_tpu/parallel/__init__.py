"""apex_tpu.parallel — data parallelism (TPU-native apex.parallel).

Gradient sync with apex-DDP knob parity, SyncBatchNorm with psum'd
Welford statistics, LARC, multi-host bootstrap.  See SURVEY.md §2.3.
"""
from .distributed import (DistributedDataParallel, allreduce_params,
                          sync_gradients)
from .LARC import LARC, larc
from .multiproc import initialize_distributed
from .sync_batchnorm import SyncBatchNorm, convert_syncbn_model

__all__ = [
    "DistributedDataParallel", "sync_gradients", "allreduce_params",
    "SyncBatchNorm", "convert_syncbn_model", "LARC", "larc",
    "initialize_distributed",
]
