"""SyncBatchNorm — cross-device batch normalization over the data axis.

TPU-native equivalent of the reference's two implementations
(ref: apex/parallel/sync_batchnorm.py:9-134 python fallback;
apex/parallel/optimized_sync_batchnorm.py:85 +
optimized_sync_batchnorm_kernel.py:10-119 CUDA Welford path, kernels
csrc/welford.cu).  Statistics are merged across devices with a single
``psum`` of (count, sum, sum-of-squares) — algebraically identical to
the reference's Welford-merge (``welford_parallel``) but in XLA's
preferred reduction form; the backward's (sum_dy, sum_dy_xmu)
all-reduce (ref: optimized_sync_batchnorm_kernel.py:94-111) falls out
of autodiff transposing the psum.

Channels-last is the native TPU layout (the reference's opt-in
``channel_last=True``); ``fuse_relu`` matches the kernel's fused
activation epilogue.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .. import parallel_state


def _maybe_psum(x, axis_name):
    """psum when the axis is bound; local value otherwise (module init and
    single-device evaluation run outside shard_map — the reference's
    SyncBN likewise degrades to local BN without torch.distributed)."""
    try:
        return jax.lax.psum(x, axis_name)
    except NameError:
        return x


class SyncBatchNorm(nn.Module):
    """Drop-in ``BatchNorm`` whose batch statistics span the data axis.

    Matches ``apex.parallel.SyncBatchNorm(num_features, eps, momentum,
    affine, track_running_stats, process_group, channel_last,
    fuse_relu)``; ``axis_name=None`` degrades to local batch norm (the
    reference outside ``torch.distributed`` init).  Input layout is
    channels-last (..., C).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = parallel_state.DATA_AXIS
    fuse_relu: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        if x.shape[-1] != self.num_features:
            raise ValueError(
                f"expected trailing channel dim {self.num_features}, got "
                f"{x.shape}")
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((self.num_features,),
                                                  jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((self.num_features,),
                                                jnp.float32))
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            x32 = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            count = jnp.float32(1.0)
            for a in axes:
                count = count * x.shape[a]
            s1 = jnp.sum(x32, axes)
            s2 = jnp.sum(x32 * x32, axes)
            if self.axis_name is not None:
                # Chan merge of per-device Welford stats == psum of raw
                # moments (ref: welford_parallel, csrc/welford.cu:597).
                count = _maybe_psum(count, self.axis_name)
                s1 = _maybe_psum(s1, self.axis_name)
                s2 = _maybe_psum(s2, self.axis_name)
            mean = s1 / count
            var = s2 / count - mean * mean  # biased, as in the forward
            if self.track_running_stats and not self.is_initializing():
                # unbiased var for the running estimate
                # (ref: optimized_sync_batchnorm_kernel.py:53-56).
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * unbiased
        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            weight = self.param("weight", nn.initializers.ones,
                                (self.num_features,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros,
                              (self.num_features,), self.param_dtype)
            y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
        if self.fuse_relu:
            y = jnp.maximum(y, 0)
        return y.astype(x.dtype)


def convert_syncbn_model(norm_factory=None, axis_name=parallel_state.DATA_AXIS):
    """Return a norm-layer factory producing :class:`SyncBatchNorm`.

    The reference walks a live module tree replacing ``BatchNorm*``
    instances (ref: apex/parallel/__init__.py:42-95); flax modules are
    declarative, so conversion happens at model construction: models in
    :mod:`apex_tpu.models` accept a ``norm_factory`` and this helper
    supplies the synchronized one.
    """
    del norm_factory

    def factory(num_features, **kw):
        kw.setdefault("axis_name", axis_name)
        return SyncBatchNorm(num_features=num_features, **kw)

    return factory
