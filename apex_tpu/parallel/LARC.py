"""LARC — layerwise adaptive rate control/clipping.

Parity with the reference's ``LARC`` optimizer wrapper
(ref: apex/parallel/LARC.py:5-107): per-parameter adaptive LR
``trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps)``, either clipped
against the base LR (``clip=True``) or used as a scale (``clip=False``),
with weight decay folded into the gradient (ref: LARC.py:94-105).

Expressed as an optax ``GradientTransformation`` to chain before the
wrapped optimizer (the reference wraps ``optimizer.step``)::

    tx = optax.chain(larc(learning_rate=0.1, clip=True), fused_sgd(0.1, ...))
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def larc(learning_rate=None,
         trust_coefficient: float = 0.02,
         clip: bool = True,
         eps: float = 1e-8,
         weight_decay: float = 0.0) -> optax.GradientTransformation:
    if clip and learning_rate is None:
        raise ValueError("clip mode needs the base learning_rate to clamp "
                         "against (ref: apex/parallel/LARC.py:99-101)")

    def init(params):
        del params
        return optax.ScaleState()

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params in update()")

        count = getattr(state, "count", None)
        lr = learning_rate(count) if callable(learning_rate) \
            else learning_rate

        def leaf(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            g_norm = jnp.sqrt(jnp.sum(g32 * g32))
            adaptive_lr = trust_coefficient * p_norm / (
                g_norm + weight_decay * p_norm + eps)
            if clip:
                # ``min(adaptive_lr/lr, 1)`` (ref: LARC.py:99-101).
                adaptive_lr = jnp.minimum(adaptive_lr / lr, 1.0)
            g32 = g32 + weight_decay * p32
            g32 = g32 * adaptive_lr
            # Zero-norm params/grads keep the raw gradient
            # (ref: LARC.py:92 ``if param_norm != 0 and grad_norm != 0``).
            keep = (p_norm != 0) & (g_norm != 0)
            return jnp.where(keep, g32, g.astype(jnp.float32)).astype(g.dtype)

        return jax.tree_util.tree_map(leaf, grads, params), state

    return optax.GradientTransformation(init, update)


LARC = larc
