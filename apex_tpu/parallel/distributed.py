"""Data-parallel gradient synchronization with DDP knob parity.

TPU-native replacement for ``apex.parallel.DistributedDataParallel``
(ref: apex/parallel/distributed.py:129-640).  The reference's machinery —
adaptive per-dtype bucketing, per-bucket CUDA streams, rank-0 bucket
structure broadcast, flatten/allreduce/unflatten — exists to overlap
NCCL with backward; under XLA the compiler owns collective scheduling
and latency-hides the ``psum`` against remaining backward work, so the
machinery disappears.  What remains (and is implemented here) are the
*semantic* knobs:

- ``gradient_average`` — divide by world size (ref :245).
- ``gradient_predivide_factor`` — split the division between before and
  after the allreduce to trade overflow vs underflow risk (ref :251,
  :426-476: ``grads /= f`` pre-allreduce, ``*= f/world`` post).
- ``allreduce_always_fp32`` — cast bf16/fp16 grads to fp32 for the
  reduction, back after (ref :248, :449-455).
- ``delay_allreduce`` / ``no_sync`` — skip the sync (gradient
  accumulation), then reduce once via :func:`allreduce_params`
  (ref :214, Reducer :89-127).

These functions run inside ``shard_map`` over the mesh's data axis (or
any axis name); under plain pjit/GSPMD sharding, gradient psums are
emitted automatically and only this module's knobs are needed when the
defaults are wrong.

Replication subtlety: modern ``shard_map`` tracks varying-ness, and
``jax.grad`` of a loss w.r.t. an *unvarying* (replicated, in_specs=P())
parameter tree already returns the cross-device SUM of local gradients —
the DDP allreduce falls out of autodiff.  :class:`DistributedDataParallel`
therefore casts params to *varying* before differentiation so the knobs
(predivide, fp32 reduction, delayed sync) stay in control of the one
collective; if you differentiate replicated params yourself, your
gradients are pre-summed and only need ``tree / world_size`` — see
:func:`average_presummed`.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Optional

import jax
from .._compat import axis_size, pcast
import jax.numpy as jnp

from .. import parallel_state


def sync_gradients(grads: Any,
                   axis_name: str = parallel_state.DATA_AXIS,
                   *,
                   gradient_average: bool = True,
                   gradient_predivide_factor: float = 1.0,
                   allreduce_always_fp32: bool = False) -> Any:
    """All-reduce a gradient pytree over ``axis_name``.

    Equivalent of one flat-bucket allreduce pass
    (ref: apex/parallel/distributed.py:426-476 ``allreduce_bucket``),
    with identical scaling semantics: grads are divided by
    ``predivide_factor`` before the reduction and by
    ``world_size / predivide_factor`` after when averaging.
    """
    world = axis_size(axis_name)

    def _one(g):
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = gradient_predivide_factor / world
            if post != 1.0:
                g = g * post
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(orig_dtype) if allreduce_always_fp32 else g

    return jax.tree_util.tree_map(_one, grads)


def average_presummed(grads: Any,
                      axis_name: str = parallel_state.DATA_AXIS) -> Any:
    """Turn autodiff's pre-summed gradients (grad w.r.t. replicated params
    inside shard_map) into the data-parallel average."""
    world = axis_size(axis_name)
    return jax.tree_util.tree_map(lambda g: g / world, grads)


def make_varying(tree: Any, axis_name: str = parallel_state.DATA_AXIS) -> Any:
    """Mark a replicated pytree as device-varying so gradients w.r.t. it
    stay local (opting out of shard_map's automatic cotangent psum)."""
    def _one(x):
        try:
            return pcast(x, axis_name, to="varying")
        except ValueError:
            return x  # already varying over this axis
    return jax.tree_util.tree_map(_one, tree)


# ``Reducer`` parity: manual-trigger reduction of a param/grad tree
# (ref: apex/parallel/distributed.py:89-127).
def allreduce_params(params: Any,
                     axis_name: str = parallel_state.DATA_AXIS,
                     average: bool = True) -> Any:
    def _one(p):
        p = jax.lax.psum(p, axis_name)
        return p / axis_size(axis_name) if average else p
    return jax.tree_util.tree_map(_one, params)


@dataclasses.dataclass
class DistributedDataParallel:
    """Callable DDP wrapper around a ``grad_fn(params, batch) -> grads``.

    Functional analogue of wrapping a module in apex DDP
    (ref: apex/parallel/distributed.py:129): ``grad_fn(params, *args)``
    must differentiate w.r.t. its first argument; calling the wrapper
    inside ``shard_map`` returns synchronized gradients; with
    ``delay_allreduce=True`` (or inside :meth:`no_sync`) raw local
    gradients are returned for accumulation and the caller reduces once
    with :func:`allreduce_params`.

    Unsupported reference knobs that are meaningless under XLA are
    accepted and ignored for API compatibility: ``message_size``
    (bucketing granularity), ``num_allreduce_streams``,
    ``retain_allreduce_buffers``, ``allreduce_trigger_params``.
    """

    grad_fn: Any
    axis_name: str = parallel_state.DATA_AXIS
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    allreduce_always_fp32: bool = False
    delay_allreduce: bool = False
    # Ignored-for-parity (bucketing/stream knobs, ref :149-213):
    message_size: int = 10_000_000
    num_allreduce_streams: int = 1
    retain_allreduce_buffers: bool = False

    def __call__(self, params, *args, **kwargs):
        # Differentiate w.r.t. a *varying* view of the params so autodiff
        # does not pre-psum the cotangent (see module docstring); the one
        # collective below then owns the knob semantics.
        grads = self.grad_fn(make_varying(params, self.axis_name),
                             *args, **kwargs)
        if self.delay_allreduce:
            return grads
        return sync_gradients(
            grads, self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32)

    @contextlib.contextmanager
    def no_sync(self):
        """Gradient-accumulation window: yields a NO-SYNC view of this
        wrapper (``with ddp.no_sync() as ddp_acc: ddp_acc(...)``) —
        microbatch calls on the view return raw local grads; reduce once
        afterwards with :func:`allreduce_params`.  Unlike the reference
        (and this wrapper's earlier revision) no shared state is
        mutated, so the wrapper can be traced/reused concurrently."""
        yield dataclasses.replace(self, delay_allreduce=True)
