"""Multi-host launcher parity.

The reference ships a one-process-per-GPU launcher
(ref: apex/parallel/multiproc.py:1-35, spawning WORLD_SIZE python
processes with RANK env vars).  JAX is single-controller per host: on
TPU pods each host runs ONE process and ``jax.distributed.initialize``
wires the cluster from the TPU metadata (or explicit coordinator
address).  This module provides the equivalent bootstrap helper.
"""
from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX.

    With no arguments on Cloud TPU, topology is discovered from the
    environment.  Env-var fallbacks mirror the reference's contract
    (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK,
    ref: apex/transformer/testing/commons.py:105-113).
    """
    if coordinator_address is None and os.environ.get("MASTER_ADDR"):  # apex-lint: disable=APX301 -- torchrun launcher contract vars (MASTER_ADDR et al.), not apex flags
        addr = os.environ["MASTER_ADDR"]  # apex-lint: disable=APX301 -- torchrun launcher contract var
        port = os.environ.get("MASTER_PORT", "29500")  # apex-lint: disable=APX301 -- torchrun launcher contract var
        coordinator_address = f"{addr}:{port}"
        num_processes = num_processes or int(
            os.environ.get("WORLD_SIZE", "1"))  # apex-lint: disable=APX301 -- torchrun launcher contract var
        process_id = process_id if process_id is not None else int(
            os.environ.get("RANK", "0"))  # apex-lint: disable=APX301 -- torchrun launcher contract var
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
