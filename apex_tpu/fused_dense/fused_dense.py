"""FusedDense / FusedDenseGeluDense.

Parity with the reference (ref: apex/fused_dense/fused_dense.py:1-86 over
fused_dense_cuda — cuBLASLt bias/gelu epilogues,
csrc/fused_dense.cpp:187-190).  XLA performs the same epilogue fusion for
``dot + bias + gelu`` chains, so these modules are the API surface; the
GELU is the exact (erf) form the reference's cuBLASLt epilogue uses.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _dense(x, kernel, bias):
    y = jax.lax.dot_general(x, kernel, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def fused_dense_function(x, weight, bias=None):
    """Functional linear+bias (ref: fused_dense_function,
    apex/fused_dense/fused_dense.py:70-76).  ``weight`` follows the
    (in_features, out_features) layout."""
    return _dense(x, weight, bias)


class FusedDense(nn.Module):
    """Linear + bias (ref: apex/fused_dense/fused_dense.py FusedDense)."""

    features: int
    use_bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype) \
            if self.use_bias else None
        return _dense(x, kernel.astype(x.dtype),
                      None if bias is None else bias)


class FusedDenseGeluDense(nn.Module):
    """linear -> bias -> GELU -> linear -> bias, one fused region
    (ref: apex/fused_dense/fused_dense.py FusedDenseGeluDense)."""

    intermediate_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        from jax.ad_checkpoint import checkpoint_name

        h = FusedDense(self.intermediate_features,
                       param_dtype=self.param_dtype, name="dense1")(x)
        # Wide-intermediate tag: under the "all_but_ffn_wide" remat
        # policy (tensor_parallel.random.CHECKPOINT_POLICIES) these are
        # recomputed in the backward instead of saved.
        h = checkpoint_name(h, "ffn_wide")
        h = jax.nn.gelu(h.astype(jnp.float32), approximate=False)
        h = checkpoint_name(h.astype(x.dtype), "ffn_wide")
        return FusedDense(self.out_features,
                          param_dtype=self.param_dtype, name="dense2")(h)
