"""apex_tpu.fused_dense (ref: apex/fused_dense)."""
from .fused_dense import (FusedDense, FusedDenseGeluDense,
                          fused_dense_function)

__all__ = ["FusedDense", "FusedDenseGeluDense", "fused_dense_function"]
