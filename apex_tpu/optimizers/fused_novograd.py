"""FusedNovoGrad (ref: apex/optimizers/fused_novograd.py:1-214).

NovoGrad keeps the second moment as ONE scalar per tensor — the moving
average of the per-tensor gradient L2 norm (ref: fused_novograd.py
``norm_type=2``, kernel csrc/multi_tensor_novograd.cu).  Options:
``grad_averaging``, ``init_zero`` (v0 = 0 vs v0 = ||g1||^2),
``adam_w_mode``-style decoupled decay, bias correction.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from .fused_adam import ScalarOrSchedule, _lr_at


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates          # per-element first moment (fp32)
    v: optax.Updates          # per-tensor scalar second moment (fp32)


def fused_novograd(learning_rate: ScalarOrSchedule = 1e-3,
                   beta1: float = 0.95,
                   beta2: float = 0.98,
                   eps: float = 1e-8,
                   weight_decay: float = 0.0,
                   grad_averaging: bool = True,
                   init_zero: bool = False,
                   bias_correction: bool = True,
                   norm_type: int = 2) -> optax.GradientTransformation:
    if norm_type != 2:
        raise ValueError("only norm_type=2 is supported "
                         "(ref: apex/optimizers/fused_novograd.py)")

    def init(params):
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            v=jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        first = state.count == 0

        def leaf_update(g, p, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            gnorm_sq = jnp.sum(g * g)
            if init_zero:
                v_new = beta2 * v + (1.0 - beta2) * gnorm_sq
            else:
                # v0 = ||g1||^2 on the first step
                # (ref: fused_novograd.py init_zero=False default).
                v_new = jnp.where(first, gnorm_sq,
                                  beta2 * v + (1.0 - beta2) * gnorm_sq)
            denom = jnp.sqrt(v_new / bc2) + eps
            scaled = g / denom + weight_decay * p32
            m_new = beta1 * m + beta3 * scaled
            upd = m_new / bc1
            return (-lr * upd).astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(leaf_update, grads, params,
                                     state.m, state.v)
        treedef = jax.tree_util.tree_structure(params)
        flat = treedef.flatten_up_to(out)
        updates = treedef.unflatten([t[0] for t in flat])
        new_m = treedef.unflatten([t[1] for t in flat])
        new_v = treedef.unflatten([t[2] for t in flat])
        return updates, FusedNovoGradState(count, new_m, new_v)

    return optax.GradientTransformation(init, update)


FusedNovoGrad = fused_novograd
