"""FusedNovoGrad (ref: apex/optimizers/fused_novograd.py:1-214).

NovoGrad keeps the second moment as ONE scalar per tensor — the moving
average of the per-tensor gradient L2 norm (ref: fused_novograd.py
``norm_type=2``, kernel csrc/multi_tensor_novograd.cu).  Options:
``grad_averaging``, ``init_zero`` (v0 = 0 vs v0 = ||g1||^2),
decoupled decay, bias correction.

TPU design mirrors FusedLAMB: params/grads/m are LANE-aligned packed
flat buffers; the per-tensor ||g||^2 is a segment reduction (the
reference's per-tensor norm pass); the normalize+decay+momentum+delta
chain is one fused Pallas pass (``ops/fused_optim.novograd_update``) or
the identical jnp math under ``use_pallas=False``.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, multi_tensor
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]   # fp32 flat buffer per dtype group
    v: Tuple[jnp.ndarray, ...]   # (num_tensors,) scalar second moments


def fused_novograd(learning_rate: ScalarOrSchedule = 1e-3,
                   beta1: float = 0.95,
                   beta2: float = 0.98,
                   eps: float = 1e-8,
                   weight_decay: float = 0.0,
                   grad_averaging: bool = True,
                   init_zero: bool = False,
                   bias_correction: bool = True,
                   norm_type: int = 2,
                   use_pallas: bool = None) -> optax.GradientTransformation:
    if norm_type != 2:
        raise ValueError("only norm_type=2 is supported "
                         "(ref: apex/optimizers/fused_novograd.py)")
    if eps <= 0.0:
        # NovoGrad's gaps are safe at any eps (per_tensor_sumsq only
        # sees the zero-filled grad buffer; gap denominators come from
        # broadcast_per_tensor's fill=1.0) — but eps=0 still NaNs any
        # tensor whose grads are all zero: v=0 gives denom=0 for that
        # tensor's REAL elements.
        raise ValueError("fused_novograd requires eps > 0 "
                         "(zero-grad tensors would divide by zero)")
    LANE = multi_tensor.LANE

    def init(params):
        metas = multi_tensor.compute_metas(params, align=LANE,
                                           split_direct=True)
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            m=multi_tensor.state_zeros(metas),
            v=tuple(jnp.zeros((len(m.sizes),), jnp.float32)
                    for m in metas))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        first = state.count == 0

        metas = multi_tensor.compute_metas(params, align=LANE,
                                           split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)

        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            g32 = gbufs[i].astype(jnp.float32)
            if multi_tensor.is_direct(meta):
                # one native-shape leaf: the per-tensor 2nd moment is a
                # scalar reduction, no segments
                gn_sq = jnp.sum(g32 * g32)[None]
            else:
                # static-slice per-tensor reductions (no segment ops —
                # see multi_tensor.per_tensor_sumsq program-size note)
                gn_sq = multi_tensor.per_tensor_sumsq(g32, meta)
            if init_zero:
                v_new = beta2 * state.v[i] + (1.0 - beta2) * gn_sq
            else:
                # v0 = ||g1||^2 on the first step
                # (ref: fused_novograd.py init_zero=False default).
                v_new = jnp.where(first, gn_sq,
                                  beta2 * state.v[i]
                                  + (1.0 - beta2) * gn_sq)
            denom_t = jnp.sqrt(v_new / bc2) + eps
            if multi_tensor.is_direct(meta):
                denom_elem = denom_t[0]  # scalar broadcast
            else:
                denom_elem = multi_tensor.broadcast_per_tensor(
                    denom_t, meta)
            if fused_optim.group_use_pallas(use_pallas, meta) \
                    and not multi_tensor.is_direct(meta):
                d, m = fused_optim.novograd_update(
                    gbufs[i], pbufs[i], state.m[i], denom_elem,
                    lr=lr, beta1=beta1, beta3=beta3,
                    weight_decay=weight_decay, bias_correction1=bc1)
            else:
                # direct groups always take this path (even under
                # forced Pallas): their per-tensor denominator is ONE
                # scalar, and shipping it to the elementwise kernel
                # would require materializing a leaf-sized broadcast —
                # the exact redundant full pass direct groups remove
                scaled = g32 / denom_elem \
                    + weight_decay * pbufs[i].astype(jnp.float32)
                m = beta1 * state.m[i] + beta3 * scaled
                d = -lr * m / bc1
            deltas.append(d)
            new_m.append(m)
            new_v.append(v_new)

        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedNovoGradState(count, tuple(new_m),
                                           tuple(new_v))

    return optax.GradientTransformation(init, update)


FusedNovoGrad = fused_novograd
