"""FusedAdam — Adam/AdamW with a single fused Pallas pass.

Capability parity with the reference's ``FusedAdam``
(ref: apex/optimizers/fused_adam.py:4-173): ``adam_w_mode`` switching
Adam-L2 vs AdamW, ``bias_correction``, bf16/fp16/fp32 params
(ref: fused_adam.py:134 bf16 support), one fused kernel launch per dtype
group (ref: fused_adam.py:147-170 multi_tensor_applier calls).

Exposed as an optax-compatible ``GradientTransformation``: update deltas
come back in param dtype; ``m``/``v`` state lives in packed fp32 flat
buffers so the Pallas kernel streams params+grads+state in one pass
(see apex_tpu/ops/fused_optim.py).  Set ``use_pallas=False`` for the
per-leaf pure-jnp path (identical math; XLA-fused per leaf).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, fused_pipeline, multi_tensor

ScalarOrSchedule = Union[float, jnp.ndarray, Callable]


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]   # fp32 flat buffer per dtype group
    v: Tuple[jnp.ndarray, ...]


class FusedTransformation(NamedTuple):
    """optax-compatible transformation with an extra single-pass
    ``fused_step``: ``(new_params, new_state, model_params) =
    fused_step(grads, state, params, model_params=None)``.

    ``update`` keeps the optax delta protocol; ``fused_step`` is the
    in-place analogue of the reference's ``FusedAdam.step()`` — it
    applies the update AND (given ``model_params``, the low-precision
    template under amp master weights) emits the cast model copy from
    the same kernel pass, saving the delta round-trip and the separate
    master->model convert.

    ``pipeline_init`` / ``pipeline_step`` (None when the optimizer has
    no pipeline form) are the persistent-packed entry points used by
    :class:`apex_tpu.amp.AmpOptimizer` in pipeline mode (see
    ops/fused_pipeline.py): state lives in packed flat fp32 buffers
    across steps, and ``pipeline_step(gbufs, state, master_bufs, metas,
    grad_scale=..., grad_norm=..., finite=...)`` performs the whole
    clip+update+cast sweep over them, returning
    ``(new_master_bufs, new_state, lowp_bufs)``."""
    init: Any
    update: Any
    fused_step: Any
    pipeline_init: Any = None
    pipeline_step: Any = None


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


def _clip_enabled(max_norm) -> bool:
    """Static clip on/off: None or a non-positive Python number disables
    (a traced max_norm is always enabled — the caller opted in)."""
    return not (max_norm is None or (isinstance(max_norm, (int, float))
                                     and max_norm <= 0))


def _grad_clip_factor(gnorm, max_norm):
    """``min(1, max_norm/gnorm)`` in the reference's guarded form
    (ref: apex/optimizers/fused_lamb.py:163-185 clipped global norm) —
    the single shared clip-factor expression, so the staged and
    pipeline paths can never diverge on clip semantics."""
    if not _clip_enabled(max_norm):
        return jnp.float32(1.0)
    return jnp.where(gnorm > max_norm,
                     max_norm / jnp.maximum(gnorm, 1e-12), 1.0)


def _staged_clip(gbufs, max_norm):
    """Grad-clip for the per-stage paths: global norm over the group
    buffers (fp32), buffers pre-scaled by the clip factor.  The
    pipeline folds the same factor into its combined kernel scale
    instead of materializing scaled grads."""
    if not _clip_enabled(max_norm):
        return gbufs
    gnorm = jnp.sqrt(sum(multi_tensor.sumsq(b) for b in gbufs))
    clip = _grad_clip_factor(gnorm, max_norm)
    return [b.astype(jnp.float32) * clip for b in gbufs]


def _lowp_dtype_for(meta, pbuf, model_leaves):
    """Model-copy dtype for a DIRECT group when it differs from the
    master dtype (packed groups cast via assemble instead)."""
    if model_leaves is None or not multi_tensor.is_direct(meta):
        return None
    mdt = model_leaves[meta.leaf_indices[0]].dtype
    return mdt if mdt != jnp.dtype(pbuf.dtype) else None


def _assemble_model(new_p, lowps, metas, model_leaves):
    return multi_tensor.assemble(
        [lp if lp is not None else p2 for lp, p2 in zip(lowps, new_p)],
        metas, out_dtypes=[l.dtype for l in model_leaves])


def fused_adam(learning_rate: ScalarOrSchedule = 1e-3,
               beta1: float = 0.9,
               beta2: float = 0.999,
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               max_grad_norm=None,
               use_pallas: bool = None) -> "FusedTransformation":
    """Build the FusedAdam transformation (ref: apex/optimizers/fused_adam.py:4).

    ``max_grad_norm`` (None = off) enables global-norm gradient
    clipping before the update, matching FusedLAMB's clipped-global-
    grad-norm semantics; in pipeline mode the clip factor comes from
    the fused norm sweep and folds into the update kernel's combined
    scale (no extra pass)."""

    def _bias_corrections(count):
        cf = count.astype(jnp.float32)
        if bias_correction:
            return (1.0 - jnp.float32(beta1) ** cf,
                    1.0 - jnp.float32(beta2) ** cf)
        return jnp.float32(1.0), jnp.float32(1.0)

    def init(params):
        metas = multi_tensor.compute_metas(params, split_direct=True)
        zeros = multi_tensor.state_zeros(metas)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              m=zeros, v=tuple(jnp.zeros_like(z)
                                               for z in zeros))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        bc1, bc2 = _bias_corrections(count)

        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = _staged_clip(multi_tensor.group_buffers(grads, metas),
                             max_grad_norm)
        pbufs = multi_tensor.group_buffers(params, metas)
        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            if fused_optim.group_use_pallas(use_pallas, meta):
                (gb, pb, mb, vb), restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.m[i], state.v[i])
                d, m, v = fused_optim.adam_update(
                    gb, pb, mb, vb,
                    lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    weight_decay=weight_decay,
                    bias_correction1=bc1, bias_correction2=bc2,
                    adam_w_mode=adam_w_mode)
                d, m, v = restore(d), restore(m), restore(v)
            else:
                d, m, v = _adam_jnp(
                    gbufs[i], pbufs[i], state.m[i], state.v[i],
                    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
                    adam_w_mode)
            deltas.append(d)
            new_m.append(m)
            new_v.append(v)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedAdamState(count, tuple(new_m), tuple(new_v))

    def fused_step(grads, state, params, model_params=None):
        """Single-pass step: new params (+ optional model copy) without
        the optax delta round-trip — see FusedTransformation."""
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        bc1, bc2 = _bias_corrections(count)

        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = _staged_clip(multi_tensor.group_buffers(grads, metas),
                             max_grad_norm)
        pbufs = multi_tensor.group_buffers(params, metas)
        model_leaves = (jax.tree_util.tree_leaves(model_params)
                        if model_params is not None else None)
        new_p, new_m, new_v, lowps = [], [], [], []
        for i, meta in enumerate(metas):
            lowp_dt = _lowp_dtype_for(meta, pbufs[i], model_leaves)
            if fused_optim.step_use_pallas(use_pallas, sum(meta.sizes)):
                flats, restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.m[i], state.v[i])
                outs = fused_optim.adam_step(
                    *flats, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    weight_decay=weight_decay, bias_correction1=bc1,
                    bias_correction2=bc2, adam_w_mode=adam_w_mode,
                    lowp_dtype=lowp_dt)
                p2, m2, v2 = (restore(o) for o in outs[:3])
                lp = restore(outs[3]) if lowp_dt is not None else None
            else:
                d, m2, v2 = _adam_jnp(
                    gbufs[i], pbufs[i], state.m[i], state.v[i],
                    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
                    adam_w_mode)
                p2 = pbufs[i] + d
                lp = p2.astype(lowp_dt) if lowp_dt is not None else None
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
            lowps.append(lp)
        leaves = jax.tree_util.tree_leaves(params)
        new_params = multi_tensor.assemble(
            new_p, metas, out_dtypes=[l.dtype for l in leaves])
        new_state = FusedAdamState(count, tuple(new_m), tuple(new_v))
        model_out = None
        if model_leaves is not None:
            model_out = _assemble_model(new_p, lowps, metas,
                                        model_leaves)
        return new_params, new_state, model_out

    def pipeline_init(metas):
        """Optimizer state in the persistent packed layout (one fp32
        flat buffer per pipeline group) — see FusedTransformation."""
        zeros = tuple(jnp.zeros((m.padded,), jnp.float32) for m in metas)
        return FusedAdamState(count=jnp.zeros((), jnp.int32), m=zeros,
                              v=tuple(jnp.zeros_like(z) for z in zeros))

    def pipeline_step(gbufs, state, master_bufs, metas, *,
                      grad_scale=1.0, grad_norm=None, finite=True):
        """The clip+Adam+cast sweep over the persistent packed buffers.

        ``grad_scale`` is the amp inverse loss scale (combined with any
        caller-side factor); ``grad_norm`` the unscaled global norm
        from the fused norm sweep (required when ``max_grad_norm`` is
        set); ``finite`` the overflow flag — non-finite steps return
        state bitwise unchanged via an in-sweep select, matching the
        staged path's ``lax.cond`` skip (count held still too)."""
        finite = jnp.asarray(finite)
        count = state.count + finite.astype(jnp.int32)
        lr = _lr_at(learning_rate, state.count + 1)
        bc1, bc2 = _bias_corrections(state.count + 1)
        gscale = jnp.asarray(grad_scale, jnp.float32)
        if _clip_enabled(max_grad_norm):
            if grad_norm is None:
                # amp elided the norm/finite sweep (static scaling):
                # derive the unscaled norm here — one fused read, only
                # paid when clipping is actually configured
                grad_norm = fused_pipeline.packed_norm(gbufs, gscale)
            gscale = gscale * _grad_clip_factor(grad_norm, max_grad_norm)
        new_p, new_m, new_v, lowps = [], [], [], []
        for i, meta in enumerate(metas):
            p2, m2, v2, lp = fused_pipeline.adam_pipeline(
                gbufs[i], master_bufs[i], state.m[i], state.v[i],
                grad_scale=gscale, lr=lr, beta1=beta1, beta2=beta2,
                eps=eps, weight_decay=weight_decay,
                bias_correction1=bc1, bias_correction2=bc2,
                adam_w_mode=adam_w_mode, finite=finite,
                lowp_dtype=fused_pipeline.group_lowp_dtype(meta),
                use_pallas=use_pallas)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
            lowps.append(lp if lp is not None else p2)
        return (tuple(new_p),
                FusedAdamState(count, tuple(new_m), tuple(new_v)),
                lowps)

    return FusedTransformation(init, update, fused_step,
                               pipeline_init, pipeline_step)


def _adam_jnp(g, p, m, v, lr, b1, b2, eps, wd, bc1, bc2, adam_w_mode):
    """Reference math in plain jnp (ref: csrc/multi_tensor_adam.cu:24-110)."""
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p32
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        upd = upd + wd * p32
    return (-lr * upd).astype(p.dtype), m, v


FusedAdam = fused_adam
