"""FusedAdam — Adam/AdamW with a single fused Pallas pass.

Capability parity with the reference's ``FusedAdam``
(ref: apex/optimizers/fused_adam.py:4-173): ``adam_w_mode`` switching
Adam-L2 vs AdamW, ``bias_correction``, bf16/fp16/fp32 params
(ref: fused_adam.py:134 bf16 support), one fused kernel launch per dtype
group (ref: fused_adam.py:147-170 multi_tensor_applier calls).

Exposed as an optax-compatible ``GradientTransformation``: update deltas
come back in param dtype; ``m``/``v`` state lives in packed fp32 flat
buffers so the Pallas kernel streams params+grads+state in one pass
(see apex_tpu/ops/fused_optim.py).  Set ``use_pallas=False`` for the
per-leaf pure-jnp path (identical math; XLA-fused per leaf).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, multi_tensor

ScalarOrSchedule = Union[float, jnp.ndarray, Callable]


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]   # fp32 flat buffer per dtype group
    v: Tuple[jnp.ndarray, ...]


class FusedTransformation(NamedTuple):
    """optax-compatible transformation with an extra single-pass
    ``fused_step``: ``(new_params, new_state, model_params) =
    fused_step(grads, state, params, model_params=None)``.

    ``update`` keeps the optax delta protocol; ``fused_step`` is the
    in-place analogue of the reference's ``FusedAdam.step()`` — it
    applies the update AND (given ``model_params``, the low-precision
    template under amp master weights) emits the cast model copy from
    the same kernel pass, saving the delta round-trip and the separate
    master->model convert."""
    init: Any
    update: Any
    fused_step: Any


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


def _lowp_dtype_for(meta, pbuf, model_leaves):
    """Model-copy dtype for a DIRECT group when it differs from the
    master dtype (packed groups cast via assemble instead)."""
    if model_leaves is None or not multi_tensor.is_direct(meta):
        return None
    mdt = model_leaves[meta.leaf_indices[0]].dtype
    return mdt if mdt != jnp.dtype(pbuf.dtype) else None


def _assemble_model(new_p, lowps, metas, model_leaves):
    return multi_tensor.assemble(
        [lp if lp is not None else p2 for lp, p2 in zip(lowps, new_p)],
        metas, out_dtypes=[l.dtype for l in model_leaves])


def fused_adam(learning_rate: ScalarOrSchedule = 1e-3,
               beta1: float = 0.9,
               beta2: float = 0.999,
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               use_pallas: bool = None) -> "FusedTransformation":
    """Build the FusedAdam transformation (ref: apex/optimizers/fused_adam.py:4)."""

    def init(params):
        metas = multi_tensor.compute_metas(params, split_direct=True)
        zeros = multi_tensor.state_zeros(metas)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              m=zeros, v=tuple(jnp.zeros_like(z)
                                               for z in zeros))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)
        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            if fused_optim.group_use_pallas(use_pallas, meta):
                (gb, pb, mb, vb), restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.m[i], state.v[i])
                d, m, v = fused_optim.adam_update(
                    gb, pb, mb, vb,
                    lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    weight_decay=weight_decay,
                    bias_correction1=bc1, bias_correction2=bc2,
                    adam_w_mode=adam_w_mode)
                d, m, v = restore(d), restore(m), restore(v)
            else:
                d, m, v = _adam_jnp(
                    gbufs[i], pbufs[i], state.m[i], state.v[i],
                    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
                    adam_w_mode)
            deltas.append(d)
            new_m.append(m)
            new_v.append(v)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedAdamState(count, tuple(new_m), tuple(new_v))

    def fused_step(grads, state, params, model_params=None):
        """Single-pass step: new params (+ optional model copy) without
        the optax delta round-trip — see FusedTransformation."""
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)
        model_leaves = (jax.tree_util.tree_leaves(model_params)
                        if model_params is not None else None)
        new_p, new_m, new_v, lowps = [], [], [], []
        for i, meta in enumerate(metas):
            lowp_dt = _lowp_dtype_for(meta, pbufs[i], model_leaves)
            if fused_optim.step_use_pallas(use_pallas, sum(meta.sizes)):
                flats, restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.m[i], state.v[i])
                outs = fused_optim.adam_step(
                    *flats, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                    weight_decay=weight_decay, bias_correction1=bc1,
                    bias_correction2=bc2, adam_w_mode=adam_w_mode,
                    lowp_dtype=lowp_dt)
                p2, m2, v2 = (restore(o) for o in outs[:3])
                lp = restore(outs[3]) if lowp_dt is not None else None
            else:
                d, m2, v2 = _adam_jnp(
                    gbufs[i], pbufs[i], state.m[i], state.v[i],
                    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
                    adam_w_mode)
                p2 = pbufs[i] + d
                lp = p2.astype(lowp_dt) if lowp_dt is not None else None
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
            lowps.append(lp)
        leaves = jax.tree_util.tree_leaves(params)
        new_params = multi_tensor.assemble(
            new_p, metas, out_dtypes=[l.dtype for l in leaves])
        new_state = FusedAdamState(count, tuple(new_m), tuple(new_v))
        model_out = None
        if model_leaves is not None:
            model_out = _assemble_model(new_p, lowps, metas,
                                        model_leaves)
        return new_params, new_state, model_out

    return FusedTransformation(init, update, fused_step)


def _adam_jnp(g, p, m, v, lr, b1, b2, eps, wd, bc1, bc2, adam_w_mode):
    """Reference math in plain jnp (ref: csrc/multi_tensor_adam.cu:24-110)."""
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p32
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        upd = upd + wd * p32
    return (-lr * upd).astype(p.dtype), m, v


FusedAdam = fused_adam
