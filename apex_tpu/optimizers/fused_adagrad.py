"""FusedAdagrad (ref: apex/optimizers/fused_adagrad.py:1-121)."""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, multi_tensor
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    h: Tuple[jnp.ndarray, ...]   # accumulated squared gradients (fp32)


def fused_adagrad(learning_rate: ScalarOrSchedule = 1e-2,
                  eps: float = 1e-10,
                  weight_decay: float = 0.0,
                  use_pallas: bool = None) -> optax.GradientTransformation:
    def init(params):
        metas = multi_tensor.compute_metas(params, split_direct=True)
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32),
            h=multi_tensor.state_zeros(metas))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)
        deltas, new_h = [], []
        for i, meta in enumerate(metas):
            if fused_optim.group_use_pallas(use_pallas, meta):
                (gb, pb, hb), restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.h[i])
                d, h = fused_optim.adagrad_update(
                    gb, pb, hb, lr=lr, eps=eps, weight_decay=weight_decay)
                d, h = restore(d), restore(h)
            else:
                g = gbufs[i].astype(jnp.float32) \
                    + weight_decay * pbufs[i].astype(jnp.float32)
                h = state.h[i] + g * g
                d = (-lr * g / (jnp.sqrt(h) + eps)).astype(meta.dtype)
            deltas.append(d)
            new_h.append(h)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedAdagradState(count, tuple(new_h))

    return optax.GradientTransformation(init, update)


FusedAdagrad = fused_adagrad
