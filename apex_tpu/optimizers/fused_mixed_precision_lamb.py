"""FusedMixedPrecisionLamb — LAMB stepping reduced-precision params with
fp32 masters, scaler-aware.

Parity with the reference
(ref: apex/optimizers/fused_mixed_precision_lamb.py:8-256): params live
in ``reduced_precision_dtype`` (bf16/fp16); the optimizer owns the fp32
full-precision copy (``_setup_full_precision_params``, :118-127) plus
fp32 m/v; ``step`` accepts a grad scaler (``_step_supports_amp_scaling``,
:56) and performs unscale + found-inf check + conditional-skip *inside*
the fused update (``multi_tensor_lamb_mp`` takes ``found_inf`` and
``inv_scale``, :245-255); the step counter only advances on finite steps
(:205 ``group['step'] += (overflow_buf != 1)``).

TPU design: masters/m/v are LANE-aligned packed fp32 buffers; the whole
step — unscale, global-norm clip (``max_grad_norm * scale`` because the
norm is of scaled grads, :182-184), LAMB stage 1 (Pallas), per-tensor
trust ratios, master update, reduced-precision emission — is one pure
function; overflow skip is a ``jnp.where`` select, so the train step
never syncs to host.  The reference's fp16 param-remainder trick
(``multi_tensor_lamb_mp.cu``) is unnecessary here: masters are the
source of truth and params are re-emitted as ``cast(master)`` each step,
which is strictly more precise.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..amp import scaler as _scaler
from ..ops import fused_optim, multi_tensor
from .fused_adam import ScalarOrSchedule, _lr_at
from .fused_lamb import _global_grad_clip, _lamb_group_update


class MixedPrecisionLambState(NamedTuple):
    count: jnp.ndarray
    masters: Tuple[jnp.ndarray, ...]  # fp32 packed full-precision params
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


class MPLambInfo(NamedTuple):
    grads_finite: jnp.ndarray
    grad_norm: jnp.ndarray


class FusedMixedPrecisionLamb:
    """``opt = FusedMixedPrecisionLamb(lr=...); state = opt.init(params);
    params, state, scaler, info = opt.step(grads, state, params, scaler)``.

    ``params`` may mix reduced-precision and fp32 leaves; every leaf gets
    an fp32 master (for fp32 leaves the master IS the param, matching the
    reference's ``None`` full-precision slot, ref:
    fused_mixed_precision_lamb.py:121-126).
    """

    def __init__(self,
                 learning_rate: ScalarOrSchedule = 1e-3,
                 beta1: float = 0.9,
                 beta2: float = 0.999,
                 eps: float = 1e-6,
                 weight_decay: float = 0.01,
                 bias_correction: bool = True,
                 grad_averaging: bool = True,
                 adam_w_mode: bool = True,
                 max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False,
                 reduced_precision_dtype=jnp.bfloat16,
                 use_pallas: Optional[bool] = None):
        if eps <= 0.0:
            # Shares fused_lamb's packed trust-ratio math
            # (_lamb_group_update): eps=0 makes zero-filled alignment
            # gaps 0/0=NaN in phase-1, which per_tensor_sumsq folds
            # into the preceding tensor's norm.
            raise ValueError("FusedMixedPrecisionLamb requires eps > 0 "
                             "(packed padding-gap invariant)")
        self.learning_rate = learning_rate
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.adam_w_mode = adam_w_mode
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.reduced_precision_dtype = reduced_precision_dtype
        self.use_pallas = use_pallas

    def init(self, params: Any) -> MixedPrecisionLambState:
        metas = multi_tensor.compute_metas(params,
                                           align=multi_tensor.LANE)
        masters = tuple(multi_tensor.pack(params, metas, jnp.float32))
        return MixedPrecisionLambState(
            count=jnp.zeros((), jnp.int32),
            masters=masters,
            m=tuple(jnp.zeros_like(b) for b in masters),
            v=tuple(jnp.zeros_like(b) for b in masters))

    def step(self, grads: Any, state: MixedPrecisionLambState, params: Any,
             scaler_state: Optional[_scaler.ScalerState] = None,
             axis_names=None):
        """One conditional LAMB step.  ``grads`` are the (possibly
        loss-scaled) gradients w.r.t. the reduced-precision params;
        ``scaler_state`` supplies the scale and receives the
        backoff/growth update (ref: step(grad_scaler=...),
        fused_mixed_precision_lamb.py:140+).  Returns
        ``(new_params, new_state, new_scaler_state, info)``.
        """
        metas = multi_tensor.compute_metas(params,
                                           align=multi_tensor.LANE)
        gbufs = multi_tensor.pack(grads, metas)

        finite = _scaler.all_finite(gbufs, axis_names=axis_names)
        scale = scaler_state.loss_scale if scaler_state is not None \
            else jnp.float32(1.0)
        inv_scale = 1.0 / scale

        # step counter advances only on finite steps
        # (ref: fused_mixed_precision_lamb.py:205).
        count = state.count + jnp.where(finite, 1, 0)
        lr = _lr_at(self.learning_rate, count)
        cf = jnp.maximum(count.astype(jnp.float32), 1.0)
        if self.bias_correction:
            bc1 = 1.0 - jnp.float32(self.beta1) ** cf
            bc2 = 1.0 - jnp.float32(self.beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - self.beta1) if self.grad_averaging else 1.0

        # Norm is of SCALED grads, so the clip threshold scales too
        # (ref: fused_mixed_precision_lamb.py:182-184).
        max_eff = self.max_grad_norm * scale \
            if (self.max_grad_norm is not None
                and self.max_grad_norm > 0) else None
        gnorm, clip = _global_grad_clip(gbufs, max_eff)
        gscale = inv_scale * clip

        new_masters, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            adapted_u, m, v = _lamb_group_update(
                meta, gbufs[i], state.masters[i], state.m[i], state.v[i],
                gscale=gscale, beta1=self.beta1, beta2=self.beta2,
                beta3=beta3, eps=self.eps, weight_decay=self.weight_decay,
                bc1=bc1, bc2=bc2, adam_w_mode=self.adam_w_mode,
                use_nvlamb=self.use_nvlamb,
                fused=fused_optim.group_use_pallas(
                    self.use_pallas, meta))
            master_new = state.masters[i] - lr * adapted_u
            # Overflow: everything holds still (the mp kernel's
            # found_inf no-op, ref: multi_tensor_lamb_mp.cu).
            new_masters.append(jnp.where(finite, master_new,
                                         state.masters[i]))
            new_m.append(jnp.where(finite, m, state.m[i]))
            new_v.append(jnp.where(finite, v, state.v[i]))

        leaves = jax.tree_util.tree_leaves(params)
        new_params = multi_tensor.unpack_groups(
            new_masters, metas, out_dtypes=[l.dtype for l in leaves])

        new_state = MixedPrecisionLambState(
            count, tuple(new_masters), tuple(new_m), tuple(new_v))
        new_scaler = _scaler.update(scaler_state, finite) \
            if scaler_state is not None else None
        return new_params, new_state, new_scaler, MPLambInfo(
            grads_finite=finite, grad_norm=gnorm * inv_scale)

    # -- checkpointing (masters must round-trip in full precision,
    # ref: fused_mixed_precision_lamb.py:73-117 load_state_dict keeps
    # state in fp32 rather than casting to param dtype) ------------------

    def state_dict(self, state: MixedPrecisionLambState) -> dict:
        return {"count": int(state.count),
                "masters": [jnp.asarray(b) for b in state.masters],
                "m": [jnp.asarray(b) for b in state.m],
                "v": [jnp.asarray(b) for b in state.v]}

    def load_state_dict(self, d: dict) -> MixedPrecisionLambState:
        return MixedPrecisionLambState(
            count=jnp.int32(d["count"]),
            masters=tuple(jnp.asarray(b, jnp.float32)
                          for b in d["masters"]),
            m=tuple(jnp.asarray(b, jnp.float32) for b in d["m"]),
            v=tuple(jnp.asarray(b, jnp.float32) for b in d["v"]))


fused_mixed_precision_lamb = FusedMixedPrecisionLamb
