"""FusedSGD — momentum SGD with a single fused Pallas pass.

Parity with the reference's ``FusedSGD``
(ref: apex/optimizers/fused_sgd.py:4-227): momentum, dampening, nesterov,
``wd_after_momentum``, torch first-step momentum semantics
(buf <- grad).  The reference's ``materialize_master_grads`` fusion of
unscale+copy+step into one kernel (ref: fused_sgd.py:76-95,
apex/amp/_process_optimizer.py:258+) is subsumed here by XLA fusing the
amp unscale into the packed-gradient read.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, fused_pipeline, multi_tensor
from .fused_adam import (FusedTransformation, ScalarOrSchedule,
                         _assemble_model, _clip_enabled,
                         _grad_clip_factor, _lowp_dtype_for, _lr_at,
                         _staged_clip)


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum: Tuple[jnp.ndarray, ...]


def fused_sgd(learning_rate: ScalarOrSchedule,
              momentum: float = 0.0,
              dampening: float = 0.0,
              weight_decay: float = 0.0,
              nesterov: bool = False,
              wd_after_momentum: bool = False,
              max_grad_norm=None,
              use_pallas: bool = None) -> "FusedTransformation":
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError(
            "Nesterov momentum requires a momentum and zero dampening "
            "(ref: apex/optimizers/fused_sgd.py:61-62)")

    def init(params):
        metas = multi_tensor.compute_metas(params, split_direct=True)
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum=multi_tensor.state_zeros(metas))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        first = (state.count == 0).astype(jnp.float32) if momentum else \
            jnp.float32(0.0)
        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = _staged_clip(multi_tensor.group_buffers(grads, metas),
                             max_grad_norm)
        pbufs = multi_tensor.group_buffers(params, metas)
        deltas, new_mom = [], []
        for i, meta in enumerate(metas):
            if momentum == 0.0:
                # No momentum buffer: plain (optionally decayed) step.
                g = gbufs[i].astype(jnp.float32)
                p32 = pbufs[i].astype(jnp.float32)
                g = g + weight_decay * p32
                deltas.append((-lr * g).astype(meta.dtype))
                new_mom.append(state.momentum[i])
            elif fused_optim.group_use_pallas(use_pallas, meta):
                (gb, pb, mb), restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.momentum[i])
                d, mom = fused_optim.sgd_update(
                    gb, pb, mb,
                    lr=lr, momentum=momentum, dampening=dampening,
                    weight_decay=weight_decay, nesterov=nesterov,
                    wd_after_momentum=wd_after_momentum, first_run=first)
                deltas.append(restore(d))
                new_mom.append(restore(mom))
            else:
                d, mom = _sgd_jnp(gbufs[i], pbufs[i], state.momentum[i],
                                  lr, momentum, dampening, weight_decay,
                                  nesterov, wd_after_momentum, first)
                deltas.append(d)
                new_mom.append(mom)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedSGDState(count, tuple(new_mom))

    def fused_step(grads, state, params, model_params=None):
        """Single-pass step (+ optional model copy) — the in-place
        ``FusedSGD.step()`` analogue; see FusedTransformation."""
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        first = (state.count == 0).astype(jnp.float32) if momentum else \
            jnp.float32(0.0)
        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = _staged_clip(multi_tensor.group_buffers(grads, metas),
                             max_grad_norm)
        pbufs = multi_tensor.group_buffers(params, metas)
        model_leaves = (jax.tree_util.tree_leaves(model_params)
                        if model_params is not None else None)
        new_p, new_mom, lowps = [], [], []
        for i, meta in enumerate(metas):
            lowp_dt = _lowp_dtype_for(meta, pbufs[i], model_leaves)
            lp = None
            if momentum == 0.0:
                g = gbufs[i].astype(jnp.float32)
                p32 = pbufs[i].astype(jnp.float32)
                p2 = (p32 - lr * (g + weight_decay * p32)).astype(
                    meta.dtype)
                mom2 = state.momentum[i]
            elif fused_optim.step_use_pallas(use_pallas,
                                             sum(meta.sizes)):
                flats, restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.momentum[i])
                outs = fused_optim.sgd_step(
                    *flats, lr=lr, momentum=momentum,
                    dampening=dampening, weight_decay=weight_decay,
                    nesterov=nesterov,
                    wd_after_momentum=wd_after_momentum,
                    first_run=first, lowp_dtype=lowp_dt)
                p2, mom2 = restore(outs[0]), restore(outs[1])
                if lowp_dt is not None:
                    lp = restore(outs[2])
            else:
                d, mom2 = _sgd_jnp(gbufs[i], pbufs[i],
                                   state.momentum[i], lr, momentum,
                                   dampening, weight_decay, nesterov,
                                   wd_after_momentum, first)
                p2 = pbufs[i] + d
            if lp is None and lowp_dt is not None:
                lp = p2.astype(lowp_dt)
            new_p.append(p2)
            new_mom.append(mom2)
            lowps.append(lp)
        leaves = jax.tree_util.tree_leaves(params)
        new_params = multi_tensor.assemble(
            new_p, metas, out_dtypes=[l.dtype for l in leaves])
        model_out = None
        if model_leaves is not None:
            model_out = _assemble_model(new_p, lowps, metas,
                                        model_leaves)
        return new_params, FusedSGDState(count, tuple(new_mom)), \
            model_out

    def pipeline_init(metas):
        """Persistent packed momentum buffers (fp32 per group)."""
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum=tuple(jnp.zeros((m.padded,), jnp.float32)
                           for m in metas))

    def pipeline_step(gbufs, state, master_bufs, metas, *,
                      grad_scale=1.0, grad_norm=None, finite=True):
        """The clip+SGD+cast sweep over the persistent packed buffers —
        see fused_adam's pipeline_step for the skip/count semantics."""
        finite = jnp.asarray(finite)
        count = state.count + finite.astype(jnp.int32)
        lr = _lr_at(learning_rate, state.count + 1)
        first = (state.count == 0).astype(jnp.float32) if momentum else \
            jnp.float32(0.0)
        gscale = jnp.asarray(grad_scale, jnp.float32)
        if _clip_enabled(max_grad_norm):
            if grad_norm is None:
                # see fused_adam.pipeline_step: static-scaling amp
                # elided the norm sweep; derive it only for the clip
                grad_norm = fused_pipeline.packed_norm(gbufs, gscale)
            gscale = gscale * _grad_clip_factor(grad_norm, max_grad_norm)
        new_p, new_mom, lowps = [], [], []
        for i, meta in enumerate(metas):
            lowp_dt = fused_pipeline.group_lowp_dtype(meta)
            if momentum == 0.0:
                p = master_bufs[i]
                g32 = gbufs[i].astype(jnp.float32) * gscale
                p2 = jnp.where(finite,
                               p - lr * (g32 + weight_decay * p), p)
                mom2, lp = state.momentum[i], None
            else:
                p2, mom2, lp = fused_pipeline.sgd_pipeline(
                    gbufs[i], master_bufs[i], state.momentum[i],
                    grad_scale=gscale, lr=lr, momentum=momentum,
                    dampening=dampening, weight_decay=weight_decay,
                    nesterov=nesterov,
                    wd_after_momentum=wd_after_momentum,
                    first_run=first, finite=finite,
                    lowp_dtype=lowp_dt, use_pallas=use_pallas)
            if lp is None:
                lp = p2.astype(lowp_dt) if lowp_dt is not None else p2
            new_p.append(p2)
            new_mom.append(mom2)
            lowps.append(lp)
        return (tuple(new_p), FusedSGDState(count, tuple(new_mom)),
                lowps)

    return FusedTransformation(init, update, fused_step,
                               pipeline_init, pipeline_step)


def _sgd_jnp(g, p, mom, lr, momentum, dampening, wd, nesterov,
             wd_after_momentum, first_run):
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not wd_after_momentum:
        g = g + wd * p32
    mom = jnp.where(first_run > 0.5, g,
                    momentum * mom + (1.0 - dampening) * g)
    upd = g + momentum * mom if nesterov else mom
    if wd_after_momentum:
        upd = upd + wd * p32
    return (-lr * upd).astype(p.dtype), mom


FusedSGD = fused_sgd
