"""FusedSGD — momentum SGD with a single fused Pallas pass.

Parity with the reference's ``FusedSGD``
(ref: apex/optimizers/fused_sgd.py:4-227): momentum, dampening, nesterov,
``wd_after_momentum``, torch first-step momentum semantics
(buf <- grad).  The reference's ``materialize_master_grads`` fusion of
unscale+copy+step into one kernel (ref: fused_sgd.py:76-95,
apex/amp/_process_optimizer.py:258+) is subsumed here by XLA fusing the
amp unscale into the packed-gradient read.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, multi_tensor
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum: Tuple[jnp.ndarray, ...]


def fused_sgd(learning_rate: ScalarOrSchedule,
              momentum: float = 0.0,
              dampening: float = 0.0,
              weight_decay: float = 0.0,
              nesterov: bool = False,
              wd_after_momentum: bool = False,
              use_pallas: bool = None) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError(
            "Nesterov momentum requires a momentum and zero dampening "
            "(ref: apex/optimizers/fused_sgd.py:61-62)")

    def init(params):
        metas = multi_tensor.compute_metas(params, split_direct=True)
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum=multi_tensor.state_zeros(metas))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        first = (state.count == 0).astype(jnp.float32) if momentum else \
            jnp.float32(0.0)
        metas = multi_tensor.compute_metas(params, split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)
        deltas, new_mom = [], []
        for i, meta in enumerate(metas):
            if momentum == 0.0:
                # No momentum buffer: plain (optionally decayed) step.
                g = gbufs[i].astype(jnp.float32)
                p32 = pbufs[i].astype(jnp.float32)
                g = g + weight_decay * p32
                deltas.append((-lr * g).astype(meta.dtype))
                new_mom.append(state.momentum[i])
            elif fused_optim.group_use_pallas(use_pallas, meta):
                (gb, pb, mb), restore = fused_optim.flatten_for_kernel(
                    gbufs[i], pbufs[i], state.momentum[i])
                d, mom = fused_optim.sgd_update(
                    gb, pb, mb,
                    lr=lr, momentum=momentum, dampening=dampening,
                    weight_decay=weight_decay, nesterov=nesterov,
                    wd_after_momentum=wd_after_momentum, first_run=first)
                deltas.append(restore(d))
                new_mom.append(restore(mom))
            else:
                d, mom = _sgd_jnp(gbufs[i], pbufs[i], state.momentum[i],
                                  lr, momentum, dampening, weight_decay,
                                  nesterov, wd_after_momentum, first)
                deltas.append(d)
                new_mom.append(mom)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, FusedSGDState(count, tuple(new_mom))

    return optax.GradientTransformation(init, update)


def _sgd_jnp(g, p, mom, lr, momentum, dampening, wd, nesterov,
             wd_after_momentum, first_run):
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not wd_after_momentum:
        g = g + wd * p32
    mom = jnp.where(first_run > 0.5, g,
                    momentum * mom + (1.0 - dampening) * g)
    upd = g + momentum * mom if nesterov else mom
    if wd_after_momentum:
        upd = upd + wd * p32
    return (-lr * upd).astype(p.dtype), mom


FusedSGD = fused_sgd
