"""apex_tpu.optimizers — fused optimizers (TPU-native apex.optimizers).

All are optax-compatible ``GradientTransformation`` factories whose hot
path is a single fused Pallas pass over packed parameter buffers, with
per-tensor reductions (LAMB trust ratios, NovoGrad second moments) as
segment reductions over the same LANE-aligned buffers.  See SURVEY.md
§2.4.  ``FusedMixedPrecisionLamb`` is the scaler-aware master-weight
variant (ref: apex/optimizers/fused_mixed_precision_lamb.py).
"""
from ..parallel.LARC import LARC, larc
from .fused_adagrad import FusedAdagrad, FusedAdagradState, fused_adagrad
from .fused_adam import FusedAdam, FusedAdamState, fused_adam
from .fused_lamb import FusedLAMB, FusedLAMBState, fused_lamb
from .fused_mixed_precision_lamb import (FusedMixedPrecisionLamb,
                                         MixedPrecisionLambState,
                                         MPLambInfo,
                                         fused_mixed_precision_lamb)
from .fused_novograd import FusedNovoGrad, FusedNovoGradState, fused_novograd
from .fused_sgd import FusedSGD, FusedSGDState, fused_sgd

__all__ = [
    "fused_adam", "FusedAdam", "FusedAdamState",
    "fused_sgd", "FusedSGD", "FusedSGDState",
    "fused_adagrad", "FusedAdagrad", "FusedAdagradState",
    "fused_lamb", "FusedLAMB", "FusedLAMBState",
    "fused_novograd", "FusedNovoGrad", "FusedNovoGradState",
    "fused_mixed_precision_lamb", "FusedMixedPrecisionLamb",
    "MixedPrecisionLambState", "MPLambInfo",
    "larc", "LARC",
]
