"""FusedLAMB — layerwise adaptive large-batch optimizer.

Parity with the reference's two-phase ``FusedLAMB``
(ref: apex/optimizers/fused_lamb.py:1-215): phase 1 computes per-tensor
L2 norms (``multi_tensor_l2norm``) and the global-grad-norm clip; phase 2
applies the trust-ratio update (``multi_tensor_lamb``,
csrc/multi_tensor_lamb.cu:24-413).  Options: ``bias_correction``,
``grad_averaging``, ``adam_w_mode``, ``max_grad_norm``, ``use_nvlamb``.

Per-tensor trust ratios make this a per-leaf computation; XLA fuses each
leaf's elementwise chain, and the norm reductions are the only extra
passes — same structure as the reference's two-kernel pipeline.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..ops import multi_tensor
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates
    v: optax.Updates


def fused_lamb(learning_rate: ScalarOrSchedule = 1e-3,
               beta1: float = 0.9,
               beta2: float = 0.999,
               eps: float = 1e-6,
               weight_decay: float = 0.01,
               bias_correction: bool = True,
               grad_averaging: bool = True,
               adam_w_mode: bool = True,
               max_grad_norm: float = 1.0,
               use_nvlamb: bool = False) -> optax.GradientTransformation:
    def init(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedLAMBState(count=jnp.zeros((), jnp.int32),
                              m=zeros,
                              v=jax.tree_util.tree_map(jnp.zeros_like, zeros))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params in update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0

        # Phase 1: global grad norm + clip factor
        # (ref: apex/optimizers/fused_lamb.py:163-185).
        gnorm = multi_tensor.l2norm(grads)
        clip = jnp.where(gnorm > max_grad_norm,
                         max_grad_norm / jnp.maximum(gnorm, 1e-12), 1.0) \
            if max_grad_norm is not None and max_grad_norm > 0 else 1.0

        def leaf_update(g, p, m, v):
            g = g.astype(jnp.float32) * clip
            p32 = p.astype(jnp.float32)
            if not adam_w_mode:
                g = g + weight_decay * p32
            m_new = beta1 * m + beta3 * g
            v_new = beta2 * v + (1.0 - beta2) * g * g
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if adam_w_mode:
                upd = upd + weight_decay * p32
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(upd * upd))
            # Trust ratio (ref: csrc/multi_tensor_lamb.cu lamb stage 2):
            # ratio = w_norm/u_norm when both > 0 else 1.  NVLamb skips the
            # ratio for params excluded from decay; plain LAMB applies it
            # everywhere (ref: fused_lamb.py use_nvlamb handling).
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
            if not use_nvlamb and weight_decay == 0.0:
                ratio = jnp.where(jnp.bool_(True), ratio, ratio)
            return (-lr * ratio * upd).astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(leaf_update, grads, params,
                                     state.m, state.v)
        # tree of tuples -> three trees
        treedef = jax.tree_util.tree_structure(params)
        flat = treedef.flatten_up_to(out)
        updates = treedef.unflatten([t[0] for t in flat])
        new_m = treedef.unflatten([t[1] for t in flat])
        new_v = treedef.unflatten([t[2] for t in flat])
        return updates, FusedLAMBState(count, new_m, new_v)

    return optax.GradientTransformation(init, update)


FusedLAMB = fused_lamb
