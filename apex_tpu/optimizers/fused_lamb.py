"""FusedLAMB — layerwise adaptive large-batch optimizer.

Parity with the reference's two-phase ``FusedLAMB``
(ref: apex/optimizers/fused_lamb.py:1-215): phase 1 computes the global
grad norm (``multi_tensor_l2norm``) and the Adam-style update
(``multi_tensor_lamb`` stage 1, csrc/multi_tensor_lamb.cu:60-200); phase 2
applies per-tensor trust ratios (stage 2, :230-330).  Options:
``bias_correction``, ``grad_averaging``, ``adam_w_mode``,
``max_grad_norm``, ``use_nvlamb``.

TPU design: params/grads/state are packed into LANE-aligned flat fp32
buffers per dtype group; stage 1 is one fused Pallas pass (4 reads /
3 writes); per-tensor param/update norms are segment reductions over the
packed buffer (the reference's per-tensor-norm kernel role); stage 2's
ratio gather+multiply is left to XLA, which fuses it into a single
elementwise pass — on TPU there is no launch overhead for a Pallas
kernel to amortize there.

Trust-ratio gating matches the reference exactly: the adaptive ratio is
applied only when ``use_nvlamb`` or the group's weight decay is nonzero
(ref: csrc/multi_tensor_lamb.cu:258 ``use_nvlamb || decay != 0.0``).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import fused_optim, fused_pipeline, multi_tensor
from .fused_adam import (FusedTransformation, ScalarOrSchedule,
                         _assemble_model, _grad_clip_factor,
                         _lowp_dtype_for, _lr_at)


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    # fp32 per group: flat (padded,) buffer for packed groups, native
    # leaf shape for DIRECT groups (split_direct metas)
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def fused_lamb(learning_rate: ScalarOrSchedule = 1e-3,
               beta1: float = 0.9,
               beta2: float = 0.999,
               eps: float = 1e-6,
               weight_decay: float = 0.01,
               bias_correction: bool = True,
               grad_averaging: bool = True,
               adam_w_mode: bool = True,
               max_grad_norm: float = 1.0,
               use_nvlamb: bool = False,
               use_pallas: bool = None) -> "FusedTransformation":
    if eps <= 0.0:
        # Packed trust-ratio math needs phase-1 to map zero-filled
        # alignment gaps to exactly 0 (per_tensor_sumsq folds each gap
        # into the preceding tensor's norm); eps=0 makes gaps 0/0=NaN
        # and silently poisons that tensor's ratio.
        raise ValueError("fused_lamb requires eps > 0 "
                         "(packed padding-gap invariant)")
    LANE = multi_tensor.LANE

    def init(params):
        metas = multi_tensor.compute_metas(params, align=LANE,
                                           split_direct=True)
        zeros = multi_tensor.state_zeros(metas)
        return FusedLAMBState(count=jnp.zeros((), jnp.int32),
                              m=zeros,
                              v=tuple(jnp.zeros_like(z) for z in zeros))

    def _deltas(grads, state, params):
        """Shared LAMB math -> (metas, pbufs, group deltas, new state).
        Grads may arrive in low precision (fused path): the packed /
        phase-1 math upcasts per group."""
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0

        metas = multi_tensor.compute_metas(params, align=LANE,
                                           split_direct=True)
        gbufs = multi_tensor.group_buffers(grads, metas)
        pbufs = multi_tensor.group_buffers(params, metas)

        # Phase 1a: global grad norm + clip factor over ALL groups
        # (ref: apex/optimizers/fused_lamb.py:163-185 multi_tensor_l2norm
        # over the union of fp16+fp32 grads; padding gaps are zero).
        gnorm, clip = _global_grad_clip(gbufs, max_grad_norm)

        deltas, new_m, new_v = [], [], []
        for i, meta in enumerate(metas):
            adapted_u, m, v = _lamb_group_update(
                meta, gbufs[i], pbufs[i], state.m[i], state.v[i],
                gscale=clip, beta1=beta1, beta2=beta2, beta3=beta3,
                eps=eps, weight_decay=weight_decay, bc1=bc1, bc2=bc2,
                adam_w_mode=adam_w_mode, use_nvlamb=use_nvlamb,
                fused=fused_optim.group_use_pallas(use_pallas, meta))
            deltas.append(-lr * adapted_u)
            new_m.append(m)
            new_v.append(v)
        new_state = FusedLAMBState(count, tuple(new_m), tuple(new_v))
        return metas, pbufs, deltas, new_state

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params in update()")
        metas, _, deltas, new_state = _deltas(grads, state, params)
        leaves = jax.tree_util.tree_leaves(params)
        updates = multi_tensor.assemble(
            deltas, metas, out_dtypes=[l.dtype for l in leaves])
        return updates, new_state

    def fused_step(grads, state, params, model_params=None):
        """Single-pass step (+ optional model copy) — see
        FusedTransformation; the apply and the amp master->model
        writeback join the update's fusion scope."""
        if params is None:
            raise ValueError("fused_lamb requires params")
        metas, pbufs, deltas, new_state = _deltas(grads, state, params)
        model_leaves = (jax.tree_util.tree_leaves(model_params)
                        if model_params is not None else None)
        new_p, lowps = [], []
        for i, meta in enumerate(metas):
            p2 = (pbufs[i].astype(jnp.float32)
                  + deltas[i]).astype(pbufs[i].dtype)
            lowp_dt = _lowp_dtype_for(meta, pbufs[i], model_leaves)
            new_p.append(p2)
            lowps.append(p2.astype(lowp_dt) if lowp_dt is not None
                         else None)
        leaves = jax.tree_util.tree_leaves(params)
        new_params = multi_tensor.assemble(
            new_p, metas, out_dtypes=[l.dtype for l in leaves])
        model_out = None
        if model_leaves is not None:
            model_out = _assemble_model(new_p, lowps, metas,
                                        model_leaves)
        return new_params, new_state, model_out

    def pipeline_init(metas):
        """Persistent packed m/v (fp32 per group); the pipeline layout
        is LANE-aligned by construction, so the per-tensor trust-ratio
        reductions stay row-friendly."""
        zeros = tuple(jnp.zeros((m.padded,), jnp.float32) for m in metas)
        return FusedLAMBState(count=jnp.zeros((), jnp.int32), m=zeros,
                              v=tuple(jnp.zeros_like(z) for z in zeros))

    def pipeline_step(gbufs, state, master_bufs, metas, *,
                      grad_scale=1.0, grad_norm=None, finite=True):
        """LAMB over the persistent packed buffers: the global grad
        norm arrives pre-computed from the pipeline's fused norm sweep
        (``grad_norm`` — the unscaled norm, so ``max_grad_norm`` keeps
        its staged meaning); clip and amp unscale fold into phase 1's
        ``gscale``; the trust-ratio stage reuses the exact
        ``_lamb_group_update`` machinery of the staged path."""
        if grad_norm is None:
            # static-scaling amp elides the norm/finite sweep; LAMB's
            # clip always needs the unscaled norm, so derive it here
            # (one fused read — the same cost the staged path pays)
            grad_norm = fused_pipeline.packed_norm(gbufs, grad_scale)
        finite = jnp.asarray(finite)
        count = state.count + finite.astype(jnp.int32)
        stepped = state.count + 1
        lr = _lr_at(learning_rate, stepped)
        cf = stepped.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.float32(beta1) ** cf
            bc2 = 1.0 - jnp.float32(beta2) ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        gscale = jnp.asarray(grad_scale, jnp.float32) \
            * _grad_clip_factor(grad_norm, max_grad_norm)
        fused = fused_pipeline.use_pallas_pipeline(use_pallas)
        new_p, new_m, new_v, lowps = [], [], [], []
        for i, meta in enumerate(metas):
            adapted_u, m2, v2 = _lamb_group_update(
                meta, gbufs[i], master_bufs[i], state.m[i], state.v[i],
                gscale=gscale, beta1=beta1, beta2=beta2, beta3=beta3,
                eps=eps, weight_decay=weight_decay, bc1=bc1, bc2=bc2,
                adam_w_mode=adam_w_mode, use_nvlamb=use_nvlamb,
                fused=fused)
            p2 = jnp.where(finite, master_bufs[i] - lr * adapted_u,
                           master_bufs[i])
            lowp_dt = fused_pipeline.group_lowp_dtype(meta)
            new_p.append(p2)
            new_m.append(jnp.where(finite, m2, state.m[i]))
            new_v.append(jnp.where(finite, v2, state.v[i]))
            lowps.append(p2.astype(lowp_dt) if lowp_dt is not None
                         else p2)
        return (tuple(new_p),
                FusedLAMBState(count, tuple(new_m), tuple(new_v)),
                lowps)

    return FusedTransformation(init, update, fused_step,
                               pipeline_init, pipeline_step)


def _global_grad_clip(gbufs, max_norm):
    """Global grad norm over all packed groups + clip factor
    (ref: apex/optimizers/fused_lamb.py:163-185).  ``max_norm`` None/0
    disables clipping.  Mixed-precision LAMB passes
    ``max_grad_norm * loss_scale`` because its norm is of scaled grads
    (ref: fused_mixed_precision_lamb.py:182-184).

    Norm structure note (measured, BERT-large step): the per-leaf
    reduces below cost ~10.6 ms/step in the UNROLLED step (~400 small
    fusions x ~25 us dispatch + forced fp32 grad materialization) and a
    per-dtype concatenated variant won ~2 ms there — but inside the
    shipping ``lax.scan`` training form the concat REGRESSED the step
    134 -> 144 ms (the scan body re-copies the concat buffer every
    iteration).  Per-leaf is the better shipping form; see
    ROUND3_NOTES "LAMB step anatomy"."""
    gsq = sum(multi_tensor.sumsq(g) for g in gbufs)
    gnorm = jnp.sqrt(gsq)
    # The enable decision must be static (max_norm may be a traced value
    # when the caller scales it by a traced loss scale — pass None to
    # disable in that case); _grad_clip_factor makes it so.
    return gnorm, _grad_clip_factor(gnorm, max_norm)


def _lamb_group_update(meta, gbuf, pbuf, m, v, *, gscale, beta1, beta2,
                       beta3, eps, weight_decay, bc1, bc2, adam_w_mode,
                       use_nvlamb, fused):
    """Stage 1 (Pallas or jnp) + per-tensor trust ratio for one packed
    dtype group.  Returns ``(ratio*update, m_new, v_new)``; the caller
    applies the learning rate (and any overflow select).  Shared by
    FusedLAMB and FusedMixedPrecisionLamb so the clip/trust-ratio
    semantics can never diverge between them."""
    if fused:
        (gb, pb, mb, vb), restore = fused_optim.flatten_for_kernel(
            gbuf, pbuf, m, v)
        u, m_new, v_new = fused_optim.lamb_phase1(
            gb, pb, mb, vb, grad_scale=gscale, beta1=beta1, beta2=beta2,
            beta3=beta3, eps=eps, weight_decay=weight_decay,
            bias_correction1=bc1, bias_correction2=bc2,
            adam_w_mode=adam_w_mode)
        u, m_new, v_new = restore(u), restore(m_new), restore(v_new)
    else:
        u, m_new, v_new = _lamb_phase1_jnp(
            gbuf, pbuf, m, v, gscale, beta1, beta2, beta3, eps,
            weight_decay, bc1, bc2, adam_w_mode)
    ratio_elem = _trust_ratio_elem(meta, u, pbuf.astype(jnp.float32),
                                   use_nvlamb, weight_decay)
    return ratio_elem * u, m_new, v_new


def _trust_ratio_elem(meta, u, p32, use_nvlamb, weight_decay):
    """Phase 2 ratios: per-tensor param/update norms broadcast back per
    element (ref: multi_tensor_lamb.cu:230-330 LAMBStage2; per-tensor
    norms are the l2norm kernel's per_tensor=True output).  Packed
    groups use static-slice reductions — no segment ops, whose
    packed-length index arrays explode program size at BERT-large scale
    (see multi_tensor.per_tensor_sumsq).

    DIRECT groups (one native-shape leaf) reduce over the whole buffer
    — one scalar ratio, no packing."""
    if multi_tensor.is_direct(meta):
        if use_nvlamb or weight_decay != 0.0:
            p_n2 = jnp.sum(p32 * p32)
            u_n2 = jnp.sum(u.astype(jnp.float32) ** 2)
            return jnp.where(
                (p_n2 > 0) & (u_n2 > 0),
                jnp.sqrt(p_n2) / jnp.sqrt(jnp.maximum(u_n2, 1e-24)),
                1.0)
        return jnp.float32(1.0)
    if not (use_nvlamb or weight_decay != 0.0):
        # ref: multi_tensor_lamb.cu:258 — plain LAMB leaves zero-decay
        # params un-adapted.
        return jnp.float32(1.0)
    p_nsq = multi_tensor.per_tensor_sumsq(p32, meta)
    u_nsq = multi_tensor.per_tensor_sumsq(u, meta)
    ratio = jnp.where((p_nsq > 0) & (u_nsq > 0),
                      jnp.sqrt(p_nsq) / jnp.sqrt(
                          jnp.maximum(u_nsq, 1e-24)), 1.0)
    return multi_tensor.broadcast_per_tensor(ratio, meta)


def _lamb_phase1_jnp(g, p, m, v, gscale, b1, b2, b3, eps, wd, bc1, bc2,
                     adam_w_mode):
    """Stage-1 math in plain jnp (ref: csrc/multi_tensor_lamb.cu:60-200)."""
    g = g.astype(jnp.float32) * gscale
    p32 = p.astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p32
    m_new = b1 * m + b3 * g
    v_new = b2 * v + (1.0 - b2) * g * g
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        u = u + wd * p32
    return u, m_new, v_new


FusedLAMB = fused_lamb
