// Native prefetching batch loader — the runtime side of the input
// pipeline.
//
// TPU-native counterpart of the reference's input pipeline
// (ref: examples/imagenet/main_amp.py:228-236 torch.utils.data.DataLoader
// with worker processes; torch's loader core is C++).  Design differs
// deliberately: instead of worker *processes* deserializing Python
// objects, a C++ thread pool gathers batches out of a memory-mapped (or
// otherwise resident) dataset into a fixed ring of pinned host buffers,
// ahead of the training loop.  Python hands us raw pointers (numpy
// memmap) — this file owns scheduling, shuffling and assembly only, so
// it composes with any storage layer.
//
// Contract:
//   * loader_create(...) -> opaque handle; spawns `num_threads` workers
//     that fill a `prefetch_depth`-deep queue of assembled batches.
//   * loader_next(handle, out_x, out_y) copies the next ready batch into
//     caller buffers (blocking; GIL is released by ctypes during the
//     call, so workers and the training loop overlap).
//   * Epochs are implicit: after the last batch of an epoch the index
//     permutation is re-drawn from (seed, epoch) — deterministic across
//     runs and across loader restarts (resume = recreate + skip).
//   * drop_last semantics: only full batches are served
//     (n / batch per epoch), matching the bench/convergence drivers.
//
// Build: see apex_tpu/data/_build.py (single g++ -O3 -shared -fPIC
// -pthread invocation, no external deps).
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

struct Batch {
  int64_t epoch;
  int64_t index;  // batch index within the epoch
  std::vector<float> x;
  std::vector<int32_t> y;
};

struct Loader {
  // Dataset views (not owned).
  const uint8_t* images;   // n * item_elems elements, dtype below
  const int32_t* labels;   // n
  int64_t n;
  int64_t item_elems;      // elements per image (H*W*C)
  int dtype;               // 0 = float32, 1 = uint8 (normalized to f32)
  // Normalization applied when dtype == uint8: (v/255 - mean[c]) / std[c]
  // with c = flat_index % channels (NHWC).
  std::vector<float> mean, stdev;
  int64_t channels;

  int64_t batch;
  uint64_t seed;
  int64_t prefetch_depth;
  int64_t n_threads;  // fixed before workers start (workers.size() is
                      // not safe to read while loader_create populates)

  // Work scheduling: a single monotonically increasing batch cursor;
  // workers claim (epoch, index) pairs and insert assembled batches
  // into an ordered ready-map so consumers see epoch order even with
  // several workers racing.
  std::atomic<int64_t> cursor{0};
  int64_t batches_per_epoch;

  std::mutex mu;
  std::condition_variable ready_cv;
  std::condition_variable space_cv;
  // Batches completed but not yet consumed, keyed by global index.
  std::vector<Batch> ready;  // unordered; consumer searches for `next`
  int64_t next = 0;          // next global batch index to hand out
  bool stop = false;

  std::vector<std::thread> workers;

  // Per-epoch shuffle permutations, cached so each epoch's sort runs
  // once, not once per batch; only a sliding window of recent epochs
  // is kept.  The shuffle is sort-by-splitmix64-key — a deliberate
  // choice over Fisher-Yates: it has no stdlib-RNG dependence (libc++
  // and libstdc++ disagree on std::uniform_int_distribution), so the
  // Python fallback reproduces it bitwise with vectorized numpy (see
  // apex_tpu/data/loader.py _epoch_perm; parity is tested).
  std::mutex perm_mu;
  std::map<int64_t, std::shared_ptr<const std::vector<int64_t>>> perms;

  static uint64_t splitmix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  std::shared_ptr<const std::vector<int64_t>> perm_for(int64_t epoch) {
    std::lock_guard<std::mutex> lk(perm_mu);
    auto it = perms.find(epoch);
    if (it != perms.end()) return it->second;
    auto p = std::make_shared<std::vector<int64_t>>(n);
    for (int64_t i = 0; i < n; ++i) (*p)[i] = i;
    if (seed != 0) {  // seed 0 = no shuffle (sequential order)
      const uint64_t base =
          splitmix64(seed ^ (0x9e3779b97f4a7c15ull
                             * static_cast<uint64_t>(epoch + 1)));
      std::vector<uint64_t> key(n);
      for (int64_t i = 0; i < n; ++i)
        key[i] = splitmix64(base + static_cast<uint64_t>(i));
      std::stable_sort(p->begin(), p->end(),
                       [&](int64_t a, int64_t b) {
                         return key[a] < key[b];
                       });
    }
    perms[epoch] = p;
    while (perms.size() > 4) perms.erase(perms.begin());
    return p;
  }

  void assemble(Batch& b) {
    // Hold the shared_ptr for the whole assembly: the cache may evict
    // this epoch concurrently, and the map reference must not be the
    // only owner while we index into the vector.
    const std::shared_ptr<const std::vector<int64_t>> perm_owner =
        perm_for(b.epoch);
    const std::vector<int64_t>& perm = *perm_owner;
    b.x.resize(batch * item_elems);
    b.y.resize(batch);
    const int64_t base = b.index * batch;
    for (int64_t r = 0; r < batch; ++r) {
      const int64_t src = perm[base + r];
      b.y[r] = labels[src];
      float* dst = b.x.data() + r * item_elems;
      if (dtype == 0) {
        std::memcpy(dst, reinterpret_cast<const float*>(images) +
                             src * item_elems,
                    item_elems * sizeof(float));
      } else {
        const uint8_t* s = images + src * item_elems;
        for (int64_t j = 0; j < item_elems; ++j) {
          const int64_t c = channels ? (j % channels) : 0;
          const float m = mean.empty() ? 0.f : mean[c];
          const float sd = stdev.empty() ? 1.f : stdev[c];
          dst[j] = (static_cast<float>(s[j]) / 255.f - m) / sd;
        }
      }
    }
  }

  void worker() {
    for (;;) {
      const int64_t g = cursor.fetch_add(1);
      Batch b;
      b.epoch = g / batches_per_epoch;
      b.index = g % batches_per_epoch;
      assemble(b);
      std::unique_lock<std::mutex> lk(mu);
      // Bound memory: don't run further than prefetch_depth ahead of
      // the consumer.
      space_cv.wait(lk, [&] {
        return stop || g < next + prefetch_depth + n_threads;
      });
      if (stop) return;
      b.epoch = g;  // reuse field as the global index for ordering
      ready.push_back(std::move(b));
      ready_cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* loader_create(const void* images, const int32_t* labels, int64_t n,
                    int64_t item_elems, int dtype, const float* mean,
                    const float* stdev, int64_t channels, int64_t batch,
                    uint64_t seed, int64_t num_threads,
                    int64_t prefetch_depth, int64_t start_batch) {
  auto* L = new Loader();
  L->images = static_cast<const uint8_t*>(images);
  L->labels = labels;
  L->n = n;
  L->item_elems = item_elems;
  L->dtype = dtype;
  L->channels = channels;
  if (mean)
    L->mean.assign(mean, mean + channels);
  if (stdev)
    L->stdev.assign(stdev, stdev + channels);
  L->batch = batch;
  L->seed = seed;
  L->prefetch_depth = prefetch_depth < 1 ? 1 : prefetch_depth;
  L->batches_per_epoch = n / batch;
  if (L->batches_per_epoch < 1) {
    delete L;
    return nullptr;
  }
  // O(1) resume: start both the work cursor and the consumer cursor at
  // start_batch so no skipped batch is ever assembled.
  L->cursor.store(start_batch < 0 ? 0 : start_batch);
  L->next = start_batch < 0 ? 0 : start_batch;
  L->n_threads = num_threads < 1 ? 1 : num_threads;
  for (int64_t i = 0; i < L->n_threads; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

// Copies the next batch into out_x (batch*item_elems floats) and out_y
// (batch int32).  Returns the global batch index (>= 0), or -1 if the
// loader was destroyed while waiting.
int64_t loader_next(void* handle, float* out_x, int32_t* out_y) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  const int64_t want = L->next;
  Batch got;
  bool found = false;
  L->ready_cv.wait(lk, [&] {
    if (L->stop) return true;
    for (size_t i = 0; i < L->ready.size(); ++i) {
      if (L->ready[i].epoch == want) {  // .epoch reused as global index
        got = std::move(L->ready[i]);
        L->ready.erase(L->ready.begin() + i);
        found = true;
        return true;
      }
    }
    return false;
  });
  if (!found) return -1;  // shut down while waiting
  L->next = want + 1;
  L->space_cv.notify_all();
  lk.unlock();
  std::memcpy(out_x, got.x.data(), got.x.size() * sizeof(float));
  std::memcpy(out_y, got.y.data(), got.y.size() * sizeof(int32_t));
  return want;
}

int64_t loader_batches_per_epoch(void* handle) {
  return static_cast<Loader*>(handle)->batches_per_epoch;
}

// Contract: must not run concurrently with loader_next on the same
// handle from another thread — a blocked loader_next wakes and returns
// -1 on stop, but the caller must have returned before the handle is
// destroyed (the Python wrapper is single-consumer and serializes
// close() with iteration).
void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
  }
  L->space_cv.notify_all();
  L->ready_cv.notify_all();
  for (auto& w : L->workers) w.join();
  delete L;
}

}  // extern "C"
