"""RNN backend: cells scanned over time, stacked, optionally
bidirectional.

Parity surface for ``apex/RNN/RNNBackend.py`` (``RNNCell`` :232-330,
``stackedRNN`` :90-230, ``bidirectionalRNN`` :25-88).  The reference
steps cells in a Python loop over timesteps with mutable per-module
hidden state; the TPU form is ``jax.lax.scan`` over the time axis
(one compiled graph, weights resident, XLA pipelines the gate matmuls),
with hidden state threaded functionally.

Layout is (seq, batch, features) — the reference "always assumes input
is NOT batch_first" (ref :240).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp



def _uniform_init(hidden_size):
    """uniform(-1/sqrt(H), 1/sqrt(H)) — the reference's reset_parameters
    (ref: RNNBackend.py:291-296)."""
    stdev = 1.0 / (hidden_size ** 0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -stdev, stdev)

    return init


class RNNCell(nn.Module):
    """One recurrent layer scanned over time (ref: RNNBackend.py:232).

    ``gate_multiplier``: 4 for LSTM-like, 3 for GRU, 1 for plain RNN.
    ``n_hidden_states``: 2 for (h, c) cells, 1 for h-only.
    ``output_size != hidden_size`` adds the ``w_ho`` recurrent
    projection (ref :259-261).
    """

    gate_multiplier: int
    input_size: int
    hidden_size: int
    cell: Callable
    n_hidden_states: int = 2
    bias: bool = False
    output_size: Optional[int] = None
    multiplicative: bool = False   # adds w_mih/w_mhh (mLSTM)

    @property
    def out_size(self) -> int:
        return self.output_size or self.hidden_size

    def setup(self):
        init = _uniform_init(self.hidden_size)
        gate_size = self.gate_multiplier * self.hidden_size
        self.w_ih = self.param("w_ih", init, (gate_size, self.input_size))
        self.w_hh = self.param("w_hh", init, (gate_size, self.out_size))
        if self.out_size != self.hidden_size:
            self.w_ho = self.param("w_ho", init,
                                   (self.out_size, self.hidden_size))
        if self.bias:
            self.b_ih = self.param("b_ih", init, (gate_size,))
            self.b_hh = self.param("b_hh", init, (gate_size,))
        if self.multiplicative:
            self.w_mih = self.param("w_mih", init,
                                    (self.out_size, self.input_size))
            self.w_mhh = self.param("w_mhh", init,
                                    (self.out_size, self.out_size))

    def initial_state(self, bsz: int) -> Tuple[jnp.ndarray, ...]:
        """Zero hidden states (ref init_hidden :300-310).  State 0 is
        the output-sized h; the rest are hidden-sized (c)."""
        sizes = [self.out_size] + [self.hidden_size] * (
            self.n_hidden_states - 1)
        return tuple(jnp.zeros((bsz, s)) for s in sizes)

    def _step(self, x_t, hidden):
        b_ih = self.b_ih if self.bias else None
        b_hh = self.b_hh if self.bias else None
        if self.multiplicative:
            new = self.cell(x_t, hidden, self.w_ih, self.w_hh,
                            self.w_mih, self.w_mhh, b_ih=b_ih, b_hh=b_hh)
        else:
            new = self.cell(x_t, hidden, self.w_ih, self.w_hh,
                            b_ih=b_ih, b_hh=b_hh)
        new = list(new)
        if self.out_size != self.hidden_size:
            new[0] = new[0] @ self.w_ho.T
        return tuple(new)

    def __call__(self, inputs, initial_state=None, reverse: bool = False):
        """Scan over (T, B, I).  Returns (outputs (T, B, out), final
        hidden tuple).  ``reverse=True`` runs right-to-left and returns
        outputs re-reversed to input order (the backward half of the
        bidirectional wrapper, ref stackedRNN.forward(reverse=True))."""
        bsz = inputs.shape[1]
        h0 = initial_state or self.initial_state(bsz)

        def body(hidden, x_t):
            new = self._step(x_t, hidden)
            return new, new[0]

        xs = jnp.flip(inputs, 0) if reverse else inputs
        final, outs = jax.lax.scan(body, h0, xs)
        if reverse:
            outs = jnp.flip(outs, 0)
        return outs, final


class stackedRNN(nn.Module):
    """num_layers cells stacked, inter-layer dropout
    (ref: RNNBackend.py:90-230)."""

    cell_factory: Callable[[int], RNNCell]  # input_size -> cell module
    num_layers: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, inputs, initial_states=None, reverse: bool = False,
                 collect_hidden: bool = False, is_training: bool = True):
        x = inputs
        finals = []
        for i in range(self.num_layers):
            # layer 0 sees the input width; deeper layers see the
            # previous layer's output width (ref new_like(), :277-289)
            layer = self.cell_factory(x.shape[-1])
            x, final = layer(x, None if initial_states is None
                             else initial_states[i], reverse=reverse)
            finals.append(final)
            if self.dropout > 0.0 and is_training \
                    and i < self.num_layers - 1:
                keep = jax.random.bernoulli(
                    self.make_rng("dropout"), 1.0 - self.dropout, x.shape)
                x = jnp.where(keep, x / (1.0 - self.dropout), 0.0)
        hiddens = tuple(finals) if collect_hidden else (finals[-1],)
        return x, hiddens


class bidirectionalRNN(nn.Module):
    """Forward + backward stacks, features concatenated
    (ref: RNNBackend.py:25-88)."""

    cell_factory: Callable[[int], RNNCell]
    num_layers: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, inputs, collect_hidden: bool = False,
                 is_training: bool = True):
        fwd = stackedRNN(self.cell_factory, self.num_layers,
                         self.dropout, name="fwd")
        bwd = stackedRNN(self.cell_factory, self.num_layers,
                         self.dropout, name="bckwrd")
        fwd_out, fwd_h = fwd(inputs, collect_hidden=collect_hidden,
                             is_training=is_training)
        bwd_out, bwd_h = bwd(inputs, reverse=True,
                             collect_hidden=collect_hidden,
                             is_training=is_training)
        output = jnp.concatenate([fwd_out, bwd_out], axis=-1)
        hiddens = tuple(
            tuple(jnp.concatenate([f, b], axis=-1)
                  for f, b in zip(fh, bh))
            for fh, bh in zip(fwd_h, bwd_h))
        return output, hiddens
