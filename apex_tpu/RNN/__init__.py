"""Legacy RNN backend (parity with ``apex/RNN``): lax.scan cells.

Exports mirror ``apex/RNN/__init__.py`` (models + backend classes).
"""
from . import cells
from .models import GRU, LSTM, ReLU, Tanh, mLSTM, toRNNBackend
from .RNNBackend import RNNCell, bidirectionalRNN, stackedRNN

__all__ = [
    "LSTM",
    "GRU",
    "ReLU",
    "Tanh",
    "mLSTM",
    "toRNNBackend",
    "RNNCell",
    "stackedRNN",
    "bidirectionalRNN",
    "cells",
]
