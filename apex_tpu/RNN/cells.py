"""RNN cell step functions.

Parity surface for the cell math the reference pulls from
``torch.nn._functions.rnn`` (LSTMCell/GRUCell/RNNReLUCell/RNNTanhCell)
plus ``apex/RNN/cells.py:55-80`` (``mLSTMCell`` — multiplicative LSTM,
Krause et al. 2016).  Each cell is a pure function
``cell(x, hidden, weights) -> new_hidden`` stepped by ``lax.scan`` in
:mod:`.RNNBackend` (the TPU substitute for the reference's per-timestep
Python loop + fused pointwise CUDA epilogues — XLA fuses the gate
nonlinearities into the matmuls on its own).

Weight convention matches torch: ``w_ih`` (gates*H, I), ``w_hh``
(gates*H, H), gate order i,f,g,o for LSTM and r,z,n for GRU.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _linear(x, w, b=None):
    y = x @ w.T
    return y + b if b is not None else y


def lstm_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    """(h, c) -> (h', c'); gate order i,f,g,o (torch LSTMCell)."""
    hx, cx = hidden
    gates = _linear(x, w_ih, b_ih) + _linear(hx, w_hh, b_hh)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return hy, cy


def gru_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    """h -> h'; gate order r,z,n (torch GRUCell)."""
    (hx,) = hidden
    gi = _linear(x, w_ih, b_ih)
    gh = _linear(hx, w_hh, b_hh)
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return ((1.0 - z) * n + z * hx,)


def rnn_relu_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    (hx,) = hidden
    return (jax.nn.relu(_linear(x, w_ih, b_ih)
                        + _linear(hx, w_hh, b_hh)),)


def rnn_tanh_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    (hx,) = hidden
    return (jnp.tanh(_linear(x, w_ih, b_ih)
                     + _linear(hx, w_hh, b_hh)),)


def mlstm_cell(x, hidden, w_ih, w_hh, w_mih, w_mhh,
               b_ih=None, b_hh=None):
    """Multiplicative LSTM (ref: apex/RNN/cells.py:55-80): the hidden
    input to the gates is ``m = (x W_mih^T) * (h W_mhh^T)``."""
    hx, cx = hidden
    m = _linear(x, w_mih) * _linear(hx, w_mhh)
    gates = _linear(x, w_ih, b_ih) + _linear(m, w_hh, b_hh)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    cy = f * cx + i * g
    hy = o * jnp.tanh(cy)
    return hy, cy
