"""RNN model factories (parity with ``apex/RNN/models.py:8-53``)."""
from __future__ import annotations

from typing import Optional

from . import cells as _cells
from .RNNBackend import RNNCell, bidirectionalRNN, stackedRNN


def toRNNBackend(cell_factory, num_layers: int, bidirectional: bool = False,
                 dropout: float = 0.0):
    """ref: models.py:8-16."""
    if bidirectional:
        return bidirectionalRNN(cell_factory, num_layers, dropout=dropout)
    return stackedRNN(cell_factory, num_layers, dropout=dropout)


def _factory(gate_multiplier, hidden_size, cell, n_hidden_states, bias,
             output_size, multiplicative=False):
    def make(input_size: int) -> RNNCell:
        return RNNCell(gate_multiplier=gate_multiplier,
                       input_size=input_size, hidden_size=hidden_size,
                       cell=cell, n_hidden_states=n_hidden_states,
                       bias=bias, output_size=output_size,
                       multiplicative=multiplicative)
    return make


def LSTM(input_size, hidden_size, num_layers, bias=True,
         batch_first=False, dropout=0.0, bidirectional=False,
         output_size: Optional[int] = None):
    """ref: models.py:19-24.  ``batch_first`` unsupported (the backend
    is seq-major, ref RNNBackend.py:240)."""
    assert not batch_first, "backend is seq-major (ref RNNBackend:240)"
    del input_size  # width is taken from the data (ref new_like)
    return toRNNBackend(
        _factory(4, hidden_size, _cells.lstm_cell, 2, bias, output_size),
        num_layers, bidirectional, dropout=dropout)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False,
        output_size: Optional[int] = None):
    """ref: models.py:26-31."""
    assert not batch_first
    del input_size
    return toRNNBackend(
        _factory(3, hidden_size, _cells.gru_cell, 1, bias, output_size),
        num_layers, bidirectional, dropout=dropout)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False,
         output_size: Optional[int] = None):
    """ref: models.py:33-38."""
    assert not batch_first
    del input_size
    return toRNNBackend(
        _factory(1, hidden_size, _cells.rnn_relu_cell, 1, bias,
                 output_size),
        num_layers, bidirectional, dropout=dropout)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False,
         output_size: Optional[int] = None):
    """ref: models.py:40-45."""
    assert not batch_first
    del input_size
    return toRNNBackend(
        _factory(1, hidden_size, _cells.rnn_tanh_cell, 1, bias,
                 output_size),
        num_layers, bidirectional, dropout=dropout)


def mLSTM(input_size, hidden_size, num_layers, bias=True,
          batch_first=False, dropout=0.0, bidirectional=False,
          output_size: Optional[int] = None):
    """ref: models.py:47-53 + cells.py:12-53."""
    assert not batch_first
    del input_size
    return toRNNBackend(
        _factory(4, hidden_size, _cells.mlstm_cell, 2, bias, output_size,
                 multiplicative=True),
        num_layers, bidirectional, dropout=dropout)
