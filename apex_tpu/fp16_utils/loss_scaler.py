"""Legacy standalone loss scalers (``LossScaler`` / ``DynamicLossScaler``).

Parity surface for the reference's deprecated scalers
(ref: apex/fp16_utils/loss_scaler.py:10,47).  These are *host-side*
objects: ``has_overflow`` synchronises with the device each call, exactly
like the reference's ``.item()``-based overflow probe.  New code should
use the functional, sync-free :mod:`apex_tpu.amp.scaler` instead — these
classes exist so reference users migrating legacy scripts find the same
names and schedule semantics.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def to_python_float(t) -> float:
    """ref: apex/fp16_utils/loss_scaler.py:4 — host scalar extraction."""
    return float(jnp.asarray(t).reshape(()))


def _tree_has_inf_or_nan(tree: Any) -> bool:
    """Host-synced finite probe over a gradient pytree
    (ref: apex/fp16_utils/loss_scaler.py:30,92 ``_has_inf_or_nan``)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    if not leaves:
        return False
    finite = jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()
    return not bool(finite)


class LossScaler:
    """Static loss scale (ref: apex/fp16_utils/loss_scaler.py:10-44).

    ``update_scale`` never changes the scale; ``has_overflow`` always
    reports False (the static scaler trusts the user-chosen scale, as the
    reference does).
    """

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    def has_overflow(self, params) -> bool:
        return False

    @staticmethod
    def _has_inf_or_nan(x) -> bool:
        return False

    def update_scale(self, overflow: bool) -> None:
        pass

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads: Any) -> Any:
        """Multiply a gradient pytree by the scale (the reference's
        module-hook form, ref: loss_scaler.py:40)."""
        s = self.loss_scale
        return jax.tree_util.tree_map(lambda g: g * s, grads)

    def scale_loss(self, loss):
        """``loss * loss_scale`` — the functional stand-in for
        ``backward(loss)`` (JAX has no tape; differentiate the scaled
        loss, ref: loss_scaler.py:43)."""
        return loss * self.loss_scale

    # Legacy alias kept for call-site parity.
    backward = scale_loss


class DynamicLossScaler:
    """Dynamic loss scale with the reference's schedule
    (ref: apex/fp16_utils/loss_scaler.py:47-131): on overflow divide by
    ``scale_factor`` (floored at 1); grow by ``scale_factor`` every
    ``scale_window`` iterations since the last overflow.
    """

    def __init__(self, init_scale: float = 2.0 ** 32,
                 scale_factor: float = 2.0, scale_window: int = 1000):
        self.cur_scale = float(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)

    def has_overflow(self, params) -> bool:
        return _tree_has_inf_or_nan(params)

    @staticmethod
    def _has_inf_or_nan(x) -> bool:
        return _tree_has_inf_or_nan(x)

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        else:
            if (self.cur_iter - self.last_overflow_iter) \
                    % self.scale_window == 0:
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def scale_gradient(self, grads: Any) -> Any:
        s = self.loss_scale
        return jax.tree_util.tree_map(lambda g: g * s, grads)

    def scale_loss(self, loss):
        return loss * self.loss_scale

    backward = scale_loss
