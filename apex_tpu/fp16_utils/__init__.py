"""Legacy fp16 utilities (parity with ``apex/fp16_utils``).

Exports mirror ``apex/fp16_utils/__init__.py:1-16``: the deprecated
``FP16_Optimizer`` master-weight wrapper, the legacy standalone loss
scalers, and the network conversion helpers.  ``convert_network`` is live
(amp O2/O5 uses the same implementation via :mod:`apex_tpu.amp.cast`).
"""
from .fp16_optimizer import FP16_Optimizer
from .fp16util import (
    BN_convert_float,
    FP16Model,
    convert_network,
    fp16_model,
    master_copy,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    tofp16,
)
from .loss_scaler import DynamicLossScaler, LossScaler, to_python_float

__all__ = [
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
    "to_python_float",
    "BN_convert_float",
    "FP16Model",
    "fp16_model",
    "convert_network",
    "master_copy",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "network_to_half",
    "prep_param_lists",
    "tofp16",
]
