"""Legacy explicit master-weight optimizer wrapper (``FP16_Optimizer``).

Parity surface for ``apex/fp16_utils/fp16_optimizer.py:13-554``.  The
reference mutates a torch optimizer in place (swaps fp32 masters into
``param_groups``, stashes fp16 grads, applies loss scaling with a
host-synced overflow probe).  Here the same *workflow* — explicit masters,
``backward``/``update_master_grads``/``clip_master_grads``/``step`` call
sequence, overflow skip, state_dict round-trip — is provided as a
host-side class holding pytrees, with the per-step math jit-compiled.

This is the deprecated API kept for migration parity; new code should use
:func:`apex_tpu.amp.initialize` (the reference deprecates FP16_Optimizer
in favour of amp the same way).

Usage (mirrors the reference's example at fp16_optimizer.py docstring)::

    opt = FP16_Optimizer(params, optax_tx, static_loss_scale=128.0)
    loss, grads = jax.value_and_grad(lambda p: opt.scale(loss_fn(p)))(
        opt.model_params)
    opt.backward(grads)            # stash + unscale into master grads
    opt.clip_master_grads(1.0)     # optional
    opt.step()                     # skip-on-overflow, masters -> model
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from ..amp import cast as _cast
from .loss_scaler import DynamicLossScaler, LossScaler, to_python_float


class FP16_Optimizer:
    """Explicit master-weight wrapper over an optax transformation
    (ref: apex/fp16_utils/fp16_optimizer.py:14-108 ``__init__``)."""

    def __init__(self, params: Any, optimizer: optax.GradientTransformation,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = optimizer
        # Model params stay in the caller's dtype (fp16/bf16); masters are
        # the fp32 stepping copy (ref: fp16_optimizer.py:40-77).
        self.model_params = params
        self.master_params = _cast.master_copy(params)
        self.opt_state = optimizer.init(self.master_params)
        self.master_grads: Optional[Any] = None
        self._scaled_model_grads: Optional[Any] = None
        self.overflow = False
        self.first_closure_call_this_step = True
        self.verbose = verbose

        if dynamic_loss_scale:
            self.dynamic_loss_scale = True
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.dynamic_loss_scale = False
            self.loss_scaler = LossScaler(static_loss_scale)

        self._jit_step = jax.jit(self._step_impl)

    def maybe_print(self, msg: str) -> None:
        if self.verbose:
            print(msg)

    # -- gradient plumbing --------------------------------------------------

    def scale(self, loss):
        """Scale a loss before differentiation (the tape-free half of the
        reference's ``backward(loss)``, ref: fp16_optimizer.py:373-434)."""
        return self.loss_scaler.scale_loss(loss)

    def zero_grad(self, set_grads_to_None: bool = True) -> None:
        """Drop stashed grads (ref: fp16_optimizer.py:120-145; grads are
        functional here, so both modes just clear the stash)."""
        self.master_grads = None
        self._scaled_model_grads = None

    def backward(self, scaled_grads: Any,
                 update_master_grads: bool = True) -> None:
        """Accept gradients of the *scaled* loss w.r.t. ``model_params``
        (ref: fp16_optimizer.py:373-434 — autograd produces scaled fp16
        grads; here the caller differentiates ``self.scale(loss)``)."""
        self._scaled_model_grads = scaled_grads
        if update_master_grads:
            self.update_master_grads()

    def update_master_grads(self, scaled_grads: Optional[Any] = None) -> None:
        """Unscale stashed model grads into fp32 master grads and run the
        overflow probe (ref: fp16_optimizer.py:436-491)."""
        if scaled_grads is not None:
            self._scaled_model_grads = scaled_grads
        assert self._scaled_model_grads is not None, \
            "no stashed gradients: call backward() first"
        inv = 1.0 / self.loss_scaler.loss_scale
        self.master_grads = jax.tree_util.tree_map(
            lambda g: jnp.asarray(g).astype(jnp.float32) * inv,
            self._scaled_model_grads)
        self.overflow = self.loss_scaler.has_overflow(self.master_grads)
        # NOTE: the scale schedule advances in step(), not here — under
        # gradient accumulation this runs once per micro-batch but the
        # scaler must tick once per optimizer step (reference semantics:
        # _update_scale inside FP16_Optimizer.step).

    def clip_master_grads(self, max_norm: float,
                          norm_type: float = 2) -> float:
        """Clip master grads by global norm, return the pre-clip norm
        (ref: fp16_optimizer.py:185-207; only norm_type=2 is supported,
        matching every in-repo reference call site)."""
        if norm_type != 2:
            raise NotImplementedError(
                "clip_master_grads supports norm_type=2 only")
        if self.master_grads is None:
            return 0.0
        leaves = jax.tree_util.tree_leaves(self.master_grads)
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        self.master_grads = jax.tree_util.tree_map(
            lambda g: g * coef, self.master_grads)
        return to_python_float(norm)

    # -- stepping -----------------------------------------------------------

    def _step_impl(self, master_params, opt_state, master_grads,
                   model_params):
        updates, new_opt_state = self.optimizer.update(
            master_grads, opt_state, master_params)
        new_masters = optax.apply_updates(master_params, updates)
        new_model = _cast.restore_dtypes(new_masters, model_params)
        return new_masters, new_opt_state, new_model

    def step(self, closure=None):
        """Apply master grads unless this iteration overflowed
        (ref: fp16_optimizer.py:272-333; closure form :334-371)."""
        if closure is not None:
            return self._step_with_closure(closure)
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            self.maybe_print(
                f"Gradient overflow.  Skipping step, reducing loss scale "
                f"to {self.loss_scaler.loss_scale}")
            return None
        assert self.master_grads is not None, \
            "call backward()/update_master_grads() before step()"
        (self.master_params, self.opt_state,
         self.model_params) = self._jit_step(
            self.master_params, self.opt_state, self.master_grads,
            self.model_params)
        return None

    def _step_with_closure(self, closure):
        """Re-evaluation loop: the closure recomputes loss+grads against
        the current params (ref: fp16_optimizer.py:334-371).  The closure
        must call ``backward``/``update_master_grads`` itself and return
        the loss."""
        loss = closure()
        # Bounded retry: once the dynamic scale has backed off to its
        # floor (1.0), a still-non-finite gradient is a genuine NaN in
        # the model, not a scaling overflow — re-evaluating can never fix
        # it, so fail instead of spinning.
        retries = 0
        while self.overflow:
            self.loss_scaler.update_scale(True)
            scale = self.loss_scaler.loss_scale
            self.maybe_print(
                f"OVERFLOW within closure! Re-evaluating at loss "
                f"scale {scale}")
            if scale <= 1.0 or retries >= 64:
                raise FloatingPointError(
                    "gradients remain non-finite at loss scale "
                    f"{scale} after {retries} closure re-evaluations — "
                    "the model is producing NaN/inf independent of loss "
                    "scaling")
            retries += 1
            loss = closure()
        self.step()
        return loss

    # -- introspection / checkpointing --------------------------------------

    def inspect_master_grad_data(self):
        """ref: fp16_optimizer.py:493-526 — expose the master grads."""
        return self.master_grads

    def _get_loss_scale(self) -> float:
        return self.loss_scaler.loss_scale

    def _set_loss_scale(self, value: float) -> None:
        self.loss_scaler.cur_scale = float(value)

    loss_scale = property(_get_loss_scale, _set_loss_scale)

    def state_dict(self) -> dict:
        """ref: fp16_optimizer.py:209-228 — scaler config + overflow flag +
        masters + inner optimizer state."""
        return {
            "loss_scaler": self.loss_scaler,
            "dynamic_loss_scale": self.dynamic_loss_scale,
            "overflow": self.overflow,
            "first_closure_call_this_step":
                self.first_closure_call_this_step,
            "optimizer_state_dict": self.opt_state,
            "fp32_from_fp16": self.master_params,
        }

    def load_state_dict(self, state_dict: dict) -> None:
        """ref: fp16_optimizer.py:230-270 — restores masters *into* the
        wrapper; model params are refreshed from them so a checkpoint
        taken at any precision resumes bitwise."""
        self.loss_scaler = state_dict["loss_scaler"]
        self.dynamic_loss_scale = state_dict["dynamic_loss_scale"]
        self.overflow = state_dict["overflow"]
        self.first_closure_call_this_step = state_dict[
            "first_closure_call_this_step"]
        self.opt_state = state_dict["optimizer_state_dict"]
        self.master_params = state_dict["fp32_from_fp16"]
        self.model_params = _cast.restore_dtypes(self.master_params,
                                                 self.model_params)
