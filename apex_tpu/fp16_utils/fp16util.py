"""Precision conversion helpers for parameter pytrees.

Parity surface for ``apex/fp16_utils/fp16util.py:7-187`` (``tofp16``,
``BN_convert_float``, ``network_to_half``, ``convert_network``,
``prep_param_lists``, ``model_grads_to_master_grads``,
``master_params_to_model_params``, ``FP16Model``) re-expressed over
pytrees.  The structural isinstance-walk of the reference becomes pure
tree maps; the ``flat_master`` option (reference packs all masters into
one contiguous fp32 buffer, ref: fp16util.py:90-133) maps onto the
multi-tensor pack used by the fused optimizers.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..amp import cast as _cast
from ..ops import multi_tensor as _mt

# Re-exports: amp's live conversion machinery is the single implementation
# (the reference likewise has amp O2/O5 call into fp16util,
# ref: apex/amp/_initialize.py:176-182).
convert_network = _cast.convert_network
master_copy = _cast.master_copy


def tofp16(x: Any) -> Any:
    """Cast floating leaves to fp16 (ref: fp16util.py:7 ``tofp16`` module —
    an input-cast layer; here a pure function usable anywhere)."""
    return _cast.tree_cast(x, jnp.float16)


def BN_convert_float(params: Any,
                     bn_predicate: Optional[Callable] = None) -> Any:
    """Force batch-norm leaves back to fp32 in an otherwise-half tree
    (ref: fp16util.py:22-32 walks modules; here the BN leaves are found by
    path predicate)."""
    pred = bn_predicate or _cast.default_bn_predicate

    def _fix(path, x):
        x = jnp.asarray(x)
        if pred(path) and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(jnp.float32)
        return x

    return jax.tree_util.tree_map_with_path(_fix, params)


def network_to_half(params: Any) -> Any:
    """Half-cast keeping BN fp32 (ref: fp16util.py:35-41 wraps the network
    in ``tofp16 -> BN_convert_float(half net)``)."""
    return _cast.convert_network(params, jnp.float16,
                                 keep_batchnorm_fp32=True)


def fp16_model(apply_fn: Callable) -> Callable:
    """Wrap an apply function so inputs are cast to fp16 on entry
    (ref: fp16util.py:73-84 ``FP16Model`` — convert network + prepend
    ``tofp16``).  Cast the params with :func:`network_to_half` separately;
    this handles the input side."""
    def wrapped(params, *args, **kwargs):
        return apply_fn(params, *[tofp16(a) for a in args], **kwargs)
    return wrapped


# Class-style alias for API parity with the reference's module wrapper.
FP16Model = fp16_model


def prep_param_lists(params: Any, flat_master: bool = False
                     ) -> Tuple[Any, Any]:
    """Return ``(model_params, master_params)``: the model tree unchanged
    plus an fp32 master copy (ref: fp16util.py:90-133).

    With ``flat_master=True`` the masters are packed into contiguous fp32
    buffers (one per shape-compatible group) exactly as the reference
    flattens into one ``_flatten_dense_tensors`` buffer; the accompanying
    metas let :func:`master_params_to_model_params` unpack.
    """
    if flat_master:
        masters = _cast.master_copy(params)
        buffers, metas = _mt.pack_groups(masters)
        return params, (buffers, metas)
    return params, _cast.master_copy(params)


def model_grads_to_master_grads(model_grads: Any, master_params: Any,
                                flat_master: bool = False) -> Any:
    """fp32-cast model grads into master layout
    (ref: fp16util.py:136-156)."""
    grads32 = _cast.tree_cast(model_grads, jnp.float32)
    if flat_master:
        buffers, metas = _mt.pack_groups(grads32)
        return (buffers, metas)
    return grads32


def master_params_to_model_params(model_params: Any, master_params: Any,
                                  flat_master: bool = False) -> Any:
    """Emit model-dtype params from the masters
    (ref: fp16util.py:158-186).  Returns the new model tree (functional —
    no in-place copy)."""
    if flat_master:
        buffers, metas = master_params
        masters = _mt.unpack_groups(buffers, metas)
    else:
        masters = master_params
    return _cast.restore_dtypes(masters, model_params)
