"""Measured-profile ingestion: join device times onto the analytical map.

The reference's pyprof pipeline has two halves: ``parse`` reads the
*measured* per-kernel times out of the nvprof SQLite database
(ref: apex/pyprof/parse/nvvp.py:282 ``getKernelInfo`` joins the CUPTI
kernel table with markers) and ``prof`` attaches the analytical
flops/bytes models (ref: apex/pyprof/prof/output.py).  Round 1/2 built
the analytical half (:mod:`apex_tpu.pyprof.prof`); this module is the
measured half for TPU: it runs a function under ``jax.profiler``,
parses the xplane protobuf with xprof's ``framework_op_stats`` tool,
and JOINS measured per-op device microseconds onto the analytical
:class:`~apex_tpu.pyprof.prof.OpRecord` rows by (scope, op) name.

XLA fuses aggressively, so the join is name-canonical rather than 1:1:
measured rows carry the scope of their fusion's root op.  Rows that
match get both columns; measured rows with no analytical counterpart
(fusions, copies, infrastructure) are kept with empty analytical
columns so the TOTAL line always reconciles against the step's device
time.
"""
from __future__ import annotations

import collections
import glob
import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from .prof import OpRecord, analyze, device_spec

__all__ = ["MeasuredOp", "collect_device_ops", "canonical_key",
           "join_measured", "parse_op_stats", "profile_call",
           "profile_measured", "measured_report"]


@dataclass
class MeasuredOp:
    """One device-op row from the profiler, normalized PER ITERATION
    (both fields are divided by the profiled repeat count)."""
    name: str           # full framework op name
    op_type: str        # HLO/op category reported by xprof
    occurrences: float  # executions per iteration
    total_us: float     # per-iteration device self-time


_WRAPPER = re.compile(r"^(jit|pjit|closed_call|core_call|remat\d?|"
                      r"checkpoint|named)\(.*\)$")
# bare call-primitive segments the analytical walker inserts when it
# recurses into sub-jaxprs (prof._walk appends the primitive name)
_BARE_WRAPPERS = frozenset({"jit", "pjit", "closed_call", "core_call",
                            "remat", "remat2", "checkpoint",
                            "custom_vjp_call", "custom_jvp_call"})


def canonical_key(name: str) -> Tuple[str, str]:
    """(op, scope) canonical join key for a framework-op-stats name or
    an analytical record's scope/op pair.

    Drops ``jit(...)`` wrapper segments (both the profiler's
    ``jit(fn)`` form and the walker's bare ``pjit`` segments) and
    trailing ``.N`` op-number suffixes so
    ``jit(step)/jvp(Model)/mlp/dot_general.1`` and the jaxpr walker's
    ``jvp(Model)/mlp`` + ``dot_general`` meet at
    ``("dot_general", "jvp(Model)/mlp")``."""
    parts = [p for p in name.split("/") if p]
    parts = [p for p in parts
             if not _WRAPPER.match(p) and p not in _BARE_WRAPPERS]
    if not parts:
        return name, ""
    op = re.sub(r"\.\d+$", "", parts[-1])
    return op, "/".join(parts[:-1])


def collect_device_ops(fn: Callable, *args, iters: int = 3,
                       trace_dir: Optional[str] = None,
                       donate: bool = False,
                       **kwargs) -> List[MeasuredOp]:
    """Run ``jit(fn)`` under ``jax.profiler`` and return per-op device
    self-times (the reference's parse stage; xplane instead of nvvp).

    .. warning:: Totals come back **already normalized to one
       execution of fn** (the trace sums all ``iters`` dispatches and
       this function divides by ``iters``) — do NOT divide by
       ``iters`` again.  Calibration anchor: a 4096^3 bf16 matmul
       reports the same 718 us ~ 191 TF/s at iters 1/3/6.
       Occurrences INSIDE one program (e.g. a ``lax.scan`` body) still
       sum within the execution — for a per-step time, profile a
       K-step scan and divide the total by K.

    ``donate=True`` profiles a TRAIN-STEP-shaped ``fn``: every
    positional arg is donated and ``fn`` must return a tuple whose
    first ``len(args)`` entries are the args' replacements (extra
    returns like the loss are fine).  Without it, state-carrying steps
    hold two copies of params+optimizer state on device — at
    GPT-345M/O5 scale that alone exceeds HBM."""
    from xprof.convert import raw_to_tool_data as _r2t

    if donate:
        jitted = jax.jit(lambda *a: fn(*a, **kwargs),
                         donate_argnums=tuple(range(len(args))))
    else:
        jitted = jax.jit(lambda *a: fn(*a, **kwargs))

    def run(args):
        out = jitted(*args)
        if donate:
            if not isinstance(out, (tuple, list)) or len(out) < len(args):
                raise TypeError(
                    "donate=True requires fn to return a tuple whose "
                    f"first {len(args)} entries replace the donated args; "
                    f"got {type(out).__name__}"
                    + ("" if not isinstance(out, (tuple, list))
                       else f" of length {len(out)}"))
            args = tuple(out[:len(args)])
        return out, args

    out, args = run(args)
    jax.block_until_ready(out)

    def loop():
        out = None
        a = args
        for _ in range(iters):
            out, a = run(a)
        return out

    data = _traced_op_stats(loop, trace_dir)
    return parse_op_stats(data, iters=iters)


def _traced_op_stats(loop: Callable[[], object],
                     trace_dir: Optional[str]):
    """Shared tracing core: run ``loop()`` under ``jax.profiler`` and
    return the raw framework_op_stats tool output."""
    from xprof.convert import raw_to_tool_data as _r2t

    tdir = trace_dir or tempfile.mkdtemp(prefix="apex_tpu_prof_")
    try:
        jax.profiler.start_trace(tdir)
        try:
            jax.block_until_ready(loop())
        finally:
            # always close the process-global profiler session, or every
            # later collect in this process fails with "only one
            # profiler session can be active"
            jax.profiler.stop_trace()
        xplanes = glob.glob(os.path.join(tdir, "**", "*.xplane.pb"),
                            recursive=True)
        if not xplanes:
            raise RuntimeError(f"no xplane.pb written under {tdir}")
        data, _ = _r2t.xspace_to_tool_data(xplanes,
                                           "framework_op_stats", {})
        return data
    finally:
        if trace_dir is None:
            shutil.rmtree(tdir, ignore_errors=True)


def profile_call(thunk: Callable[[], object], iters: int = 1,
                 trace_dir: Optional[str] = None) -> List[MeasuredOp]:
    """Trace ``iters`` calls of an ALREADY-COMPILED zero-arg callable
    and return per-op device self-times normalized to one call.

    Unlike :func:`collect_device_ops` this wraps nothing in a new
    ``jax.jit`` — use it to profile an existing executable with its
    live (possibly donated) buffers without paying a retrace/recompile
    (the bench's optimizer rows re-used their timed executables this
    way).  The caller is responsible for warmup (typically the timing
    loop that just ran).

    .. note:: With ``iters > 1`` a thunk over a DONATING executable
       must rebind its own operands from each call's outputs (e.g. the
       bench's rn50 ``holder`` pattern) — a closure over fixed donated
       buffers works only at ``iters=1``; the second call would
       dispatch on deleted buffers."""

    def loop():
        out = None
        for _ in range(iters):
            out = thunk()
        return out

    data = _traced_op_stats(loop, trace_dir)
    return parse_op_stats(data, iters=iters)


def parse_op_stats(data, iters: int = 1) -> List[MeasuredOp]:
    """Parse xprof's ``framework_op_stats`` tool output (gviz JSON —
    bytes or str, a table or a list of tables) into device
    :class:`MeasuredOp` rows, normalized to one execution.

    Split out of :func:`collect_device_ops` so the parse is
    regression-testable without TPU hardware: a recorded tool output
    lives at ``tests/data/framework_op_stats_gpt.json`` (the round-4
    GPT-345M train-step capture)."""
    text = data.decode() if isinstance(data, bytes) else data
    tables = json.loads(text)
    table = tables[0] if isinstance(tables, list) else tables
    cols = [c["label"] for c in table["cols"]]
    rows = [dict(zip(cols, [c.get("v") for c in r["c"]]))
            for r in table["rows"]]
    out_rows = []
    for r in rows:
        if r.get("Host/device") != "Device":
            continue
        name = r.get("Operation Name") or ""
        if name == "IDLE":
            continue
        out_rows.append(MeasuredOp(
            name=name,
            op_type=r.get("Operation Type") or "",
            occurrences=float(r.get("#Occurrences") or 0) / iters,
            total_us=float(r.get("Total self-time (us)") or 0.0) / iters,
        ))
    return out_rows


@dataclass
class JoinedRow:
    op: str
    scope: str
    flops: float            # analytical (0 when measured-only)
    bytes: float
    est_us: float           # roofline estimate
    measured_us: float      # device self-time (0 when unmatched)
    matched: bool


def join_measured(records: Sequence[OpRecord],
                  measured: Sequence[MeasuredOp],
                  spec=None) -> List[JoinedRow]:
    """Join analytical rows with measured rows on the canonical
    (op, scope) key, aggregating both sides first (XLA fuses; the jaxpr
    walker unrolls — neither side is 1:1)."""
    spec = spec or device_spec()
    ana: Dict[Tuple[str, str], dict] = collections.defaultdict(
        lambda: {"flops": 0.0, "bytes": 0.0, "est": 0.0})
    for r in records:
        k = canonical_key((r.scope + "/" if r.scope else "") + r.op)
        a = ana[k]
        a["flops"] += r.flops
        a["bytes"] += r.bytes
        a["est"] += r.est_time_us(spec)
    mea: Dict[Tuple[str, str], float] = collections.defaultdict(float)
    for m in measured:
        mea[canonical_key(m.name)] += m.total_us

    rows: List[JoinedRow] = []
    consumed: set = set()
    # Pass 2: measured rows whose op the walker RECURSED into
    # (pallas_call bodies, custom calls) carry the call's scope while
    # the analytical rows live under scope/op/...; attribute such a
    # measured row to the aggregate of its (unconsumed) subtree.
    leftovers = {}
    for k, mus in list(mea.items()):
        if k in ana:
            continue
        prefix = (k[1] + "/" if k[1] else "") + k[0]
        subtree = [k2 for k2 in ana
                   if k2 not in consumed
                   and (k2[1] == prefix
                        or k2[1].startswith(prefix + "/"))]
        if not subtree and k[1]:
            # XLA sometimes hoists an op to its enclosing scope (layout
            # transposes/concats); attribute to same-op rows under the
            # measured scope's subtree ('/'-bounded: 'layer/attn' must
            # not swallow 'layer/attn2')
            subtree = [k2 for k2 in ana
                       if k2 not in consumed and k2[0] == k[0]
                       and (k2[1] == k[1]
                            or k2[1].startswith(k[1] + "/"))]
        if subtree:
            agg = {"flops": 0.0, "bytes": 0.0, "est": 0.0}
            for k2 in subtree:
                for f in agg:
                    agg[f] += ana[k2][f]
                consumed.add(k2)
            rows.append(JoinedRow(op=k[0], scope=k[1],
                                  flops=agg["flops"],
                                  bytes=agg["bytes"],
                                  est_us=agg["est"], measured_us=mus,
                                  matched=True))
        else:
            leftovers[k] = mus
        del mea[k]

    for k, a in ana.items():
        mus = mea.pop(k, 0.0)
        if k in consumed:
            if mus > 0.0:
                # the analytical side was attributed to a subtree row;
                # keep this row's MEASURED time (flops zeroed) so the
                # TOTAL still reconciles against device time
                rows.append(JoinedRow(op=k[0], scope=k[1], flops=0.0,
                                      bytes=0.0, est_us=0.0,
                                      measured_us=mus, matched=True))
            continue
        rows.append(JoinedRow(op=k[0], scope=k[1], flops=a["flops"],
                              bytes=a["bytes"], est_us=a["est"],
                              measured_us=mus, matched=mus > 0.0))
    for k, mus in leftovers.items():
        rows.append(JoinedRow(op=k[0], scope=k[1], flops=0.0, bytes=0.0,
                              est_us=0.0, measured_us=mus,
                              matched=False))
    rows.sort(key=lambda r: -(r.measured_us or r.est_us))
    return rows


def measured_report(rows: Sequence[JoinedRow], top: Optional[int] = None
                    ) -> str:
    """TSV: op, scope, flops, bytes, est_us, measured_us, achieved
    TFLOP/s (the reference's output.py table with the measured column
    the nvvp parser supplied)."""
    shown = rows[:top] if top else rows
    lines = ["op\tscope\tflops\tbytes\test_us\tmeasured_us\t"
             "achieved_tflops"]
    for r in shown:
        tf = (r.flops / r.measured_us * 1e-6) if r.measured_us else 0.0
        lines.append(f"{r.op}\t{r.scope}\t{r.flops:.3e}\t{r.bytes:.3e}"
                     f"\t{r.est_us:.1f}\t{r.measured_us:.1f}\t{tf:.1f}")
    tot_meas = sum(r.measured_us for r in rows)
    tot_matched = sum(r.measured_us for r in rows if r.flops > 0)
    lines.append(f"TOTAL\t\t{sum(r.flops for r in rows):.3e}\t"
                 f"{sum(r.bytes for r in rows):.3e}\t"
                 f"{sum(r.est_us for r in rows):.1f}\t{tot_meas:.1f}\t")
    pct = 100.0 * tot_matched / tot_meas if tot_meas else 0.0
    lines.append(f"# measured device time on rows with analytical "
                 f"flops: {tot_matched:.1f} us ({pct:.1f}% of device "
                 f"total)")
    return "\n".join(lines)


def profile_measured(fn: Callable, *args, iters: int = 3,
                     **kwargs) -> List[JoinedRow]:
    """One-call pipeline: analytical walk + profiled run + join.

    Returns rows where hot ops carry BOTH analytical flops/bytes and
    measured device microseconds; print with :func:`measured_report`.
    """
    records = analyze(fn, *args, **kwargs)
    measured = collect_device_ops(fn, *args, iters=iters, **kwargs)
    return join_measured(records, measured)
