"""Analytical per-op FLOP/byte attribution over jaxprs.

Parity surface for ``apex/pyprof/prof/`` (~30 files of per-op analytical
models: conv at prof/conv.py:236, blas at prof/blas.py:340, pointwise,
reductions, index/slice/join/mutate at :419) and the ``pyprof.parse``
pipeline.  The reference reconstructs op identity from NVTX markers in an
nvprof SQLite dump; on TPU the program IS available as a jaxpr, so the
analyzer walks it directly — no marker round-trip — and attributes each
equation to its ``named_scope`` stack (the annotations from
:mod:`apex_tpu.pyprof.nvtx`).

Output: a list of :class:`OpRecord` and a TSV report (the reference's
``prof/output.py`` table), with FLOPs, bytes moved, arithmetic intensity,
and a roofline time estimate against the device's peak specs.  Estimated
time is analytical (the reference's is too — measured kernel time comes
from nvprof; here the measured cross-check is ``measure()``'s wall-clock
on the whole function, plus XLA's own ``cost_analysis``).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend import core as jcore

# ---------------------------------------------------------------------------
# Device roofline specs (public figures; used only for the time-estimate
# column, clearly labeled as analytical).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    name: str
    peak_bf16_tflops: float
    peak_hbm_gbps: float


_DEVICE_SPECS = {
    # Google-published peak numbers.
    "v5 lite": DeviceSpec("TPU v5e", 197.0, 819.0),
    "v5e": DeviceSpec("TPU v5e", 197.0, 819.0),
    "v5p": DeviceSpec("TPU v5p", 459.0, 2765.0),
    "v4": DeviceSpec("TPU v4", 275.0, 1228.0),
    "v6": DeviceSpec("TPU v6e", 918.0, 1640.0),
    "cpu": DeviceSpec("host CPU", 1.0, 50.0),
}


def device_spec(device=None) -> DeviceSpec:
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, spec in _DEVICE_SPECS.items():
        if key in kind:
            return spec
    return _DEVICE_SPECS["cpu"]


# ---------------------------------------------------------------------------
# Records
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OpRecord:
    """One jaxpr equation's cost attribution (the reference's per-kernel
    TSV row, ref: apex/pyprof/prof/output.py fields: idx, dir, op, params,
    flops, bytes, silicon time)."""

    index: int
    op: str                   # primitive name
    scope: str                # named_scope stack ("" at top level)
    params: str               # shape summary, e.g. "(128,512)x(512,512)"
    flops: float              # multiply-add counted as 2, reference style
    bytes: float              # operand + result bytes
    count: int = 1            # trip multiplier (scan length etc.)

    @property
    def intensity(self) -> float:
        return self.flops / self.bytes if self.bytes else 0.0

    def est_time_us(self, spec: DeviceSpec) -> float:
        if not (self.flops or self.bytes):
            return 0.0
        t_flops = self.flops / (spec.peak_bf16_tflops * 1e12)
        t_bytes = self.bytes / (spec.peak_hbm_gbps * 1e9)
        return max(t_flops, t_bytes) * 1e6


def _nbytes(aval) -> float:
    try:
        return float(np.prod(aval.shape, dtype=np.float64)
                     * jnp.dtype(aval.dtype).itemsize)
    except (TypeError, ValueError, AttributeError, OverflowError):
        return 0.0


def _numel(aval) -> float:
    try:
        return float(np.prod(aval.shape, dtype=np.float64))
    except (TypeError, ValueError, AttributeError, OverflowError):
        return 0.0


def _shape_str(avals) -> str:
    def one(a):
        try:
            return "(" + ",".join(str(int(d)) for d in a.shape) + ")"
        except (TypeError, ValueError, AttributeError):
            return "?"
    return "x".join(one(a) for a in avals)


# ---------------------------------------------------------------------------
# Per-primitive FLOP models (ref: apex/pyprof/prof/{blas,conv,pointwise,
# reductions,...}.py analytical formulas)
# ---------------------------------------------------------------------------

def _dot_general_flops(eqn) -> float:
    """2*M*N*K*batch (ref: prof/blas.py:340 GEMM model)."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = np.prod([lhs.shape[i] for i in lb], dtype=np.float64) if lb \
        else 1.0
    k = np.prod([lhs.shape[i] for i in lc], dtype=np.float64) if lc else 1.0
    m = np.prod([lhs.shape[i] for i in range(len(lhs.shape))
                 if i not in tuple(lc) + tuple(lb)], dtype=np.float64)
    n = np.prod([rhs.shape[i] for i in range(len(rhs.shape))
                 if i not in tuple(rc) + tuple(rb)], dtype=np.float64)
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    """2 * out_numel * (Cin/groups) * prod(kernel_spatial)
    (ref: prof/conv.py:236 conv model).  XLA's kernel in-feature dim
    (rhs_spec[1]) is already Cin/feature_group_count, so grouping needs
    no extra division here."""
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    k_spatial = np.prod([rhs.shape[i] for i in dn.rhs_spec[2:]],
                        dtype=np.float64)
    cin_per_group = rhs.shape[dn.rhs_spec[1]]
    return 2.0 * _numel(out) * cin_per_group * k_spatial


_TRANSCENDENTAL = {
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan", "erf",
    "erfc", "erf_inv", "logistic", "rsqrt", "sqrt", "pow", "cbrt",
    "atan2", "digamma", "lgamma",
}
_POINTWISE_2 = {"div", "rem"}
_CHEAP_POINTWISE = {
    "add", "sub", "mul", "max", "min", "neg", "abs", "sign", "floor",
    "ceil", "round", "and", "or", "not", "xor", "eq", "ne", "lt", "le",
    "gt", "ge", "select_n", "clamp", "nextafter", "integer_pow",
    "add_any", "square",
}
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "reduce_precision", "cumsum",
    "cummax", "cummin", "cumprod", "cumlogsumexp",
}
_DATA_MOVEMENT = {
    "reshape", "transpose", "broadcast_in_dim", "concatenate", "slice",
    "dynamic_slice", "dynamic_update_slice", "gather", "scatter",
    "scatter-add", "scatter_add", "rev", "pad", "squeeze", "convert_element_type",
    "bitcast_convert_type", "copy", "iota", "split",
}
_COLLECTIVES = {
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pbroadcast",
}


def _eqn_cost(eqn) -> Tuple[float, float]:
    """(flops, bytes) for one equation."""
    name = eqn.primitive.name
    in_bytes = sum(_nbytes(v.aval) for v in eqn.invars
                   if hasattr(v, "aval"))
    out_bytes = sum(_nbytes(v.aval) for v in eqn.outvars)
    bytes_ = in_bytes + out_bytes
    out_numel = sum(_numel(v.aval) for v in eqn.outvars)

    if name == "dot_general":
        return _dot_general_flops(eqn), bytes_
    if name == "conv_general_dilated":
        return _conv_flops(eqn), bytes_
    if name in _TRANSCENDENTAL:
        # transcendental ~ 10 flops/elem (reference's pointwise op table
        # distinguishes transcendental cost, ref: prof/pointwise.py)
        return 10.0 * out_numel, bytes_
    if name in _POINTWISE_2:
        return 2.0 * out_numel, bytes_
    if name in _CHEAP_POINTWISE:
        return 1.0 * out_numel, bytes_
    if name in _REDUCTIONS:
        in_numel = sum(_numel(v.aval) for v in eqn.invars
                       if hasattr(v, "aval"))
        return in_numel, bytes_
    if name in _DATA_MOVEMENT or name in _COLLECTIVES:
        return 0.0, bytes_
    return 0.0, bytes_


# Sub-jaxpr trip-count handling -------------------------------------------

# Branch-cost memo for cond selection.  Keyed by jaxpr object id; the
# jaxpr itself is stored as the value's first element so the id cannot be
# recycled while the memo is alive.  Without this, nested conds make the
# analyzer re-walk branches exponentially.
_BRANCH_FLOPS_MEMO: dict = {}


def _branch_flops(closed) -> float:
    key = id(closed)
    hit = _BRANCH_FLOPS_MEMO.get(key)
    if hit is not None and hit[0] is closed:
        return hit[1]
    recs = _walk(closed, scope="", mult=1, out=None)
    cost = sum(r.flops for r in recs)
    _BRANCH_FLOPS_MEMO[key] = (closed, cost)
    if len(_BRANCH_FLOPS_MEMO) > 4096:
        _BRANCH_FLOPS_MEMO.clear()
    return cost


def _subjaxprs(eqn):
    """Yield (closed_jaxpr, trip_count) pairs for call-like primitives."""
    name = eqn.primitive.name
    p = eqn.params
    if name == "scan":
        yield p["jaxpr"], int(p["length"])
        return
    if name == "while":
        # unknown trip count: count one iteration, scope-tagged
        yield p["body_jaxpr"], 1
        return
    if name == "cond":
        # worst-case branch (reference reports kernels actually run; a
        # static analyzer takes the max)
        branches = p["branches"]
        costs = [_branch_flops(br) for br in branches]
        best = int(np.argmax(costs)) if branches else 0
        yield branches[best], 1
        return
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in p:
            sub = p[key]
            yield sub, 1
            return


def _walk(jaxpr, scope: str, mult: int,
          out: Optional[List[OpRecord]],
          counter: Optional[List[int]] = None) -> List[OpRecord]:
    if out is None:
        out = []
    if counter is None:
        counter = [0]
    if isinstance(jaxpr, jcore.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        subs = list(_subjaxprs(eqn))
        eqn_scope = scope
        try:
            ns = str(eqn.source_info.name_stack)
            if ns:
                eqn_scope = (scope + "/" + ns) if scope else ns
        except AttributeError:
            pass  # jaxpr without source info (synthetic/cached)
        if subs:
            inner = f"{eqn.primitive.name}"
            for sub, trips in subs:
                _walk(sub,
                      scope=(eqn_scope + "/" + inner) if eqn_scope
                      else inner,
                      mult=mult * trips, out=out,
                      counter=counter)
            continue
        flops, bytes_ = _eqn_cost(eqn)
        rec = OpRecord(
            index=counter[0],
            op=eqn.primitive.name,
            scope=eqn_scope,
            params=_shape_str([v.aval for v in eqn.invars
                               if hasattr(v, "aval")]),
            flops=flops * mult,
            bytes=bytes_ * mult,
            count=mult,
        )
        counter[0] += 1
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def analyze(fn: Callable, *args, **kwargs) -> List[OpRecord]:
    """Trace ``fn`` and return per-op cost records
    (the reference pipeline's ``parse`` + ``prof`` stages in one step)."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return _walk(closed, scope="", mult=1, out=None)


def total_flops(records: Sequence[OpRecord]) -> float:
    return sum(r.flops for r in records)


def total_bytes(records: Sequence[OpRecord]) -> float:
    return sum(r.bytes for r in records)


def summary_by_op(records: Sequence[OpRecord]) -> Dict[str, dict]:
    """Aggregate flops/bytes per primitive (the reference's per-op-class
    rollup)."""
    agg: Dict[str, dict] = {}
    for r in records:
        a = agg.setdefault(r.op, {"calls": 0, "flops": 0.0, "bytes": 0.0})
        a["calls"] += r.count
        a["flops"] += r.flops
        a["bytes"] += r.bytes
    return dict(sorted(agg.items(), key=lambda kv: -kv[1]["flops"]))


def report(records: Sequence[OpRecord], spec: Optional[DeviceSpec] = None,
           top: Optional[int] = None) -> str:
    """TSV report, one row per op (ref: apex/pyprof/prof/output.py).

    Columns: idx, op, scope, params, count, flops, bytes, intensity
    (flops/byte), est_us (roofline vs ``spec``).
    """
    spec = spec or device_spec()
    rows = sorted(records, key=lambda r: -r.flops)
    if top:
        rows = rows[:top]
    lines = ["idx\top\tscope\tparams\tcount\tflops\tbytes\t"
             "intensity\test_us"]
    for r in rows:
        lines.append(
            f"{r.index}\t{r.op}\t{r.scope}\t{r.params}\t{r.count}\t"
            f"{r.flops:.3e}\t{r.bytes:.3e}\t{r.intensity:.2f}\t"
            f"{r.est_time_us(spec):.2f}")
    ftot, btot = total_flops(records), total_bytes(records)
    est = sum(r.est_time_us(spec) for r in records)
    lines.append(f"TOTAL\t\t\t\t\t{ftot:.3e}\t{btot:.3e}\t"
                 f"{(ftot / btot if btot else 0):.2f}\t{est:.2f}")
    return "\n".join(lines)


def xla_cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """XLA's own cost model for cross-checking the analytical walker
    (flops here are post-fusion/optimization)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def measure(fn: Callable, *args, iters: int = 10, **kwargs) -> float:
    """Measured wall-clock seconds per call (device-synced), the
    empirical cross-check column."""
    import time

    jitted = jax.jit(fn)
    out = jitted(*args, **kwargs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jitted(*args, **kwargs)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters
