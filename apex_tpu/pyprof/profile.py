"""Profiler session façade: ``jax.profiler`` traces wired to the Timers.

Parity surface for the reference's profiling *workflow* — run N warmup
iterations, switch the profiler on, run the profiled window, emit ranges
(ref: examples/imagenet/main_amp.py:335-362 ``--prof`` window with
``cudaProfilerStart/Stop`` + nvtx push/pop; apex/pyprof/parse consumes the
dump offline).  On TPU the dump is a TensorBoard-loadable trace directory
produced by ``jax.profiler``; op-level attribution comes from
:mod:`apex_tpu.pyprof.prof` instead of an offline SQLite parse.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

# Timers are imported lazily inside trace(): pyprof.prof.analyze() must be
# usable without dragging in the transformer stack.


@contextlib.contextmanager
def trace(logdir: str, timers=None,
          name: str = "profile-window",
          create_perfetto_link: bool = False) -> Iterator[None]:
    """Profiled window: starts a ``jax.profiler`` trace into ``logdir``
    and times the window on the shared :class:`Timers` registry (so the
    trace wall-time shows up next to the schedule timers the transformer
    stack already logs).

    Usage (the imagenet ``--prof`` pattern)::

        for it, batch in enumerate(loader):
            if it == args.prof_start:
                ctx = pyprof.trace("/tmp/tb"); ctx.__enter__()
            ...
    """
    from ..transformer.pipeline_parallel.utils import get_timers

    t = (timers or get_timers())(name)
    # Start the timer FIRST: if it is already running (shared registry),
    # this raises before the profiler starts, so a timer error can never
    # leak a running profiler session.
    t.start()
    try:
        jax.profiler.start_trace(
            logdir, create_perfetto_link=create_perfetto_link)
    except Exception:
        t.stop()
        raise
    try:
        yield
    finally:
        t.stop()
        jax.profiler.stop_trace()


class ProfileWindow:
    """Iteration-window profiler switch (ref: main_amp.py:335-345 —
    ``--prof`` starts at iteration A, stops at B).

    Besides the fixed CLI-configured window, this is the mechanism
    behind on-demand mid-run capture:
    :class:`apex_tpu.monitor.tracing.CaptureTrigger` opens one of
    these at the triggering step boundary (file touch / SIGUSR1 /
    ``wall_device_ratio`` auto-capture) and drives :meth:`step` until
    the window closes — see docs/api/observability.md."""

    def __init__(self, logdir: str, start_iter: int, stop_iter: int,
                 timers=None):
        self.logdir = logdir
        self.start_iter = int(start_iter)
        self.stop_iter = int(stop_iter)
        self.timers = timers
        self._ctx: Optional[contextlib.AbstractContextManager] = None

    def step(self, iteration: int) -> None:
        """Call once per training iteration.  The window is
        [start_iter, stop_iter); an empty window never opens, and an
        iteration counter that jumps past stop_iter (checkpoint resume)
        still closes the trace."""
        if (self._ctx is None and iteration == self.start_iter
                and iteration < self.stop_iter):
            self._ctx = trace(self.logdir, timers=self.timers)
            self._ctx.__enter__()
        if self._ctx is not None and iteration >= self.stop_iter:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    @property
    def active(self) -> bool:
        """True while the profiler trace is open (between the start
        and stop iterations) — the state the capture trigger's
        exactly-once tests pin down."""
        return self._ctx is not None

    def close(self) -> None:
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None


def server(port: int = 9999):
    """Start the on-demand profiling server (TensorBoard 'capture
    profile' target) — the always-on alternative to a fixed window."""
    return jax.profiler.start_server(port)
