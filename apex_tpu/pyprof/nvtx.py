"""Op annotation: profiler ranges encoding call site + arg shapes/dtypes.

Parity surface for ``apex/pyprof/nvtx/nvmarker.py:1-222``, which
monkey-patches ~all of ``torch.*`` to push NVTX ranges whose message is a
JSON dict of {module, function, args shapes/dtypes}.  JAX is functional —
there is no global namespace to patch — so the same capability is a
*decorator/wrapper* API: :func:`annotate` wraps any function so each call
runs under a :func:`jax.named_scope` (visible in XLA HLO op names and in
``jax.profiler`` traces) carrying the serialized call signature, and
:func:`push`/:func:`pop` / :func:`range` give the manual-range API
(``torch.cuda.nvtx.range_push`` parity, used by the reference's DDP hooks
and imagenet ``--prof`` driver, ref: apex/parallel/distributed.py:357,
examples/imagenet/main_amp.py:335-362).

Scope names flow into the jaxpr ``name_stack``, so
:mod:`apex_tpu.pyprof.prof` can attribute FLOPs/bytes back to these
annotations — the role the NVTX->nvvp->prof pipeline plays in the
reference.
"""
from __future__ import annotations

import contextlib
import functools
import json
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

_enabled = False


def init() -> None:
    """Enable annotation (ref: apex/pyprof/nvtx/nvmarker.py ``init()``
    patches the world; here it just arms the wrappers so ``annotate`` is
    zero-cost until profiling is wanted)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def _describe(x: Any):
    """Shape/dtype summary of one argument (the reference serializes
    tensor shapes+dtypes into the NVTX message,
    ref: nvmarker.py ``argMarker``)."""
    if isinstance(x, (jnp.ndarray, jax.Array)) or hasattr(x, "shape"):
        try:
            return {"shape": tuple(int(d) for d in x.shape),
                    "dtype": str(getattr(x, "dtype", "?"))}
        except (TypeError, ValueError, AttributeError):
            return {"type": type(x).__name__}
    if isinstance(x, (int, float, bool, str)) or x is None:
        return x
    return {"type": type(x).__name__}


def call_signature(fn_name: str, args, kwargs, module: str = "") -> str:
    """JSON call record matching the reference's marker payload
    (ref: nvmarker.py — {'mod', 'op', 'args'})."""
    payload = {
        "mod": module,
        "op": fn_name,
        "args": [_describe(a) for a in args],
    }
    if kwargs:
        payload["kwargs"] = {k: _describe(v) for k, v in kwargs.items()}
    return json.dumps(payload, default=str)


def _sanitize(name: str) -> str:
    # named_scope names end up in HLO metadata; keep them short and safe.
    return name.replace("/", ".").replace(" ", "")[:128]


def annotate(fn: Optional[Callable] = None, *, name: Optional[str] = None,
             detailed: bool = False):
    """Decorator: run ``fn`` under a named scope carrying its signature.

    With ``detailed=True`` the scope name embeds the JSON arg record
    (shapes/dtypes) — the full nvmarker payload; default is the plain
    qualified name, which is what you want inside jit (stable scope names
    avoid retrace churn).  Works on traced and untraced functions alike.
    """
    def deco(f):
        scope = name or getattr(f, "__qualname__", f.__name__)

        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            if not _enabled:
                return f(*args, **kwargs)
            label = scope
            if detailed:
                label = _sanitize(
                    scope + ":" + call_signature(scope, args, kwargs))
            with jax.named_scope(_sanitize(label)):
                return f(*args, **kwargs)

        return wrapped

    return deco(fn) if fn is not None else deco


class _RangeStack:
    """Manual push/pop ranges (``nvtx.range_push/range_pop`` parity).

    Outside jit these become ``jax.profiler.TraceAnnotation``s (visible in
    profiler timelines); inside jit a named_scope cannot be push/popped
    imperatively, so use :func:`range` (context manager) there.
    """

    def __init__(self):
        self._stack = []

    def push(self, msg: str) -> None:
        ann = jax.profiler.TraceAnnotation(_sanitize(msg))
        ann.__enter__()
        self._stack.append(ann)

    def pop(self) -> None:
        if self._stack:
            self._stack.pop().__exit__(None, None, None)


_ranges = _RangeStack()
push = _ranges.push
pop = _ranges.pop


@contextlib.contextmanager
def range(msg: str):  # noqa: A001 - parity name (nvtx.range)
    """Scoped range usable both inside jit (named_scope -> HLO metadata)
    and outside (TraceAnnotation -> profiler timeline)."""
    with jax.named_scope(_sanitize(msg)), \
            jax.profiler.TraceAnnotation(_sanitize(msg)):
        yield
