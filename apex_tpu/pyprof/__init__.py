"""Profiling stack (parity with ``apex/pyprof``).

Three layers, mirroring the reference's nvtx -> parse -> prof pipeline
(ref: apex/pyprof/nvtx/nvmarker.py, pyprof/parse/nvvp.py, pyprof/prof/):

- :mod:`.nvtx` — op annotation: ``annotate``/``push``/``pop``/``range``
  emitting ``jax.named_scope`` + ``TraceAnnotation`` ranges with
  serialized call signatures.
- :mod:`.profile` — trace session façade over ``jax.profiler`` wired into
  the transformer Timers (the ``--prof`` window workflow).
- :mod:`.prof` — analytical per-op FLOP/byte/roofline attribution by
  walking the jaxpr directly (no offline SQLite parse needed on TPU),
  with ``report()`` producing the reference's TSV table and
  ``xla_cost_analysis``/``measure`` as cross-checks.
- :mod:`.measured` — MEASURED per-op device times from
  ``jax.profiler``'s xplane output joined onto the analytical rows
  (the reference's parse stage, ref: apex/pyprof/parse/nvvp.py:282):
  ``profile_measured(fn, *args)`` -> rows with flops AND microseconds;
  ``measured_report`` prints the combined table.
"""
from . import nvtx
from .nvtx import annotate, pop, push
from .nvtx import range as range_annotation
from .profile import ProfileWindow, trace
from .prof import (
    DeviceSpec,
    OpRecord,
    analyze,
    device_spec,
    measure,
    report,
    summary_by_op,
    total_bytes,
    total_flops,
    xla_cost_analysis,
)
from .measured import (
    MeasuredOp,
    collect_device_ops,
    join_measured,
    measured_report,
    parse_op_stats,
    profile_call,
    profile_measured,
)


def init() -> None:
    """Arm annotation (ref: ``import apex.pyprof; pyprof.nvtx.init()``)."""
    nvtx.init()


__all__ = [
    "init",
    "nvtx",
    "annotate",
    "push",
    "pop",
    "range_annotation",
    "trace",
    "ProfileWindow",
    "analyze",
    "report",
    "summary_by_op",
    "total_flops",
    "total_bytes",
    "xla_cost_analysis",
    "measure",
    "OpRecord",
    "DeviceSpec",
    "device_spec",
    "MeasuredOp",
    "collect_device_ops",
    "join_measured",
    "measured_report",
    "parse_op_stats",
    "profile_call",
    "profile_measured",
]
