"""MeshPlan — a parallel topology as *data*, not code paths.

ROADMAP item 3's unification refactor: before this module,
``expert_parallel``, ``sequence_parallel``, the pipeline schedules, and
the ZeRO optimizers each owned ad-hoc axis names and implicit sharding
conventions — a topology lived in scattered string constants and
``in_specs`` tuples, and nothing could check that what a layer
*declared* is what the partitioner *did*.  A :class:`MeshPlan` is one
frozen object carrying:

* the mesh **axes** — name, size, and *parallelism kind* (``data`` /
  ``tensor`` / ``pipeline`` / ``sequence`` / ``expert`` / ``zero``), so
  "which axis is the ZeRO axis" is a query, not a convention;
* per-tensor **partition specs** — ``(path pattern, spec)`` pairs
  declaring how named tensors shard over the axes (the contract the
  SPMD auditor checks against the partitioner's propagated shardings,
  rules APX701/APX703);
* a **collective budget** — the maximum collective ops per kind one
  step of this topology is allowed to emit (an accidental extra
  all-gather is a budget overrun, APX703).

Constructed by the parallel stack itself (``parallel_state.
initialize_model_parallel``, ``ExpertParallelMLP.mesh_plan``,
``SequenceParallelTransformerLayer.mesh_plan``, ``pipeline_plan``,
``zero_adam_plan``) and consumed by BOTH the runtime (shard_map
in/out_specs derive from :meth:`MeshPlan.partition_spec`) and the
static auditor (:mod:`apex_tpu.analysis.sharding`): one object, so
drift between the plan and the program is a CI failure, not a TPU
bill.

Import-light on purpose (stdlib only — the linter's ``--paths`` fast
path and the doc generators never pay a jax import); jax is imported
lazily inside :meth:`MeshPlan.make_mesh` / :meth:`partition_spec`.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Union

__all__ = ["MeshAxis", "MeshPlan", "PARALLELISM_KINDS", "Spec"]

# The parallelism alphabet the framework implements (SURVEY §2.10).
PARALLELISM_KINDS = ("data", "tensor", "pipeline", "sequence", "expert",
                     "zero")

# One tensor dimension's sharding: replicated (None), one axis name, or
# a tuple of axis names (multi-axis sharding of one dim).  A Spec is a
# tuple of those over the leading dims; trailing dims are replicated.
DimSpec = Union[None, str, Tuple[str, ...]]
Spec = Tuple[DimSpec, ...]


@dataclasses.dataclass(frozen=True)
class MeshAxis:
    """One mesh axis: the name programs use, its size, and what KIND of
    parallelism rides it — the kind is what makes a topology diffable
    (``data=8`` and ``zero=8`` are different contracts on the same
    8-device mesh)."""

    name: str
    size: int
    kind: str

    def __post_init__(self):
        if self.kind not in PARALLELISM_KINDS:
            raise ValueError(
                f"unknown parallelism kind {self.kind!r} for axis "
                f"{self.name!r}; known: {PARALLELISM_KINDS}")
        if self.size < 1:
            raise ValueError(
                f"axis {self.name!r} size must be >= 1, got {self.size}")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A frozen parallel-topology contract.

    ``tensor_specs`` maps *path patterns* (regex, searched against the
    auditor's rendered tensor paths — ``in0['wi']``, ``out1.m`` — or
    any other consumer's naming) to declared :data:`Spec` tuples.
    First match wins, so order from specific to general.
    """

    axes: Tuple[MeshAxis, ...]
    tensor_specs: Tuple[Tuple[str, Spec], ...] = ()
    collective_budget: Tuple[Tuple[str, int], ...] = ()

    def __post_init__(self):
        names = [a.name for a in self.axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate axis names in plan: {names}")
        known = set(names)
        for pattern, spec in self.tensor_specs:
            for dim in spec:
                for ax in () if dim is None else (
                        dim if isinstance(dim, tuple) else (dim,)):
                    if ax not in known:
                        raise ValueError(
                            f"spec for {pattern!r} names axis {ax!r} "
                            f"not in the plan's axes {sorted(known)}")
        for kind, budget in self.collective_budget:
            if budget < 0:
                raise ValueError(
                    f"collective budget for {kind!r} must be >= 0")

    # --- construction helpers ---------------------------------------------

    @classmethod
    def build(cls, axes: Sequence[Tuple[str, int, str]],
              tensor_specs: Optional[Mapping[str, Sequence[DimSpec]]]
              = None,
              collective_budget: Optional[Mapping[str, int]] = None
              ) -> "MeshPlan":
        """Dict-friendly constructor (the dataclass itself is tuples so
        it can be frozen/hashable)."""
        return cls(
            axes=tuple(MeshAxis(n, int(s), k) for n, s, k in axes),
            tensor_specs=tuple(
                (p, tuple(spec)) for p, spec in
                (tensor_specs or {}).items()),
            collective_budget=tuple(sorted(
                (collective_budget or {}).items())))

    def with_specs(self, extra: Mapping[str, Sequence[DimSpec]],
                   budget: Optional[Mapping[str, int]] = None
                   ) -> "MeshPlan":
        """A copy with entry-specific specs PREPENDED (they win over the
        layer's generic patterns) and budget entries replaced/added —
        how an entry point specializes a layer's plan to its own
        argument naming."""
        merged = dict(self.collective_budget)
        merged.update(budget or {})
        return MeshPlan(
            axes=self.axes,
            tensor_specs=tuple((p, tuple(s)) for p, s in extra.items())
            + self.tensor_specs,
            collective_budget=tuple(sorted(merged.items())))

    # --- queries ------------------------------------------------------------

    def axis(self, name: str) -> MeshAxis:
        for a in self.axes:
            if a.name == name:
                return a
        raise KeyError(f"no axis {name!r} in plan "
                       f"{[a.name for a in self.axes]}")

    def axes_of_kind(self, kind: str) -> Tuple[MeshAxis, ...]:
        return tuple(a for a in self.axes if a.kind == kind)

    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a.name for a in self.axes)

    @property
    def world_size(self) -> int:
        n = 1
        for a in self.axes:
            n *= a.size
        return n

    def budget(self) -> Dict[str, int]:
        return dict(self.collective_budget)

    def spec_for(self, path: str) -> Optional[Spec]:
        """Declared spec of the first pattern matching ``path`` (regex
        search), or None when the plan declares nothing for it."""
        import re

        for pattern, spec in self.tensor_specs:
            if re.search(pattern, path):
                return spec
        return None

    def expected_shard_shape(self, shape: Sequence[int],
                             spec: Spec) -> Tuple[int, ...]:
        """Per-device shape of a ``shape``-d tensor under ``spec``.
        Raises ValueError when the spec does not divide the shape —
        a mis-declared plan must fail loudly, not round."""
        if len(spec) > len(shape):
            raise ValueError(
                f"spec {spec} has more dims than shape {tuple(shape)}")
        out = []
        for d, dim in enumerate(shape):
            entry = spec[d] if d < len(spec) else None
            factor = 1
            for ax in () if entry is None else (
                    entry if isinstance(entry, tuple) else (entry,)):
                factor *= self.axis(ax).size
            if dim % factor != 0:
                raise ValueError(
                    f"dim {d} of shape {tuple(shape)} not divisible by "
                    f"sharding factor {factor} ({entry!r})")
            out.append(dim // factor)
        return tuple(out)

    # --- jax bridges (lazy imports) -----------------------------------------

    def make_mesh(self, devices=None):
        """Build the ``jax.sharding.Mesh`` this plan describes from the
        first ``world_size`` devices (axis order = plan order)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = self.world_size
        if len(devices) < n:
            raise ValueError(
                f"plan needs {n} devices, host has {len(devices)}")
        grid = np.asarray(devices[:n], dtype=object).reshape(
            tuple(a.size for a in self.axes))
        return Mesh(grid, self.axis_names())

    def partition_spec(self, path: str):
        """``jax.sharding.PartitionSpec`` for ``path`` per the declared
        specs (replicated when undeclared) — the runtime-side consumer:
        shard_map in/out_specs derive from the same object the auditor
        checks."""
        from jax.sharding import PartitionSpec

        spec = self.spec_for(path)
        if spec is None:
            return PartitionSpec()
        return PartitionSpec(*spec)

    # --- serialization ------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        """JSON-stable form: what MULTICHIP rows record and
        tools/sharding_baseline.json commits — the diffable topology.
        ``tensor_specs`` serializes as an ORDERED pair list, never a
        dict: first-match-wins means a with_specs override and the
        base pattern it shadows can share a pattern string, and a
        pattern-keyed dict would keep the losing spec."""
        return {
            "axes": [{"name": a.name, "size": a.size, "kind": a.kind}
                     for a in self.axes],
            "tensor_specs": [
                [p, [list(d) if isinstance(d, tuple) else d
                     for d in spec]]
                for p, spec in self.tensor_specs],
            "collective_budget": dict(self.collective_budget),
        }

    @classmethod
    def from_json(cls, data: Mapping[str, Any]) -> "MeshPlan":
        specs = data.get("tensor_specs", ())
        pairs = specs.items() if isinstance(specs, Mapping) else specs
        return cls(
            axes=tuple(MeshAxis(a["name"], int(a["size"]), a["kind"])
                       for a in data.get("axes", ())),
            tensor_specs=tuple(
                (p, tuple(tuple(d) if isinstance(d, list) else d
                          for d in spec))
                for p, spec in pairs),
            collective_budget=tuple(sorted(
                {k: int(v) for k, v in
                 data.get("collective_budget", {}).items()}.items())))

    def describe(self) -> str:
        """Human one-liner: ``data=2(data) x tensor=2(tensor) ...``."""
        return " x ".join(f"{a.name}={a.size}({a.kind})"
                          for a in self.axes)

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)
