"""Pallas flash-decode: single-query attention against a block-paged
KV cache (the serving counterpart of :mod:`.flash_attention`).

Decode-time attention is the degenerate q-dimension case of flash
attention: one query row per sequence, attending over everything that
sequence has generated so far.  The KV history lives in a **paged**
cache — fixed-size blocks owned by a free-list pool
(:class:`apex_tpu.serving.KVCacheManager`), so admitting or evicting a
request never moves another request's bytes — and the kernel gathers a
sequence's pages through its **block table** with a scalar-prefetched
index map: page ``j`` of batch row ``b`` is fetched from cache block
``block_tables[b, j]`` directly by the Pallas pipeline, no materialized
(b, pages, bs, d) copy anywhere (the naive decode baseline bench.py's
``serving`` section measures against does exactly that copy).

Layouts (``bs`` = tokens per cache block, the APEX_TPU_SERVE_KV_BLOCK
grain):

* q            (b, h, d)         — one query token per sequence
* k/v cache    (nb, hk, bs, dk)  — block-major; ``hk``/``dk`` are the
  STORAGE head axes: ``(h, d)`` unpacked, ``(h/2, 2d)`` head-packed
* block_tables (b, max_pages) int32 — cache-block id per page; pages
  past a sequence's length point at block 0 (the reserved dump page)
* seq_lens     (b,) int32        — attend over positions < seq_len;
  0 marks an inactive batch row (output is exactly 0)

Head packing at d=64 reuses the PR-1 sign-rotation trick
(:mod:`.flash_attention` module note) and is FREE at decode time: with
one token per step, packing adjacent head pairs onto one 128-lane tile
is a plain reshape ``(h, 64) -> (h/2, 128)`` — no transpose, because
the degenerate q dimension is exactly the axis the training-side pack
had to move.  The cache is *stored* packed (the manager's layout), the
per-step append is a reshape, and every matmul runs full-width: the
scores come from the same half-sum/half-difference rotation
(:func:`flash_attention._packed_scores`), the output from the mirrored
combine.  ``APEX_TPU_FLASH_PACK_D64=0`` forces the half-width layout
end to end (cache layout and kernel agree by construction — both ask
:func:`use_decode_head_packing`).

Online softmax runs across pages exactly as the training forward runs
across k-blocks: per-(batch, head-group) scratch carries m/l/acc over
the page grid dimension, pages wholly past ``seq_len`` are skipped via
``pl.when``, and the straddling page masks by global position.  Softmax
math is fp32 with the exp2 pre-folded constants.

Int8 KV (weight-only storage; APEX_TPU_SERVE_KV_DTYPE=int8): k/v store
as int8 with **per-row** (per cached token, per head) fp32 scales, so
appending a token never requantizes history; the kernel dequantizes
each page block in-VMEM before the matmuls.  Scales ride their own
``(nb, h, bs)`` arrays and are gathered through the same block table.

Inference-only: no VJP is defined (decode never differentiates).

The jnp twin is :func:`paged_attention_reference` — the CPU oracle the
parity audit (APX401/402) pins this kernel to and the dense math the
serving tests diff against.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (_LOG2E, _NEG, _dot, _interpret,
                              _packed_out, _packed_scores,
                              _pack_lane_cols, _use_head_packing)

__all__ = ["flash_decode", "flash_decode_multi",
           "paged_attention_reference",
           "paged_attention_multi_reference",
           "use_decode_head_packing", "pack_decode_heads",
           "unpack_decode_heads", "dequantize_kv"]


def use_decode_head_packing(h: int, d: int) -> bool:
    """Whether decode (and therefore the CACHE LAYOUT — the two must
    agree) packs d=64 head pairs onto 128 lanes; same predicate and
    escape hatch (``APEX_TPU_FLASH_PACK_D64`` /
    ``flash_attention.set_head_packing``) as the training kernels."""
    return _use_head_packing(h, d)


def pack_decode_heads(x: jnp.ndarray) -> jnp.ndarray:
    """(..., h, d) -> (..., h/2, 2d): adjacent head pairs share a lane
    tile.  For single-token decode rows this is a pure reshape (the
    packed lane axis is contiguous in memory) — the reason packing is
    free at decode time where the training pack needed a transpose."""
    *lead, h, d = x.shape
    return x.reshape(*lead, h // 2, 2 * d)


def unpack_decode_heads(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_decode_heads`."""
    *lead, hp, d2 = x.shape
    return x.reshape(*lead, hp * 2, d2 // 2)


def _pos_mask(shape, page0, sl):
    """cols are global positions [page0, page0 + bs); True = attend."""
    pos = page0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return pos < sl


def _decode_kernel(a, bs, pack, has_scale, *refs):
    """One (batch row, head group, page) program.  Scalar-prefetch refs
    lead: block tables (consumed by the index maps, unused here) and
    seq_lens.  Scratch m/l ride columns 0..g-1 of a (1, 128) carry —
    the training kernels' column-per-head idiom at bq=1."""
    bt_ref, sl_ref, q_ref, k_ref, v_ref, *rest = refs
    if has_scale:
        ks_ref, vs_ref, *rest = rest
    o_ref, m_sc, l_sc, acc = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    sl = sl_ref[b]

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    # a page wholly past the sequence contributes nothing — skip it
    # (its block-table entry points at the dump page; the DMA is the
    # bucketed cost the ladder accounts for, the FLOPs are not paid)
    @pl.when(j * bs < sl)
    def _page():
        q = q_ref[0]                                  # (1, dk)
        k = k_ref[0, 0]                               # (bs, dk)
        v = v_ref[0, 0]
        if has_scale:
            # int8 rows -> f32 in VMEM; per-row scales so history is
            # never requantized by an append.  Packed: each lane half
            # is one head's row, scaled by that head's factor.
            if pack:
                ks = _pack_lane_cols(ks_ref[0, 0, :][:, None],
                                     ks_ref[0, 1, :][:, None],
                                     k.shape[-1])
                vs = _pack_lane_cols(vs_ref[0, 0, :][:, None],
                                     vs_ref[0, 1, :][:, None],
                                     v.shape[-1])
            else:
                ks = ks_ref[0, 0, :][:, None]
                vs = vs_ref[0, 0, :][:, None]
            k = k.astype(jnp.float32) * ks
            v = v.astype(jnp.float32) * vs
        heads = _packed_scores(q, k) if pack \
            else (_dot(q, k, trans_b=True),)           # (1, bs) fp32
        mask = _pos_mask(heads[0].shape, j * bs, sl)
        pas, corrs = [], []
        for hh, s in enumerate(heads):
            s = jnp.where(mask, s, _NEG)
            m_prev = m_sc[:, hh:hh + 1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1,
                                                keepdims=True))
            corr = jnp.exp2((m_prev - m_cur) * a)
            p = jnp.exp2((s - m_cur) * a)
            # the straddling page's masked tail: (s - m_cur) = 0 there
            # when every column so far is masked — zero p explicitly
            # so dead rows sum to l = 0 and emit exactly 0
            p = jnp.where(mask, p, 0.0)
            l_sc[:, hh:hh + 1] = l_sc[:, hh:hh + 1] * corr \
                + jnp.sum(p, axis=1, keepdims=True)
            m_sc[:, hh:hh + 1] = m_cur
            pas.append(p)
            corrs.append(corr)
        if pack:
            corr_w = _pack_lane_cols(corrs[0], corrs[1], acc.shape[1])
            acc[:] = acc[:] * corr_w + _packed_out(pas[0], pas[1], v)
        else:
            acc[:] = acc[:] * corrs[0] \
                + _dot(pas[0].astype(v.dtype), v)

    @pl.when(j == nj - 1)
    def _finish():
        if pack:
            l0 = l_sc[:, :1]
            l1 = l_sc[:, 1:2]
            sl0 = jnp.where(l0 == 0.0, 1.0, l0)   # inactive rows -> 0
            sl1 = jnp.where(l1 == 0.0, 1.0, l1)
            inv = _pack_lane_cols(1.0 / sl0, 1.0 / sl1, acc.shape[1])
            dead = _pack_lane_cols(l0 == 0.0, l1 == 0.0, acc.shape[1])
            o_ref[0] = jnp.where(dead, 0.0,
                                 acc[:] * inv).astype(o_ref.dtype)
            return
        l = l_sc[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc[:] / safe).astype(o_ref.dtype)


def _decode_paged(q3, k_cache, v_cache, block_tables, seq_lens, scale,
                  k_scale, v_scale, pack):
    """The pallas_call driver: grid (b, head groups, pages), block
    tables + seq_lens scalar-prefetched so the k/v index maps read the
    page id directly — the gather IS the pipeline's block fetch."""
    b, hk, dk = q3.shape
    nb, _, bs, _ = k_cache.shape
    mp = block_tables.shape[1]
    a = float(scale) * _LOG2E
    has_scale = k_scale is not None
    g = 2 if pack else 1

    def qo_spec():
        return pl.BlockSpec((1, 1, dk),
                            lambda b_, h_, j, bt, sl: (b_, h_, 0),
                            memory_space=pltpu.VMEM)

    kv_spec = pl.BlockSpec(
        (1, 1, bs, dk),
        lambda b_, h_, j, bt, sl: (bt[b_, j], h_, 0, 0),
        memory_space=pltpu.VMEM)
    in_specs = [qo_spec(), kv_spec, kv_spec]
    operands = [q3, k_cache, v_cache]
    if has_scale:
        # scales keep GLOBAL head order (nb, h, bs); a packed program
        # reads its pair as a size-2 block on the head axis
        sc_spec = pl.BlockSpec(
            (1, g, bs), lambda b_, h_, j, bt, sl: (bt[b_, j], h_, 0),
            memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hk, mp),
        in_specs=in_specs,
        out_specs=qo_spec(),
        scratch_shapes=[
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, dk), jnp.float32),
        ])
    return pl.pallas_call(
        functools.partial(_decode_kernel, a, bs, pack, has_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, dk), q3.dtype),
        interpret=_interpret(),
    )(block_tables, seq_lens, *operands)


def flash_decode(q: jnp.ndarray, k_cache: jnp.ndarray,
                 v_cache: jnp.ndarray, block_tables: jnp.ndarray,
                 seq_lens: jnp.ndarray, *,
                 scale: Optional[float] = None,
                 k_scale: Optional[jnp.ndarray] = None,
                 v_scale: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Single-query attention over a block-paged KV cache.

    ``q`` is (b, h, d) — one query token per sequence; the cache is
    (nb, hk, bs, dk) block-major (see the module note for the packed
    ``hk``/``dk`` convention — the cache layout decides the kernel
    path, so the pool that allocated it is the single source of
    truth).  ``block_tables`` (b, max_pages) int32 names each row's
    pages; ``seq_lens`` (b,) bounds the attended positions, 0 marking
    an inactive row (output exactly 0).  ``k_scale``/``v_scale``
    (nb, h, bs) fp32 arm the int8 weight-only dequant path.  Returns
    (b, h, d) in q's dtype.  Inference-only (no VJP).
    """
    b, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    nb, hk, bs, dk = k_cache.shape
    if v_cache.shape != k_cache.shape:
        raise ValueError(f"k/v cache shapes differ: {k_cache.shape} "
                         f"vs {v_cache.shape}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if hk == h and dk == d:
        pack = False
    elif h % 2 == 0 and hk == h // 2 and dk == 2 * d:
        pack = True
    else:
        raise ValueError(
            f"cache head layout {(hk, dk)} matches neither unpacked "
            f"{(h, d)} nor head-packed {(h // 2, 2 * d)} for q "
            f"{q.shape}")
    for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
        if sc is not None and sc.shape != (nb, h, bs):
            raise ValueError(f"{name} shape {sc.shape} != expected "
                             f"{(nb, h, bs)} (global head order)")
    q3 = pack_decode_heads(q) if pack else q
    out = _decode_paged(q3, k_cache, v_cache,
                        block_tables.astype(jnp.int32),
                        seq_lens.astype(jnp.int32), scale,
                        k_scale, v_scale, pack)
    return unpack_decode_heads(out) if pack else out


# --- multi-token path (speculative verify / chunked prefill) ---------------

def _decode_multi_kernel(a, bs, t, pack, has_scale, *refs):
    """One (batch row, head group, page) program over a CHUNK of ``t``
    query rows.  Row ``r`` of batch ``b`` sits at global position
    ``seq_lens[b] - t + r`` (chunk positions are contiguous and end at
    the last written slot), so the per-row causal mask is
    ``pos <= sl - t + r`` — at ``t == 1`` this is exactly the decode
    kernel's ``pos < sl``.  m/l scratch carries one row per query in
    columns 0..g-1; everything else mirrors :func:`_decode_kernel`."""
    bt_ref, sl_ref, q_ref, k_ref, v_ref, *rest = refs
    if has_scale:
        ks_ref, vs_ref, *rest = rest
    o_ref, m_sc, l_sc, acc = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    sl = sl_ref[b]

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    @pl.when(j * bs < sl)
    def _page():
        q = q_ref[0, 0]                               # (t, dk)
        k = k_ref[0, 0]                               # (bs, dk)
        v = v_ref[0, 0]
        if has_scale:
            if pack:
                ks = _pack_lane_cols(ks_ref[0, 0, :][:, None],
                                     ks_ref[0, 1, :][:, None],
                                     k.shape[-1])
                vs = _pack_lane_cols(vs_ref[0, 0, :][:, None],
                                     vs_ref[0, 1, :][:, None],
                                     v.shape[-1])
            else:
                ks = ks_ref[0, 0, :][:, None]
                vs = vs_ref[0, 0, :][:, None]
            k = k.astype(jnp.float32) * ks
            v = v.astype(jnp.float32) * vs
        heads = _packed_scores(q, k) if pack \
            else (_dot(q, k, trans_b=True),)           # (t, bs) fp32
        # per-row causal mask: row r attends positions <= sl - t + r
        shape = heads[0].shape
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        mask = pos <= sl - t + row
        corrs = []
        pas = []
        for hh, s in enumerate(heads):
            s = jnp.where(mask, s, _NEG)
            m_prev = m_sc[:, hh:hh + 1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1,
                                                keepdims=True))
            corr = jnp.exp2((m_prev - m_cur) * a)
            p = jnp.exp2((s - m_cur) * a)
            p = jnp.where(mask, p, 0.0)
            l_sc[:, hh:hh + 1] = l_sc[:, hh:hh + 1] * corr \
                + jnp.sum(p, axis=1, keepdims=True)
            m_sc[:, hh:hh + 1] = m_cur
            pas.append(p)
            corrs.append(corr)
        if pack:
            corr_w = _pack_lane_cols(corrs[0], corrs[1], acc.shape[1])
            acc[:] = acc[:] * corr_w + _packed_out(pas[0], pas[1], v)
        else:
            acc[:] = acc[:] * corrs[0] \
                + _dot(pas[0].astype(v.dtype), v)

    @pl.when(j == nj - 1)
    def _finish():
        if pack:
            l0 = l_sc[:, :1]
            l1 = l_sc[:, 1:2]
            sl0 = jnp.where(l0 == 0.0, 1.0, l0)
            sl1 = jnp.where(l1 == 0.0, 1.0, l1)
            inv = _pack_lane_cols(1.0 / sl0, 1.0 / sl1, acc.shape[1])
            dead = _pack_lane_cols(l0 == 0.0, l1 == 0.0, acc.shape[1])
            o_ref[0, 0] = jnp.where(dead, 0.0,
                                    acc[:] * inv).astype(o_ref.dtype)
            return
        l = l_sc[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = jnp.where(l == 0.0, 0.0,
                                acc[:] / safe).astype(o_ref.dtype)


def _decode_paged_multi(q4, k_cache, v_cache, block_tables, seq_lens,
                        scale, k_scale, v_scale, pack):
    """pallas_call driver for the t-row chunk path: grid
    (b, head groups, pages) like the single-token driver, q/o blocks
    carry the whole (t, dk) chunk per program."""
    b, hk, t, dk = q4.shape
    nb, _, bs, _ = k_cache.shape
    mp = block_tables.shape[1]
    a = float(scale) * _LOG2E
    has_scale = k_scale is not None
    g = 2 if pack else 1

    def qo_spec():
        return pl.BlockSpec((1, 1, t, dk),
                            lambda b_, h_, j, bt, sl: (b_, h_, 0, 0),
                            memory_space=pltpu.VMEM)

    kv_spec = pl.BlockSpec(
        (1, 1, bs, dk),
        lambda b_, h_, j, bt, sl: (bt[b_, j], h_, 0, 0),
        memory_space=pltpu.VMEM)
    in_specs = [qo_spec(), kv_spec, kv_spec]
    operands = [q4, k_cache, v_cache]
    if has_scale:
        sc_spec = pl.BlockSpec(
            (1, g, bs), lambda b_, h_, j, bt, sl: (bt[b_, j], h_, 0),
            memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hk, mp),
        in_specs=in_specs,
        out_specs=qo_spec(),
        scratch_shapes=[
            pltpu.VMEM((t, 128), jnp.float32),
            pltpu.VMEM((t, 128), jnp.float32),
            pltpu.VMEM((t, dk), jnp.float32),
        ])
    return pl.pallas_call(
        functools.partial(_decode_multi_kernel, a, bs, t, pack,
                          has_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, t, dk), q4.dtype),
        interpret=_interpret(),
    )(block_tables, seq_lens, *operands)


def flash_decode_multi(q: jnp.ndarray, k_cache: jnp.ndarray,
                       v_cache: jnp.ndarray, block_tables: jnp.ndarray,
                       seq_lens: jnp.ndarray, *,
                       scale: Optional[float] = None,
                       k_scale: Optional[jnp.ndarray] = None,
                       v_scale: Optional[jnp.ndarray] = None
                       ) -> jnp.ndarray:
    """Multi-token paged attention: ``t`` contiguous query tokens per
    sequence against the block-paged cache — the speculative-verify /
    chunked-prefill counterpart of :func:`flash_decode`.

    ``q`` is (b, t, h, d); row ``r`` of sequence ``b`` sits at global
    position ``seq_lens[b] - t + r`` (its k/v, like every earlier
    position's, must already be written to the cache — the serving
    step writes the whole chunk before attending, so each token sees
    itself and its in-chunk predecessors through the pages).  The
    causal rule is per row: attend to positions ``<= seq_lens[b] - t
    + r``.  Rows whose position is negative (front padding of a short
    chunk) and rows of an inactive sequence (``seq_lens == 0``) emit
    exactly 0.  Layout/packing/int8 conventions are identical to
    :func:`flash_decode`; at ``t == 1`` the two paths compute the
    same attention.  Inference-only (no VJP)."""
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    nb, hk, bs, dk = k_cache.shape
    if v_cache.shape != k_cache.shape:
        raise ValueError(f"k/v cache shapes differ: {k_cache.shape} "
                         f"vs {v_cache.shape}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if hk == h and dk == d:
        pack = False
    elif h % 2 == 0 and hk == h // 2 and dk == 2 * d:
        pack = True
    else:
        raise ValueError(
            f"cache head layout {(hk, dk)} matches neither unpacked "
            f"{(h, d)} nor head-packed {(h // 2, 2 * d)} for q "
            f"{q.shape}")
    for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
        if sc is not None and sc.shape != (nb, h, bs):
            raise ValueError(f"{name} shape {sc.shape} != expected "
                             f"{(nb, h, bs)} (global head order)")
    # (b, t, h, d) -> (b, hk, t, dk): the pack is a reshape on the
    # trailing axes (same free-at-decode property as the single-token
    # path), then heads move ahead of the chunk axis
    q4 = pack_decode_heads(q) if pack else q
    q4 = q4.transpose(0, 2, 1, 3)
    out = _decode_paged_multi(q4, k_cache, v_cache,
                              block_tables.astype(jnp.int32),
                              seq_lens.astype(jnp.int32), scale,
                              k_scale, v_scale, pack)
    out = out.transpose(0, 2, 1, 3)                    # (b, t, hk, dk)
    return unpack_decode_heads(out) if pack else out


# --- jnp twin ---------------------------------------------------------------

def dequantize_kv(cache: jnp.ndarray,
                  scale: Optional[jnp.ndarray]) -> jnp.ndarray:
    """int8 (nb, hk, bs, dk) cache + (nb, h, bs) per-row scales -> f32
    (handles the packed lane-half layout); float caches pass through."""
    if scale is None:
        return cache
    nb, hk, bs, dk = cache.shape
    h = scale.shape[1]
    if hk == h:
        s = scale[..., None]                           # (nb, h, bs, 1)
    else:
        # packed: lane half i of pair p is global head 2p+i
        s = scale.reshape(nb, hk, 2, bs).transpose(0, 1, 3, 2)
        s = jnp.repeat(s, dk // 2, axis=-1)            # (nb, hk, bs, dk)
    return cache.astype(jnp.float32) * s


def paged_attention_reference(q, k_cache, v_cache, block_tables,
                              seq_lens, scale=None, k_scale=None,
                              v_scale=None):
    """Dense jnp twin of :func:`flash_decode`: gather every row's pages
    into contiguous (b, h, pages*bs, d) k/v, mask by global position,
    fp32 softmax.  The parity oracle and the naive full-gather decode
    baseline the serving bench row compares the kernel against."""
    b, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    nb, hk, bs, dk = k_cache.shape
    k_cache = dequantize_kv(k_cache, k_scale)
    v_cache = dequantize_kv(v_cache, v_scale)
    if hk != h:   # packed storage -> per-head view
        k_cache = unpack_decode_heads(
            k_cache.transpose(0, 2, 1, 3)).transpose(0, 2, 1, 3)
        v_cache = unpack_decode_heads(
            v_cache.transpose(0, 2, 1, 3)).transpose(0, 2, 1, 3)
    mp = block_tables.shape[1]
    # (b, mp, h, bs, d) -> (b, h, mp*bs, d)
    k = k_cache[block_tables].transpose(0, 2, 1, 3, 4) \
        .reshape(b, h, mp * bs, d)
    v = v_cache[block_tables].transpose(0, 2, 1, 3, 4) \
        .reshape(b, h, mp * bs, d)
    s = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(mp * bs, dtype=jnp.int32)[None, None, :]
    mask = pos < seq_lens[:, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)             # inactive rows
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhk,bhkd->bhd", p / safe, v.astype(jnp.float32))
    o = jnp.where(l == 0.0, 0.0, o)
    return o.astype(q.dtype)


def paged_attention_multi_reference(q, k_cache, v_cache, block_tables,
                                    seq_lens, scale=None, k_scale=None,
                                    v_scale=None):
    """Dense jnp twin of :func:`flash_decode_multi`: gather every
    row's pages, mask per query row by the contiguous-chunk causal
    rule (row ``r`` attends positions ``<= seq_lens[b] - t + r``),
    fp32 softmax.  The parity oracle for the multi-token kernel and
    the dense verify/chunk baseline (``decode_attention="reference"``)."""
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    nb, hk, bs, dk = k_cache.shape
    k_cache = dequantize_kv(k_cache, k_scale)
    v_cache = dequantize_kv(v_cache, v_scale)
    if hk != h:
        k_cache = unpack_decode_heads(
            k_cache.transpose(0, 2, 1, 3)).transpose(0, 2, 1, 3)
        v_cache = unpack_decode_heads(
            v_cache.transpose(0, 2, 1, 3)).transpose(0, 2, 1, 3)
    mp = block_tables.shape[1]
    k = k_cache[block_tables].transpose(0, 2, 1, 3, 4) \
        .reshape(b, h, mp * bs, d)
    v = v_cache[block_tables].transpose(0, 2, 1, 3, 4) \
        .reshape(b, h, mp * bs, d)
    s = jnp.einsum("bthd,bhkd->bthk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale    # (b, t, h, k)
    pos = jnp.arange(mp * bs, dtype=jnp.int32)[None, None, None, :]
    qpos = (seq_lens[:, None].astype(jnp.int32) - t
            + jnp.arange(t, dtype=jnp.int32)[None, :])   # (b, t)
    mask = pos <= qpos[:, :, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bthk,bhkd->bthd", p / safe,
                   v.astype(jnp.float32))
    o = jnp.where(l == 0.0, 0.0, o)
    return o.astype(q.dtype)
