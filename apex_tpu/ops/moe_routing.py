"""Fused Pallas MoE routing + capacity-drop dispatch.

The MoE dispatch pipeline — router softmax, top-k expert select, GShard
choice-major capacity slotting, and the scatter into the
``(experts, capacity, H)`` dispatch buffer — was four separate XLA
stages, the last of which (the one-hot einsum in
:mod:`~apex_tpu.transformer.layers_moe`) materializes a ``(T, E, C)``
dispatch tensor in HBM whose bytes dwarf the tokens being routed.  This
module fuses the whole pipeline into one VMEM-resident Pallas pass: the
routing probabilities, slot arithmetic, and buffer scatter never leave
the core, and the dispatch tensor is never built.

Semantics contract (bit-identical to
:func:`~apex_tpu.transformer.expert_parallel._dispatch_indices` — the
spec the tests pin both backends to):

* top-1 (Switch) or top-2 (GShard Algorithm 1) routing; top-2 gates are
  renormalized over the pair, ``second_policy="random"`` keeps the
  second choice with probability ``min(1, 2 * gate2)`` and a dropped
  second choice claims NO capacity slot;
* slotting is choice-major cumsum: all first choices outrank all second
  choices, overflow beyond ``capacity`` is dropped (``keep=False``);
* the auxiliary load-balancing loss is the Switch/GShard
  ``E * sum(frac * mean_prob)`` over FIRST choices only.

The jnp twin is :func:`moe_route_dispatch_reference` — the CPU oracle
the parity audit (APX401/402) pins the kernel to, the XLA fallback
:func:`moe_route_dispatch` dispatches to off TPU, and the function the
custom VJP differentiates (routing decisions are bit-identical across
backends, so the reference's gradient IS the kernel's gradient).

Integer outputs (``slot``/``keep``/``expert_index``) are exact across
backends; float outputs (``gate``/``buf``/aux) may differ in the last
bit only through summation-order effects of the kernel's lane padding.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _interpret

__all__ = ["RouteDispatch", "moe_route_dispatch",
           "moe_route_dispatch_reference", "moe_combine", "self_check"]

# TPU grains: dims that land on lanes pad to 128; the capacity dim is a
# sublane-only dim and pads to 8.
_LANE = 128
_SUB = 8

# Finite column mask for expert padding: softmax of a row whose masked
# entries sit at -1e30 underflows them to exactly 0.0; an all-masked
# (padded-token) row softmaxes to uniform — finite, never 0/0 NaN.
_NEG_INF = -1e30


class RouteDispatch(NamedTuple):
    """Everything the combine (and the router loss) needs downstream."""

    buf: jnp.ndarray            # (E, capacity, H) dispatched tokens
    expert_index: jnp.ndarray   # (k, T) int32 chosen expert per choice
    gate: jnp.ndarray           # (k, T) f32 gates (top-2: renormalized)
    slot: jnp.ndarray           # (k*T,) int32 capacity slot, clipped
    keep: jnp.ndarray           # (k*T,) bool False = overflow/no-dispatch
    load_balancing_loss: jnp.ndarray  # scalar f32 aux loss


def _pad_to(v: int, grain: int) -> int:
    return -(-v // grain) * grain


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _route_kernel(x_ref, logits_ref, u_ref, idx_ref, gate_ref,
                  slot_ref, keep_ref, mp_ref, buf_ref, *, top_k: int,
                  second_policy: str, capacity: int, t_true: int):
    """Single-program pass: softmax -> top-k select -> choice-major
    cumsum slotting -> row scatter into the dispatch buffer.  Padded
    token rows (>= ``t_true``) are carried as invalid — they claim no
    slot, and integer cumsum over their all-zero one-hot rows leaves
    every real token's position untouched (the bit-identity argument)."""
    tp = logits_ref.shape[0]
    probs = jax.nn.softmax(logits_ref[...].astype(jnp.float32), axis=-1)
    tok_valid = (jax.lax.broadcasted_iota(jnp.int32, (tp, 1), 0)
                 < t_true)                                   # (Tp, 1)
    mp_ref[...] = (jnp.sum(jnp.where(tok_valid, probs, 0.0),
                           axis=0, keepdims=True) / t_true)
    ep = probs.shape[1]
    idx1 = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    # max IS the argmax'd element — same bits as take_along_axis
    gate1 = jnp.max(probs, axis=-1)
    if top_k == 2:
        masked = probs * (1.0 - jax.nn.one_hot(idx1, ep,
                                               dtype=probs.dtype))
        idx2 = jnp.argmax(masked, axis=-1).astype(jnp.int32)
        gate2 = jnp.max(masked, axis=-1)
        denom = jnp.maximum(gate1 + gate2, 1e-9)
        g1n, g2n = gate1 / denom, gate2 / denom
        if second_policy == "random":
            keep2 = u_ref[0, :] < 2.0 * g2n
            g2n = jnp.where(keep2, g2n, 0.0)
        idx = jnp.stack([idx1, idx2])
        gates = jnp.stack([g1n, g2n])
    else:
        idx = idx1[None]
        gates = gate1[None]
    idx_ref[...] = idx
    gate_ref[...] = gates

    k = idx.shape[0]
    # gate == 0 marks a choice the router decided not to dispatch
    valid = (gates > 0.0) & tok_valid[:, 0][None, :]
    one_hot = (jax.nn.one_hot(idx.reshape(-1), ep, dtype=jnp.int32)
               * valid.reshape(-1).astype(jnp.int32)[:, None])
    position = jnp.cumsum(one_hot, axis=0) * one_hot         # 1-based
    slot = jnp.sum(position, axis=1) - 1                     # (k*Tp,)
    keep = (slot >= 0) & (slot < capacity)
    slot = jnp.clip(slot, 0, capacity - 1)
    slot_ref[...] = slot.reshape(k, tp)
    keep_ref[...] = keep.reshape(k, tp).astype(jnp.int32)

    buf_ref[...] = jnp.zeros_like(buf_ref)

    def body(i, carry):
        c = i // tp
        t = i - c * tp

        # each kept (expert, slot) pair is unique, so a row store is
        # the scatter-add with the zero-initialized buffer
        @pl.when(keep_ref[c, t] > 0)
        def _store():
            buf_ref[idx_ref[c, t], slot_ref[c, t], :] = x_ref[t, :]

        return carry

    jax.lax.fori_loop(0, k * tp, body, 0)


def _route_dispatch_pallas(x: jnp.ndarray, logits: jnp.ndarray,
                           u: jnp.ndarray, *, capacity: int,
                           top_k: int, second_policy: str
                           ) -> RouteDispatch:
    """Pad to TPU grains, run the fused kernel, slice back.  ``keep``
    is evaluated against the TRUE capacity before padding, so padded
    capacity rows stay zero and drop decisions match the reference."""
    t, h = x.shape
    e = logits.shape[1]
    tp = _pad_to(t, _LANE)       # lane dim of the (k, Tp) outputs
    ep = _pad_to(e, _LANE)
    hp = _pad_to(h, _LANE)
    cp = _pad_to(capacity, _SUB)
    x_p = jnp.pad(x, ((0, tp - t), (0, hp - h)))
    logits_p = jnp.pad(logits.astype(jnp.float32),
                       ((0, tp - t), (0, ep - e)),
                       constant_values=_NEG_INF)
    u_p = jnp.pad(u.astype(jnp.float32).reshape(1, t),
                  ((0, 0), (0, tp - t)))
    out_shapes = (
        jax.ShapeDtypeStruct((top_k, tp), jnp.int32),    # expert_index
        jax.ShapeDtypeStruct((top_k, tp), jnp.float32),  # gate
        jax.ShapeDtypeStruct((top_k, tp), jnp.int32),    # slot
        jax.ShapeDtypeStruct((top_k, tp), jnp.int32),    # keep
        jax.ShapeDtypeStruct((1, ep), jnp.float32),      # mean_prob
        jax.ShapeDtypeStruct((e, cp, hp), x.dtype),      # buf
    )
    idx, gates, slot, keep, mp, buf = pl.pallas_call(
        functools.partial(_route_kernel, top_k=top_k,
                          second_policy=second_policy,
                          capacity=capacity, t_true=t),
        out_shape=out_shapes,
        interpret=_interpret())(x_p, logits_p, u_p)
    idx = idx[:, :t]
    gates = gates[:, :t]
    frac = jnp.mean(jax.nn.one_hot(idx[0], e, dtype=jnp.float32),
                    axis=0)
    aux = e * jnp.sum(frac * mp[0, :e])
    return RouteDispatch(
        buf=buf[:, :capacity, :h], expert_index=idx, gate=gates,
        slot=slot[:, :t].reshape(-1), keep=keep[:, :t].reshape(-1) > 0,
        load_balancing_loss=aux)


# ---------------------------------------------------------------------------
# jnp twin
# ---------------------------------------------------------------------------

def moe_route_dispatch_reference(x: jnp.ndarray, logits: jnp.ndarray,
                                 u: Optional[jnp.ndarray] = None, *,
                                 capacity: int, top_k: int = 1,
                                 second_policy: str = "all"
                                 ) -> RouteDispatch:
    """The jnp twin: the same router math as
    :func:`~apex_tpu.transformer.expert_parallel.top1_router` /
    ``top2_router`` followed by the ``_dispatch_indices`` cumsum and a
    scatter-add — the spec both the parity audit and the custom VJP
    differentiate.  ``u``: the (T,) uniform draw for
    ``second_policy="random"`` (drawn by the public wrapper so kernel
    and twin consume identical randomness)."""
    t, h = x.shape
    e = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)
    gate1 = jnp.take_along_axis(probs, idx1[:, None], axis=1)[:, 0]
    if top_k == 2:
        masked = probs * (1.0 - jax.nn.one_hot(idx1, e,
                                               dtype=probs.dtype))
        idx2 = jnp.argmax(masked, axis=-1)
        gate2 = jnp.take_along_axis(masked, idx2[:, None],
                                    axis=1)[:, 0]
        denom = jnp.maximum(gate1 + gate2, 1e-9)
        g1n, g2n = gate1 / denom, gate2 / denom
        if second_policy == "random":
            if u is None:
                raise ValueError(
                    "second_policy='random' requires the uniform "
                    "draw u")
            # the Bernoulli draw is a dispatch decision, not a gate
            # transformation (GShard): no gradient through the
            # threshold
            keep2 = u < jax.lax.stop_gradient(2.0 * g2n)
            g2n = jnp.where(keep2, g2n, 0.0)
        idx = jnp.stack([idx1, idx2]).astype(jnp.int32)
        gates = jnp.stack([g1n, g2n])
    else:
        idx = idx1[None].astype(jnp.int32)
        gates = gate1[None]
    # aux loss over the FIRST choice (GShard load estimator)
    frac = jnp.mean(jax.nn.one_hot(idx1, e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))

    valid = gates.reshape(-1) > 0.0
    one_hot = (jax.nn.one_hot(idx.reshape(-1), e, dtype=jnp.int32)
               * valid.astype(jnp.int32)[:, None])
    position = jnp.cumsum(one_hot, axis=0) * one_hot         # 1-based
    slot = jnp.sum(position, axis=1) - 1
    keep = (slot >= 0) & (slot < capacity)
    slot = jnp.clip(slot, 0, capacity - 1)

    k = idx.shape[0]
    xk = jnp.broadcast_to(x[None], (k, t, h)).reshape(k * t, h)
    buf = jnp.zeros((e, capacity, h), x.dtype)
    buf = buf.at[idx.reshape(-1), slot].add(
        jnp.where(keep[:, None], xk, 0))
    return RouteDispatch(buf=buf, expert_index=idx, gate=gates,
                         slot=slot, keep=keep,
                         load_balancing_loss=aux)


# ---------------------------------------------------------------------------
# public entry point
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _fused(capacity: int, top_k: int, second_policy: str,
           backend: str):
    """custom_vjp closure per static config: the forward runs the
    requested backend; the backward differentiates the jnp twin at the
    saved residuals — exact for both backends because the routing
    decisions (idx/slot/keep) are bit-identical, and the float paths
    they select are the same math."""
    if backend == "pallas":
        run = functools.partial(_route_dispatch_pallas,
                                capacity=capacity, top_k=top_k,
                                second_policy=second_policy)
    else:
        run = functools.partial(moe_route_dispatch_reference,
                                capacity=capacity, top_k=top_k,
                                second_policy=second_policy)
    ref = functools.partial(moe_route_dispatch_reference,
                            capacity=capacity, top_k=top_k,
                            second_policy=second_policy)

    @jax.custom_vjp
    def routed(x, logits, u):
        return run(x, logits, u)

    def fwd(x, logits, u):
        return run(x, logits, u), (x, logits, u)

    def bwd(res, ct):
        x, logits, u = res
        _, pull = jax.vjp(lambda xx, ll: ref(xx, ll, u), x, logits)
        dx, dl = pull(ct)
        return dx, dl, jnp.zeros_like(u)

    routed.defvjp(fwd, bwd)
    return routed


def moe_route_dispatch(x: jnp.ndarray, logits: jnp.ndarray, *,
                       capacity: int, top_k: int = 1,
                       second_policy: str = "all",
                       rng: Optional[jax.Array] = None,
                       backend: Optional[str] = None) -> RouteDispatch:
    """Fused route + dispatch: ``x`` (T, H) tokens, ``logits`` (T, E)
    router scores -> :class:`RouteDispatch`.

    ``backend``: ``None`` picks the Pallas kernel on TPU and the jnp
    twin elsewhere (the XLA-fallback discipline the parity registry
    sanctions); ``"pallas"`` / ``"xla"`` force a side for parity
    tests.  ``rng`` is required only for ``top_k=2`` with
    ``second_policy="random"`` — the (T,) uniform draw happens here so
    both backends consume identical randomness.  Differentiable in
    ``x`` and ``logits`` (custom VJP through the twin)."""
    x = jnp.asarray(x)
    logits = jnp.asarray(logits)
    if x.ndim != 2 or logits.ndim != 2 \
            or logits.shape[0] != x.shape[0]:
        raise ValueError(f"x (T, H) / logits (T, E) mismatch: "
                         f"{x.shape} vs {logits.shape}")
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1|2, got {top_k}")
    if second_policy not in ("all", "random"):
        raise ValueError(f"second_policy must be 'all'|'random', got "
                         f"{second_policy!r}")
    if backend not in (None, "pallas", "xla"):
        raise ValueError(f"backend {backend!r} not in "
                         f"(None, 'pallas', 'xla')")
    if backend is None:
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    t = x.shape[0]
    if top_k == 2 and second_policy == "random":
        if rng is None:
            raise ValueError("second_policy='random' requires rng")
        u = jax.random.uniform(rng, (t,))
    else:
        u = jnp.zeros((t,), jnp.float32)
    return _fused(int(capacity), int(top_k), second_policy,
                  backend)(x, logits, u)


def moe_combine(out: jnp.ndarray, expert_index: jnp.ndarray,
                slot: jnp.ndarray, keep: jnp.ndarray,
                gate: jnp.ndarray, out_dtype=None) -> jnp.ndarray:
    """Gather each choice's slot output from the expert result buffer
    ``out`` (E, capacity, H), weight by its gate (dropped choices
    contribute 0), sum over choices -> (T, H).  Plain jnp: the combine
    is a gather XLA already does well, and keeping it out of the
    kernel keeps the kernel inference/training agnostic."""
    k, t = expert_index.shape
    tok = out[expert_index.reshape(-1), slot]            # (k*T, H)
    g = jnp.where(keep, gate.reshape(-1), 0.0).astype(jnp.float32)
    y = (tok.astype(jnp.float32) * g[:, None]).reshape(k, t, -1).sum(0)
    return y.astype(out_dtype if out_dtype is not None else out.dtype)


def self_check() -> None:
    """Interpret-mode kernel-vs-twin parity on CI-sized shapes (the
    :mod:`.quant_matmul` ``self_check`` pattern): integer routing
    decisions must match EXACTLY, float outputs to fp32 tolerance.
    Raises on divergence."""
    import numpy as np

    key = jax.random.PRNGKey(0)
    for t, h, e, cap, k, pol in (
            (16, 8, 4, 5, 1, "all"),
            (16, 8, 4, 3, 2, "all"),
            (24, 16, 6, 1, 2, "random"),
            (3, 8, 8, 2, 1, "all")):
        kx, kl, kr = jax.random.split(jax.random.fold_in(key, t), 3)
        x = jax.random.normal(kx, (t, h), jnp.float32)
        logits = jax.random.normal(kl, (t, e), jnp.float32)
        a = moe_route_dispatch(x, logits, capacity=cap, top_k=k,
                               second_policy=pol, rng=kr,
                               backend="pallas")
        b = moe_route_dispatch(x, logits, capacity=cap, top_k=k,
                               second_policy=pol, rng=kr,
                               backend="xla")
        for name in ("expert_index", "slot", "keep"):
            ga, gb = getattr(a, name), getattr(b, name)
            if not bool(jnp.all(ga == gb)):
                raise AssertionError(
                    f"{name} diverged (T={t} E={e} cap={cap} "
                    f"top_k={k} {pol})")
        np.testing.assert_allclose(np.asarray(a.gate),
                                   np.asarray(b.gate), atol=1e-6)
        np.testing.assert_allclose(np.asarray(a.buf),
                                   np.asarray(b.buf), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(a.load_balancing_loss),
            np.asarray(b.load_balancing_loss), rtol=1e-5)
