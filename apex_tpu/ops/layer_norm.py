"""Pallas fused LayerNorm: forward + backward, fp32 statistics.

TPU-native equivalent of ``fused_layer_norm_cuda``
(ref: csrc/layer_norm_cuda_kernel.cu — ``cuApplyLayerNorm`` :332,
``cuComputePartGradGammaBeta`` :428, ``cuComputeGradInput`` :547; host
dispatch incl. the mixed-dtype paths csrc/layer_norm_cuda.cpp:133-158).

Layout: inputs are reshaped to (rows, hidden); the grid tiles rows, each
block normalizes its rows entirely in VMEM.  Statistics are always fp32
(``MATH_T`` float in the reference) while inputs/outputs may be
bf16/fp16; weights may be fp32 over low-precision activations — the
"mixed" variant (ref: apex/normalization/fused_layer_norm.py:202
``MixedFusedLayerNorm``).  Gamma/beta gradients are produced as per-block
partials (the reference's part-grad two-stage reduction) and summed by
XLA outside the kernel.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_rows(hidden: int, dtype_bytes: int = 4) -> int:
    # Aim for ~2 MiB per buffer per block, rows multiple of 8.
    target = 2 * 1024 * 1024 // max(1, hidden * dtype_bytes)
    return max(8, min(1024, (target // 8) * 8))


# --- forward ---------------------------------------------------------------

def _ln_fwd_kernel(eps: float, affine: bool, x_ref, *rest):
    if affine:
        g_ref, b_ref, y_ref, mean_ref, rstd_ref = rest
    else:
        y_ref, mean_ref, rstd_ref = rest
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    if affine:
        y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    else:
        y = xhat
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_forward(x2d, gamma, beta, eps, interpret=None):
    rows, hidden = x2d.shape
    br = _block_rows(hidden)
    prows = -(-rows // br) * br
    xp = jnp.pad(x2d, ((0, prows - rows), (0, 0))) if prows != rows else x2d

    row_spec = pl.BlockSpec((br, hidden), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    affine = gamma is not None
    in_specs = [row_spec]
    args = [xp]
    if affine:
        w_spec = pl.BlockSpec((1, hidden), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
        in_specs += [w_spec, w_spec]
        args += [gamma.reshape(1, hidden), beta.reshape(1, hidden)]
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps, affine),
        grid=(prows // br,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((prows, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((prows, 1), jnp.float32),
            jax.ShapeDtypeStruct((prows, 1), jnp.float32),
        ],
        interpret=_interpret() if interpret is None else interpret,
    )(*args)
    return y[:rows], mean[:rows], rstd[:rows]


# --- backward --------------------------------------------------------------

def _ln_bwd_kernel(affine: bool, x_ref, *rest):
    if affine:
        (g_ref, dy_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref) = rest
    else:
        dy_ref, mean_ref, rstd_ref, dx_ref = rest
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    if affine:
        gdy = dy * g_ref[:].astype(jnp.float32)
    else:
        gdy = dy
    # dx = rstd * (gdy - mean(gdy) - xhat * mean(gdy * xhat))
    # (ref: cuComputeGradInput, csrc/layer_norm_cuda_kernel.cu:547).
    m1 = jnp.mean(gdy, axis=1, keepdims=True)
    m2 = jnp.mean(gdy * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (gdy - m1 - xhat * m2)).astype(dx_ref.dtype)
    if affine:
        # Per-block partial reductions over rows, folded into 8 sublane
        # rows to satisfy TPU (8, lane) tiling; XLA sums the partials
        # (ref: cuComputePartGradGammaBeta :428 two-stage reduction).
        br, hidden = dy.shape
        dgamma_ref[0] = jnp.sum((dy * xhat).reshape(br // 8, 8, hidden),
                                axis=0)
        dbeta_ref[0] = jnp.sum(dy.reshape(br // 8, 8, hidden), axis=0)


def _ln_backward(x2d, gamma, dy2d, mean, rstd, interpret=None):
    rows, hidden = x2d.shape
    br = _block_rows(hidden)
    prows = -(-rows // br) * br
    pad = prows - rows

    def padr(a):
        return jnp.pad(a, ((0, pad), (0, 0))) if pad else a

    row_spec = pl.BlockSpec((br, hidden), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((1, 8, hidden), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    affine = gamma is not None
    nblocks = prows // br
    if affine:
        w_spec = pl.BlockSpec((1, hidden), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
        dx, dgamma_p, dbeta_p = pl.pallas_call(
            functools.partial(_ln_bwd_kernel, True),
            grid=(nblocks,),
            in_specs=[row_spec, w_spec, row_spec, stat_spec, stat_spec],
            out_specs=[row_spec, part_spec, part_spec],
            out_shape=[
                jax.ShapeDtypeStruct((prows, hidden), x2d.dtype),
                jax.ShapeDtypeStruct((nblocks, 8, hidden), jnp.float32),
                jax.ShapeDtypeStruct((nblocks, 8, hidden), jnp.float32),
            ],
            interpret=_interpret() if interpret is None else interpret,
        )(padr(x2d), gamma.reshape(1, hidden), padr(dy2d),
          padr(mean), padr(rstd))
        dgamma = jnp.sum(dgamma_p, axis=(0, 1)).astype(gamma.dtype)
        dbeta = jnp.sum(dbeta_p, axis=(0, 1)).astype(gamma.dtype)
        return dx[:rows], dgamma, dbeta
    dx, = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, False),
        grid=(nblocks,),
        in_specs=[row_spec, row_spec, stat_spec, stat_spec],
        out_specs=[row_spec],
        out_shape=[jax.ShapeDtypeStruct((prows, hidden), x2d.dtype)],
        interpret=_interpret() if interpret is None else interpret,
    )(padr(x2d), padr(dy2d), padr(mean), padr(rstd))
    return dx[:rows], None, None


# --- public functional API with custom_vjp ---------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_fused(x: jnp.ndarray,
                      gamma: Optional[jnp.ndarray],
                      beta: Optional[jnp.ndarray],
                      eps: float = 1e-5) -> jnp.ndarray:
    return _layer_norm_fwd(x, gamma, beta, eps)[0]


def _layer_norm_fwd(x, gamma, beta, eps):
    shape = x.shape
    hidden = shape[-1]
    x2d = x.reshape(-1, hidden)
    y, mean, rstd = _ln_forward(x2d, gamma, beta, eps)
    return y.reshape(shape), (x2d, gamma, mean, rstd, shape)


def _layer_norm_bwd(eps, res, dy):
    x2d, gamma, mean, rstd, shape = res
    dy2d = dy.reshape(x2d.shape)
    dx, dgamma, dbeta = _ln_backward(x2d, gamma, dy2d, mean, rstd)
    return dx.reshape(shape), dgamma, dbeta


_layer_norm_fused.defvjp(lambda x, g, b, eps: _layer_norm_fwd(x, g, b, eps),
                         _layer_norm_bwd)


def _layer_norm_reference(x, gamma, beta, eps):
    """XLA-fusion path: identical math (fp32 statistics, mixed-dtype
    affine), used inside shard_map manual contexts."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def layer_norm(x: jnp.ndarray,
               gamma: Optional[jnp.ndarray],
               beta: Optional[jnp.ndarray],
               eps: float = 1e-5) -> jnp.ndarray:
    """Fused layer norm over the last dimension.

    ``gamma``/``beta`` may be fp32 while ``x`` is bf16/fp16 (the
    mixed-dtype variant, ref: csrc/layer_norm_cuda.cpp:133-158), or None
    for the non-affine form.  Inside shard_map manual axes the XLA
    reference path runs (Pallas calls cannot yet carry VMA types).

    Under ``amp.autocast`` (O1/O4) this call site runs in FP32 — the
    reference's O1 lists put ``layer_norm`` in FP32_FUNCS
    (ref: apex/amp/lists/torch_overrides.py) — by casting the inputs at
    trace time (the interpreter cannot re-bind the dtype-frozen
    custom_vjp body; see apex_tpu/_autocast_ctx.py).
    """
    from ._context import in_manual_axis_context
    from .._autocast_ctx import autocast_compute_dtype

    if autocast_compute_dtype() is not None \
            and jnp.issubdtype(x.dtype, jnp.floating) \
            and x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
        gamma = None if gamma is None else gamma.astype(jnp.float32)
        beta = None if beta is None else beta.astype(jnp.float32)
    if in_manual_axis_context(x):
        return _layer_norm_reference(x, gamma, beta, eps)
    return _layer_norm_fused(x, gamma, beta, eps)
