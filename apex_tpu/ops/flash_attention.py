"""Pallas flash attention (forward + backward), bf16-first.

TPU-native successor to the reference's fused attention kernels: FMHA
(ref: apex/contrib/csrc/fmha — sm80, seqlen <= 512, head dim 64) and the
fast_multihead_attn family (ref: apex/contrib/csrc/multihead_attn).
Blockwise online-softmax attention removes both the O(s^2)
materialization (the reference's core attention materializes
[b, np, sq, sk], ref: apex/transformer/testing/standalone_gpt.py) and
the shape caps: any sq/sk (padded to block multiples), head dim 64-256,
causal or full attention.

Layout: q (b, h, sq, d), k/v (b, h, sk, d).  Grid (b*h, q-blocks,
k-blocks), k innermost: VMEM scratch carries the running max, sum and
accumulator across k-blocks (TPU grids iterate sequentially, so scratch
is a legal carry).  Matmuls hit the MXU in the input dtype with fp32
accumulation; softmax math is fp32.

Backward: when the padded sequence fits one block and d <= 64 (the
common case at the default 1024 blocks — e.g. GPT-345M s=1024), a
single fused kernel produces dq/dk/dv in one pass (5 matmuls; scores
and dp computed once).  Otherwise the standard two-kernel flash
backward runs: a dq pass (grid over q-blocks, accumulate over k) and a
dk/dv pass (grid over k-blocks, accumulate over q), both recomputing
probabilities from the saved per-row logsumexp.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
# Tuned on v5e via the GPT-345M train-step profile (b=8, h=16, s=1024,
# d=64; device-time deltas are stable run-to-run even when wall clock is
# not): (1024, 1024) beats (512, 1024) — 56.4 vs 62.2 ms/step of kernel
# time across fwd+bwd — and (512, 512) loses despite its finer causal
# block skipping; wide lanes win on the MXU.  VMEM at (1024, 1024),
# d<=256: q/k/v/acc blocks + fp32 scores ~7 MB, within the 16 MB
# budget (at d > 64 block_q is halved — see _clamp_blocks).  Env
# overrides (read at import) for bench-driven re-tuning.
import os as _os


def _env_block(var: str, default: int) -> int:
    raw = _os.environ.get(var)
    if raw is None:
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        raise ValueError(f"{var}={raw!r} is not an integer") from None
    if not 8 <= val <= 4096:
        raise ValueError(f"{var}={val} out of range [8, 4096]")
    return val


DEFAULT_BLOCK_Q = _env_block("APEX_TPU_FLASH_BLOCK_Q", 1024)
DEFAULT_BLOCK_K = _env_block("APEX_TPU_FLASH_BLOCK_K", 1024)


def _clamp_blocks(block_q: int, block_k: int, d: int):
    """VMEM guard: the dk/dv backward holds four fp32 score-shaped
    temporaries (bq, bk) plus blocks and accumulators scaling with d.
    At d=64 (1024, 1024) fits comfortably; beyond that halve block_q so
    the worst case (d=256) stays ~11 MB of the 16 MB budget."""
    if d > 64:
        block_q = min(block_q, 512)
    return block_q, block_k


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot(a, b, trans_b=False):
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


# --- forward ---------------------------------------------------------------

def _fwd_kernel(scale, causal, has_kvm, sq, sk, bq, bk,
                q_ref, k_ref, v_ref, *rest):
    if has_kvm:
        kvm_ref, o_ref, lse_ref, acc, m_sc, l_sc = rest
    else:
        kvm_ref = None
        o_ref, lse_ref, acc, m_sc, l_sc = rest
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    run = (j * bk <= i * bq + bq - 1) if causal else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, trans_b=True) * scale          # (bq, bk) fp32
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < sk
        if causal:
            mask &= q_pos >= k_pos
        if has_kvm:
            mask &= kvm_ref[0, 0, 0, :][None, :] > 0
        s = jnp.where(mask, s, _NEG)
        m_prev = m_sc[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)
        # explicit zero for masked entries: when a row is FULLY masked
        # the running max equals _NEG and exp(s - m) would be 1, not 0
        # — with the explicit mask such rows sum to l = 0, hit the
        # zero-guard at the end, and emit exactly 0 (matching the
        # backward kernels, which also zero p; gradients are 0 too).
        p = jnp.where(mask, jnp.exp(s - m_cur), 0.0)
        l_new = l_sc[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * corr + _dot(p.astype(v_ref.dtype), v_ref[0])
        m_sc[:] = jnp.broadcast_to(m_cur, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_sc[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse = m_sc[:, :1] + jnp.log(l)
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0][None, :],
                                         lse_ref.shape[2:])


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _kvm8(kv_mask, b, psk, bk):
    """(b, sk) key-validity mask -> (b, nkb, 8, bk) sublane-replicated
    fp32 blocks (same trick as :func:`_rows8`)."""
    m = _pad_to(kv_mask.astype(jnp.float32), 1, bk)  # (b, psk), pads 0
    return jnp.broadcast_to(
        m.reshape(b, psk // bk, 1, bk), (b, psk // bk, 8, bk))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, kv_mask=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    bq = min(block_q, max(8, sq))
    bk = min(block_k, max(128, sk))
    q3 = _pad_to(q.reshape(b * h, sq, d), 1, bq)
    k3 = _pad_to(k.reshape(b * h, sk, d), 1, bk)
    v3 = _pad_to(v.reshape(b * h, sk, d), 1, bk)
    bh, psq, _ = q3.shape
    psk = k3.shape[1]
    nq, nk = psq // bq, psk // bk

    q_spec = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)
    has_kvm = kv_mask is not None
    in_specs = [q_spec, k_spec, k_spec]
    operands = [q3, k3, v3]
    if has_kvm:
        kvm_spec = pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM)
        in_specs.append(kvm_spec)
        operands.append(_kvm8(kv_mask, b, psk, bk))
    o, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, causal, has_kvm, sq, sk,
                          bq, bk),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, bq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    lse = lse8[:, :, 0, :].reshape(bh, psq)[:, :sq]
    return o[:, :sq].reshape(b, h, sq, d), lse


# --- backward --------------------------------------------------------------

def _bwd_dq_kernel(scale, causal, has_kvm, sq, sk, bq, bk,
                   q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest):
    if has_kvm:
        kvm_ref, dq_ref, dq_acc = rest
    else:
        kvm_ref = None
        dq_ref, dq_acc = rest
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (j * bk <= i * bq + bq - 1) if causal else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, trans_b=True) * scale
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < sk
        if causal:
            mask &= q_pos >= k_pos
        if has_kvm:
            mask &= kvm_ref[0, 0, 0, :][None, :] > 0
        lse = lse_ref[0, 0, 0, :][:, None]
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = _dot(do_ref[0], v_ref[0], trans_b=True)
        delta = delta_ref[0, 0, 0, :][:, None]
        ds = p * (dp - delta) * scale
        dq_acc[:] += _dot(ds.astype(k.dtype), k)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(scale, causal, has_kvm, sq, sk, bq, bk,
                    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest):
    if has_kvm:
        kvm_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        kvm_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    i = pl.program_id(1)   # k block
    j = pl.program_id(2)   # q block
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (j * bq + bq - 1 >= i * bk) if causal else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        s = _dot(q, k, trans_b=True) * scale          # (bq, bk)
        q_pos = j * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (k_pos < sk) & (q_pos < sq)
        if causal:
            mask &= q_pos >= k_pos
        if has_kvm:
            mask &= kvm_ref[0, 0, 0, :][None, :] > 0
        lse = lse_ref[0, 0, 0, :][:, None]
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        do = do_ref[0]
        dv_acc[:] += _dot(p.astype(do.dtype).T, do)
        dp = _dot(do, v_ref[0], trans_b=True)
        delta = delta_ref[0, 0, 0, :][:, None]
        ds = p * (dp - delta) * scale                 # (bq, bk)
        dk_acc[:] += _dot(ds.astype(q.dtype).T, q)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _rows8(x2d, bq):
    """(bh, rows) -> (bh, rows/bq, 8, bq) sublane-replicated view."""
    bh, rows = x2d.shape
    return jnp.broadcast_to(
        x2d.reshape(bh, rows // bq, 1, bq), (bh, rows // bq, 8, bq))


def _bwd_fused_kernel(scale, causal, has_kvm, sq, sk,
                      q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *rest):
    if has_kvm:
        kvm_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        kvm_ref = None
        dq_ref, dk_ref, dv_ref = rest
    """Single-block backward: when the whole (padded) sequence fits one
    q-block and one k-block, dq/dk/dv come from ONE pass — the scores
    ``s`` and ``dp`` are computed once instead of once per kernel (the
    two-kernel flash backward recomputes both), removing 2 of the 7
    matmuls; the two it removes are the d-contracted (half-MXU-lane)
    ones, so the saving exceeds their FLOP share."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = _dot(q, k, trans_b=True) * scale              # (sq, sk) fp32
    q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = (k_pos < sk) & (q_pos < sq)
    if causal:
        mask &= q_pos >= k_pos
    if has_kvm:
        mask &= kvm_ref[0, 0, 0, :][None, :] > 0
    lse = lse_ref[0, 0, 0, :][:, None]
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)
    dv_ref[0] = _dot(p.astype(do.dtype).T, do).astype(dv_ref.dtype)
    dp = _dot(do, v, trans_b=True)
    delta = delta_ref[0, 0, 0, :][:, None]
    ds = p * (dp - delta) * scale
    dq_ref[0] = _dot(ds.astype(k.dtype), k).astype(dq_ref.dtype)
    dk_ref[0] = _dot(ds.astype(q.dtype).T, q).astype(dk_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, res, do, kv_mask=None):
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    bq = min(block_q, max(8, sq))
    bk = min(block_k, max(128, sk))
    q3 = _pad_to(q.reshape(b * h, sq, d), 1, bq)
    k3 = _pad_to(k.reshape(b * h, sk, d), 1, bk)
    v3 = _pad_to(v.reshape(b * h, sk, d), 1, bk)
    do3 = _pad_to(do.reshape(b * h, sq, d), 1, bq)
    bh, psq, _ = q3.shape
    psk = k3.shape[1]
    nq, nk = psq // bq, psk // bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, sq)
    delta = _pad_to(delta, 1, bq)
    lse_p = _pad_to(lse, 1, bq)
    lse8 = _rows8(lse_p, bq)
    delta8 = _rows8(delta, bq)
    has_kvm = kv_mask is not None
    kvm = _kvm8(kv_mask, b, psk, bk) if has_kvm else None

    if nq == 1 and nk == 1 and d <= 64:
        # Single-block fast path (e.g. GPT-345M s=1024 at the default
        # 1024-blocks; ring-attention shards): one fused kernel, 5
        # matmuls instead of 7.  d <= 64 keeps VMEM ~10 MB
        # (2 score-shaped fp32 temps + 7 thin operands).
        qb_spec = pl.BlockSpec((1, psq, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        kb_spec = pl.BlockSpec((1, psk, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        rb_spec = pl.BlockSpec((1, 1, 8, bq), lambda b_: (b_, 0, 0, 0),
                               memory_space=pltpu.VMEM)
        in_specs = [qb_spec, kb_spec, kb_spec, qb_spec, rb_spec,
                    rb_spec]
        operands = [q3, k3, v3, do3, lse8, delta8]
        if has_kvm:
            in_specs.append(pl.BlockSpec(
                (1, 1, 8, bk), lambda b_: (b_ // h, 0, 0, 0),
                memory_space=pltpu.VMEM))
            operands.append(kvm)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale, causal,
                              has_kvm, sq, sk),
            grid=(bh,),
            in_specs=in_specs,
            out_specs=[qb_spec, kb_spec, kb_spec],
            out_shape=[jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, psk, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, psk, d), v.dtype)],
            interpret=_interpret(),
        )(*operands)
        return (dq[:, :sq].reshape(b, h, sq, d),
                dk[:, :sk].reshape(b, h, sk, d),
                dv[:, :sk].reshape(b, h, sk, d))

    q_spec_i = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_j = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0),
                            memory_space=pltpu.VMEM)
    r_spec_i = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [q_spec_i, k_spec_j, k_spec_j, q_spec_i, r_spec_i,
                r_spec_i]
    operands = [q3, k3, v3, do3, lse8, delta8]
    if has_kvm:
        # kv mask indexed by the K block (grid dim 2 here)
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale, causal, has_kvm, sq,
                          sk, bq, bk),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    q_spec_j = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, j, 0),
                            memory_space=pltpu.VMEM)
    k_spec_i = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, i, 0),
                            memory_space=pltpu.VMEM)
    r_spec_j = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, j, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec_j, k_spec_i, k_spec_i, q_spec_j, r_spec_j,
                r_spec_j]
    operands = [q3, k3, v3, do3, lse8, delta8]
    if has_kvm:
        # kv mask indexed by the K block (grid dim 1 here)
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, i, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale, causal, has_kvm, sq,
                          sk, bq, bk),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[k_spec_i, k_spec_i],
        out_shape=[jax.ShapeDtypeStruct((bh, psk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, psk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    return (dq[:, :sq].reshape(b, h, sq, d),
            dk[:, :sk].reshape(b, h, sk, d),
            dv[:, :sk].reshape(b, h, sk, d))


# --- public API ------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_fused(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           scale: Optional[float] = None,
                           causal: bool = False,
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K) -> jnp.ndarray:
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)[0]


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    scale: Optional[float] = None,
                    causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    kv_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Fused attention: softmax(q k^T * scale [masked]) v.

    Shapes: q (b, h, sq, d); k, v (b, h, sk, d).  ``scale`` defaults to
    1/sqrt(d).  ``kv_mask`` (b, sk), True/nonzero = attend, masks
    padding KEYS (the BERT padding-attention case) — a capability the
    reference's FMHA lacks entirely (seqlen<=512, no mask support,
    ref: setup.py:408-424); composes with ``causal``.  Inside
    shard_map manual axes the XLA reference path runs (Pallas calls
    cannot yet carry VMA types).
    """
    from ._context import in_manual_axis_context

    if in_manual_axis_context(q, k, v):
        return mha_reference(q, k, v, scale=scale, causal=causal,
                             kv_mask=kv_mask)
    if kv_mask is not None:
        return _flash_attention_masked(q, k, v,
                                       kv_mask.astype(jnp.float32),
                                       scale, causal, block_q, block_k)
    return _flash_attention_fused(q, k, v, scale, causal, block_q, block_k)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, do):
    if scale is None:
        scale = res[0].shape[-1] ** -0.5
    return _flash_bwd(scale, causal, block_q, block_k, res, do)


_flash_attention_fused.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention_masked(q, k, v, kv_mask, scale, causal,
                            block_q, block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                      kv_mask=kv_mask)[0]


def _flash_masked_vjp_fwd(q, k, v, kv_mask, scale, causal, block_q,
                          block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        kv_mask=kv_mask)
    return o, (q, k, v, o, lse, kv_mask)


def _flash_masked_vjp_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse, kv_mask = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k,
                            (q, k, v, o, lse), do, kv_mask=kv_mask)
    # the (float) mask is a constant of the computation
    return dq, dk, dv, jnp.zeros_like(kv_mask)


_flash_attention_masked.defvjp(_flash_masked_vjp_fwd,
                               _flash_masked_vjp_bwd)


def mha_reference(q, k, v, scale=None, causal=False, kv_mask=None):
    """Unfused reference (the [b,h,sq,sk]-materializing baseline the
    reference's standalone GPT uses) — for parity tests and benchmarks.
    ``kv_mask`` (b, sk): True/nonzero = attend."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    sq, sk = s.shape[-2:]
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, _NEG)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :].astype(bool), s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
