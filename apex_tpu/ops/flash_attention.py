"""Pallas flash attention (forward + backward), bf16-first.

TPU-native successor to the reference's fused attention kernels: FMHA
(ref: apex/contrib/csrc/fmha — sm80, seqlen <= 512, head dim 64) and the
fast_multihead_attn family (ref: apex/contrib/csrc/multihead_attn).
Blockwise online-softmax attention removes both the O(s^2)
materialization (the reference's core attention materializes
[b, np, sq, sk], ref: apex/transformer/testing/standalone_gpt.py) and
the shape caps: any sq/sk (padded to block multiples), head dim 64-256,
causal or full attention.

Layout: q (b, h, sq, d), k/v (b, h, sk, d).  Matmuls hit the MXU in the
input dtype with fp32 accumulation; softmax math is fp32.  At d=64 with
even h the per-tensor drivers pack head PAIRS onto one 128-lane tile
and run every matmul full-width via a sign rotation — see the
head-packing note above ``set_head_packing`` (escape hatch:
``APEX_TPU_FLASH_PACK_D64=0``).

Kernel-economy notes (v5e profile at GPT-345M shapes, b=8 h=16 s=1024
d=64; structural matmul minimum fwd 262 us / bwd 611 us per call):
- ``exp2`` with pre-folded constants: softmax runs as
  ``exp2(s*a - m*a)`` with ``a = scale*log2(e)``, so no separate
  ``s*scale`` pass over the (bq, bk) score array and no ln<->log2
  conversion inside the hot loop.
- scale folding: the backward feeds ``v*scale`` to the ``dp`` matmul
  and pre-scales ``delta`` outside the kernel, turning
  ``ds = p*(dp-delta)*scale`` into ``ds = p*(dp'-delta')`` — one fewer
  score-shaped multiply.
- no materialized transposes: ``dv = p^T do`` / ``dk = ds^T q`` use
  ``dot_general`` contracting dim 0 of both operands (MXU-native)
  instead of ``.T``-then-matmul, which lowers to cross-lane VPU
  shuffles over the full score block.
- static mask elision: block-aligned sequences (the common case) skip
  the ``k_pos < sk`` compare entirely; q-padded rows are killed by
  padding the saved logsumexp with +BIG (``exp2 -> 0``) rather than by
  per-element masks.  Only ``causal`` and ``kv_mask`` pay a select.

Backward: when the padded sequence fits one block and d <= 64 (the
common case at the default 1024 blocks — e.g. GPT-345M s=1024), a
single fused kernel produces dq/dk/dv in one pass (5 matmuls; scores
and dp computed once).  Otherwise the standard two-kernel flash
backward runs: a dq pass (grid over q-blocks, accumulate over k) and a
dk/dv pass (grid over k-blocks, accumulate over q), both recomputing
probabilities from the saved per-row logsumexp.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_BIG = 1e30
_LOG2E = math.log2(math.e)
# Tuned on v5e via the GPT-345M train-step profile (b=8, h=16, s=1024,
# d=64; device-time deltas are stable run-to-run even when wall clock is
# not): (1024, 1024) beats (512, 1024) — 56.4 vs 62.2 ms/step of kernel
# time across fwd+bwd — and (512, 512) loses despite its finer causal
# block skipping; wide lanes win on the MXU.  VMEM at (1024, 1024),
# d<=256: q/k/v/acc blocks + fp32 scores ~7 MB, within the 16 MB
# budget (at d > 64 block_q is halved — see _clamp_blocks).  Env
# overrides (read at import) for bench-driven re-tuning.
from ..analysis.flags import flag_bool, flag_int

DEFAULT_BLOCK_Q = flag_int("APEX_TPU_FLASH_BLOCK_Q")
DEFAULT_BLOCK_K = flag_int("APEX_TPU_FLASH_BLOCK_K")

# --- d=64 head packing ------------------------------------------------------
#
# A d=64 head fills only HALF the 128-wide MXU lane tile: q k^T contracts
# 64 of 128 lanes and p v emits 64 of 128 output lanes, so the unpacked
# kernels cap near half the d=128 rate (round-5 BENCH_FULL.json:
# 52.6/52.8 TF/s device at s=8192/16384 vs 97.3-98.2 at d=128) — at the
# reference FMHA's ONLY supported head dim (ref: setup.py:408-424).
#
# Fix: when d == 64 and h is even, the (b, h, s, d) drivers pack adjacent
# head pairs into one 128-lane tile, (b, h, s, 64) -> (b, h/2, s, 128),
# and every per-head matmul pair is recovered from two FULL-WIDTH
# matmuls via a sign rotation.  With sigma = [+1]*64 ++ [-1]*64 on the
# packed lane axis and packed operands X = [X0|X1], W = [W0|W1]:
#
#   X W^T         = X0 W0^T + X1 W1^T        (contraction: all 128 lanes)
#   X (W*sigma)^T = X0 W0^T - X1 W1^T
#
# so S0/S1 fall out of a half-sum/half-difference instead of two
# half-width d=64 contractions; the mirrored combine
# ((A0+A1) W + (A0-A1) (W*sigma)) / 2 = [A0 W0 | A1 W1] does the same
# for the products whose OUTPUT axis is the packed lane axis (p v, ds k,
# and the dim-0-contracting dk/dv forms).  Cross-head terms cancel in
# the rotation algebra — no block-diagonal masking pass exists anywhere.
# Per k-block a packed program runs 2 matmuls per score-side product for
# BOTH heads where the unpacked kernel ran 2 half-width ones PER head:
# ~2x useful MXU throughput.  Softmax, causal/segment masking, the
# dropout coordinate hash (per GLOBAL head) and the lse/delta sidebands
# stay per-head, so the packed path is numerically the same computation
# up to fp reassociation in the rotation.  One rounding caveat beyond
# pure reassociation: in the low-precision combines the SUM/DIFFERENCE
# of the pair's score-shaped arrays is what gets rounded to the input
# dtype, so each head's products carry absolute error ~ulp of the
# PAIR's combined magnitude — in bf16, a head whose ds/p run orders of
# magnitude below its partner's absorbs noise at the partner's ulp
# scale (the unpacked path rounds each head alone).  Harmless at
# training tolerances; flip the escape hatch if a workload needs
# per-head-exact bf16 rounding.
#
# Escape hatch: APEX_TPU_FLASH_PACK_D64=0 (read at import) or
# set_head_packing(False) forces the old half-width path.  Packing is an
# implementation detail with no semantic contract — even a packed-fwd /
# unpacked-bwd mix is exact, because the backward recomputes p from the
# per-head lse and the dropout mask is coordinate-hashed, never
# tiling-derived.
_PACK_D64 = {"enabled": flag_bool("APEX_TPU_FLASH_PACK_D64")}


def set_head_packing(enabled: bool) -> None:
    """Toggle the d=64 head-pair packing (see the module note above).
    Flip OUTSIDE jit traces: a cached trace keeps whatever layout it was
    traced with (the results agree either way)."""
    _PACK_D64["enabled"] = bool(enabled)


def head_packing_enabled() -> bool:
    return _PACK_D64["enabled"]


def _use_head_packing(h: int, d: int) -> bool:
    return d == 64 and h % 2 == 0 and _PACK_D64["enabled"]


def _pack_head_pairs(x):
    """(b, h, s, d) -> (b, h/2, s, 2d): head 2j in lanes [0, d), head
    2j+1 in lanes [d, 2d) of pair j."""
    b, h, s, d = x.shape
    return x.reshape(b, h // 2, 2, s, d).transpose(0, 1, 3, 2, 4) \
        .reshape(b, h // 2, s, 2 * d)


def _unpack_head_pairs(x):
    """Inverse of :func:`_pack_head_pairs`."""
    b, hp, s, d2 = x.shape
    return x.reshape(b, hp, s, 2, d2 // 2).transpose(0, 1, 3, 2, 4) \
        .reshape(b, 2 * hp, s, d2 // 2)


def _lane_sign(dtype, width):
    """sigma row of the packing rotation: +1 on the first lane half,
    -1 on the second."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, width), 1)
    return jnp.where(lane < width // 2, 1.0, -1.0).astype(dtype)


def _packed_scores(x, w):
    """Both heads' (m, n) score-shaped products from lane-packed
    x = [X0|X1], w = [W0|W1]: returns (X0 W0^T, X1 W1^T) via the sum
    and sigma-rotated difference — two matmuls whose contraction spans
    all 128 lanes.  Serves q k^T and the backward's do (v*scale)^T."""
    sig = _lane_sign(w.dtype, w.shape[-1])
    ssum = _dot(x, w, trans_b=True)
    sdif = _dot(x, w * sig, trans_b=True)
    return 0.5 * (ssum + sdif), 0.5 * (ssum - sdif)


def _packed_out(a0, a1, w):
    """[A0 W0 | A1 W1] from per-head score-shaped A and lane-packed
    w = [W0|W1] — the mirrored combine keeps the OUTPUT lane axis
    full-width.  Serves p v (forward acc) and ds k (dq)."""
    sig = _lane_sign(w.dtype, w.shape[-1])
    asum = (a0 + a1).astype(w.dtype)
    adif = (a0 - a1).astype(w.dtype)
    return 0.5 * (_dot(asum, w) + _dot(adif, w * sig))


def _packed_out_t0(a0, a1, w):
    """[A0^T W0 | A1^T W1] — the dim-0-contracting (dk/dv) form of
    :func:`_packed_out`."""
    sig = _lane_sign(w.dtype, w.shape[-1])
    asum = (a0 + a1).astype(w.dtype)
    adif = (a0 - a1).astype(w.dtype)
    return 0.5 * (_dot_t0(asum, w) + _dot_t0(adif, w * sig))


def _pack_lane_cols(c0, c1, width):
    """Per-head (rows, 1) columns -> a (rows, width) lane-selected
    array: head 0's value on the first lane half, head 1's on the
    second (the packed accumulator's corr / 1/l multiplier)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, width), 1)
    return jnp.where(lane < width // 2, c0, c1)


def _clamp_blocks(block_q: int, block_k: int, d: int):
    """VMEM guard: the dk/dv backward holds four fp32 score-shaped
    temporaries (bq, bk) plus blocks and accumulators scaling with d.
    At d=64 (1024, 1024) fits comfortably; beyond that halve block_q so
    the worst case (d=256) stays ~11 MB of the 16 MB budget."""
    if d > 64:
        block_q = min(block_q, 512)
    return block_q, block_k


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot(a, b, trans_b=False):
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _dot_t0(a, b):
    """a^T @ b via dot_general contracting dim 0 of both operands —
    the MXU consumes the transposed layout natively; an explicit
    ``a.T`` would materialize the block through VPU lane shuffles."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _tri_mask(shape, q_off, k_off):
    """q_pos >= k_pos causal mask from thin iotas (broadcast compare:
    no full-block int32 position arrays)."""
    q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (shape[0], 1), 0)
    k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (1, shape[1]), 1)
    return q_pos >= k_pos


def _kcol_mask(shape, k_off, sk):
    k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (1, shape[1]), 1)
    return jnp.broadcast_to(k_pos < sk, shape)


def _u32of(x):
    """Non-negative int -> uint32 view (mask to 31 bits first: Mosaic
    has no checked int32->uint32 cast; see the seed contract note in
    :func:`flash_attention_e`)."""
    return jnp.bitwise_and(jnp.asarray(x, jnp.int32),
                           jnp.int32(0x7FFFFFFF)).astype(jnp.uint32)


def _keep_from_x(x, rate):
    """fmix32 + top-24-bit uniform -> keep mask (prob. 1 - rate)."""
    u32 = functools.partial(jnp.asarray, dtype=jnp.uint32)
    x = (x ^ (x >> 16)) * u32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * u32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # bitcast to int32 before the float convert — Mosaic has no
    # uint32->f32 cast, and after >> 8 the sign bit is 0
    f = jax.lax.bitcast_convert_type(x >> 8, jnp.int32) \
        .astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
    return f >= jnp.float32(rate)


def _rand_keep_coords(shape, seed, salt_b, salt_head, row0, col0, rate):
    """Tiling-INDEPENDENT dropout keep mask: a pure function of
    (seed, batch, global head, GLOBAL row, GLOBAL col), so any block
    decomposition of the score matrix regenerates identical bits.  The
    sequence-parallel paths need exactly this: ring shards evaluate
    disjoint (row, col) windows of one global score matrix across
    differently-tiled fwd/bwd kernels, and the union must equal the
    mask a dense evaluation would draw (Liu et al. ring attention +
    the reference's in-kernel philox role, ref:
    apex/contrib/csrc/multihead_attn/dropout.h).

    ``row0``/``col0`` place ``shape`` in global coordinates (traced
    OK).  Global cols must stay below the 0x01000193 row stride for
    per-element uniqueness — 16.7M, far past any sequence here."""
    u32 = functools.partial(jnp.asarray, dtype=jnp.uint32)
    salt = (_u32of(seed) * u32(0x85EBCA6B)
            ^ _u32of(salt_b) * u32(0xC2B2AE35)
            ^ _u32of(salt_head) * u32(0x27D4EB2F))
    r = _u32of(row0) + jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    c = _u32of(col0) + jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    return _keep_from_x(r * u32(0x01000193) + c + salt, rate)


def rand_keep_global(shape, seed, rate, batch_offset=0, head_offset=0,
                     q_offset=0, k_offset=0):
    """(b, h, sq, sk) version of :func:`_rand_keep_coords` —
    bit-identical to the dropout partial kernels' masks, for the
    einsum sequence-parallel paths and for tests reassembling the
    expected global mask."""
    u32 = functools.partial(jnp.asarray, dtype=jnp.uint32)
    bi = _u32of(batch_offset) \
        + jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    hi = _u32of(head_offset) \
        + jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    salt = (_u32of(seed) * u32(0x85EBCA6B)
            ^ bi * u32(0xC2B2AE35)
            ^ hi * u32(0x27D4EB2F))
    r = _u32of(q_offset) + jax.lax.broadcasted_iota(jnp.uint32, shape, 2)
    c = _u32of(k_offset) + jax.lax.broadcasted_iota(jnp.uint32, shape, 3)
    return _keep_from_x(r * u32(0x01000193) + c + salt, rate)


# --- forward ---------------------------------------------------------------

def _fwd_single_kernel(scale, a, causal, has_kvm, has_off, kpad, sq, sk,
                       *refs, drop=0.0, h=1, pack=False):
    """Whole-(padded)-sequence-in-one-block forward: plain softmax, no
    online-correction carries (the default 1024 blocks put GPT s=1024
    and BERT s=512 here).  ``has_off``: a leading SMEM ref carries
    [q_offset, k_offset] GLOBAL positions for the causal mask (the
    ring-attention partial — offsets are traced, so the mask compare
    runs every call; VPU work is hidden behind the MXU).  ``drop``:
    after the (optional) off ref an SMEM [seed, head_offset, q_offset,
    k_offset] ref salts the coordinate-hash keep mask (the SP dropout
    route; dropout's own offsets are separate from ``has_off`` because
    non-causal ring blocks drop the causal offsets entirely).
    ``pack``: q/k/v blocks carry a d=64 head PAIR on 128 lanes and
    ``h`` counts head PAIRS; per-head scores come from the sigma
    rotation (see the module head-packing note) and softmax/masking/
    dropout/lse run per head; lse_ref carries 16 sublanes (head 2j on
    rows 0-7, 2j+1 on 8-15)."""
    if has_off:
        off_ref, *refs = refs
        qoff, koff = off_ref[0], off_ref[1]
    else:
        qoff = koff = 0
    if drop > 0.0:
        dsalt_ref, *refs = refs
    q_ref, k_ref, v_ref, *rest = refs
    if has_kvm:
        kvm_ref, o_ref, lse_ref = rest
    else:
        kvm_ref = None
        o_ref, lse_ref = rest
    q = q_ref[0]
    k = k_ref[0]
    # raw logits, fp32; packed: both heads via two full-width matmuls
    heads = _packed_scores(q, k) if pack \
        else (_dot(q, k, trans_b=True),)
    mask = None
    if causal:
        mask = _tri_mask(heads[0].shape, qoff, koff)
    if kpad and not has_kvm:
        # _kvm8 zero-pads, so kv_mask already masks pad columns
        km = _kcol_mask(heads[0].shape, 0, sk)
        mask = km if mask is None else (mask & km)
    if has_kvm:
        vm = kvm_ref[0, 0, 0, :][None, :] > 0
        mask = vm if mask is None else (mask & vm)
    guard_dead = has_kvm or (has_off and causal)
    if drop > 0.0:
        bh_i = pl.program_id(0)
    stats = []
    pas = []
    for hh, s in enumerate(heads):
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        m = jnp.max(s, axis=1, keepdims=True)         # raw units
        p = jnp.exp2((s - m) * a)
        l = jnp.sum(p, axis=1, keepdims=True)
        if guard_dead:
            # fully-masked rows (all keys masked, or an offset block
            # whose keys are all in the causal future): m stayed at
            # _NEG so (s - m) = 0 and p = 1 spuriously; zero them via
            # the row max instead of a score-shaped select.
            dead = m <= _NEG * 0.5
            l = jnp.where(dead, 0.0, l)
        else:
            dead = None
        pa = p
        if drop > 0.0:
            # l stays undropped (normalization by the true denominator);
            # only the accumulated values drop — the lse-merge across
            # ring blocks then reproduces dense in-kernel dropout
            # exactly.  Packed: the GLOBAL head index salts each half.
            head_ix = dsalt_ref[1] + (2 * (bh_i % h) + hh if pack
                                      else bh_i % h)
            keep = _rand_keep_coords(p.shape, dsalt_ref[0], bh_i // h,
                                     head_ix, dsalt_ref[2],
                                     dsalt_ref[3], drop)
            pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
        stats.append((m, l, dead))
        pas.append(pa)
    if pack:
        acc = _packed_out(pas[0], pas[1], v_ref[0])
        (m0, l0, dead0), (m1, l1, dead1) = stats
        sl0 = jnp.where(l0 == 0.0, 1.0, l0)
        sl1 = jnp.where(l1 == 0.0, 1.0, l1)
        o = acc * _pack_lane_cols(1.0 / sl0, 1.0 / sl1, acc.shape[1])
        if guard_dead:
            o = jnp.where(_pack_lane_cols(dead0, dead1, acc.shape[1]),
                          0.0, o)
        o_ref[0] = o.astype(o_ref.dtype)
        half = lse_ref.shape[2] // 2
        tail = lse_ref.shape[3:]
        lse0 = m0 * scale + jnp.log(sl0)
        lse1 = m1 * scale + jnp.log(sl1)
        lse_ref[0, 0] = jnp.concatenate(
            [jnp.broadcast_to(lse0[:, 0][None, :], (half,) + tail),
             jnp.broadcast_to(lse1[:, 0][None, :], (half,) + tail)],
            axis=0)
        return
    acc = _dot(pas[0].astype(v_ref.dtype), v_ref[0])
    m, l, dead = stats[0]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o = acc / safe_l
    if guard_dead:
        o = jnp.where(dead, 0.0, o)
    o_ref[0] = o.astype(o_ref.dtype)
    lse = m * scale + jnp.log(safe_l)
    lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0][None, :],
                                     lse_ref.shape[2:])


def _fwd_kernel(scale, a, causal, has_kvm, has_off, kpad, sq, sk, bq, bk,
                *refs, drop=0.0, h=1, pack=False):
    if has_off:
        off_ref, *refs = refs
        qoff, koff = off_ref[0], off_ref[1]
    else:
        qoff = koff = 0
    if drop > 0.0:
        dsalt_ref, *refs = refs
    q_ref, k_ref, v_ref, *rest = refs
    if has_kvm:
        kvm_ref, o_ref, lse_ref, acc, m_sc, l_sc = rest
    else:
        kvm_ref = None
        o_ref, lse_ref, acc, m_sc, l_sc = rest
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    # program ids read OUTSIDE the pl.when bodies: inside them the
    # primitive sits in a cond branch that interpret mode cannot lower
    bh_i = pl.program_id(0) if drop > 0.0 else 0

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    run = (j * bk + koff <= i * bq + qoff + bq - 1) if causal \
        else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        # raw logits, fp32; packed: two heads per program, m/l carries
        # in scratch column hh (the E blocked kernel's idiom)
        heads = _packed_scores(q, k) if pack \
            else (_dot(q, k, trans_b=True),)
        mask = None
        if causal:
            mask = _tri_mask(heads[0].shape, i * bq + qoff,
                             j * bk + koff)
        if kpad and not has_kvm:
            # _kvm8 zero-pads, so kv_mask already masks pad columns
            km = _kcol_mask(heads[0].shape, j * bk, sk)
            mask = km if mask is None else (mask & km)
        if has_kvm:
            vm = kvm_ref[0, 0, 0, :][None, :] > 0
            mask = vm if mask is None else (mask & vm)
        pas, corrs = [], []
        for hh, s in enumerate(heads):
            if mask is not None:
                s = jnp.where(mask, s, _NEG)
            m_prev = m_sc[:, hh:hh + 1]
            m_cur = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp2((m_prev - m_cur) * a)
            p = jnp.exp2((s - m_cur) * a)
            if has_kvm or (has_off and causal):
                # rows with every key masked so far keep m_cur = _NEG
                # and (s - m_cur) = 0 at masked entries — zero p
                # explicitly so such rows sum to l = 0 and emit exactly
                # 0 (matching the backward, where masked entries
                # recompute p = 0).  The has_off case: a q-block
                # straddling the k_offset boundary runs with some rows
                # entirely in the causal future.
                p = jnp.where(mask, p, 0.0)
            l_new = l_sc[:, hh:hh + 1] * corr \
                + jnp.sum(p, axis=1, keepdims=True)
            pa = p
            if drop > 0.0:
                # see _fwd_single_kernel: values drop, l does not;
                # packed salts by the GLOBAL head index of each half
                head_ix = dsalt_ref[1] + (2 * (bh_i % h) + hh if pack
                                          else bh_i % h)
                keep = _rand_keep_coords(
                    p.shape, dsalt_ref[0], bh_i // h, head_ix,
                    dsalt_ref[2] + i * bq, dsalt_ref[3] + j * bk, drop)
                pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
            pas.append(pa)
            corrs.append(corr)
            if pack:
                m_sc[:, hh:hh + 1] = m_cur
                l_sc[:, hh:hh + 1] = l_new
            else:
                m_sc[:] = jnp.broadcast_to(m_cur, m_sc.shape)
                l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)
        if pack:
            corr_w = _pack_lane_cols(corrs[0], corrs[1], acc.shape[1])
            acc[:] = acc[:] * corr_w \
                + _packed_out(pas[0], pas[1], v_ref[0])
        else:
            acc[:] = acc[:] * corrs[0] \
                + _dot(pas[0].astype(v_ref.dtype), v_ref[0])

    @pl.when(j == nk - 1)
    def _finish():
        if pack:
            l0 = l_sc[:, :1]
            l1 = l_sc[:, 1:2]
            sl0 = jnp.where(l0 == 0.0, 1.0, l0)   # fully-masked rows
            sl1 = jnp.where(l1 == 0.0, 1.0, l1)   # -> zeros
            inv = _pack_lane_cols(1.0 / sl0, 1.0 / sl1, acc.shape[1])
            o_ref[0] = (acc[:] * inv).astype(o_ref.dtype)
            half = lse_ref.shape[2] // 2
            tail = lse_ref.shape[3:]
            lse0 = m_sc[:, :1] * scale + jnp.log(sl0)
            lse1 = m_sc[:, 1:2] * scale + jnp.log(sl1)
            lse_ref[0, 0] = jnp.concatenate(
                [jnp.broadcast_to(lse0[:, 0][None, :], (half,) + tail),
                 jnp.broadcast_to(lse1[:, 0][None, :], (half,) + tail)],
                axis=0)
            return
        l = l_sc[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse = m_sc[:, :1] * scale + jnp.log(l)
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0][None, :],
                                         lse_ref.shape[2:])


def _pad_to(x, axis, mult, value=0.0):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _kvm8(kv_mask, b, psk, bk):
    """(b, sk) key-validity mask -> (b, psk/bk, 8, bk) sublane-
    replicated fp32 blocks (same trick as :func:`_rows8`).  Pads with
    zeros (= masked) to ``psk`` EXACTLY — the packed path's padded
    length can exceed the next bk multiple of sk."""
    m = kv_mask.astype(jnp.float32)
    if m.shape[1] < psk:
        m = jnp.pad(m, ((0, 0), (0, psk - m.shape[1])))
    return jnp.broadcast_to(
        m.reshape(b, psk // bk, 1, bk), (b, psk // bk, 8, bk))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, kv_mask=None,
               offsets=None, drop=0.0, dsalt=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    pack = _use_head_packing(h, d)
    if pack:
        # d=64 head-pair packing (module note): adjacent heads share a
        # 128-lane tile; h counts PAIRS below, lse carries 2 sublane
        # groups per q-block and unpacks to per-head order at the end.
        q, k, v = (_pack_head_pairs(x) for x in (q, k, v))
        h, d = h // 2, 2 * d
    g = 2 if pack else 1
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    bq = min(block_q, max(8, sq))
    bk = min(block_k, max(128, sk))
    q3 = _pad_to(q.reshape(b * h, sq, d), 1, bq)
    k3 = _pad_to(k.reshape(b * h, sk, d), 1, bk)
    v3 = _pad_to(v.reshape(b * h, sk, d), 1, bk)
    bh, psq, _ = q3.shape
    psk = k3.shape[1]
    nq, nk = psq // bq, psk // bk
    a = scale * _LOG2E
    kpad = psk != sk

    def _unpack(o, lse8):
        lse = lse8[:, :, 0, :].reshape(bh, psq)[:, :sq]
        if not pack:
            return o[:, :sq].reshape(b, h, sq, d), lse
        o4 = _unpack_head_pairs(o[:, :sq].reshape(b, h, sq, d))
        lse1 = lse8[:, :, 8, :].reshape(bh, psq)[:, :sq]
        # (bh_pairs, 2, sq) flattens straight to global head order:
        # pair j holds heads 2j / 2j+1
        lse = jnp.stack([lse, lse1], axis=1).reshape(bh * 2, sq)
        return o4, lse

    has_kvm = kv_mask is not None
    has_off = offsets is not None and causal
    if nq == 1 and nk == 1:
        qb_spec = pl.BlockSpec((1, psq, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        kb_spec = pl.BlockSpec((1, psk, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        lse_spec = pl.BlockSpec((1, 1, 8 * g, bq),
                                lambda b_: (b_, 0, 0, 0),
                                memory_space=pltpu.VMEM)
        in_specs = [qb_spec, kb_spec, kb_spec]
        operands = [q3, k3, v3]
        if drop > 0.0:
            in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.insert(0, dsalt)
        if has_off:
            in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.insert(0, offsets)
        if has_kvm:
            in_specs.append(pl.BlockSpec(
                (1, 1, 8, bk), lambda b_: (b_ // h, 0, 0, 0),
                memory_space=pltpu.VMEM))
            operands.append(_kvm8(kv_mask, b, psk, bk))
        o, lse8 = pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale, a, causal,
                              has_kvm, has_off, kpad, sq, sk,
                              drop=drop, h=h, pack=pack),
            grid=(bh,),
            in_specs=in_specs,
            out_specs=[qb_spec, lse_spec],
            out_shape=[
                jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, 1, 8 * g, bq), jnp.float32),
            ],
            interpret=_interpret(),
        )(*operands)
        return _unpack(o, lse8)

    q_spec = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, 8 * g, bq),
                            lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, k_spec, k_spec]
    operands = [q3, k3, v3]
    if drop > 0.0:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, dsalt)
    if has_off:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, offsets)
    if has_kvm:
        kvm_spec = pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM)
        in_specs.append(kvm_spec)
        operands.append(_kvm8(kv_mask, b, psk, bk))
    o, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, a, causal, has_kvm,
                          has_off, kpad, sq, sk, bq, bk,
                          drop=drop, h=h, pack=pack),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8 * g, bq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return _unpack(o, lse8)


def _flash_fwd_packed(qkv, b, h, scale, causal, block_q, block_k,
                      kv_mask=None):
    """Self-attention forward over PACKED qkv (3*b*h, s, d): q/k/v are
    row-ranges of one contiguous array, read via index-map offsets into
    the SAME operand — no per-tensor relayout copies at the custom-call
    boundary (measured 7.5 ms/step of pure (b,h,s,d) layout copies at
    GPT-345M with the unpacked entry)."""
    bh = b * h
    s, d = qkv.shape[1], qkv.shape[2]
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    # clamp both blocks to s rounded up to the 128-lane grain: an
    # s-sized bq next to a 128-floored bk would make lcm(bq, bk) — the
    # shared padded length both block grids must divide — blow up
    # (s=50 with default blocks: lcm(50, 128) = 3200).
    grain = -(-s // 128) * 128
    bq = min(block_q, grain)
    bk = min(block_k, grain)
    qkv3 = _pad_to(qkv, 1, math.lcm(bq, bk))
    ps = qkv3.shape[1]
    nq, nk = ps // bq, ps // bk
    a = scale * _LOG2E
    kpad = ps != s
    has_kvm = kv_mask is not None

    if nq == 1 and nk == 1:
        def blkspec(off):
            return pl.BlockSpec((1, ps, d),
                                lambda b_, o=off: (b_ + o, 0, 0),
                                memory_space=pltpu.VMEM)
        lse_spec = pl.BlockSpec((1, 1, 8, bq), lambda b_: (b_, 0, 0, 0),
                                memory_space=pltpu.VMEM)
        in_specs = [blkspec(0), blkspec(bh), blkspec(2 * bh)]
        operands = [qkv3, qkv3, qkv3]
        if has_kvm:
            in_specs.append(pl.BlockSpec(
                (1, 1, 8, bk), lambda b_: (b_ // h, 0, 0, 0),
                memory_space=pltpu.VMEM))
            operands.append(_kvm8(kv_mask, b, ps, bk))
        o_spec = pl.BlockSpec((1, ps, d), lambda b_: (b_, 0, 0),
                              memory_space=pltpu.VMEM)
        o, lse8 = pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale, a, causal,
                              has_kvm, False, kpad, s, s),
            grid=(bh,),
            in_specs=in_specs,
            out_specs=[o_spec, lse_spec],
            out_shape=[
                jax.ShapeDtypeStruct((bh, ps, d), qkv.dtype),
                jax.ShapeDtypeStruct((bh, 1, 8, bq), jnp.float32),
            ],
            interpret=_interpret(),
        )(*operands)
        lse = lse8[:, :, 0, :].reshape(bh, ps)[:, :s]
        return o[:, :s], lse

    def qspec(off):
        return pl.BlockSpec((1, bq, d),
                            lambda b_, i, j, o=off: (b_ + o, i, 0),
                            memory_space=pltpu.VMEM)

    def kspec(off):
        return pl.BlockSpec((1, bk, d),
                            lambda b_, i, j, o=off: (b_ + o, j, 0),
                            memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [qspec(0), kspec(bh), kspec(2 * bh)]
    operands = [qkv3, qkv3, qkv3]
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(_kvm8(kv_mask, b, ps, bk))
    o, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, a, causal, has_kvm,
                          False, kpad, s, s, bq, bk),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[qspec(0), lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, ps, d), qkv.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, bq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    lse = lse8[:, :, 0, :].reshape(bh, ps)[:, :s]
    return o[:, :s], lse


# --- backward --------------------------------------------------------------
#
# All backward kernels recompute p as exp2(s*a - lse2) where
# lse2 = lse*log2(e) is pre-scaled OUTSIDE the kernel and q-padded rows
# get lse2 = +BIG (p underflows to exactly 0 — no q-position masks).
# v arrives pre-multiplied by ``scale`` so ds = p*(dp' - delta') needs
# no trailing ``*scale`` (delta' = delta*scale, also outside).  k-padded
# columns keep a (static, unaligned-only) mask: their s is 0 so
# p = exp2(-lse2) which can overflow to inf when lse is very negative,
# and inf * the zero k-pad rows would NaN dq.  The kv_mask path needs
# no kpad mask — _kvm8 zero-pads, masking pad columns for free.

def _bwd_dq_kernel(a, vscale, causal, has_kvm, has_off, kpad, sq, sk,
                   bq, bk, *refs, drop=0.0, h=1, pack=False):
    if has_off:
        off_ref, *refs = refs
        qoff, koff = off_ref[0], off_ref[1]
    else:
        qoff = koff = 0
    if drop > 0.0:
        dsalt_ref, *refs = refs
    q_ref, k_ref, v_ref, do_ref, lse2_ref, delta_ref, *rest = refs
    if has_kvm:
        kvm_ref, dq_ref, dq_acc = rest
    else:
        kvm_ref = None
        dq_ref, dq_acc = rest
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    bh_i = pl.program_id(0) if drop > 0.0 else 0   # see _fwd_kernel

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (j * bk + koff <= i * bq + qoff + bq - 1) if causal \
        else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        heads = _packed_scores(q, k) if pack \
            else (_dot(q, k, trans_b=True),)
        mask = None
        if causal:
            mask = _tri_mask(heads[0].shape, i * bq + qoff,
                             j * bk + koff)
        if kpad and not has_kvm:
            km = _kcol_mask(heads[0].shape, j * bk, sk)
            mask = km if mask is None else (mask & km)
        if has_kvm:
            vm = kvm_ref[0, 0, 0, :][None, :] > 0
            mask = vm if mask is None else (mask & vm)
        vs = v_ref[0] * jnp.asarray(vscale, v_ref.dtype)
        dps = _packed_scores(do_ref[0], vs) if pack \
            else (_dot(do_ref[0], vs, trans_b=True),)
        dss = []
        for hh, (s, dp) in enumerate(zip(heads, dps)):
            lse2 = lse2_ref[0, 0, 8 * hh, :][:, None]
            arg = s * a - lse2
            if mask is not None:
                arg = jnp.where(mask, arg, _NEG)
            p = jnp.exp2(arg)
            if drop > 0.0:
                # regenerate the forward's keep mask from the same
                # global coordinates; ds = p*(keep*dp/(1-r) - delta)
                head_ix = dsalt_ref[1] + (2 * (bh_i % h) + hh if pack
                                          else bh_i % h)
                keep = _rand_keep_coords(
                    p.shape, dsalt_ref[0], bh_i // h, head_ix,
                    dsalt_ref[2] + i * bq, dsalt_ref[3] + j * bk, drop)
                dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - drop))
            delta = delta_ref[0, 0, 8 * hh, :][:, None]
            dss.append(p * (dp - delta))
        if pack:
            dq_acc[:] += _packed_out(dss[0], dss[1], k)
        else:
            dq_acc[:] += _dot(dss[0].astype(k.dtype), k)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(a, vscale, causal, has_kvm, has_off, kpad, sq, sk,
                    bq, bk, *refs, drop=0.0, h=1, pack=False):
    if has_off:
        off_ref, *refs = refs
        qoff, koff = off_ref[0], off_ref[1]
    else:
        qoff = koff = 0
    if drop > 0.0:
        dsalt_ref, *refs = refs
    q_ref, k_ref, v_ref, do_ref, lse2_ref, delta_ref, *rest = refs
    if has_kvm:
        kvm_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        kvm_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    i = pl.program_id(1)   # k block
    j = pl.program_id(2)   # q block
    nq = pl.num_programs(2)
    bh_i = pl.program_id(0) if drop > 0.0 else 0   # see _fwd_kernel

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (j * bq + qoff + bq - 1 >= i * bk + koff) if causal \
        else (j >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        heads = _packed_scores(q, k) if pack \
            else (_dot(q, k, trans_b=True),)          # (bq, bk)
        mask = None
        if causal:
            mask = _tri_mask(heads[0].shape, j * bq + qoff,
                             i * bk + koff)
        if kpad and not has_kvm:
            km = _kcol_mask(heads[0].shape, i * bk, sk)
            mask = km if mask is None else (mask & km)
        if has_kvm:
            vm = kvm_ref[0, 0, 0, :][None, :] > 0
            mask = vm if mask is None else (mask & vm)
        vs = v_ref[0] * jnp.asarray(vscale, v_ref.dtype)
        dps = _packed_scores(do, vs) if pack \
            else (_dot(do, vs, trans_b=True),)
        pas, dss = [], []
        for hh, (s, dp) in enumerate(zip(heads, dps)):
            lse2 = lse2_ref[0, 0, 8 * hh, :][:, None]
            arg = s * a - lse2
            if mask is not None:
                arg = jnp.where(mask, arg, _NEG)
            p = jnp.exp2(arg)
            pa = p
            if drop > 0.0:
                # rows are q-block j, cols k-block i on this side — the
                # coordinate hash makes the orientation swap free
                head_ix = dsalt_ref[1] + (2 * (bh_i % h) + hh if pack
                                          else bh_i % h)
                keep = _rand_keep_coords(
                    p.shape, dsalt_ref[0], bh_i // h, head_ix,
                    dsalt_ref[2] + j * bq, dsalt_ref[3] + i * bk, drop)
                pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
                dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - drop))
            delta = delta_ref[0, 0, 8 * hh, :][:, None]
            pas.append(pa)
            dss.append(p * (dp - delta))              # (bq, bk)
        if pack:
            dv_acc[:] += _packed_out_t0(pas[0], pas[1], do)
            dk_acc[:] += _packed_out_t0(dss[0], dss[1], q)
        else:
            dv_acc[:] += _dot_t0(pas[0].astype(do.dtype), do)
            dk_acc[:] += _dot_t0(dss[0].astype(q.dtype), q)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _rows8(x2d, bq):
    """(bh, rows) -> (bh, rows/bq, 8, bq) sublane-replicated view."""
    bh, rows = x2d.shape
    return jnp.broadcast_to(
        x2d.reshape(bh, rows // bq, 1, bq), (bh, rows // bq, 8, bq))


def _rows16(x2d, bq):
    """Per-head (b*h, rows) sidebands -> the packed kernels' paired
    (b*h/2, rows/bq, 16, bq) layout: head 2j broadcast over sublanes
    0-7 of pair j, head 2j+1 over 8-15 (matching the packed forward's
    lse emission and the ``8 * hh`` row reads in the backwards)."""
    bh2, rows = x2d.shape
    x = x2d.reshape(bh2 // 2, 2, rows // bq, 1, bq)
    x = jnp.broadcast_to(x, (bh2 // 2, 2, rows // bq, 8, bq))
    return x.transpose(0, 2, 1, 3, 4) \
        .reshape(bh2 // 2, rows // bq, 16, bq)


def _bwd_fused_kernel(a, vscale, causal, has_kvm, has_off, kpad, sq, sk,
                      *refs, drop=0.0, h=1, pack=False):
    """Single-block backward: when the whole (padded) sequence fits one
    q-block and one k-block, dq/dk/dv come from ONE pass — the scores
    ``s`` and ``dp`` are computed once instead of once per kernel (the
    two-kernel flash backward recomputes both), removing 2 of the 7
    matmuls; the two it removes are the d-contracted (half-MXU-lane)
    ones, so the saving exceeds their FLOP share.  ``pack``: d=64 head
    pairs on 128 lanes (module note) — all five products run full-width
    via the sigma rotation, lse/delta ride 16-sublane blocks."""
    if has_off:
        off_ref, *refs = refs
        qoff, koff = off_ref[0], off_ref[1]
    else:
        qoff = koff = 0
    if drop > 0.0:
        dsalt_ref, *refs = refs
    q_ref, k_ref, v_ref, do_ref, lse2_ref, delta_ref, *rest = refs
    if has_kvm:
        kvm_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        kvm_ref = None
        dq_ref, dk_ref, dv_ref = rest
    q = q_ref[0]
    k = k_ref[0]
    do = do_ref[0]
    heads = _packed_scores(q, k) if pack \
        else (_dot(q, k, trans_b=True),)              # (sq, sk) fp32
    # dp next: it does not depend on the softmax, so the VPU's
    # exp2/select work on p overlaps this MXU pass.
    vs = v_ref[0] * jnp.asarray(vscale, v_ref.dtype)
    dps = _packed_scores(do, vs) if pack \
        else (_dot(do, vs, trans_b=True),)
    mask = None
    if causal:
        mask = _tri_mask(heads[0].shape, qoff, koff)
    if kpad and not has_kvm:
        km = _kcol_mask(heads[0].shape, 0, sk)
        mask = km if mask is None else (mask & km)
    if has_kvm:
        vm = kvm_ref[0, 0, 0, :][None, :] > 0
        mask = vm if mask is None else (mask & vm)
    if drop > 0.0:
        bh_i = pl.program_id(0)
    pas, dss = [], []
    for hh, (s, dp) in enumerate(zip(heads, dps)):
        lse2 = lse2_ref[0, 0, 8 * hh, :][:, None]
        arg = s * a - lse2
        if mask is not None:
            arg = jnp.where(mask, arg, _NEG)
        p = jnp.exp2(arg)
        pa = p
        if drop > 0.0:
            head_ix = dsalt_ref[1] + (2 * (bh_i % h) + hh if pack
                                      else bh_i % h)
            keep = _rand_keep_coords(p.shape, dsalt_ref[0], bh_i // h,
                                     head_ix, dsalt_ref[2],
                                     dsalt_ref[3], drop)
            pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - drop))
        delta = delta_ref[0, 0, 8 * hh, :][:, None]
        pas.append(pa)
        dss.append(p * (dp - delta))
    if pack:
        dv_ref[0] = _packed_out_t0(pas[0], pas[1], do) \
            .astype(dv_ref.dtype)
        dq_ref[0] = _packed_out(dss[0], dss[1], k).astype(dq_ref.dtype)
        dk_ref[0] = _packed_out_t0(dss[0], dss[1], q) \
            .astype(dk_ref.dtype)
        return
    dv_ref[0] = _dot_t0(pas[0].astype(do.dtype), do).astype(dv_ref.dtype)
    dq_ref[0] = _dot(dss[0].astype(k.dtype), k).astype(dq_ref.dtype)
    dk_ref[0] = _dot_t0(dss[0].astype(q.dtype), q).astype(dk_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, res, do, kv_mask=None,
               offsets=None, dlse=None, drop=0.0, dsalt=None):
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    pack = _use_head_packing(h, d)
    # delta scales by the SAME v.dtype-rounded constant the kernels
    # fold into v: a non-power-of-two scale (e.g. d=96) rounds in bf16,
    # and mixing rounded dp' with exact-scaled delta' would bias
    # ds = p*(dp'-delta') wherever dp ~ delta.  Computed BEFORE any
    # head packing: lse/delta sidebands stay per-head either way.
    scale_v = float(np.asarray(scale).astype(v.dtype))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(b * h, sq)
    if dlse is not None:
        # lse cotangent (the partial entry): dlse/ds_raw = scale*p, so
        # it folds into delta — ds = p*(dp' - (delta - dlse)*scale_v)
        delta = delta - dlse.reshape(b * h, sq)
    delta = delta * scale_v
    lse2 = lse * _LOG2E
    if pack:
        # d=64 head-pair packing (module note): operands to the packed
        # lane layout, sidebands to paired 16-sublane blocks
        q, k, v, do = (_pack_head_pairs(x) for x in (q, k, v, do))
        h, d = h // 2, 2 * d
    g = 2 if pack else 1
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    bq = min(block_q, max(8, sq))
    bk = min(block_k, max(128, sk))
    a = scale * _LOG2E
    q3 = _pad_to(q.reshape(b * h, sq, d), 1, bq)
    k3 = _pad_to(k.reshape(b * h, sk, d), 1, bk)
    # scale folds into v INSIDE the kernels (a (bk, d) multiply in
    # VMEM) so dp' = do (v*scale)^T and ds needs no score-shaped
    # *scale; doing it here instead would cost a whole-array
    # read+write pass per layer (measured ~1.4 ms/step at GPT-345M).
    vs3 = _pad_to(v.reshape(b * h, sk, d), 1, bk)
    do3 = _pad_to(do.reshape(b * h, sq, d), 1, bq)
    bh, psq, _ = q3.shape
    psk = k3.shape[1]
    nq, nk = psq // bq, psk // bk
    kpad = psk != sk

    delta = _pad_to(delta, 1, bq)
    # +BIG pad: q-padded rows recompute p = exp2(s*a - BIG) = 0, so
    # they contribute nothing to dk/dv and need no position masks.
    lse2_p = _pad_to(lse2, 1, bq, value=_BIG)
    rows = _rows16 if pack else _rows8
    lse8 = rows(lse2_p, bq)
    delta8 = rows(delta, bq)
    has_kvm = kv_mask is not None
    has_off = offsets is not None and causal
    kvm = _kvm8(kv_mask, b, psk, bk) if has_kvm else None

    def _unpack_grads(dq, dk, dv):
        dq = dq[:, :sq].reshape(b, h, sq, d)
        dk = dk[:, :sk].reshape(b, h, sk, d)
        dv = dv[:, :sk].reshape(b, h, sk, d)
        if pack:
            dq, dk, dv = (_unpack_head_pairs(x) for x in (dq, dk, dv))
        return dq, dk, dv

    if nq == 1 and nk == 1 and (d <= 64 or pack):
        # Single-block fast path (e.g. GPT-345M s=1024 at the default
        # 1024-blocks; ring-attention shards): one fused kernel, 5
        # matmuls instead of 7.  d <= 64 keeps VMEM ~10 MB
        # (2 score-shaped fp32 temps + 7 thin operands); the packed
        # path qualifies too — its _clamp_blocks-halved bq caps the
        # per-head temps at (512, 1024) while the operand lanes double.
        qb_spec = pl.BlockSpec((1, psq, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        kb_spec = pl.BlockSpec((1, psk, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        rb_spec = pl.BlockSpec((1, 1, 8 * g, bq),
                               lambda b_: (b_, 0, 0, 0),
                               memory_space=pltpu.VMEM)
        in_specs = [qb_spec, kb_spec, kb_spec, qb_spec, rb_spec,
                    rb_spec]
        operands = [q3, k3, vs3, do3, lse8, delta8]
        if drop > 0.0:
            in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.insert(0, dsalt)
        if has_off:
            in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
            operands.insert(0, offsets)
        if has_kvm:
            in_specs.append(pl.BlockSpec(
                (1, 1, 8, bk), lambda b_: (b_ // h, 0, 0, 0),
                memory_space=pltpu.VMEM))
            operands.append(kvm)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, a, scale, causal,
                              has_kvm, has_off, kpad, sq, sk,
                              drop=drop, h=h, pack=pack),
            grid=(bh,),
            in_specs=in_specs,
            out_specs=[qb_spec, kb_spec, kb_spec],
            out_shape=[jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, psk, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, psk, d), v.dtype)],
            interpret=_interpret(),
        )(*operands)
        return _unpack_grads(dq, dk, dv)

    q_spec_i = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0),
                            memory_space=pltpu.VMEM)
    k_spec_j = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0),
                            memory_space=pltpu.VMEM)
    r_spec_i = pl.BlockSpec((1, 1, 8 * g, bq),
                            lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [q_spec_i, k_spec_j, k_spec_j, q_spec_i, r_spec_i,
                r_spec_i]
    operands = [q3, k3, vs3, do3, lse8, delta8]
    if drop > 0.0:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, dsalt)
    if has_off:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, offsets)
    if has_kvm:
        # kv mask indexed by the K block (grid dim 2 here)
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, a, scale, causal, has_kvm,
                          has_off, kpad, sq, sk, bq, bk,
                          drop=drop, h=h, pack=pack),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, psq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    q_spec_j = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, j, 0),
                            memory_space=pltpu.VMEM)
    k_spec_i = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, i, 0),
                            memory_space=pltpu.VMEM)
    r_spec_j = pl.BlockSpec((1, 1, 8 * g, bq),
                            lambda b_, i, j: (b_, j, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec_j, k_spec_i, k_spec_i, q_spec_j, r_spec_j,
                r_spec_j]
    operands = [q3, k3, vs3, do3, lse8, delta8]
    if drop > 0.0:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, dsalt)
    if has_off:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, offsets)
    if has_kvm:
        # kv mask indexed by the K block (grid dim 1 here)
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, i, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, a, scale, causal, has_kvm,
                          has_off, kpad, sq, sk, bq, bk,
                          drop=drop, h=h, pack=pack),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[k_spec_i, k_spec_i],
        out_shape=[jax.ShapeDtypeStruct((bh, psk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, psk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    return _unpack_grads(dq, dk, dv)


def _flash_bwd_packed(scale, causal, block_q, block_k, res, do,
                      kv_mask=None):
    """Backward of :func:`_flash_fwd_packed`: the saved PACKED qkv is
    read three times through offset index maps (no q/k/v relayout
    copies); dq/dk/dv come back as one (3*b*h, s, d) array so the
    caller's qkv-cotangent transpose fuses with this concatenation."""
    qkv, o, lse, b, h = res
    bh = b * h
    s, d = qkv.shape[1], qkv.shape[2]
    block_q, block_k = _clamp_blocks(block_q, block_k, d)
    grain = -(-s // 128) * 128      # see _flash_fwd_packed
    bq = min(block_q, grain)
    bk = min(block_k, grain)
    a = scale * _LOG2E
    # everything q-indexed pads to the SAME ps as the packed qkv: the
    # q-block grid spans ps // bq blocks, and a shorter do/lse/delta
    # would alias real rows through Pallas' clamped block indexing.
    lcm = math.lcm(bq, bk)
    qkv3 = _pad_to(qkv, 1, lcm)
    do3 = _pad_to(do, 1, lcm)
    ps = qkv3.shape[1]
    nq, nk = ps // bq, ps // bk
    kpad = ps != s

    scale_v = float(np.asarray(scale).astype(qkv.dtype))  # see _flash_bwd
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1) * scale_v
    delta = _pad_to(delta, 1, lcm)
    lse2_p = _pad_to(lse * _LOG2E, 1, lcm, value=_BIG)
    lse8 = _rows8(lse2_p, bq)
    delta8 = _rows8(delta, bq)
    has_kvm = kv_mask is not None
    kvm = _kvm8(kv_mask, b, ps, bk) if has_kvm else None

    if nq == 1 and nk == 1 and d <= 64:
        def blkspec(off):
            return pl.BlockSpec((1, ps, d),
                                lambda b_, o_=off: (b_ + o_, 0, 0),
                                memory_space=pltpu.VMEM)
        ob_spec = pl.BlockSpec((1, ps, d), lambda b_: (b_, 0, 0),
                               memory_space=pltpu.VMEM)
        rb_spec = pl.BlockSpec((1, 1, 8, bq), lambda b_: (b_, 0, 0, 0),
                               memory_space=pltpu.VMEM)
        in_specs = [blkspec(0), blkspec(bh), blkspec(2 * bh), ob_spec,
                    rb_spec, rb_spec]
        operands = [qkv3, qkv3, qkv3, do3, lse8, delta8]
        if has_kvm:
            in_specs.append(pl.BlockSpec(
                (1, 1, 8, bk), lambda b_: (b_ // h, 0, 0, 0),
                memory_space=pltpu.VMEM))
            operands.append(kvm)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, a, scale, causal,
                              has_kvm, False, kpad, s, s),
            grid=(bh,),
            in_specs=in_specs,
            out_specs=[ob_spec, ob_spec, ob_spec],
            out_shape=[jax.ShapeDtypeStruct((bh, ps, d), qkv.dtype)] * 3,
            interpret=_interpret(),
        )(*operands)
        return jnp.concatenate([dq[:, :s], dk[:, :s], dv[:, :s]],
                               axis=0)

    def spec_q(off):
        return pl.BlockSpec((1, bq, d),
                            lambda b_, i, j, o_=off: (b_ + o_, i, 0),
                            memory_space=pltpu.VMEM)

    def spec_k(off):
        return pl.BlockSpec((1, bk, d),
                            lambda b_, i, j, o_=off: (b_ + o_, j, 0),
                            memory_space=pltpu.VMEM)
    r_spec_i = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, i, 0, 0),
                            memory_space=pltpu.VMEM)
    # do3 is its own (bh, ps, d) operand; spec_q(0) indexes it too
    in_specs = [spec_q(0), spec_k(bh), spec_k(2 * bh), spec_q(0),
                r_spec_i, r_spec_i]
    operands = [qkv3, qkv3, qkv3, do3, lse8, delta8]
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, a, scale, causal, has_kvm,
                          False, kpad, s, s, bq, bk),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, ps, d), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    def spec_qj(off):
        return pl.BlockSpec((1, bq, d),
                            lambda b_, i, j, o_=off: (b_ + o_, j, 0),
                            memory_space=pltpu.VMEM)

    def spec_ki(off):
        return pl.BlockSpec((1, bk, d),
                            lambda b_, i, j, o_=off: (b_ + o_, i, 0),
                            memory_space=pltpu.VMEM)
    r_spec_j = pl.BlockSpec((1, 1, 8, bq), lambda b_, i, j: (b_, j, 0, 0),
                            memory_space=pltpu.VMEM)
    do_spec_j = pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, j, 0),
                             memory_space=pltpu.VMEM)
    out_ki = pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, i, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [spec_qj(0), spec_ki(bh), spec_ki(2 * bh), do_spec_j,
                r_spec_j, r_spec_j]
    operands = [qkv3, qkv3, qkv3, do3, lse8, delta8]
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bk), lambda b_, i, j: (b_ // h, i, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(kvm)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, a, scale, causal, has_kvm,
                          False, kpad, s, s, bq, bk),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[out_ki, out_ki],
        out_shape=[jax.ShapeDtypeStruct((bh, ps, d), qkv.dtype)] * 2,
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    return jnp.concatenate([dq[:, :s], dk[:, :s], dv[:, :s]], axis=0)


# --- public API ------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_fused(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           scale: Optional[float] = None,
                           causal: bool = False,
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K) -> jnp.ndarray:
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)[0]


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    scale: Optional[float] = None,
                    causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    kv_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Fused attention: softmax(q k^T * scale [masked]) v.

    Shapes: q (b, h, sq, d); k, v (b, h, sk, d).  ``scale`` defaults to
    1/sqrt(d).  ``kv_mask`` (b, sk), True/nonzero = attend, masks
    padding KEYS (the BERT padding-attention case) — a capability the
    reference's FMHA lacks entirely (seqlen<=512, no mask support,
    ref: setup.py:408-424); composes with ``causal``.  Inside
    shard_map manual axes the XLA reference path runs (Pallas calls
    cannot yet carry VMA types).

    d=64 with even ``h`` (the reference FMHA's native head size) runs
    the head-packed full-width kernels — two heads per 128-lane MXU
    tile, ~2x the half-width rate; ``APEX_TPU_FLASH_PACK_D64=0`` or
    :func:`set_head_packing` force the old path (module note).
    """
    from ._context import in_manual_axis_context
    from .._autocast_ctx import autocast_compute_dtype

    # under amp.autocast (O1/O4) this call site is whitelisted: cast
    # inputs to the compute dtype here, at trace time, because the
    # interpreter cannot re-bind the dtype-frozen custom_vjp body
    act = autocast_compute_dtype()
    if act is not None and q.dtype != act \
            and jnp.issubdtype(q.dtype, jnp.floating):
        q, k, v = (x.astype(act) for x in (q, k, v))
    if in_manual_axis_context(q, k, v):
        return mha_reference(q, k, v, scale=scale, causal=causal,
                             kv_mask=kv_mask)
    if kv_mask is not None:
        return _flash_attention_masked(q, k, v,
                                       kv_mask.astype(jnp.float32),
                                       scale, causal, block_q, block_k)
    return _flash_attention_fused(q, k, v, scale, causal, block_q, block_k)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, do):
    if scale is None:
        scale = res[0].shape[-1] ** -0.5
    return _flash_bwd(scale, causal, block_q, block_k, res, do)


_flash_attention_fused.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention_masked(q, k, v, kv_mask, scale, causal,
                            block_q, block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                      kv_mask=kv_mask)[0]


def _flash_masked_vjp_fwd(q, k, v, kv_mask, scale, causal, block_q,
                          block_k):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        kv_mask=kv_mask)
    return o, (q, k, v, o, lse, kv_mask)


def _flash_masked_vjp_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse, kv_mask = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k,
                            (q, k, v, o, lse), do, kv_mask=kv_mask)
    # the (float) mask is a constant of the computation
    return dq, dk, dv, jnp.zeros_like(kv_mask)


_flash_attention_masked.defvjp(_flash_masked_vjp_fwd,
                               _flash_masked_vjp_bwd)


# --- packed-qkv self-attention entry ---------------------------------------

def flash_attention_qkv(qkv: jnp.ndarray,
                        scale: Optional[float] = None,
                        causal: bool = False,
                        block_q: int = DEFAULT_BLOCK_Q,
                        block_k: int = DEFAULT_BLOCK_K,
                        kv_mask: Optional[jnp.ndarray] = None
                        ) -> jnp.ndarray:
    """Self-attention over PACKED projections: ``qkv`` (3, b, h, s, d),
    returns the context (b, h, s, d).

    .. warning:: **Measured to LOSE ~5 ms/step end-to-end at the
       framework's own bench shapes** (GPT-345M, ROUND3_NOTES): the big
       (3,b,h,s,d) transpose XLA emits to build the packed operand
       costs more than the three per-tensor relayout copies it
       replaces.  Prefer :func:`flash_attention_e` — the
       projection-native layout with ZERO boundary copies — for
       self-attention; use this entry only if your model already holds
       qkv in this exact packed layout (the kernels themselves time
       identically to the per-tensor entry).

    Inside the kernel q/k/v are row-ranges of one contiguous array read
    via index-map offsets.  Semantics match
    ``flash_attention(qkv[0], qkv[1], qkv[2], ...)``.
    """
    from ._context import in_manual_axis_context
    from .._autocast_ctx import autocast_compute_dtype

    # same autocast boundary contract as flash_attention (this entry's
    # documented semantics are flash_attention(qkv[0], qkv[1], qkv[2]))
    act = autocast_compute_dtype()
    if act is not None and qkv.dtype != act \
            and jnp.issubdtype(qkv.dtype, jnp.floating):
        qkv = qkv.astype(act)
    if in_manual_axis_context(qkv):
        return mha_reference(qkv[0], qkv[1], qkv[2], scale=scale,
                             causal=causal, kv_mask=kv_mask)
    if kv_mask is not None:
        return _flash_qkv_masked(qkv, kv_mask.astype(jnp.float32),
                                 scale, causal, block_q, block_k)
    return _flash_qkv_fused(qkv, scale, causal, block_q, block_k)


def _qkv_flat(qkv):
    three, b, h, s, d = qkv.shape
    assert three == 3, f"qkv leading dim must be 3, got {three}"
    return qkv.reshape(3 * b * h, s, d), b, h


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _flash_qkv_fused(qkv, scale, causal, block_q, block_k):
    flat, b, h = _qkv_flat(qkv)
    if scale is None:
        scale = qkv.shape[-1] ** -0.5
    o, _ = _flash_fwd_packed(flat, b, h, scale, causal, block_q,
                             block_k)
    return o.reshape(b, h, *o.shape[1:])


def _flash_qkv_vjp_fwd(qkv, scale, causal, block_q, block_k):
    flat, b, h = _qkv_flat(qkv)
    if scale is None:
        scale = qkv.shape[-1] ** -0.5
    o, lse = _flash_fwd_packed(flat, b, h, scale, causal, block_q,
                               block_k)
    return o.reshape(b, h, *o.shape[1:]), (flat, o, lse, b, h)


def _flash_qkv_vjp_bwd(scale, causal, block_q, block_k, res, do):
    flat, o, lse, b, h = res
    if scale is None:
        scale = flat.shape[-1] ** -0.5
    dflat = _flash_bwd_packed(scale, causal, block_q, block_k,
                              (flat, o, lse, b, h),
                              do.reshape(b * h, *do.shape[2:]))
    return (dflat.reshape(3, b, h, *dflat.shape[1:]),)


_flash_qkv_fused.defvjp(_flash_qkv_vjp_fwd, _flash_qkv_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _flash_qkv_masked(qkv, kv_mask, scale, causal, block_q, block_k):
    flat, b, h = _qkv_flat(qkv)
    if scale is None:
        scale = qkv.shape[-1] ** -0.5
    o, _ = _flash_fwd_packed(flat, b, h, scale, causal, block_q,
                             block_k, kv_mask=kv_mask)
    return o.reshape(b, h, *o.shape[1:])


def _flash_qkv_masked_vjp_fwd(qkv, kv_mask, scale, causal, block_q,
                              block_k):
    flat, b, h = _qkv_flat(qkv)
    if scale is None:
        scale = qkv.shape[-1] ** -0.5
    o, lse = _flash_fwd_packed(flat, b, h, scale, causal, block_q,
                               block_k, kv_mask=kv_mask)
    return o.reshape(b, h, *o.shape[1:]), (flat, o, lse, b, h, kv_mask)


def _flash_qkv_masked_vjp_bwd(scale, causal, block_q, block_k, res, do):
    flat, o, lse, b, h, kv_mask = res
    if scale is None:
        scale = flat.shape[-1] ** -0.5
    dflat = _flash_bwd_packed(scale, causal, block_q, block_k,
                              (flat, o, lse, b, h),
                              do.reshape(b * h, *do.shape[2:]),
                              kv_mask=kv_mask)
    return (dflat.reshape(3, b, h, *dflat.shape[1:]),
            jnp.zeros_like(kv_mask))


_flash_qkv_masked.defvjp(_flash_qkv_masked_vjp_fwd,
                         _flash_qkv_masked_vjp_bwd)


# --- partial (o, lse) entry: ring / blockwise composition -------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_partial(q, k, v, offsets, scale, causal, block_q, block_k):
    """Dynamic-offset partial (the ring path); static-zero offsets take
    :func:`_flash_partial_nooff` instead."""
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        offsets=offsets)
    return o, lse.reshape(q.shape[0], q.shape[1], -1)


def _flash_partial_vjp_fwd(q, k, v, offsets, scale, causal, block_q,
                           block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        offsets=offsets)
    out = (o, lse.reshape(q.shape[0], q.shape[1], -1))
    return out, (q, k, v, o, lse, offsets)


def _flash_partial_vjp_bwd(scale, causal, block_q, block_k, res, cts):
    q, k, v, o, lse, offsets = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k,
                            (q, k, v, o, lse), do, offsets=offsets,
                            dlse=dlse.reshape(lse.shape))
    return dq, dk, dv, np.zeros(offsets.shape, dtype=jax.dtypes.float0)


_flash_partial.defvjp(_flash_partial_vjp_fwd, _flash_partial_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_partial_nooff(q, k, v, scale, causal, block_q, block_k):
    """Static-zero-offset partial: same (o, lse) contract as
    :func:`_flash_partial` without the offsets operand (no dead input /
    float0 cotangent on the non-ring path, e.g. the Ulysses wrapper)."""
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, lse.reshape(q.shape[0], q.shape[1], -1)


def _flash_partial_nooff_vjp_fwd(q, k, v, scale, causal, block_q,
                                 block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    out = (o, lse.reshape(q.shape[0], q.shape[1], -1))
    return out, (q, k, v, o, lse)


def _flash_partial_nooff_vjp_bwd(scale, causal, block_q, block_k, res,
                                 cts):
    q, k, v, o, lse = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k,
                            (q, k, v, o, lse), do,
                            dlse=dlse.reshape(lse.shape))
    return dq, dk, dv


_flash_partial_nooff.defvjp(_flash_partial_nooff_vjp_fwd,
                            _flash_partial_nooff_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_partial_drop(q, k, v, offsets, dsalt, scale, causal, drop,
                        block_q, block_k):
    """Partial with IN-KERNEL dropout: ``dsalt`` = int32[4] of
    [seed, head_offset, q_offset, k_offset] salting the coordinate-hash
    keep mask in GLOBAL positions — ring/Ulysses shards draw
    non-repeating windows of one global mask, and the lse merge of
    value-dropped partials reproduces dense in-kernel dropout exactly
    (l and lse stay undropped; see _fwd_single_kernel)."""
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        offsets=offsets, drop=drop, dsalt=dsalt)
    return o, lse.reshape(q.shape[0], q.shape[1], -1)


def _flash_partial_drop_vjp_fwd(q, k, v, offsets, dsalt, scale, causal,
                                drop, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        offsets=offsets, drop=drop, dsalt=dsalt)
    out = (o, lse.reshape(q.shape[0], q.shape[1], -1))
    return out, (q, k, v, o, lse, offsets, dsalt)


def _flash_partial_drop_vjp_bwd(scale, causal, drop, block_q, block_k,
                                res, cts):
    q, k, v, o, lse, offsets, dsalt = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd(scale, causal, block_q, block_k,
                            (q, k, v, o, lse), do, offsets=offsets,
                            dlse=dlse.reshape(lse.shape), drop=drop,
                            dsalt=dsalt)
    return (dq, dk, dv,
            np.zeros(offsets.shape, dtype=jax.dtypes.float0),
            np.zeros(dsalt.shape, dtype=jax.dtypes.float0))


_flash_partial_drop.defvjp(_flash_partial_drop_vjp_fwd,
                           _flash_partial_drop_vjp_bwd)


def flash_attention_partial(q: jnp.ndarray, k: jnp.ndarray,
                            v: jnp.ndarray,
                            scale: Optional[float] = None,
                            causal: bool = False,
                            q_offset=0, k_offset=0,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            dropout_rate: float = 0.0,
                            dropout_seed=None,
                            head_offset=0):
    """Blockwise-attention PARTIAL: returns ``(o, lse)`` — the
    softmax-normalized context of q against THIS k/v block plus the
    per-row log-sum-exp — so callers can combine blocks exactly with
    the flash merge ``lse = logaddexp(lse1, lse2); o = o1*exp(lse1-lse)
    + o2*exp(lse2-lse)``.  This is the ring-attention building block
    (and the general two-level flash composition primitive).

    ``q_offset``/``k_offset`` (traced ints OK — they ride an SMEM
    scalar into the kernels) place the block in GLOBAL coordinates for
    ``causal``: row i is position ``q_offset + i``, key j is
    ``k_offset + j``.  Fully-future blocks produce o = 0 and lse ~
    -1e30 (annihilated by the merge).  Gradients flow through both
    outputs (the lse cotangent folds into the backward's delta term).

    Unlike :func:`flash_attention` there is NO automatic shard_map
    fallback: this entry is designed to run inside
    ``shard_map(..., check_vma=False)``, where Pallas calls are legal
    (with ``check_vma=True`` the custom call is rejected by JAX —
    use ``check_vma=False`` on the enclosing shard_map).

    ``dropout_rate`` applies IN-KERNEL attention dropout from a
    coordinate-hash keep mask in GLOBAL positions: bit-identical to
    :func:`rand_keep_global` evaluated at (``q_offset``,
    ``head_offset``, ``k_offset``), so sequence-parallel shards draw
    non-repeating windows of one global mask and the lse merge of the
    value-dropped partials equals dense in-kernel dropout exactly.
    ``dropout_seed``: non-negative int32 (traced OK; same contract as
    :func:`flash_attention_e`).  ``head_offset``: global index of head
    0 of this shard (the Ulysses head-sharded case).
    """
    from .._autocast_ctx import autocast_compute_dtype

    if scale is None:
        scale = q.shape[-1] ** -0.5
    act = autocast_compute_dtype()
    if act is not None and q.dtype != act \
            and jnp.issubdtype(q.dtype, jnp.floating):
        q, k, v = (x.astype(act) for x in (q, k, v))
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                             jnp.asarray(k_offset, jnp.int32)])
        dsalt = jnp.stack([jnp.asarray(dropout_seed, jnp.int32),
                           jnp.asarray(head_offset, jnp.int32),
                           jnp.asarray(q_offset, jnp.int32),
                           jnp.asarray(k_offset, jnp.int32)])
        return _flash_partial_drop(q, k, v, offsets, dsalt, scale,
                                   causal, float(dropout_rate),
                                   block_q, block_k)
    # static-zero offsets (e.g. Ulysses' plain full-sequence causal
    # local attention) take the static-mask kernels — the dynamic
    # SMEM-offset masks cost ~10% kernel time (ROUND3_NOTES)
    use_off = not (isinstance(q_offset, int) and q_offset == 0
                   and isinstance(k_offset, int) and k_offset == 0)
    if not use_off:
        return _flash_partial_nooff(q, k, v, scale, causal, block_q,
                                    block_k)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    return _flash_partial(q, k, v, offsets, scale, causal, block_q,
                          block_k)


# --- E-layout (head-interleaved) self-attention ----------------------------
#
# Consumes the qkv projection's NATIVE output layout — (b, s, h, 3d),
# lanes ordered [head][q(d) k(d) v(d)], exactly what
# ``qkv.reshape(b, s, h, 3*d)`` of a fused projection yields — via
# lane-blocked BlockSpecs, and emits the context as (b, s, h*d) plus (in
# the vjp) ONE dqkv cotangent in the same interleaved layout.  No
# (b, h, s, d) transpose and no dq/dk/dv concatenate exists anywhere on
# this path: XLA cannot fuse transposes into a custom call, so the
# per-tensor entry forces eight bf16[b,h,s,d] relayout copies per layer
# (measured ~14 ms/step at GPT-345M, ~16 ms at BERT-large).  Heads are
# sliced out of the wide block INSIDE the kernel — measured free on v5e
# (the head-group microbench beat the per-head grid: lane slices
# pipeline behind the MXU).
#
# Single-block only: the whole (128-aligned) sequence must fit one
# q/k-block (ps <= 1024 keeps the fp32 score temporaries inside VMEM).
# Longer sequences keep the transposing path — `flash_e_supported`
# tells callers which side they're on.

_E_MAX_SEQ = 1024
# Blocked sequence walk: sequences whose 128-aligned padding exceeds
# _E_MAX_SEQ (one VMEM block) stream (bs, bs) tiles with online softmax
# instead of falling back to the transposing path (the fallback re-pays
# the ~14-16 ms/step of (b,h,s,d) relayout glue the E layout exists to
# kill).  The cap bounds the lse/delta sideband arrays, not VMEM —
# at s=32768/h=16 the (b, h, 8, ps) fp32 sidebands are 64 MB of HBM
# per batch row, a sane ceiling; the walk itself is shape-generic
# (hardware-verified blocked parity at s=16384 for d in {64, 128}).
_E_MAX_SEQ_BLOCKED = flag_int("APEX_TPU_FLASH_E_MAX_SEQ")
_E_BLOCK = flag_int("APEX_TPU_FLASH_E_BLOCK")  # registry enforces %128
# lane budget per head-group block (3*hg*d lanes): sized so the bwd's
# score-shaped fp32 temporaries (~10 MB at ps=1024) plus double-buffered
# qkv/do/dqkv blocks stay inside the 16 MB VMEM window.
_E_LANE_BUDGET = flag_int("APEX_TPU_FLASH_E_LANES")


def _pick_heads_per_group(h: int, d: int, ps: int,
                          drop: bool = False) -> Optional[int]:
    """Largest divisor of ``h`` with 3*hg*d lanes within budget, lane-
    aligned (3*hg*d % 128 == 0), and few enough unrolled heads that the
    per-head (ps, ps) fp32 score temporaries stay inside VMEM — Mosaic
    only partially reuses them across the unrolled loop (measured: hg=4
    at ps=1024/d=64 fits with ~2 MB slack; hg=16 at ps=1024/d=16 asks
    for 43.6 MB).  ``drop`` halves the temp budget: the in-kernel keep
    mask adds score-shaped uint32/f32 temporaries per head (measured:
    hg=4/ps=1024/d=64 with dropout overflows scoped VMEM by 600 KB on
    hardware).  None when no grouping qualifies (callers fall back to
    the blocked walk or the transposing path)."""
    cap = max(1, _E_LANE_BUDGET // (3 * d))
    budget = (2 if drop else 4) * 1024 * 1024
    cap = min(cap, max(1, budget // (ps * ps)))
    for hg in range(min(cap, h), 0, -1):
        if h % hg == 0 and (3 * hg * d) % 128 == 0:
            return hg
    return None


def _pick_heads_per_group_blocked(h: int, d: int, bs: int,
                                  drop: bool = False) -> Optional[int]:
    """Head grouping for the BLOCKED E walk: same lane constraints as
    :func:`_pick_heads_per_group`, but the score-temporary budget counts
    (bs, bs) tiles and halves (the combined backward keeps both the dq
    and dk/dv sides' temporaries live in one kernel).  ``drop`` halves
    it again for the keep-mask temporaries (same VMEM class the
    single-block picker budgets for; hg=4 at bs=512 with dropout is
    measured to fit on hardware — the halved cap keeps exactly that)."""
    cap = max(1, _E_LANE_BUDGET // (3 * d))
    budget = (1 if drop else 2) * 1024 * 1024
    cap = min(cap, max(1, budget // (bs * bs)))
    for hg in range(min(cap, h), 0, -1):
        if h % hg == 0 and (3 * hg * d) % 128 == 0:
            return hg
    return None


def _e_mode(s: int, h: int, d: int, drop: bool = False):
    """('single'|'blocked', hg) when the E-layout kernels can run this
    shape, else (None, reason) — the reason string is what fallback
    sites log.  Short sequences whose whole-block grouping misfits
    (e.g. tiny d where the unrolled (ps, ps) temps blow VMEM) still
    take the blocked walk — its (bs, bs) tiles admit more shapes.
    ``drop`` mirrors the kernels' dropout-halved temp budgets so the
    reported mode/hg are the ones that actually execute."""
    ps = -(-s // 128) * 128
    if ps <= _E_MAX_SEQ:
        hg = _pick_heads_per_group(h, d, ps, drop=drop)
        if hg is not None:
            return "single", hg
    if ps <= _E_MAX_SEQ_BLOCKED:
        hg = _pick_heads_per_group_blocked(h, d, min(_E_BLOCK, ps),
                                           drop=drop)
        if hg is not None:
            return "blocked", hg
        return None, (f"no head grouping for h={h} d={d} within the "
                      f"VMEM lane budget (need 3*hg*d lanes % 128 == 0)")
    return None, (f"padded seq {ps} > APEX_TPU_FLASH_E_MAX_SEQ="
                  f"{_E_MAX_SEQ_BLOCKED}")


def flash_e_supported(s: int, h: int, d: int) -> bool:
    return _e_mode(s, h, d)[0] is not None


def _rand_keep(shape, seed, salt_b, salt_head, salt_i, salt_j, rate):
    """Deterministic dropout keep-mask from a counter-based hash
    (murmur3 fmix32 over per-element counters + call-site salts).

    Plain jnp uint32 ops — no pltpu PRNG — so the SAME bits come out on
    TPU hardware and in interpret mode, and the backward regenerates the
    forward's mask from the same ``(seed, batch, head, q-block,
    k-block)`` salt tuple instead of materializing an O(s^2) mask array
    (the reference's in-kernel philox dropout plays this role,
    ref: apex/contrib/csrc/multihead_attn/dropout.h)."""
    u32 = functools.partial(jnp.asarray, dtype=jnp.uint32)

    def _u(x):
        # int32 program ids / traced seeds: mask to non-negative before
        # the uint32 view so XLA's checked conversions cannot trap
        return jnp.bitwise_and(jnp.asarray(x, jnp.int32),
                               jnp.int32(0x7FFFFFFF)).astype(jnp.uint32)

    salt = (_u(seed) * u32(0x85EBCA6B)
            ^ _u(salt_b) * u32(0xC2B2AE35)
            ^ _u(salt_head) * u32(0x27D4EB2F)
            ^ _u(salt_i) * u32(0x165667B1)
            ^ _u(salt_j) * u32(0x9E3779B9))
    r = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    c = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    x = r * u32(shape[1]) + c + salt
    x = (x ^ (x >> 16)) * u32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * u32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # top 24 bits to [0, 1): bitcast to int32 before the float convert —
    # Mosaic has no uint32->f32 cast, and after >> 8 the sign bit is 0
    f = jax.lax.bitcast_convert_type(x >> 8, jnp.int32) \
        .astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
    return f >= jnp.float32(rate)


def _fwd_e_kernel(scale, a, causal, has_kvm, drop, kpad, s_real, hg, d,
                  *refs):
    if drop > 0.0:
        seed_ref, *refs = refs
    qkv_ref, *rest = refs
    if has_kvm:
        kvm_ref, o_ref, lse_ref = rest
    else:
        kvm_ref = None
        o_ref, lse_ref = rest
    blk = qkv_ref[0]                       # (ps, hg*3*d)
    if has_kvm:
        vm = kvm_ref[0, 0, 0, :][None, :] > 0
    bidx = pl.program_id(0)
    gidx = pl.program_id(1)
    for j in range(hg):
        off = j * 3 * d
        qh = blk[:, off:off + d]
        kh = blk[:, off + d:off + 2 * d]
        vh = blk[:, off + 2 * d:off + 3 * d]
        s = _dot(qh, kh, trans_b=True)     # (ps, ps) raw logits, fp32
        mask = None
        if causal:
            mask = _tri_mask(s.shape, 0, 0)
        if kpad and not has_kvm:
            km = _kcol_mask(s.shape, 0, s_real)
            mask = km if mask is None else (mask & km)
        if has_kvm:
            mask = vm if mask is None else (mask & vm)
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp2((s - m) * a)
        l = jnp.sum(p, axis=1, keepdims=True)
        if has_kvm:
            dead = m <= _NEG * 0.5         # see _fwd_single_kernel
            l = jnp.where(dead, 0.0, l)
        pa = p
        if drop > 0.0:
            # l comes from the UNDROPPED p (normalization is by the true
            # softmax denominator); only the accumulated values drop.
            keep = _rand_keep(p.shape, seed_ref[0], bidx,
                              gidx * hg + j, 0, 0, drop)
            pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
        acc = _dot(pa.astype(blk.dtype), vh)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o = acc / safe_l
        if has_kvm:
            o = jnp.where(dead, 0.0, o)
        o_ref[0, :, j * d:(j + 1) * d] = o.astype(o_ref.dtype)
        lse = m * scale + jnp.log(safe_l)
        lse_ref[0, j] = jnp.broadcast_to(lse[:, 0][None, :],
                                         lse_ref.shape[2:])


def _flash_fwd_e(qkv_e, h, scale, causal, kv_mask=None, drop=0.0,
                 seed=None):
    b, s, width = qkv_e.shape
    d = width // (3 * h)
    ps = -(-s // 128) * 128
    hg = _pick_heads_per_group(h, d, ps, drop=drop > 0.0) \
        if ps <= _E_MAX_SEQ else None
    if hg is None:                   # matches _e_mode's 'blocked' arm
        return _flash_fwd_e_blocked(qkv_e, h, scale, causal,
                                    kv_mask=kv_mask, drop=drop,
                                    seed=seed)
    g = h // hg
    qkv3 = _pad_to(qkv_e, 1, ps)
    a = scale * _LOG2E
    kpad = ps != s
    has_kvm = kv_mask is not None

    qkv_spec = pl.BlockSpec((1, ps, hg * 3 * d),
                            lambda b_, g_: (b_, 0, g_),
                            memory_space=pltpu.VMEM)
    o_spec = pl.BlockSpec((1, ps, hg * d), lambda b_, g_: (b_, 0, g_),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, hg, 8, ps),
                            lambda b_, g_: (b_, g_, 0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = []
    operands = []
    if drop > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(seed, jnp.int32).reshape(1))
    in_specs.append(qkv_spec)
    operands.append(qkv3)
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, ps), lambda b_, g_: (b_, 0, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(_kvm8(kv_mask, b, ps, ps))
    o, lse8 = pl.pallas_call(
        functools.partial(_fwd_e_kernel, scale, a, causal, has_kvm,
                          drop, kpad, s, hg, d),
        grid=(b, g),
        in_specs=in_specs,
        out_specs=[o_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, ps, h * d), qkv_e.dtype),
            jax.ShapeDtypeStruct((b, h, 8, ps), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    lse = lse8[:, :, 0, :s]                # (b, h, s)
    return o[:, :s], lse


def _fwd_e_blocked_kernel(scale, a, causal, has_kvm, drop, kpad, s_real,
                          hg, d, bs, *refs):
    """Blocked E-layout forward: grid (b, g, i, j) walks (bs, bs) tiles
    with the online-softmax recurrence of :func:`_fwd_kernel`, but over
    the head-interleaved lane layout — q rows come from sequence-block
    ``i`` and k/v rows from block ``j`` of the SAME (b, ps, hg*3d)
    operand.  Per-head m/l carries live in single-lane columns of one
    (bs, 128) scratch."""
    if drop > 0.0:
        seed_ref, *refs = refs
    qkv_q_ref, qkv_k_ref, *rest = refs
    if has_kvm:
        kvm_ref, o_ref, lse_ref, acc, m_sc, l_sc = rest
    else:
        kvm_ref = None
        o_ref, lse_ref, acc, m_sc, l_sc = rest
    bidx = pl.program_id(0)
    gidx = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    run = (j * bs <= i * bs + bs - 1) if causal else (j >= 0)

    @pl.when(run)
    def _block():
        qblk = qkv_q_ref[0]                # (bs, hg*3d)
        kblk = qkv_k_ref[0]
        if has_kvm:
            vm = kvm_ref[0, 0, 0, :][None, :] > 0
        for jh in range(hg):
            off = jh * 3 * d
            qh = qblk[:, off:off + d]
            kh = kblk[:, off + d:off + 2 * d]
            vh = kblk[:, off + 2 * d:off + 3 * d]
            s = _dot(qh, kh, trans_b=True)
            mask = None
            if causal:
                mask = _tri_mask(s.shape, i * bs, j * bs)
            if kpad and not has_kvm:
                km = _kcol_mask(s.shape, j * bs, s_real)
                mask = km if mask is None else (mask & km)
            if has_kvm:
                mask = vm if mask is None else (mask & vm)
            if mask is not None:
                s = jnp.where(mask, s, _NEG)
            m_prev = m_sc[:, jh:jh + 1]
            m_cur = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp2((m_prev - m_cur) * a)
            p = jnp.exp2((s - m_cur) * a)
            if has_kvm:
                p = jnp.where(mask, p, 0.0)    # see _fwd_kernel
            l_new = l_sc[:, jh:jh + 1] * corr \
                + jnp.sum(p, axis=1, keepdims=True)
            pa = p
            if drop > 0.0:
                keep = _rand_keep(p.shape, seed_ref[0], bidx,
                                  gidx * hg + jh, i, j, drop)
                pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
            sl = slice(jh * d, (jh + 1) * d)
            acc[:, sl] = acc[:, sl] * corr \
                + _dot(pa.astype(qblk.dtype), vh)
            m_sc[:, jh:jh + 1] = m_cur
            l_sc[:, jh:jh + 1] = l_new

    @pl.when(j == nk - 1)
    def _finish():
        for jh in range(hg):
            l = l_sc[:, jh:jh + 1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o = acc[:, jh * d:(jh + 1) * d] / safe_l
            if has_kvm:
                dead = m_sc[:, jh:jh + 1] <= _NEG * 0.5
                o = jnp.where(dead, 0.0, o)
            o_ref[0, :, jh * d:(jh + 1) * d] = o.astype(o_ref.dtype)
            lse = m_sc[:, jh:jh + 1] * scale + jnp.log(safe_l)
            lse_ref[0, jh] = jnp.broadcast_to(lse[:, 0][None, :],
                                              lse_ref.shape[2:])


def _flash_fwd_e_blocked(qkv_e, h, scale, causal, kv_mask=None,
                         drop=0.0, seed=None):
    b, s, width = qkv_e.shape
    d = width // (3 * h)
    ps128 = -(-s // 128) * 128
    bs = min(_E_BLOCK, ps128)
    # Forward-only block widening: with one live score temp per head the
    # forward affords 1024-wide blocks (half the online-softmax carries;
    # measured: s=2048 E substep 2.35 vs 3.16 ms transposing after this,
    # from a dead-even tie at 512 blocks) — but dropout pins the forward
    # to the backward's block size so the counter-hash keep masks tile
    # identically in both directions, and d=128 stays at 512 (the
    # 1024-block d=128 kernel fails TPU compile; at 512 it already runs
    # 88 TF/s vs 41 transposing at Llama shape).
    if drop == 0.0 and d <= 64 and ps128 % 1024 == 0 \
            and _pick_heads_per_group_blocked(h, d, 1024) is not None:
        bs = 1024
        hg = _pick_heads_per_group_blocked(h, d, 1024)
    else:
        hg = _pick_heads_per_group_blocked(h, d, bs, drop=drop > 0.0)
    if hg is None:
        raise ValueError(
            f"blocked E-layout kernel cannot run h={h} d={d} bs={bs} "
            f"(no head grouping with 3*hg*d lanes % 128 == 0 inside "
            f"the VMEM budget); route through flash_attention_e, which "
            f"checks _e_mode and falls back")
    g = h // hg
    qkv3 = _pad_to(qkv_e, 1, bs)
    ps = qkv3.shape[1]
    nb = ps // bs
    a = scale * _LOG2E
    kpad = ps != s
    has_kvm = kv_mask is not None

    qkv_q_spec = pl.BlockSpec((1, bs, hg * 3 * d),
                              lambda b_, g_, i, j: (b_, i, g_),
                              memory_space=pltpu.VMEM)
    qkv_k_spec = pl.BlockSpec((1, bs, hg * 3 * d),
                              lambda b_, g_, i, j: (b_, j, g_),
                              memory_space=pltpu.VMEM)
    o_spec = pl.BlockSpec((1, bs, hg * d),
                          lambda b_, g_, i, j: (b_, i, g_),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, hg, 8, bs),
                            lambda b_, g_, i, j: (b_, g_, 0, i),
                            memory_space=pltpu.VMEM)
    in_specs = []
    operands = []
    if drop > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.asarray(seed, jnp.int32).reshape(1))
    in_specs += [qkv_q_spec, qkv_k_spec]
    operands += [qkv3, qkv3]
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bs), lambda b_, g_, i, j: (b_, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(_kvm8(kv_mask, b, ps, bs))
    o, lse8 = pl.pallas_call(
        functools.partial(_fwd_e_blocked_kernel, scale, a, causal,
                          has_kvm, drop, kpad, s, hg, d, bs),
        grid=(b, g, nb, nb),
        in_specs=in_specs,
        out_specs=[o_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, ps, h * d), qkv_e.dtype),
            jax.ShapeDtypeStruct((b, h, 8, ps), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bs, hg * d), jnp.float32),
            pltpu.VMEM((bs, 128), jnp.float32),
            pltpu.VMEM((bs, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    lse = lse8[:, :, 0, :s]                # (b, h, s)
    return o[:, :s], lse


def _bwd_e_kernel(a, vscale, causal, has_kvm, drop, kpad, s_real, hg, d,
                  *refs):
    if drop > 0.0:
        seed_ref, *refs = refs
    qkv_ref, do_ref, lse2_ref, delta_ref, *rest = refs
    if has_kvm:
        kvm_ref, dqkv_ref = rest
    else:
        kvm_ref = None
        (dqkv_ref,) = rest
    blk = qkv_ref[0]                       # (ps, hg*3*d)
    do_blk = do_ref[0]                     # (ps, hg*d)
    if has_kvm:
        vm = kvm_ref[0, 0, 0, :][None, :] > 0
    bidx = pl.program_id(0)
    gidx = pl.program_id(1)
    for j in range(hg):
        off = j * 3 * d
        qh = blk[:, off:off + d]
        kh = blk[:, off + d:off + 2 * d]
        vh = blk[:, off + 2 * d:off + 3 * d]
        doh = do_blk[:, j * d:(j + 1) * d]
        s = _dot(qh, kh, trans_b=True)
        # NOTE: unlike _bwd_fused_kernel, dp is NOT hoisted before the
        # softmax here — a third live fp32 score buffer puts the kernel
        # ~124 KB over the VMEM stack limit at hg=4/ps=1024, and the
        # unrolled head loop already overlaps head j's VPU work with
        # head j+1's MXU passes.
        lse2 = lse2_ref[0, j, 0, :][:, None]
        arg = s * a - lse2
        mask = None
        if causal:
            mask = _tri_mask(s.shape, 0, 0)
        if kpad and not has_kvm:
            km = _kcol_mask(s.shape, 0, s_real)
            mask = km if mask is None else (mask & km)
        if has_kvm:
            mask = vm if mask is None else (mask & vm)
        if mask is not None:
            arg = jnp.where(mask, arg, _NEG)
        p = jnp.exp2(arg)
        if drop > 0.0:
            # regenerate the forward's keep mask; dv consumes the
            # dropped/rescaled probabilities, ds the undropped p with
            # the mask applied to dp (dS = P*(dP@M/(1-r) - delta))
            keep = _rand_keep(p.shape, seed_ref[0], bidx,
                              gidx * hg + j, 0, 0, drop)
            pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
        else:
            pa = p
        dv = _dot_t0(pa.astype(doh.dtype), doh)
        vs = vh * jnp.asarray(vscale, vh.dtype)
        dp = _dot(doh, vs, trans_b=True)
        if drop > 0.0:
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - drop))
        delta = delta_ref[0, j, 0, :][:, None]
        ds = p * (dp - delta)
        dq = _dot(ds.astype(kh.dtype), kh)
        dk = _dot_t0(ds.astype(qh.dtype), qh)
        dqkv_ref[0, :, off:off + d] = dq.astype(dqkv_ref.dtype)
        dqkv_ref[0, :, off + d:off + 2 * d] = dk.astype(dqkv_ref.dtype)
        dqkv_ref[0, :, off + 2 * d:off + 3 * d] = \
            dv.astype(dqkv_ref.dtype)


def _flash_bwd_e(h, scale, causal, res, do, kv_mask=None, drop=0.0,
                 seed=None):
    qkv3, o3, lse, b, s = res              # qkv3/o3 already ps-padded
    ps, width = qkv3.shape[1], qkv3.shape[2]
    d = width // (3 * h)
    hg = _pick_heads_per_group(h, d, ps, drop=drop > 0.0) \
        if ps <= _E_MAX_SEQ else None
    if hg is None:                   # same dispatch as _flash_fwd_e
        return _flash_bwd_e_blocked(h, scale, causal, res, do,
                                    kv_mask=kv_mask, drop=drop,
                                    seed=seed)
    g = h // hg
    a = scale * _LOG2E
    kpad = ps != s
    has_kvm = kv_mask is not None

    do3 = _pad_to(do, 1, ps)
    scale_v = float(np.asarray(scale).astype(qkv3.dtype))  # see _flash_bwd
    delta = (do3.astype(jnp.float32) * o3.astype(jnp.float32)) \
        .reshape(b, ps, h, d).sum(-1).transpose(0, 2, 1) * scale_v
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, ps))
    lse2 = _pad_to(lse * _LOG2E, 2, ps, value=_BIG)        # (b, h, ps)
    lse28 = jnp.broadcast_to(lse2[:, :, None, :], (b, h, 8, ps))

    qkv_spec = pl.BlockSpec((1, ps, hg * 3 * d),
                            lambda b_, g_: (b_, 0, g_),
                            memory_space=pltpu.VMEM)
    do_spec = pl.BlockSpec((1, ps, hg * d), lambda b_, g_: (b_, 0, g_),
                           memory_space=pltpu.VMEM)
    r_spec = pl.BlockSpec((1, hg, 8, ps), lambda b_, g_: (b_, g_, 0, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [qkv_spec, do_spec, r_spec, r_spec]
    operands = [qkv3, do3, lse28, delta8]
    if drop > 0.0:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, jnp.asarray(seed, jnp.int32).reshape(1))
    if has_kvm:
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, ps), lambda b_, g_: (b_, 0, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(_kvm8(kv_mask, b, ps, ps))
    dqkv = pl.pallas_call(
        functools.partial(_bwd_e_kernel, a, scale, causal, has_kvm,
                          drop, kpad, s, hg, d),
        grid=(b, g),
        in_specs=in_specs,
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((b, ps, width), qkv3.dtype),
        interpret=_interpret(),
    )(*operands)
    return dqkv[:, :s]


def _bwd_e_blocked_kernel(a, vscale, causal, has_kvm, drop, kpad,
                          s_real, hg, d, bs, *refs):
    """Blocked E-layout backward, ONE kernel: grid (b, g, i, j) where
    ``i`` is the sequence block whose full-width dqkv tile this cell
    owns and ``j`` walks all sequence blocks.  Each cell accumulates
    BOTH sides into VMEM scratch:

    - dq side (q-block i vs k-block j, causal keeps j <= i):
      ds = p*(dp' - delta'),  dq_i += ds @ k_j
    - dk/dv side (q-block j vs k-block i, causal keeps j >= i):
      dv_i += p^T do_j,  dk_i += ds^T q_j

    Every (i, j) score tile is computed exactly twice across the grid —
    the same total as the classic two-kernel flash backward — but the
    output is ONE (bs, hg*3d) head-interleaved dqkv tile per i: no dq
    vs dk/dv split, no concatenate, zero relayout copies at the
    custom-call boundary (the whole point of the E layout)."""
    if drop > 0.0:
        seed_ref, *refs = refs
    (qkv_i_ref, qkv_j_ref, do_i_ref, do_j_ref, lse_i_ref, lse_j_ref,
     delta_i_ref, delta_j_ref, *rest) = refs
    if has_kvm:
        kvm_i_ref, kvm_j_ref, dqkv_ref, dq_acc, dk_acc, dv_acc = rest
    else:
        kvm_i_ref = kvm_j_ref = None
        dqkv_ref, dq_acc, dk_acc, dv_acc = rest
    bidx = pl.program_id(0)
    gidx = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)
    ns = pl.num_programs(3)
    inv = 1.0 / (1.0 - drop) if drop > 0.0 else 1.0

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run_dq = (j <= i) if causal else (j >= 0)
    run_dkv = (j >= i) if causal else (j >= 0)

    @pl.when(run_dq)
    def _dq_side():
        iblk = qkv_i_ref[0]
        jblk = qkv_j_ref[0]
        do_i = do_i_ref[0]
        if has_kvm:
            vm = kvm_j_ref[0, 0, 0, :][None, :] > 0
        for jh in range(hg):
            off = jh * 3 * d
            qh = iblk[:, off:off + d]
            kh = jblk[:, off + d:off + 2 * d]
            vh = jblk[:, off + 2 * d:off + 3 * d]
            doh = do_i[:, jh * d:(jh + 1) * d]
            s = _dot(qh, kh, trans_b=True)
            lse2 = lse_i_ref[0, jh, 0, :][:, None]
            arg = s * a - lse2
            mask = None
            if causal:
                mask = _tri_mask(s.shape, i * bs, j * bs)
            if kpad and not has_kvm:
                km = _kcol_mask(s.shape, j * bs, s_real)
                mask = km if mask is None else (mask & km)
            if has_kvm:
                mask = vm if mask is None else (mask & vm)
            if mask is not None:
                arg = jnp.where(mask, arg, _NEG)
            p = jnp.exp2(arg)
            vs = vh * jnp.asarray(vscale, vh.dtype)
            dp = _dot(doh, vs, trans_b=True)
            if drop > 0.0:
                keep = _rand_keep(p.shape, seed_ref[0], bidx,
                                  gidx * hg + jh, i, j, drop)
                dp = jnp.where(keep, dp, 0.0) * inv
            delta = delta_i_ref[0, jh, 0, :][:, None]
            ds = p * (dp - delta)
            sl = slice(jh * d, (jh + 1) * d)
            dq_acc[:, sl] = dq_acc[:, sl] + _dot(ds.astype(kh.dtype), kh)

    @pl.when(run_dkv)
    def _dkv_side():
        iblk = qkv_i_ref[0]
        jblk = qkv_j_ref[0]
        do_j = do_j_ref[0]
        if has_kvm:
            vm = kvm_i_ref[0, 0, 0, :][None, :] > 0
        for jh in range(hg):
            off = jh * 3 * d
            qh = jblk[:, off:off + d]              # q rows: block j
            kh = iblk[:, off + d:off + 2 * d]      # k rows: block i
            vh = iblk[:, off + 2 * d:off + 3 * d]
            doh = do_j[:, jh * d:(jh + 1) * d]
            s = _dot(qh, kh, trans_b=True)         # rows=q_j, cols=k_i
            lse2 = lse_j_ref[0, jh, 0, :][:, None]
            arg = s * a - lse2
            mask = None
            if causal:
                mask = _tri_mask(s.shape, j * bs, i * bs)
            if kpad and not has_kvm:
                km = _kcol_mask(s.shape, i * bs, s_real)
                mask = km if mask is None else (mask & km)
            if has_kvm:
                mask = vm if mask is None else (mask & vm)
            if mask is not None:
                arg = jnp.where(mask, arg, _NEG)
            p = jnp.exp2(arg)
            if drop > 0.0:
                # same salt orientation as the forward: (q-block,
                # k-block) = (j, i) on this side
                keep = _rand_keep(p.shape, seed_ref[0], bidx,
                                  gidx * hg + jh, j, i, drop)
                pa = jnp.where(keep, p, 0.0) * inv
            else:
                pa = p
            sl = slice(jh * d, (jh + 1) * d)
            dv_acc[:, sl] = dv_acc[:, sl] \
                + _dot_t0(pa.astype(doh.dtype), doh)
            vs = vh * jnp.asarray(vscale, vh.dtype)
            dp = _dot(doh, vs, trans_b=True)
            if drop > 0.0:
                dp = jnp.where(keep, dp, 0.0) * inv
            delta = delta_j_ref[0, jh, 0, :][:, None]
            ds = p * (dp - delta)
            dk_acc[:, sl] = dk_acc[:, sl] \
                + _dot_t0(ds.astype(qh.dtype), qh)

    @pl.when(j == ns - 1)
    def _finish():
        for jh in range(hg):
            off = jh * 3 * d
            sl = slice(jh * d, (jh + 1) * d)
            dqkv_ref[0, :, off:off + d] = \
                dq_acc[:, sl].astype(dqkv_ref.dtype)
            dqkv_ref[0, :, off + d:off + 2 * d] = \
                dk_acc[:, sl].astype(dqkv_ref.dtype)
            dqkv_ref[0, :, off + 2 * d:off + 3 * d] = \
                dv_acc[:, sl].astype(dqkv_ref.dtype)


def _flash_bwd_e_blocked(h, scale, causal, res, do, kv_mask=None,
                         drop=0.0, seed=None):
    qkv3, o3, lse, b, s = res              # 128-aligned from the vjp fwd
    width = qkv3.shape[2]
    d = width // (3 * h)
    bs = min(_E_BLOCK, -(-s // 128) * 128)
    # residuals are 128-aligned; the blocked walk needs bs multiples
    qkv3 = _pad_to(qkv3, 1, bs)
    o3 = _pad_to(o3, 1, bs)
    ps = qkv3.shape[1]
    hg = _pick_heads_per_group_blocked(h, d, bs, drop=drop > 0.0)
    if hg is None:
        raise ValueError(
            f"blocked E-layout backward cannot run h={h} d={d} bs={bs} "
            f"(no head grouping with 3*hg*d lanes % 128 == 0 inside "
            f"the VMEM budget); route through flash_attention_e, which "
            f"checks _e_mode and falls back")
    g = h // hg
    nb = ps // bs
    a = scale * _LOG2E
    kpad = ps != s
    has_kvm = kv_mask is not None

    do3 = _pad_to(do, 1, ps)
    scale_v = float(np.asarray(scale).astype(qkv3.dtype))  # see _flash_bwd
    delta = (do3.astype(jnp.float32) * o3.astype(jnp.float32)) \
        .reshape(b, ps, h, d).sum(-1).transpose(0, 2, 1) * scale_v
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, ps))
    lse2 = _pad_to(lse * _LOG2E, 2, ps, value=_BIG)        # (b, h, ps)
    lse28 = jnp.broadcast_to(lse2[:, :, None, :], (b, h, 8, ps))

    def qkv_spec(which):
        return pl.BlockSpec(
            (1, bs, hg * 3 * d),
            (lambda b_, g_, i, j: (b_, i, g_)) if which == "i"
            else (lambda b_, g_, i, j: (b_, j, g_)),
            memory_space=pltpu.VMEM)

    def do_spec(which):
        return pl.BlockSpec(
            (1, bs, hg * d),
            (lambda b_, g_, i, j: (b_, i, g_)) if which == "i"
            else (lambda b_, g_, i, j: (b_, j, g_)),
            memory_space=pltpu.VMEM)

    def r_spec(which):
        return pl.BlockSpec(
            (1, hg, 8, bs),
            (lambda b_, g_, i, j: (b_, g_, 0, i)) if which == "i"
            else (lambda b_, g_, i, j: (b_, g_, 0, j)),
            memory_space=pltpu.VMEM)

    in_specs = [qkv_spec("i"), qkv_spec("j"), do_spec("i"), do_spec("j"),
                r_spec("i"), r_spec("j"), r_spec("i"), r_spec("j")]
    operands = [qkv3, qkv3, do3, do3, lse28, lse28, delta8, delta8]
    if drop > 0.0:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.insert(0, jnp.asarray(seed, jnp.int32).reshape(1))
    if has_kvm:
        kvm = _kvm8(kv_mask, b, ps, bs)
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bs), lambda b_, g_, i, j: (b_, i, 0, 0),
            memory_space=pltpu.VMEM))
        in_specs.append(pl.BlockSpec(
            (1, 1, 8, bs), lambda b_, g_, i, j: (b_, j, 0, 0),
            memory_space=pltpu.VMEM))
        operands += [kvm, kvm]
    dqkv = pl.pallas_call(
        functools.partial(_bwd_e_blocked_kernel, a, scale, causal,
                          has_kvm, drop, kpad, s, hg, d, bs),
        grid=(b, g, nb, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bs, hg * 3 * d),
                               lambda b_, g_, i, j: (b_, i, g_),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, ps, width), qkv3.dtype),
        scratch_shapes=[
            pltpu.VMEM((bs, hg * d), jnp.float32),
            pltpu.VMEM((bs, hg * d), jnp.float32),
            pltpu.VMEM((bs, hg * d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return dqkv[:, :s]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _flash_e_fused(qkv_e, h, scale, causal):
    return _flash_fwd_e(qkv_e, h, scale, causal)[0]


def _flash_e_vjp_fwd(qkv_e, h, scale, causal):
    b, s, _ = qkv_e.shape
    ps = -(-s // 128) * 128
    o, lse = _flash_fwd_e(qkv_e, h, scale, causal)
    o3 = _pad_to(o, 1, ps)
    return o, (_pad_to(qkv_e, 1, ps), o3, lse, b, s)


def _flash_e_vjp_bwd(h, scale, causal, res, do):
    return (_flash_bwd_e(h, scale, causal, res, do),)


_flash_e_fused.defvjp(_flash_e_vjp_fwd, _flash_e_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _flash_e_masked(qkv_e, kv_mask, h, scale, causal):
    return _flash_fwd_e(qkv_e, h, scale, causal, kv_mask=kv_mask)[0]


def _flash_e_masked_vjp_fwd(qkv_e, kv_mask, h, scale, causal):
    b, s, _ = qkv_e.shape
    ps = -(-s // 128) * 128
    o, lse = _flash_fwd_e(qkv_e, h, scale, causal, kv_mask=kv_mask)
    o3 = _pad_to(o, 1, ps)
    return o, (_pad_to(qkv_e, 1, ps), o3, lse, b, s, kv_mask)


def _flash_e_masked_vjp_bwd(h, scale, causal, res, do):
    *core, kv_mask = res
    dqkv = _flash_bwd_e(h, scale, causal, tuple(core), do,
                        kv_mask=kv_mask)
    return dqkv, jnp.zeros_like(kv_mask)


_flash_e_masked.defvjp(_flash_e_masked_vjp_fwd, _flash_e_masked_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _flash_e_drop(qkv_e, seed, h, scale, causal, rate):
    return _flash_fwd_e(qkv_e, h, scale, causal, drop=rate,
                        seed=seed)[0]


def _flash_e_drop_vjp_fwd(qkv_e, seed, h, scale, causal, rate):
    b, s, _ = qkv_e.shape
    ps = -(-s // 128) * 128
    o, lse = _flash_fwd_e(qkv_e, h, scale, causal, drop=rate, seed=seed)
    o3 = _pad_to(o, 1, ps)
    return o, (_pad_to(qkv_e, 1, ps), o3, lse, b, s, seed)


def _flash_e_drop_vjp_bwd(h, scale, causal, rate, res, do):
    *core, seed = res
    dqkv = _flash_bwd_e(h, scale, causal, tuple(core), do, drop=rate,
                        seed=seed)
    return dqkv, np.zeros(jnp.shape(seed), dtype=jax.dtypes.float0)


_flash_e_drop.defvjp(_flash_e_drop_vjp_fwd, _flash_e_drop_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_e_masked_drop(qkv_e, kv_mask, seed, h, scale, causal, rate):
    return _flash_fwd_e(qkv_e, h, scale, causal, kv_mask=kv_mask,
                        drop=rate, seed=seed)[0]


def _flash_e_masked_drop_vjp_fwd(qkv_e, kv_mask, seed, h, scale, causal,
                                 rate):
    b, s, _ = qkv_e.shape
    ps = -(-s // 128) * 128
    o, lse = _flash_fwd_e(qkv_e, h, scale, causal, kv_mask=kv_mask,
                          drop=rate, seed=seed)
    o3 = _pad_to(o, 1, ps)
    return o, (_pad_to(qkv_e, 1, ps), o3, lse, b, s, kv_mask, seed)


def _flash_e_masked_drop_vjp_bwd(h, scale, causal, rate, res, do):
    *core, kv_mask, seed = res
    dqkv = _flash_bwd_e(h, scale, causal, tuple(core), do,
                        kv_mask=kv_mask, drop=rate, seed=seed)
    return (dqkv, jnp.zeros_like(kv_mask),
            np.zeros(jnp.shape(seed), dtype=jax.dtypes.float0))


_flash_e_masked_drop.defvjp(_flash_e_masked_drop_vjp_fwd,
                            _flash_e_masked_drop_vjp_bwd)


def flash_attention_e(qkv: jnp.ndarray,
                      scale: Optional[float] = None,
                      causal: bool = False,
                      kv_mask: Optional[jnp.ndarray] = None,
                      dropout_rate: float = 0.0,
                      dropout_seed=None) -> jnp.ndarray:
    """Self-attention over the projection-native layout: ``qkv``
    (b, s, h, 3*d) — lanes [head][q|k|v] exactly as
    ``proj(x).reshape(b, s, h, 3*d)`` produces — returning the context
    (b, s, h*d) ready for the output projection.  Semantically equal to
    splitting/transposing and calling :func:`flash_attention`, but the
    whole attention boundary carries ZERO relayout copies: inputs are
    lane-blocked views of the projection output, and the backward emits
    one dqkv array in the same layout.

    Eligibility (:func:`flash_e_supported`): 128-aligned-padded
    s <= 1024 runs whole-sequence blocks; longer sequences (up to
    ``APEX_TPU_FLASH_E_MAX_SEQ``, default 32768) stream (bs, bs) tiles
    with online softmax — both keep the zero-relayout property.
    Remaining fallbacks (head/lane-budget misfits, very long s, manual
    shard_map axes) log their reason once and take the transposing
    path.

    ``dropout_rate`` applies attention dropout INSIDE the kernels (the
    reference's fused-MHA in-kernel philox, ref:
    apex/contrib/csrc/multihead_attn/dropout.h): the backward
    regenerates the forward's keep mask from ``dropout_seed`` (an int32
    scalar, traced OK) instead of materializing O(s^2) mask bits.

    ``dropout_seed`` contract: NON-NEGATIVE int32.  The counter hash
    folds the seed through a 31-bit mask (Mosaic-safe uint32 view), so
    a negative seed silently aliases the mask of ``seed & 0x7FFFFFFF``.
    :func:`dropout_seed_from_key` — the canonical derivation — only
    produces non-negative seeds; hand-built seeds must do the same.
    """
    from ._context import in_manual_axis_context
    from .._autocast_ctx import autocast_compute_dtype

    b, s, h, td = qkv.shape
    d = td // 3
    if scale is None:
        scale = d ** -0.5
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    act = autocast_compute_dtype()
    if act is not None and qkv.dtype != act \
            and jnp.issubdtype(qkv.dtype, jnp.floating):
        qkv = qkv.astype(act)
    manual = in_manual_axis_context(qkv)
    mode, why = _e_mode(s, h, d, drop=dropout_rate > 0.0)
    if manual or mode is None:
        reason = "inside shard_map manual axes" if manual else why
        _log_e_fallback(reason, b, s, h, d)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        if dropout_rate > 0.0:
            # dropout needs the probabilities, which the reduced flash
            # output no longer carries — the reference path applies the
            # same post-softmax counter-hash mask
            ctx = _fallback_dropout_attention(
                q, k, v, scale, causal, kv_mask, dropout_rate,
                dropout_seed)
        elif manual:
            ctx = mha_reference(q, k, v, scale=scale, causal=causal,
                                kv_mask=kv_mask)
        else:
            ctx = flash_attention(q, k, v, scale=scale, causal=causal,
                                  kv_mask=kv_mask)
        return ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d)
    qkv_e = qkv.reshape(b, s, h * td)
    seed = dropout_seed
    if dropout_rate > 0.0:
        if kv_mask is not None:
            return _flash_e_masked_drop(
                qkv_e, kv_mask.astype(jnp.float32),
                jnp.asarray(seed, jnp.int32), h, scale, causal,
                float(dropout_rate))
        return _flash_e_drop(qkv_e, jnp.asarray(seed, jnp.int32), h,
                             scale, causal, float(dropout_rate))
    if kv_mask is not None:
        return _flash_e_masked(qkv_e, kv_mask.astype(jnp.float32), h,
                               scale, causal)
    return _flash_e_fused(qkv_e, h, scale, causal)


def dropout_seed_from_key(key) -> jnp.ndarray:
    """Derive the int32 ``dropout_seed`` :func:`flash_attention_e`
    expects from a JAX PRNG key — the one canonical mapping, so every
    call site (transformer layers, contrib MHA) stays in sync."""
    return jax.random.randint(key, (), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)


_E_FALLBACK_SEEN: set = set()


def _log_e_fallback(reason: str, b: int, s: int, h: int, d: int):
    """One line per distinct (shape, reason) per process — the VERDICT
    requirement that silent E-layout fallbacks do not silently re-pay
    the relayout glue."""
    key = (reason, b, s, h, d)
    if key in _E_FALLBACK_SEEN:
        return
    _E_FALLBACK_SEEN.add(key)
    from ..utils.log_util import get_logger

    get_logger(__name__).info(
        "flash_attention_e fallback to transposing path for "
        "(b=%d, s=%d, h=%d, d=%d): %s", b, s, h, d, reason)


def _fallback_dropout_attention(q, k, v, scale, causal, kv_mask, rate,
                                seed):
    """Reference-path attention with the same post-softmax dropout
    semantics as the kernels (counter-hash keep mask; normalization by
    the undropped softmax denominator)."""
    b, h, sq, sk = q.shape[0], q.shape[1], q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, _NEG)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :].astype(bool), s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if kv_mask is not None:
        # fully-masked rows: softmax over all-_NEG is uniform garbage;
        # emit exact zeros like the kernels' dead-row guard
        dead = jnp.max(s, axis=-1, keepdims=True) <= _NEG * 0.5
        p = jnp.where(dead, 0.0, p)
    # 4-D counter hash: same fmix32 mixing, element-unique counters
    u32 = functools.partial(jnp.asarray, dtype=jnp.uint32)
    seed_u = jnp.bitwise_and(jnp.asarray(seed, jnp.int32),
                             jnp.int32(0x7FFFFFFF)).astype(jnp.uint32)
    bi = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 0)
    hi = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 1)
    qi = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 2)
    ki = jax.lax.broadcasted_iota(jnp.uint32, p.shape, 3)
    x = (seed_u * u32(0x85EBCA6B) ^ bi * u32(0xC2B2AE35)
         ^ hi * u32(0x27D4EB2F)) + qi * u32(sk) + ki
    x = (x ^ (x >> 16)) * u32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * u32(0xC2B2AE35)
    x = x ^ (x >> 16)
    f = (x >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
    p = jnp.where(f >= jnp.float32(rate), p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def mha_reference(q, k, v, scale=None, causal=False, kv_mask=None):
    """Unfused reference (the [b,h,sq,sk]-materializing baseline the
    reference's standalone GPT uses) — for parity tests and benchmarks.
    ``kv_mask`` (b, sk): True/nonzero = attend."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    sq, sk = s.shape[-2:]
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, _NEG)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :].astype(bool), s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
