"""Persistent packed optimizer pipeline: the whole post-backward step in
two HBM sweeps.

The reference's core perf feature is ``multi_tensor_apply`` — fused
kernels that stream many small tensors per launch (ref:
apex/optimizers/fused_adam.py:147-170, csrc/multi_tensor_l2norm /
_scale / _adam).  On TPU the equivalent economics is HBM traffic, and
the measured reason the earlier packed path lost (0.60-0.73x vs direct,
see ops/multi_tensor.py's DIRECT_MIN_ELEMS log) was *re-packing every
step*: pack/unpack of params+state cost more memory traffic than the
fusion saved.  This module removes the per-step repack instead of the
packing:

* **Persistent packing** — fp32 masters and optimizer state live in
  LANE-aligned packed flat buffers *across* steps
  (:class:`PackedMasters` + the optimizers' ``pipeline_init``), donated
  buffer-for-buffer through the jitted train step.  Only gradients are
  packed per step (:func:`pack_grads`), via per-leaf
  ``dynamic_update_slice`` writes into a zero-initialized flat buffer —
  static offsets, so XLA fuses the writes with the gradient producers.
  The only per-step unpack is the master->model-dtype cast the update
  sweep already emits (``multi_tensor.assemble`` of the ``lowp``
  outputs).

* **Sweep 1** (:func:`grad_norm_finite`) — one read-only pass over the
  packed grad buffers fusing amp unscale, the overflow finite-check,
  and the global-L2-norm partials (the reference's
  ``multi_tensor_l2norm`` + ``multi_tensor_scale`` overflow-buffer
  roles).  Nothing grad-sized is written: the unscale itself is folded
  into sweep 2's combined scale factor.

* **Sweep 2** (:func:`adam_pipeline` / :func:`sgd_pipeline`, LAMB via
  its shared phase-1/trust-ratio machinery) — one read-modify-write
  pass fusing clip-scale, the optimizer update, the overflow skip-select
  and the master->model cast (the ``multi_tensor_adam`` role).  The
  skip is a ``where``-select inside the same sweep, so overflow steps
  cost no extra pass and no ``lax.cond`` double-compilation.

Each sweep has a Pallas kernel and a pure-jnp twin with identical math.
Auto dispatch (``use_pallas=None``) resolves to the jnp twin: measured
on v5e, XLA's fused elementwise loops reach ~880 GB/s where a
hand-rolled Pallas elementwise stream reached ~190 GB/s
(ops/fused_optim.py ``step_use_pallas`` log) — the pipeline's win is
the persistent layout plus expression adjacency, not the kernel
authorship.  ``APEX_TPU_PIPELINE_PALLAS=1`` (or ``use_pallas=True``)
routes both sweeps through the Pallas kernels for hardware where the
trade-off shifts; tools/ci.sh runs them in interpret mode on CPU every
run (:func:`self_check`).

``APEX_TPU_FUSED_PIPELINE=0`` disables the pipeline wholesale —
:class:`apex_tpu.amp.AmpOptimizer` then keeps the per-stage path
(unscale pass, finite pass, ``fused_step``, master->model convert).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.flags import flag_bool
from . import fused_optim, multi_tensor
from .multi_tensor import LANE, FlatMeta

# Force every leaf into chunked per-dtype packs (no direct groups):
# persistent buffers amortize the pack across the whole run, so the
# per-step packing loss DIRECT_MIN_ELEMS guards against does not apply.
_ALL_PACKED = 1 << 62


def pipeline_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the pipeline on/off switch: an explicit flag wins, else
    the ``APEX_TPU_FUSED_PIPELINE`` env var (default ON; ``0`` is the
    escape hatch back to the per-stage path).  Read per call so setting
    the var after import still takes effect for new optimizers."""
    if flag is not None:
        return bool(flag)
    return flag_bool("APEX_TPU_FUSED_PIPELINE")


def use_pallas_pipeline(flag: Optional[bool] = None) -> bool:
    """Kernel dispatch for the two pipeline sweeps.  Explicit flag wins;
    auto resolves to the jnp twins (see module docstring for the
    measured rationale) unless ``APEX_TPU_PIPELINE_PALLAS=1``."""
    if flag is not None:
        return bool(flag)
    return flag_bool("APEX_TPU_PIPELINE_PALLAS")


def pipeline_metas(tree: Any) -> List[FlatMeta]:
    """Packing layout for the persistent pipeline: LANE-aligned offsets
    (row-friendly per-tensor reductions for LAMB), every leaf packed,
    chunked at ``PACK_MAX_ELEMS`` (the XLA pair-layout temp guard).
    Group key is the leaf dtype — compute the metas from the MODEL
    (cast) tree so gradient buffers group identically; masters pack
    into the same layout with ``dtype=float32``."""
    return multi_tensor.compute_metas(tree, align=LANE, split_direct=True,
                                      direct_min=_ALL_PACKED)


def packed_nbytes(tree: Any) -> int:
    """Pre-alignment byte total of ``tree`` in its own leaf dtypes
    (shapes/dtypes only — safe on arrays, tracers, and
    ``ShapeDtypeStruct`` templates).  The quantity the
    ``APEX_TPU_PIPELINE_PACK_MIN_BYTES`` routing cutoff compares: the
    persistent pipeline's win is amortizing the pack across a run, and
    below a packed-size floor the measured 0.73x small-tree residue
    says direct per-leaf updates are the faster regime."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            arr = jnp.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
        size = 1
        for d in shape:
            size *= int(d)
        total += size * jnp.dtype(dtype).itemsize
    return total


def pack_grads(tree: Any, metas: Sequence[FlatMeta]) -> List[jnp.ndarray]:
    """Pack a gradient pytree into flat buffers by per-leaf
    ``dynamic_update_slice`` writes into a zero-initialized buffer.

    This replaces the concatenate-based :func:`multi_tensor.pack` on
    the per-step path: offsets are static Python ints, so each write
    lowers to a fusible in-place update-slice — XLA can emit the
    gradient producer's output directly into the flat buffer instead of
    materializing the leaf then gathering it (the copy chain behind the
    measured 0.60-0.73x packed_vs_direct loss).  Alignment gaps and the
    tail stay exactly zero (the LAMB ``per_tensor_sumsq`` gap
    invariant).

    Each group's buffer dtype is the widest dtype among its member
    gradients (at least the group's model dtype): a user feeding fp32
    accumulated gradients against an fp16/bf16 model must never have
    them silently downcast — under a 2^16 loss scale an fp32->fp16
    cast would overflow to inf BEFORE the unscale sweep (the staged
    path accepts any grad dtype; so does the pipeline)."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    out = []
    for meta in metas:
        dt = jnp.result_type(meta.dtype,
                             *(jnp.asarray(leaves[i]).dtype
                               for i in meta.leaf_indices))
        buf = jnp.zeros((meta.padded,), dt)
        for k, i in enumerate(meta.leaf_indices):
            piece = jnp.ravel(jnp.asarray(leaves[i])).astype(dt)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, piece, meta.offsets[k], axis=0)
        out.append(buf)
    return out


@dataclasses.dataclass(frozen=True)
class PackedMasters:
    """fp32 master weights as persistent packed flat buffers.

    A pytree whose leaves are the per-group buffers and whose aux data
    is the static packing layout — it checkpoints, donates, and
    ``tree_map``s like any other master tree while never being
    unpacked.  The model-dtype view is produced by the update sweep
    (``lowp`` outputs); :meth:`to_model` exists for the cold paths
    (checkpoint restore, debugging) that need params without a step.
    """

    bufs: Tuple[jnp.ndarray, ...]
    metas: Tuple[FlatMeta, ...]

    def to_model(self, template: Any) -> Any:
        """Assemble the model-dtype param pytree from the packed
        masters.  ``template`` may hold abstract leaves
        (``ShapeDtypeStruct``) — only dtypes are read; the tree
        structure comes from the packing metas."""
        leaves = jax.tree_util.tree_leaves(template)
        dtypes = [getattr(l, "dtype", None) or jnp.asarray(l).dtype
                  for l in leaves]
        return multi_tensor.assemble(list(self.bufs), list(self.metas),
                                     out_dtypes=dtypes)


jax.tree_util.register_pytree_node(
    PackedMasters,
    lambda pm: (pm.bufs, pm.metas),
    lambda metas, bufs: PackedMasters(tuple(bufs), metas),
)


def _pm_to_state_dict(pm: PackedMasters) -> dict:
    from flax import serialization

    return {"bufs": serialization.to_state_dict(list(pm.bufs))}


def _pm_from_state_dict(pm: PackedMasters, state: dict) -> PackedMasters:
    from flax import serialization

    bufs = serialization.from_state_dict(list(pm.bufs), state["bufs"])
    return PackedMasters(tuple(bufs), pm.metas)


try:
    # flax msgpack checkpointing (examples/imagenet/main_amp.py) needs
    # an explicit handler for custom pytree nodes: the buffers
    # serialize, the static layout comes from the restore target.
    from flax import serialization as _flax_serialization

    _flax_serialization.register_serialization_state(
        PackedMasters, _pm_to_state_dict, _pm_from_state_dict)
except ImportError:  # flax-less deployments still get the pipeline
    pass


def pack_masters(params: Any, model_template: Any) -> PackedMasters:
    """Build the persistent packed master state: layout from the MODEL
    (cast) tree — so per-step gradient packing groups identically —
    buffers snapshotted fp32 from the original (highest-precision)
    ``params``, exactly as the reference clones masters before the
    low-precision cast (ref: apex/amp/_process_optimizer.py:28-44)."""
    metas = pipeline_metas(model_template)
    bufs = tuple(multi_tensor.pack(params, [m], jnp.float32)[0]
                 for m in metas)
    return PackedMasters(bufs, tuple(metas))


# --------------------------------------------------------------------------
# Sweep 1: unscale + finite-check + global-norm partials (read-only)
# --------------------------------------------------------------------------

def _norm_finite_kernel(total_rows: int, block_rows: int, hyp_ref,
                        g_ref, part_ref, fin_ref):
    """Per-block partial sum-of-squares of (g * inv_scale) plus a
    finite flag; partials land in per-block SMEM slots (no
    cross-iteration accumulation) and are reduced outside.  The ragged
    last block is masked by row index — the buffer's own zero padding
    needs no mask (zeros contribute nothing and are finite)."""
    i = pl.program_id(0)
    g = g_ref[:].astype(jnp.float32) * hyp_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, g.shape, 0) \
        + i * block_rows
    g = jnp.where(rows < total_rows, g, 0.0)
    part_ref[0] = jnp.sum(g * g)
    fin_ref[0] = jnp.all(jnp.isfinite(g)).astype(jnp.int32)


def _norm_finite_pallas(buf: jnp.ndarray, inv: jnp.ndarray,
                        interpret=None):
    n = buf.shape[0]
    assert n % LANE == 0, f"flat buffer length {n} not a multiple of {LANE}"
    rows = n // LANE
    block_rows = min(fused_optim.BLOCK_ROWS, rows)
    grid = -(-rows // block_rows)
    view = buf.reshape(rows, LANE)
    kernel = functools.partial(_norm_finite_kernel, rows, block_rows)
    parts, fins = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((block_rows, LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((1,), lambda i: (i,),
                                memory_space=pltpu.SMEM)] * 2,
        out_shape=[jax.ShapeDtypeStruct((grid,), jnp.float32),
                   jax.ShapeDtypeStruct((grid,), jnp.int32)],
        interpret=fused_optim._interpret() if interpret is None
        else interpret,
    )(inv.reshape(1), view)
    return jnp.sum(parts), jnp.all(fins > 0)


def _norm_finite_jnp(buf: jnp.ndarray, inv: jnp.ndarray):
    """jnp twin of :func:`_norm_finite_pallas` — one buffer's
    (sum-of-squares, finite) partial for the norm/finite sweep."""
    g = buf.astype(jnp.float32) * inv
    return multi_tensor.sumsq(g), jnp.all(jnp.isfinite(g))


def grad_norm_finite(gbufs: Sequence[jnp.ndarray], inv_scale=1.0,
                     use_pallas: Optional[bool] = None, interpret=None):
    """ONE read-only sweep over the packed grad buffers ->
    ``(global_norm, finite)`` of the *unscaled* gradients
    (``g * inv_scale`` in fp32) — the fused
    ``multi_tensor_l2norm`` + overflow-buffer stage of the pipeline.
    The unscaled values are never written: callers fold ``inv_scale``
    into the update sweep's combined scale instead."""
    inv = jnp.asarray(inv_scale, jnp.float32)
    sums, fins = [], []
    for buf in gbufs:
        if use_pallas_pipeline(use_pallas):
            s, f = _norm_finite_pallas(buf, inv, interpret=interpret)
        else:
            s, f = _norm_finite_jnp(buf, inv)
        sums.append(s)
        fins.append(f)
    if not sums:
        return jnp.float32(0.0), jnp.bool_(True)
    total = sums[0]
    for s in sums[1:]:
        total = total + s
    return jnp.sqrt(total), jnp.stack(fins).all()


def packed_norm(gbufs: Sequence[jnp.ndarray], scale=1.0) -> jnp.ndarray:
    """Global L2 norm of ``g * scale`` over packed buffers — the
    norm-only form for callers that already know the grads are finite
    (or don't care): optimizer-level clipping when amp elided the
    norm/finite sweep under static scaling."""
    if not gbufs:
        return jnp.float32(0.0)
    s = jnp.asarray(scale, jnp.float32)
    total = None
    for buf in gbufs:
        part = multi_tensor.sumsq(buf.astype(jnp.float32) * s)
        total = part if total is None else total + part
    return jnp.sqrt(total)


# --------------------------------------------------------------------------
# Sweep 2: clip-scale + update + skip-select + master->model cast
# --------------------------------------------------------------------------

def _adam_pipeline_kernel(adam_w_mode: bool, emit_lowp: bool, hyp_ref,
                          g_ref, p_ref, m_ref, v_ref, *out_refs):
    if emit_lowp:
        p_out, m_out, v_out, lowp_ref = out_refs
    else:
        p_out, m_out, v_out = out_refs
    lr, b1, b2, eps, wd, bc1, bc2, gscale, keep = (hyp_ref[i]
                                                   for i in range(9))
    g = g_ref[:].astype(jnp.float32) * gscale
    p = p_ref[:]
    m_old = m_ref[:]
    v_old = v_ref[:]
    if not adam_w_mode:
        # ADAM_MODE_0: L2 decay folds into the gradient
        # (ref: multi_tensor_adam.cu:60-78).
        g = g + wd * p
    m = b1 * m_old + (1.0 - b1) * g
    v = b2 * v_old + (1.0 - b2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode:
        upd = upd + wd * p
    ok = keep > 0.5
    p_new = jnp.where(ok, p - lr * upd, p)
    p_out[:] = p_new
    m_out[:] = jnp.where(ok, m, m_old)
    v_out[:] = jnp.where(ok, v, v_old)
    if emit_lowp:
        lowp_ref[:] = p_new.astype(lowp_ref.dtype)


def _adam_pipeline_jnp(g, p, m, v, lr, b1, b2, eps, wd, bc1, bc2,
                       gscale, finite, adam_w_mode, lowp_dtype):
    g = g.astype(jnp.float32) * gscale
    if not adam_w_mode:
        g = g + wd * p
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        upd = upd + wd * p
    p_new = jnp.where(finite, p - lr * upd, p)
    m_new = jnp.where(finite, m_new, m)
    v_new = jnp.where(finite, v_new, v)
    lowp = p_new.astype(lowp_dtype) if lowp_dtype is not None else None
    return p_new, m_new, v_new, lowp


def adam_pipeline(g, p, m, v, *, grad_scale, lr, beta1, beta2, eps,
                  weight_decay, bias_correction1, bias_correction2,
                  adam_w_mode=True, finite=True, lowp_dtype=None,
                  use_pallas: Optional[bool] = None, interpret=None):
    """The Adam update sweep over one packed group: combined-scale the
    grads (unscale x clip, pre-folded into ``grad_scale``), Adam/AdamW
    update, overflow skip-select (``finite``), and the master->model
    cast (``lowp_dtype``) — one read of g/p/m/v, one write of
    p/m/v[/lowp].  Returns ``(new_p, new_m, new_v, lowp_or_None)``."""
    finite = jnp.asarray(finite)
    if not use_pallas_pipeline(use_pallas):
        return _adam_pipeline_jnp(
            g, p, m, v, lr, beta1, beta2, eps, weight_decay,
            bias_correction1, bias_correction2, grad_scale, finite,
            adam_w_mode, lowp_dtype)
    hyp = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(beta1),
        jnp.float32(beta2), jnp.float32(eps), jnp.float32(weight_decay),
        jnp.asarray(bias_correction1, jnp.float32),
        jnp.asarray(bias_correction2, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
        finite.astype(jnp.float32)])
    out_dtypes = [jnp.float32, jnp.float32, jnp.float32]
    if lowp_dtype is not None:
        out_dtypes.append(lowp_dtype)
    kernel = functools.partial(_adam_pipeline_kernel, adam_w_mode,
                               lowp_dtype is not None)
    outs = fused_optim._elementwise_call(kernel, hyp, [g, p, m, v],
                                         out_dtypes, interpret=interpret)
    if lowp_dtype is None:
        return outs[0], outs[1], outs[2], None
    return outs[0], outs[1], outs[2], outs[3]


def _sgd_pipeline_kernel(nesterov: bool, wd_after_momentum: bool,
                         emit_lowp: bool, hyp_ref, g_ref, p_ref,
                         mom_ref, *out_refs):
    if emit_lowp:
        p_out, mom_out, lowp_ref = out_refs
    else:
        p_out, mom_out = out_refs
    lr, momentum, dampening, wd, first_run, gscale, keep = (
        hyp_ref[i] for i in range(7))
    g = g_ref[:].astype(jnp.float32) * gscale
    p = p_ref[:]
    mom_old = mom_ref[:]
    if not wd_after_momentum:
        g = g + wd * p
    mom = jnp.where(first_run > 0.5, g,
                    momentum * mom_old + (1.0 - dampening) * g)
    upd = g + momentum * mom if nesterov else mom
    if wd_after_momentum:
        upd = upd + wd * p
    ok = keep > 0.5
    p_new = jnp.where(ok, p - lr * upd, p)
    p_out[:] = p_new
    mom_out[:] = jnp.where(ok, mom, mom_old)
    if emit_lowp:
        lowp_ref[:] = p_new.astype(lowp_ref.dtype)


def _sgd_pipeline_jnp(g, p, mom, lr, momentum, dampening, wd,
                      first_run, gscale, finite, nesterov,
                      wd_after_momentum, lowp_dtype):
    g = g.astype(jnp.float32) * gscale
    if not wd_after_momentum:
        g = g + wd * p
    mom_new = jnp.where(first_run > 0.5, g,
                        momentum * mom + (1.0 - dampening) * g)
    upd = g + momentum * mom_new if nesterov else mom_new
    if wd_after_momentum:
        upd = upd + wd * p
    p_new = jnp.where(finite, p - lr * upd, p)
    mom_new = jnp.where(finite, mom_new, mom)
    lowp = p_new.astype(lowp_dtype) if lowp_dtype is not None else None
    return p_new, mom_new, lowp


def sgd_pipeline(g, p, mom, *, grad_scale, lr, momentum, dampening,
                 weight_decay, nesterov=False, wd_after_momentum=False,
                 first_run, finite=True, lowp_dtype=None,
                 use_pallas: Optional[bool] = None, interpret=None):
    """The momentum-SGD update sweep over one packed group — see
    :func:`adam_pipeline`.  Returns ``(new_p, new_mom, lowp_or_None)``."""
    finite = jnp.asarray(finite)
    if not use_pallas_pipeline(use_pallas):
        return _sgd_pipeline_jnp(
            g, p, mom, lr, momentum, dampening, weight_decay,
            jnp.asarray(first_run, jnp.float32), grad_scale, finite,
            nesterov, wd_after_momentum, lowp_dtype)
    hyp = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(momentum),
        jnp.float32(dampening), jnp.float32(weight_decay),
        jnp.asarray(first_run, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
        finite.astype(jnp.float32)])
    out_dtypes = [jnp.float32, jnp.float32]
    if lowp_dtype is not None:
        out_dtypes.append(lowp_dtype)
    kernel = functools.partial(_sgd_pipeline_kernel, nesterov,
                               wd_after_momentum, lowp_dtype is not None)
    outs = fused_optim._elementwise_call(kernel, hyp, [g, p, mom],
                                         out_dtypes, interpret=interpret)
    if lowp_dtype is None:
        return outs[0], outs[1], None
    return outs[0], outs[1], outs[2]


def group_lowp_dtype(meta: FlatMeta):
    """The update sweep's model-copy output dtype for one group: the
    group's (model) dtype, or None when the model group is already fp32
    (the master buffer itself is the model copy then)."""
    return None if jnp.dtype(meta.dtype) == jnp.dtype(jnp.float32) \
        else meta.dtype


# --------------------------------------------------------------------------
# CI self-check: Pallas interpret-mode kernels vs staged path on CPU
# --------------------------------------------------------------------------

def self_check(steps: int = 3) -> None:
    """Kernel-regression guard run by tools/ci.sh on every CI pass (no
    TPU needed): drives the full amp pipeline with the Pallas sweeps
    FORCED (interpret mode on CPU) for ``steps`` steps on a tiny
    mixed-dtype tree and asserts parity against the per-stage path —
    masters, model params, and optimizer state."""
    import numpy as np

    from .. import amp
    from ..optimizers import fused_adam

    params = {
        "w": jnp.linspace(-1.0, 1.0, 96, dtype=jnp.float32).reshape(8, 12),
        "b": jnp.linspace(0.1, 0.5, 7, dtype=jnp.float32),
        "deep": {"k": jnp.full((5, 3), 0.25, jnp.float32)},
    }
    grads = jax.tree_util.tree_map(lambda x: 0.01 * x + 0.003, params)
    policy = amp.get_policy("O5", loss_scale=256.0)

    def run(pipeline, use_pallas):
        tx = fused_adam(1e-2, weight_decay=0.01, max_grad_norm=0.5,
                        use_pallas=use_pallas)
        opt = amp.AmpOptimizer(tx, policy, check_finite=True,
                               pipeline=pipeline)
        state = opt.init(params)
        model = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params)
        for i in range(steps):
            g = jax.tree_util.tree_map(
                lambda x: (x * (1.0 + 0.1 * i)
                           * policy.effective_loss_scale
                           ).astype(jnp.bfloat16), grads)
            model, state, info = opt.apply_gradients(g, state, model)
        return model, state, info

    model_k, state_k, info_k = run(pipeline=True, use_pallas=True)
    model_s, state_s, _ = run(pipeline=False, use_pallas=False)
    masters_k = state_k.master_params.to_model(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), params))
    # rtol covers the clip factor's reduction-order ulps (packed-buffer
    # norm vs the staged path's per-group norm); the unclipped update
    # math itself is bitwise (tests/test_fused_pipeline.py proves that)
    for a, b in zip(jax.tree_util.tree_leaves(masters_k),
                    jax.tree_util.tree_leaves(state_s.master_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(model_k),
                    jax.tree_util.tree_leaves(model_s)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-2, atol=1e-2)
    assert info_k.grad_norm is not None and bool(
        jnp.isfinite(info_k.grad_norm))
    # the norm/finite sweep agrees between Pallas (interpret) and jnp
    gb = pack_grads(jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), grads),
        pipeline_metas(model_k))
    n_p, f_p = grad_norm_finite(gb, 0.5, use_pallas=True)
    n_j, f_j = grad_norm_finite(gb, 0.5, use_pallas=False)
    np.testing.assert_allclose(float(n_p), float(n_j), rtol=1e-6)
    assert bool(f_p) and bool(f_j)
    print(f"[fused_pipeline] self-check OK: {steps} steps, Pallas "
          f"interpret sweeps == staged path (grad_norm "
          f"{float(info_k.grad_norm):.4f})")


if __name__ == "__main__":
    self_check()
