"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context capability the reference does NOT have (its fused softmax
caps at seq 2048 and FMHA at 512, ref: fused_softmax.py:151-170,
setup.py:408-424; SURVEY §2.10 records SP/CP as absent).  Here sequence
length becomes a *scaling axis*: Q, K, V are sharded over a mesh axis,
K/V blocks rotate around the ring with one ``ppermute`` per step, and
each device merges blockwise-attention partials with the online-softmax
(max, sumexp, accumulator) recurrence — attention memory per chip is
O(s_local^2) and the K/V hops ride ICI neighbour links (Liu et al. 2023,
"Ring Attention with Blockwise Transformers"; merge math is the flash
attention combine).

Call inside ``shard_map`` with q, k, v sequence-sharded on
``axis_name``; the result is the bit-for-tolerance equivalent of dense
softmax attention over the full sequence.

Head dim 64 (the reference FMHA's native size): the flash mode's
per-shard partials automatically ride the head-packed d=64 kernels —
two heads per 128-lane MXU tile via the sigma rotation (see the
head-packing note in :mod:`.flash_attention`) — whenever ``h`` is even,
roughly doubling per-shard MXU throughput over the old half-width path
(escape hatch: ``APEX_TPU_FLASH_PACK_D64=0`` /
``flash_attention.set_head_packing(False)``).  The dropout keep masks
are coordinate-hashed in GLOBAL positions, so packed and unpacked
shards draw identical masks and the ring merge is unaffected.
"""
from __future__ import annotations

from typing import Optional

import jax
from .._compat import (HAS_VMA, axis_index, axis_size,
                       rewrite_trace_free, typeof)
import jax.numpy as jnp

_NEG = -1e30


def flash_legal_here(*operands) -> bool:
    """True when a Pallas call on these operands is legal in the current
    trace context — i.e. the enclosing ``shard_map`` runs with
    ``check_vma=False`` (no operand carries a varying-mesh-axis type).
    Under ``check_vma=True`` sequence-sharded operands are vma-typed and
    pallas_call is rejected by JAX, so the einsum path must run.

    This is what lets ``use_flash=None`` (the default) pick the fast
    kernel automatically: probed on the CPU mesh, a ``P('sp')`` operand
    shows ``vma={'sp'}`` under ``check_vma=True`` and ``vma=set()``
    under ``check_vma=False``."""
    if not HAS_VMA:
        # VMA types unavailable (older JAX): there pallas_call is
        # rejected by the check_rep=True rewrite interpreter ("no
        # replication rule"), so legality = not being under it.
        return rewrite_trace_free(*operands)
    for x in operands:
        try:
            vma = getattr(typeof(x), "vma", None)
        except (AttributeError, TypeError):
            return False  # operand untypable
        if vma is None or vma:
            return False
    return True


def _block_attend(q, k, v, scale, qpos, kpos, causal, drop=0.0,
                  seed=None, q_off=0, k_off=0, head_off=0):
    """One blockwise partial: returns (m, l, acc) for local q against
    this k/v block, with causal masking by GLOBAL positions.  ``drop``
    applies the coordinate-hash keep mask (bit-identical to the flash
    kernels' — :func:`..flash_attention.rand_keep_global` at global
    offsets ``q_off``/``k_off``/``head_off``) to the VALUE accumulation
    only; ``l`` stays undropped so the cross-block merge normalizes by
    the true softmax denominator, exactly like dense in-kernel
    dropout."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = kpos[None, :] <= qpos[:, None]          # True = attend
        s = jnp.where(mask[None, None], s, _NEG)
    m = jnp.max(s, axis=-1)                            # (b, h, sq)
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m = _NEG -> p rows would be exp(0)=1; zero them
    p = jnp.where((m > _NEG / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    pa = p
    if drop > 0.0:
        from .flash_attention import rand_keep_global

        keep = rand_keep_global(s.shape, seed, drop, q_offset=q_off,
                                k_offset=k_off, head_offset=head_off)
        pa = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - drop))
    acc = jnp.einsum("bhqk,bhkd->bhqd", pa.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str,
                   scale: Optional[float] = None,
                   causal: bool = False,
                   use_flash: Optional[bool] = None,
                   dropout_rate: float = 0.0,
                   dropout_seed=None) -> jnp.ndarray:
    """Exact attention with K/V rotating around ``axis_name``.

    Shapes (per shard): q, k, v are (b, h, s_local, d); the global
    sequence is ``axis_size * s_local`` with shard i owning positions
    ``[i*s_local, (i+1)*s_local)``.  Returns the local output shard
    (b, h, s_local, d).

    ``use_flash=None`` (default) picks automatically: the Pallas flash
    partial runs whenever the enclosing ``shard_map`` legality allows
    it (``check_vma=False`` — detected via :func:`flash_legal_here`),
    else the einsum path.  ``use_flash=True`` asserts the flash path
    (errors loudly under ``check_vma=True``); ``False`` forces einsum.

    The flash mode computes each block with
    :func:`..flash_attention.flash_attention_partial` and merges
    (o, lse) pairs — per-step attention memory drops from the
    materialized O(s_local^2) fp32 scores to the kernel's blockwise
    working set, and the MXU kernel replaces the unfused einsum
    softmax.  At d=64 with even ``h`` the partial runs the head-packed
    full-width kernels (module note above).  Same math either way;
    causal blocks wholly in the future still run their (masked)
    matmuls in both modes — the merge annihilates them.

    ``dropout_rate`` applies attention dropout with GLOBAL-position
    keep masks (the round-4 in-kernel dropout, threaded through SP):
    shard r draws rows [r*s_local, ...) and rotated-block columns of
    ONE global mask — bit-identical in both modes and equal to a dense
    evaluation of :func:`..flash_attention.rand_keep_global` — so
    long-context SP training configs get the same dropout semantics as
    the single-chip kernels.  ``dropout_seed``: non-negative int32
    (see :func:`..flash_attention.dropout_seed_from_key`).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if use_flash is None:
        use_flash = flash_legal_here(q, k, v)
    nshards = axis_size(axis_name)
    rank = axis_index(axis_name)
    s_local = q.shape[-2]
    perm = [(i, (i + 1) % nshards) for i in range(nshards)]
    drop_kw = (dict(dropout_rate=dropout_rate,
                    dropout_seed=dropout_seed)
               if dropout_rate > 0.0 else {})

    if use_flash:
        from .flash_attention import flash_attention_partial

        qoff = rank * s_local

        def fstep(carry, i):
            kk, vv, o, lse = carry
            kk = jax.lax.ppermute(kk, axis_name, perm)
            vv = jax.lax.ppermute(vv, axis_name, perm)
            src = (rank - i) % nshards
            bo, blse = flash_attention_partial(
                q, kk, vv, scale=scale, causal=causal,
                q_offset=qoff, k_offset=src * s_local, **drop_kw)
            lse_new = jnp.logaddexp(lse, blse)
            o = (o * jnp.exp(lse - lse_new)[..., None]
                 + bo.astype(o.dtype) * jnp.exp(blse - lse_new)[..., None])
            return (kk, vv, o, lse_new), None

        o0, lse0 = flash_attention_partial(
            q, k, v, scale=scale, causal=causal,
            q_offset=qoff, k_offset=qoff, **drop_kw)
        if nshards > 1:
            (_, _, o, _), _ = jax.lax.scan(
                fstep, (k, v, o0.astype(jnp.float32), lse0),
                jnp.arange(1, nshards))
        else:
            o = o0
        return o.astype(q.dtype)

    qpos = rank * s_local + jnp.arange(s_local)

    def merge(m, l, acc, bm, bl, bacc):
        m_new = jnp.maximum(m, bm)
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(bm - m_new)
        # guard: rows never touched keep m = _NEG; exp(_NEG-_NEG)=1 ok
        l = l * c_old + bl * c_blk
        acc = acc * c_old[..., None] + bacc * c_blk[..., None]
        return m_new, l, acc

    def step(carry, i):
        kk, vv, m, l, acc = carry
        # Rotate FIRST (steps 1..n-1): after i rotations the held block
        # originated at rank - i, and no trailing hop is wasted (the
        # final iteration's rotation would otherwise be discarded — one
        # superfluous pair of ICI collectives per layer per step).
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        src = (rank - i) % nshards
        kpos = src * s_local + jnp.arange(s_local)
        bm, bl, bacc = _block_attend(q, kk, vv, scale, qpos, kpos,
                                     causal, drop=dropout_rate,
                                     seed=dropout_seed,
                                     q_off=rank * s_local,
                                     k_off=src * s_local)
        m, l, acc = merge(m, l, acc, bm, bl, bacc)
        return (kk, vv, m, l, acc), None

    # step 0: the local block, no hop
    m0, l0, acc0 = _block_attend(q, k, v, scale, qpos, qpos, causal,
                                 drop=dropout_rate, seed=dropout_seed,
                                 q_off=rank * s_local,
                                 k_off=rank * s_local)
    if nshards > 1:
        (_, _, m, l, acc), _ = jax.lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(1, nshards))
    else:
        m, l, acc = m0, l0, acc0
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str,
                      scale: Optional[float] = None,
                      causal: bool = False,
                      attention_fn=None,
                      use_flash: Optional[bool] = None,
                      dropout_rate: float = 0.0,
                      dropout_seed=None) -> jnp.ndarray:
    """DeepSpeed-Ulysses style sequence parallelism: all-to-all swaps
    the sharded axis from SEQUENCE to HEADS, runs full-sequence
    attention locally on a head subset, and swaps back.

    Per-shard shapes (b, h, s_local, d) with ``h %% axis_size == 0``.
    Two all-to-alls replace the ring's ``axis_size`` ppermutes —
    preferable when heads are plentiful and ICI all-to-all bandwidth is
    good; ring attention wins when s_local is large enough to overlap
    compute with the hops.

    ``use_flash=None`` (default) runs the real Pallas kernel for the
    local full-sequence attention whenever the enclosing ``shard_map``
    legality allows it (``check_vma=False``, via
    :func:`flash_legal_here`); under ``check_vma=True`` the local core
    is ``flash_attention``'s XLA reference fallback.  ``True`` asserts
    the kernel, ``False`` forces the fallback core.

    ``dropout_rate``: attention dropout with the SAME global
    coordinate-hash mask as :func:`ring_attention` — here the shard
    owns a HEAD subset of the full sequence, so the mask window is
    selected by ``head_offset = rank * h_local`` instead of sequence
    offsets.  A fixed seed draws identical global masks in ring and
    Ulysses mode.
    """
    nshards = axis_size(axis_name)
    b, h, s_local, d = q.shape
    assert h % nshards == 0, (
        f"heads {h} not divisible by axis size {nshards}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if use_flash is None:
        use_flash = flash_legal_here(q, k, v)

    def seq_to_heads(x):
        # (b, h, s_local, d) -> (b, h/P, P*s_local, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                               tiled=True)
        return x

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    head_off = axis_index(axis_name) * (h // nshards)
    if attention_fn is None:
        if use_flash:
            # bypass flash_attention's manual-axis fallback: the Pallas
            # call is legal under shard_map(check_vma=False)
            from .flash_attention import flash_attention_partial

            def attention_fn(q, k, v, scale=None, causal=False):
                kw = (dict(dropout_rate=dropout_rate,
                           dropout_seed=dropout_seed,
                           head_offset=head_off)
                      if dropout_rate > 0.0 else {})
                return flash_attention_partial(q, k, v, scale=scale,
                                               causal=causal, **kw)[0]
        elif dropout_rate > 0.0:
            # einsum core with the same global coordinate-hash mask
            # (the check_vma=True context, e.g. the CPU-mesh dryrun);
            # head_off selects this shard's window of the global mask.
            # Reuses the ring path's _block_attend (whole sequence as
            # one block) so the attention/dropout math lives once.
            def attention_fn(q, k, v, scale=None, causal=False):
                if scale is None:
                    scale = q.shape[-1] ** -0.5
                pos = jnp.arange(q.shape[-2])
                _, l, acc = _block_attend(
                    q, k, v, scale, pos, pos, causal,
                    drop=dropout_rate, seed=dropout_seed,
                    head_off=head_off)
                out = acc / jnp.maximum(l, 1e-30)[..., None]
                return out.astype(q.dtype)
        else:
            from .flash_attention import flash_attention as attention_fn
    out = attention_fn(qh, kh, vh, scale=scale, causal=causal)
    return heads_to_seq(out)
