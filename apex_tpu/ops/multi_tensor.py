"""Multi-tensor ops over flat buffer views of parameter pytrees.

TPU-native replacement for the ``amp_C`` multi-tensor-apply machinery
(ref: csrc/multi_tensor_apply.cuh:16-115 packs tensor pointer tables into
kernel launches; apex/multi_tensor_apply/multi_tensor_apply.py:3-29
dispatches).  On GPU the win is amortizing launch overhead across hundreds
of small tensors; on TPU the equivalent is shaping memory traffic: leaves
are packed (per dtype) into one contiguous 1-D buffer so a single Pallas
kernel makes one pass over params+state.  Packing metadata is static, so
XLA lowers pack/unpack to pure data movement that fuses with neighbours.

Ops mirroring the exported ``amp_C`` list (ref: csrc/amp_C_frontend.cpp:148-173):
``scale`` (multi_tensor_scale), ``axpby`` (multi_tensor_axpby),
``l2norm`` (multi_tensor_l2norm, incl. per-tensor), ``l2norm_scale``.
The overflow-buffer convention becomes a returned finite flag.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.flags import flag_int

# TPU lane/sublane tile for fp32; flat buffers are padded to this so Pallas
# kernels can view them as (rows, 128) without remainder handling.
LANE = 128
_PAD_TO = 8 * LANE


@dataclasses.dataclass(frozen=True)
class FlatMeta:
    """Static packing metadata for one dtype group."""

    treedef: Any
    leaf_indices: Tuple[int, ...]      # positions in the flat leaf list
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int                          # unpadded element count
    padded: int                         # padded to _PAD_TO
    dtype: Any
    # direct group: a single large leaf processed in NATIVE shape (no
    # packing).  Only set when compute_metas is called with
    # split_direct=True — consumers that genuinely need flat packed
    # buffers (LAMB segments, ZeRO shards, flat_master) keep the
    # classic one-group-per-dtype layout.
    direct: bool = False


# Leaves with at least this many elements form their own DIRECT group
# (opt-in via compute_metas(split_direct=True)): their buffer is the
# leaf itself — never packed, never copied.
#
# Default 0 = EVERY leaf direct: on TPU, measured three times at
# successively honest harnesses, packing always lost to XLA's native
# fusion of the identical per-leaf math — there is no launch overhead
# for a packed kernel to amortize inside one jitted program:
#   * 355M/8-leaf trees: packed ~2x slower (2 extra passes over
#     params+grads) — round-1 measurement, threshold 2^22;
#   * BERT-large end-to-end: packing its 1-3M leaves cost ~30 ms/step
#     in layout copies/converts (134.9 -> 105.4 ms at 2^20);
#   * 400x65K-leaf microbench with single-dispatch scan timing and
#     non-hoistable per-step packing: packed 0.44x (adam) / 0.59x
#     (sgd) of native — the regime the pack was built for loses too.
# The reference's multi-tensor design amortizes CUDA *launch* overhead
# (ref: csrc/multi_tensor_apply.cuh), a cost class XLA does not have;
# the Pallas packed kernels remain available via use_pallas=True /
# APEX_TPU_DIRECT_MIN_ELEMS for hardware where the trade-off shifts.
DIRECT_MIN_ELEMS = flag_int("APEX_TPU_DIRECT_MIN_ELEMS")

# Upper bound on a single packed group's element count (split_direct
# consumers only; classic one-group-per-dtype callers like ZeRO keep a
# monolithic buffer by design).  Empirical TPU-compiler guard: in large
# fused programs, XLA materialized a ~10^8-element packed fp32 buffer as
# an (N/2, 2) pair-layout temp whose 2->128 lane padding is 64x the data
# (26.5 GB at BERT-large — compile-time OOM).  Bounded chunks keep the
# multi-tensor launch-amortization win while capping any such temp.
PACK_MAX_ELEMS = 1 << 24


def _group_leaves(leaves, split_direct: bool = False,
                  direct_min: Optional[int] = None) -> dict:
    """leaf indices by (dtype, bucket): bucket None/int chunk id =
    shared per-dtype pack (chunked at PACK_MAX_ELEMS), bucket
    ("direct", i) = leaf i's own direct group (split_direct only).
    ``direct_min`` overrides the module-level DIRECT_MIN_ELEMS (the
    fused pipeline passes a huge value to force every leaf into
    chunked packs — its buffers persist across steps, so the measured
    per-step packing loss the default guards against does not apply)."""
    threshold = DIRECT_MIN_ELEMS if direct_min is None else direct_min
    groups: dict = {}
    if not split_direct:
        for i, leaf in enumerate(leaves):
            arr = jnp.asarray(leaf)
            groups.setdefault((arr.dtype, None), []).append(i)
        return groups
    fill: dict = {}  # dtype -> (chunk id, elems in chunk)
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        if arr.size >= threshold:
            groups[(arr.dtype, ("direct", i))] = [i]
            continue
        chunk, used = fill.get(arr.dtype, (0, 0))
        if used and used + arr.size > PACK_MAX_ELEMS:
            chunk, used = chunk + 1, 0
        fill[arr.dtype] = (chunk, used + arr.size)
        groups.setdefault((arr.dtype, chunk), []).append(i)
    return groups


def compute_metas(tree: Any, align: int = 1,
                  split_direct: bool = False,
                  direct_min: Optional[int] = None) -> List[FlatMeta]:
    """Static packing metadata (shapes/dtypes only — works on tracers).

    ``align`` rounds each leaf's start offset up to a multiple of
    ``align`` elements (zero-filled gaps).  LAMB/NovoGrad pack with
    ``align=LANE`` so every 128-lane row of the packed buffer belongs to
    exactly one tensor, making per-tensor segment reductions
    row-friendly (the per-tensor-norm role of
    csrc/multi_tensor_l2norm_kernel.cu's tensor-table bookkeeping).

    ``split_direct`` gives leaves >= :data:`DIRECT_MIN_ELEMS` their own
    native-shape group (see :func:`group_buffers`); leave it False for
    consumers that need genuinely flat buffers (ZeRO sharding,
    flat_master, segment reductions).  ``direct_min`` overrides the
    module threshold per call (see :func:`_group_leaves`).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    metas = []
    for (dtype, bucket), idxs in _group_leaves(
            leaves, split_direct=split_direct,
            direct_min=direct_min).items():
        shapes = tuple(tuple(jnp.asarray(leaves[i]).shape) for i in idxs)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        offsets, off = [], 0
        for s in sizes:
            offsets.append(off)
            off += -(-s // align) * align
        total = off
        padded = max(_PAD_TO, -(-total // _PAD_TO) * _PAD_TO)
        metas.append(FlatMeta(
            treedef, tuple(idxs), shapes, sizes, tuple(offsets), total,
            padded, dtype,
            direct=isinstance(bucket, tuple) and bucket[0] == "direct"))
    return metas


def pack(tree: Any, metas: Sequence[FlatMeta],
         dtype=None) -> List[jnp.ndarray]:
    """Pack ``tree``'s leaves into flat buffers following ``metas``' layout
    (use params' metas to pack grads so group assignment matches).
    Alignment gaps between leaves are zero-filled."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    out = []
    for meta in metas:
        pieces = []
        pos = 0
        for k, i in enumerate(meta.leaf_indices):
            gap = meta.offsets[k] - pos
            if gap:
                pieces.append(jnp.zeros((gap,), meta.dtype))
            pieces.append(jnp.ravel(leaves[i]))
            pos = meta.offsets[k] + meta.sizes[k]
        if meta.padded > pos:
            pieces.append(jnp.zeros((meta.padded - pos,),
                                    pieces[-1].dtype if pieces
                                    else meta.dtype))
        flat = jnp.concatenate(pieces)
        out.append(flat.astype(dtype) if dtype is not None else flat)
    return out


def is_direct(meta: FlatMeta) -> bool:
    """Direct group: a single large leaf processed in native shape
    (only produced by ``compute_metas(split_direct=True)``)."""
    return meta.direct


def group_buffers(tree: Any, metas: Sequence[FlatMeta],
                  dtype=None) -> List[jnp.ndarray]:
    """Per-group working buffers: multi-leaf groups pack to a flat 1-D
    buffer; DIRECT groups return the leaf array itself — no ravel, no
    copy, no aliasing barrier.  Measured on v5e at 355M params, even
    'free' reshape-only packs cost ~1.8x over native-shape processing
    (XLA cannot alias donated leaf buffers through the pack/unpack
    views), so elementwise optimizer math runs on native shapes."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    out = []
    for meta in metas:
        if is_direct(meta):
            x = jnp.asarray(leaves[meta.leaf_indices[0]])
            out.append(x.astype(dtype) if dtype is not None else x)
        else:
            out.append(pack(tree, [meta], dtype)[0])
    return out


def assemble(group_bufs: Sequence[jnp.ndarray],
             metas: Sequence[FlatMeta],
             out_dtypes: Optional[Sequence[Any]] = None) -> Any:
    """Rebuild the pytree from :func:`group_buffers` outputs (direct
    groups reshape from native/whatever shape; packed groups unpack via
    the same slicing as :func:`unpack_groups`)."""
    n_leaves = sum(len(m.leaf_indices) for m in metas)
    leaves: List[Optional[jnp.ndarray]] = [None] * n_leaves
    for buf, meta in zip(group_bufs, metas):
        if is_direct(meta):
            idx = meta.leaf_indices[0]
            piece = buf.reshape(meta.shapes[0])
            if out_dtypes is not None:
                piece = piece.astype(out_dtypes[idx])
            leaves[idx] = piece
        else:
            _unpack_into(leaves, buf, meta, out_dtypes)
    return jax.tree_util.tree_unflatten(metas[0].treedef, leaves)


def state_zeros(metas: Sequence[FlatMeta]) -> Tuple[jnp.ndarray, ...]:
    """fp32 optimizer-state zeros per group: native leaf shape for
    direct groups, padded flat buffer for packed groups."""
    out = []
    for meta in metas:
        if is_direct(meta):
            out.append(jnp.zeros(meta.shapes[0], jnp.float32))
        else:
            out.append(jnp.zeros((meta.padded,), jnp.float32))
    return tuple(out)


def pack_groups(tree: Any) -> Tuple[List[jnp.ndarray], List[FlatMeta]]:
    """Pack a pytree into one padded 1-D buffer per leaf dtype.

    The per-dtype grouping mirrors the reference's
    ``split_half_float_double_bfloat16`` bucketing
    (ref: apex/parallel/distributed.py:60-76)."""
    metas = compute_metas(tree)
    return pack(tree, metas), metas


def _unpack_into(leaves: List, buf: jnp.ndarray, meta: FlatMeta,
                 out_dtypes: Optional[Sequence[Any]]) -> None:
    """Slice one packed buffer back into its leaf slots (shared by
    unpack_groups and assemble)."""
    for k, leaf_idx in enumerate(meta.leaf_indices):
        piece = jax.lax.dynamic_slice_in_dim(
            buf, meta.offsets[k], meta.sizes[k]).reshape(meta.shapes[k])
        if out_dtypes is not None:
            piece = piece.astype(out_dtypes[leaf_idx])
        leaves[leaf_idx] = piece


def unpack_groups(buffers: Sequence[jnp.ndarray],
                  metas: Sequence[FlatMeta],
                  out_dtypes: Optional[Sequence[Any]] = None) -> Any:
    """Rebuild the pytree from packed buffers (inverse of pack_groups)."""
    n_leaves = sum(len(m.leaf_indices) for m in metas)
    leaves: List[Optional[jnp.ndarray]] = [None] * n_leaves
    for buf, meta in zip(buffers, metas):
        _unpack_into(leaves, buf, meta, out_dtypes)
    return jax.tree_util.tree_unflatten(metas[0].treedef, leaves)


def segment_ids(meta: FlatMeta) -> jnp.ndarray:
    """Per-element tensor index for a packed buffer (padding gets the id
    ``len(sizes)``).

    NOTE: prefer :func:`per_tensor_sumsq` / :func:`broadcast_per_tensor`
    for per-tensor norm work — this materializes a host constant of
    ``padded`` elements, which inlines into the program text and
    explodes lowering size at scale (measured: 88 MB of StableHLO for a
    2-layer BERT train step; an HTTP-413 compile-request rejection at
    24 layers).  Kept for small buffers and tests."""
    ids = np.full((meta.padded,), len(meta.sizes), np.int32)
    for k, (o, s) in enumerate(zip(meta.offsets, meta.sizes)):
        ids[o:o + s] = k
    return jnp.asarray(ids)


def sumsq(x: jnp.ndarray) -> jnp.ndarray:
    """fp32 sum of squares with the TPU-safe reduction shape.

    Long 1-D reductions make XLA:TPU materialize an (N/2, 2) stage whose
    2->128 lane padding is 64x the data (a 26.5 GB compile-time OOM at
    BERT-large scale); reducing over a (rows, LANE) view avoids it.
    The single shared implementation of that workaround — keep every
    whole-buffer norm on this helper."""
    x = x.astype(jnp.float32)
    if x.ndim == 1 and x.size and x.size % LANE == 0:
        x = x.reshape(-1, LANE)
    return jnp.sum(x * x)


def per_tensor_sumsq(buf: jnp.ndarray, meta: FlatMeta) -> jnp.ndarray:
    """Per-tensor sum-of-squares over a packed fp32 buffer, one entry
    per leaf, via *static* slices (offsets/sizes are Python ints).

    This is the multi_tensor_l2norm(per_tensor=True) role
    (ref: csrc/multi_tensor_l2norm_kernel.cu) in a form whose program
    size is O(n_leaves) — no scatter/segment ops, no packed-length
    index constants (which OOM/413 at BERT-large scale).

    Each slice spans to the next LANE-aligned offset (the padding gap
    belongs to its preceding tensor) so the reduction input reshapes to
    (rows, LANE) — a flat mega-vector reduce makes XLA:TPU materialize
    an (N/2, 2) stage whose lane padding is 64x the data.

    PRECONDITION: padding gaps in ``buf`` must be exactly zero so they
    contribute nothing to the preceding tensor's sum.  ``pack`` zero-
    fills gaps and the LAMB phase-1 math maps 0 -> 0 only when eps > 0
    (enforced by the fused_lamb AND FusedMixedPrecisionLamb
    constructors — both share _lamb_group_update); any new caller
    writing gaps must keep them zero or switch to
    ``device_segment_ids``-based masking."""
    x = buf.astype(jnp.float32)
    sums = []
    for k, o in enumerate(meta.offsets):
        end = meta.offsets[k + 1] if k + 1 < len(meta.offsets) \
            else meta.padded
        sums.append(sumsq(jax.lax.slice_in_dim(x, o, end)))
    return jnp.stack(sums)


def device_segment_ids(meta: FlatMeta, idx: jnp.ndarray) -> jnp.ndarray:
    """Tensor index for arbitrary (possibly traced) packed-buffer
    positions ``idx``; padding gaps map to ``len(sizes)``.

    On-device binary search over the tiny offset table
    (``jnp.searchsorted`` scan method — log(n_leaves) fused gathers per
    element, no packed-length constants, no (N, k) temporaries), for
    callers whose positions are dynamic — e.g. ZeRO shards indexed by
    ``axis_index`` (distributed_fused_lamb)."""
    starts = jnp.asarray(meta.offsets, jnp.int32)
    ends = starts + jnp.asarray(meta.sizes, jnp.int32)
    idx = idx.astype(jnp.int32)
    k = jnp.searchsorted(starts, idx, side="right").astype(jnp.int32) - 1
    k_safe = jnp.clip(k, 0, len(meta.sizes) - 1)
    ok = (k >= 0) & (idx < ends[k_safe])
    return jnp.where(ok, k_safe, jnp.int32(len(meta.sizes)))


def broadcast_per_tensor(values: jnp.ndarray, meta: FlatMeta,
                         fill: float = 1.0) -> jnp.ndarray:
    """Expand per-tensor scalars ``values[k]`` back to a packed-buffer
    element array (padding gaps get ``fill``) — the stage-2 broadcast of
    the reference's LAMB/NovoGrad kernels, with the same O(n_leaves)
    program-size property as :func:`per_tensor_sumsq`."""
    pieces = []
    pos = 0
    for k, (o, s) in enumerate(zip(meta.offsets, meta.sizes)):
        if o > pos:
            pieces.append(jnp.full((o - pos,), fill, jnp.float32))
        pieces.append(jnp.broadcast_to(values[k].astype(jnp.float32),
                                       (s,)))
        pos = o + s
    if meta.padded > pos:
        pieces.append(jnp.full((meta.padded - pos,), fill, jnp.float32))
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


# --- amp_C-parity ops ------------------------------------------------------

def scale(tree: Any, scale_factor) -> Tuple[Any, jnp.ndarray]:
    """Multiply every leaf by ``scale_factor``; returns (scaled, finite_flag)
    (ref: multi_tensor_scale_kernel.cu — scale + overflow check fused)."""
    scaled = jax.tree_util.tree_map(
        lambda x: (x.astype(jnp.float32) * scale_factor).astype(x.dtype),
        tree)
    finite = jnp.stack([
        jnp.all(jnp.isfinite(leaf.astype(jnp.float32)))
        for leaf in jax.tree_util.tree_leaves(scaled)]).all() \
        if jax.tree_util.tree_leaves(scaled) else jnp.bool_(True)
    return scaled, finite


def axpby(a, x_tree: Any, b, y_tree: Any, out_dtype=None) -> Any:
    """``a*x + b*y`` leafwise in fp32
    (ref: multi_tensor_axpby_kernel.cu, used for fused unscale+copy,
    apex/amp/scaler.py:161-193)."""
    def _axpby(x, y):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return r.astype(out_dtype or x.dtype)
    return jax.tree_util.tree_map(_axpby, x_tree, y_tree)


def l2norm(tree: Any, per_tensor: bool = False):
    """Global L2 norm, optionally also per-leaf norms
    (ref: multi_tensor_l2norm_kernel.cu; LAMB phase 1,
    apex/optimizers/fused_lamb.py)."""
    leaves = jax.tree_util.tree_leaves(tree)
    sq = [jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves]
    total = jnp.sqrt(jnp.sum(jnp.stack(sq))) if sq else jnp.float32(0)
    if per_tensor:
        return total, jnp.sqrt(jnp.stack(sq))
    return total


def l2norm_scale(tree: Any, max_norm, per_tensor: bool = False) -> Any:
    """Scale the whole tree by ``min(1, max_norm/global_norm)`` — fused
    norm+clip (ref: multi_tensor_l2norm_scale_kernel.cu semantics)."""
    norm = l2norm(tree)
    factor = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda x: (x.astype(jnp.float32) * factor).astype(x.dtype), tree)
