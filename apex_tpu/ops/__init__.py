"""apex_tpu.ops — Pallas kernels and multi-tensor utilities."""
from . import fused_optim, fused_pipeline, multi_tensor
from .multi_tensor import axpby, l2norm, l2norm_scale, scale

__all__ = ["multi_tensor", "fused_optim", "fused_pipeline", "scale",
           "axpby", "l2norm", "l2norm_scale"]
