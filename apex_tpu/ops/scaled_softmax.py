"""Pallas scaled masked softmax (causal and padding-mask variants).

TPU-native equivalent of the Megatron fused softmax kernels
(ref: csrc/megatron/scaled_upper_triang_masked_softmax.h,
scaled_masked_softmax.h; python wrappers
apex/transformer/functional/fused_softmax.py:21-93).  Scale, mask and a
numerically-stable fp32 softmax are fused into one VMEM pass; inputs may
be bf16/fp16, math is fp32, output matches the input dtype.

Backward uses the saved probabilities:
``dx = scale * y * (dy - sum(dy * y))`` (ref: the *_backward kernels in
the same headers).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


_NEG = -1e30


def _block_rows(sk: int) -> int:
    target = (1 * 1024 * 1024) // max(1, sk * 4)
    return max(8, min(256, (target // 8) * 8))


# --- causal (upper-triangular masked) --------------------------------------

def _causal_fwd_kernel(scale, br, x_ref, y_ref):
    i = pl.program_id(1)  # q-row block index within the sequence
    x = x_ref[0].astype(jnp.float32) * scale
    rows = i * br + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x = jnp.where(cols <= rows, x, _NEG)
    x = x - jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x)
    y_ref[0] = (e / jnp.sum(e, axis=1, keepdims=True)).astype(y_ref.dtype)


def _softmax_bwd_kernel(scale, y_ref, dy_ref, dx_ref):
    y = y_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    s = jnp.sum(y * dy, axis=1, keepdims=True)
    dx_ref[0] = (scale * y * (dy - s)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scaled_upper_triang_masked_softmax_fused(x: jnp.ndarray,
                                              scale: float = 1.0
                                              ) -> jnp.ndarray:
    return _causal_fwd(x, scale)[0]


def _causal_softmax_xla(x: jnp.ndarray, scale: float) -> jnp.ndarray:
    """jnp twin of the causal kernel (:func:`_causal_fwd`) — the XLA
    reference path used inside shard_map manual axes, and the parity
    anchor the kernel audit checks against."""
    sq, sk = x.shape[-2:]
    s = x.astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((sq, sk), bool))
    s = jnp.where(mask, s, jnp.float32(-10000.0))
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def scaled_upper_triang_masked_softmax(x: jnp.ndarray,
                                       scale: float = 1.0) -> jnp.ndarray:
    """Causal softmax over (..., sq, sk) attention scores
    (ref: ScaledUpperTriangMaskedSoftmax,
    apex/transformer/functional/fused_softmax.py:21-42).  Inside
    shard_map manual axes the XLA reference path runs."""
    from ._context import in_manual_axis_context

    if in_manual_axis_context(x):
        return _causal_softmax_xla(x, scale)
    return _scaled_upper_triang_masked_softmax_fused(x, scale)


def _causal_fwd(x, scale):
    *lead, sq, sk = x.shape
    b3 = 1
    for d in lead:
        b3 *= d
    x3 = x.reshape(b3, sq, sk)
    br = _block_rows(sk)
    psq = -(-sq // br) * br
    xp = jnp.pad(x3, ((0, 0), (0, psq - sq), (0, 0))) if psq != sq else x3
    spec = pl.BlockSpec((1, br, sk), lambda b, i: (b, i, 0),
                        memory_space=pltpu.VMEM)
    y = pl.pallas_call(
        functools.partial(_causal_fwd_kernel, scale, br),
        grid=(b3, psq // br),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        interpret=_interpret(),
    )(xp)
    y = y[:, :sq].reshape(*lead, sq, sk)
    return y, y


def _causal_bwd(scale, y, dy):
    return (_softmax_backward(y, dy, scale),)


def _softmax_backward(y, dy, scale):
    *lead, sq, sk = y.shape
    b3 = 1
    for d in lead:
        b3 *= d
    y3 = y.reshape(b3, sq, sk)
    dy3 = dy.reshape(b3, sq, sk)
    br = _block_rows(sk)
    psq = -(-sq // br) * br

    def padq(a):
        return jnp.pad(a, ((0, 0), (0, psq - sq), (0, 0))) \
            if psq != sq else a

    spec = pl.BlockSpec((1, br, sk), lambda b, i: (b, i, 0),
                        memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        functools.partial(_softmax_bwd_kernel, scale),
        grid=(b3, psq // br),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((b3, psq, sk), y.dtype),
        interpret=_interpret(),
    )(padq(y3), padq(dy3))
    return dx[:, :sq].reshape(*lead, sq, sk)


_scaled_upper_triang_masked_softmax_fused.defvjp(
    lambda x, scale: _causal_fwd(x, scale), _causal_bwd)


# --- general padding mask ---------------------------------------------------

def _masked_fwd_kernel(scale, x_ref, m_ref, y_ref):
    x = x_ref[0, 0].astype(jnp.float32) * scale
    masked = m_ref[0, 0] != 0
    x = jnp.where(masked, _NEG, x)
    x = x - jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x)
    y_ref[0, 0] = (e / jnp.sum(e, axis=1, keepdims=True)).astype(y_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax_fused(x: jnp.ndarray, mask: jnp.ndarray,
                                 scale: float = 1.0) -> jnp.ndarray:
    return _masked_fwd(x, mask, scale)[0]


def scaled_masked_softmax(x: jnp.ndarray, mask: jnp.ndarray,
                          scale: float = 1.0) -> jnp.ndarray:
    """Softmax over (b, np, sq, sk) with a boolean padding mask
    (b, 1, sq, sk); True/nonzero entries are masked out
    (ref: ScaledMaskedSoftmax,
    apex/transformer/functional/fused_softmax.py:67-93).  Inside
    shard_map manual axes the XLA reference path runs."""
    from ._context import in_manual_axis_context

    if in_manual_axis_context(x, mask):
        return _masked_softmax_xla(x, mask, scale)
    return _scaled_masked_softmax_fused(x, mask, scale)


def _masked_softmax_xla(x: jnp.ndarray, mask: jnp.ndarray,
                        scale: float) -> jnp.ndarray:
    """jnp twin of the masked kernel (:func:`_masked_fwd`)."""
    s = x.astype(jnp.float32) * scale
    s = jnp.where(mask, jnp.float32(-10000.0), s)
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def _masked_fwd(x, mask, scale):
    b, np_, sq, sk = x.shape
    br = _block_rows(sk)
    psq = -(-sq // br) * br

    def padq(a):
        return jnp.pad(a, ((0, 0), (0, 0), (0, psq - sq), (0, 0))) \
            if psq != sq else a

    mask_i = mask.astype(jnp.int32)
    x_spec = pl.BlockSpec((1, 1, br, sk), lambda bi, ni, si: (bi, ni, si, 0),
                          memory_space=pltpu.VMEM)
    m_spec = pl.BlockSpec((1, 1, br, sk), lambda bi, ni, si: (bi, 0, si, 0),
                          memory_space=pltpu.VMEM)
    y = pl.pallas_call(
        functools.partial(_masked_fwd_kernel, scale),
        grid=(b, np_, psq // br),
        in_specs=[x_spec, m_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((b, np_, psq, sk), x.dtype),
        interpret=_interpret(),
    )(padq(x), padq(mask_i))
    y = y[:, :, :sq]
    return y, y


def _masked_bwd(scale, y, dy):
    return _softmax_backward(y, dy, scale), None


_scaled_masked_softmax_fused.defvjp(
    lambda x, m, scale: _masked_fwd(x, m, scale), _masked_bwd)
