"""Execution-context helpers shared by the Pallas op wrappers."""
from __future__ import annotations

from .._compat import typeof


def in_manual_axis_context(*operands) -> bool:
    """True when the computation is inside ``shard_map`` manual axes.

    Pallas calls cannot yet express varying-mesh-axis (VMA) types on
    their outputs, so inside ``shard_map(check_vma=True)`` every fused op
    routes to its XLA-fusion reference implementation — same math, XLA
    still fuses it per shard.  Outside (plain jit / pjit / GSPMD) the
    Pallas kernels run.

    The public ``jax.typeof(operand).vma`` type (via the
    :mod:`apex_tpu._compat` shim — old jax has no ``typeof`` and its
    avals carry no ``vma``) gives a fast positive (any varying operand
    => manual context); the axis-env probe then decides the rest.  The axis env CANNOT be skipped even when every
    operand is unvarying: ``pallas_call`` inside
    ``shard_map(check_vma=True)`` demands vma-typed out specs regardless
    of operand variance, so replicated inputs still need the fallback.
    Deliberate trade-off: this also routes ``vmap(axis_name=...)``
    bodies (where the Pallas call would be legal) to the fallback —
    named-axis vmap is rare and the fallback is merely the XLA-fused
    reference implementation; choosing correctness under shard_map over
    that corner's kernel dispatch.
    The axis-env probe is deliberately NOT wrapped in a blanket except —
    if the private API drifts, failing loudly here beats silently
    running a Pallas call that check_vma rejects later.
    """
    for x in operands:
        try:
            if typeof(x).vma:
                return True
        except (AttributeError, TypeError):
            continue
    from jax._src import core as _jax_core

    return bool(_jax_core.get_axis_env().axis_sizes)
