"""Execution-context helpers shared by the Pallas op wrappers."""
from __future__ import annotations

import jax


def in_manual_axis_context(*operands) -> bool:
    """True when the computation is inside ``shard_map`` manual axes.

    Pallas calls cannot yet express varying-mesh-axis (VMA) types on
    their outputs, so inside ``shard_map(check_vma=True)`` every fused op
    routes to its XLA-fusion reference implementation — same math, XLA
    still fuses it per shard.  Outside (plain jit / pjit / GSPMD) the
    Pallas kernels run.

    Detection prefers the public ``jax.typeof(operand).vma`` type when
    operands are given: only values actually *varying* over manual axes
    force the fallback, so ``vmap(axis_name=...)`` and replicated values
    inside shard_map keep the Pallas path (the private axis-env check
    this replaces disabled it for any named axis).  With no operands the
    axis-env heuristic is used; if both probes break (API drift) the
    error propagates rather than silently choosing a path.
    """
    probed = False
    for x in operands:
        try:
            vma = jax.typeof(x).vma
        except (AttributeError, TypeError):
            continue
        probed = True
        if vma:
            return True
    if probed:
        return False
    # No operands (or none carried a vma type): conservative axis-env
    # probe.  Deliberately NOT wrapped in a blanket except — if this
    # private API drifts, failing loudly here beats silently running a
    # Pallas call inside shard_map where check_vma rejects it later.
    from jax._src import core as _jax_core

    return bool(_jax_core.get_axis_env().axis_sizes)
