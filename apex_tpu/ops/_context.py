"""Execution-context helpers shared by the Pallas op wrappers."""
from __future__ import annotations

from jax._src import core as _jax_core


def in_manual_axis_context() -> bool:
    """True when tracing inside ``shard_map`` manual axes.

    Pallas calls cannot yet express varying-mesh-axis (VMA) types on
    their outputs, so inside ``shard_map(check_vma=True)`` every fused op
    routes to its XLA-fusion reference implementation — same math, XLA
    still fuses it per shard.  Outside (plain jit / pjit / GSPMD) the
    Pallas kernels run.
    """
    try:
        return bool(_jax_core.get_axis_env().axis_sizes)
    except Exception:  # pragma: no cover - private-API drift safety
        return False
